"""HF-checkpoint importer: load pretrained weights onto the native trunk.

This is the TPU-native answer to the reference's kernel-injection / AutoTP
machinery (``module_inject/replace_module.py:182``, ``auto_tp.py:175``,
``module_inject/load_checkpoint.py``): instead of walking a live torch module
graph and swapping layers for fused replacements, we map a *checkpoint* —
HF-format ``safetensors`` / ``pytorch_model.bin`` plus ``config.json`` — onto
the native :class:`TransformerLM` parameter pytree.  The trunk's
``param_specs()`` then plays the role of the ~20 per-architecture injection
policies: sharding is a property of the destination, not a rewrite of the
source, so TP/ZeRO/offload all apply to imported models for free.

Per-architecture mapping lives in small ``_Family`` converters (the analog of
``module_inject/containers/*``): name mapping, per-layer stacking into the
scan-friendly ``(L, ...)`` layout, qkv handling (GPT-2's fused ``c_attn`` is
split; Llama's separate projections are transposed from torch's ``(out, in)``
to matmul ``(in, out)``), and the RoPE basis permutation (HF "rotate-half"
→ interleaved pairs) absorbed into the q/k projection weights.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .transformer import TransformerConfig

__all__ = ["load_hf_checkpoint", "import_state_dict", "config_from_hf"]


# ----------------------------------------------------------- tensor plumbing
def _to_numpy(t) -> np.ndarray:
    """torch / jax / numpy tensor → numpy, preserving the storage dtype.

    bf16 checkpoints stay bf16 (``ml_dtypes.bfloat16`` numpy arrays — the
    stack/transpose/permute ops all work on them), so a 70B import costs
    ~1× the checkpoint size in host RAM, not 3×; fp32 master creation
    upcasts leaf-by-leaf downstream in the engine."""
    if isinstance(t, np.ndarray):
        return t
    if isinstance(t, jnp.ndarray):
        return np.asarray(t)          # bf16 → ml_dtypes.bfloat16 view
    import torch

    if isinstance(t, torch.Tensor):
        t = t.detach().cpu()
        if t.dtype == torch.bfloat16:
            import ml_dtypes

            return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
        return t.numpy()
    raise TypeError(f"unsupported tensor type {type(t)!r}")


def _rope_interleave_perm(n_heads: int, head_dim: int,
                          rotary_dim: int | None = None) -> np.ndarray:
    """Column permutation converting HF rotate-half q/k projections to the
    trunk's interleaved-pair RoPE basis.

    HF rotates dim ``j`` with dim ``j + rd/2`` (shared freq_j); the trunk
    rotates dims ``(2j, 2j+1)``.  Mapping output column ``2j ← j`` and
    ``2j+1 ← j + rd/2`` per head makes both compute identical attention
    scores (the permutation is applied to q AND k, so dot products are
    invariant and ``wo`` needs no change).  With partial rotary
    (``rotary_dim`` < head_dim, NeoX ``rotary_pct``), only the leading
    rotary columns permute; the pass-through tail keeps identity order.
    GPT-J needs NO permutation — its rotary is natively interleaved."""
    rd = rotary_dim or head_dim
    half = rd // 2
    per_head = np.arange(head_dim, dtype=np.int64)
    rot = np.empty((rd,), dtype=np.int64)
    rot[0::2] = np.arange(half)
    rot[1::2] = np.arange(half) + half
    per_head[:rd] = rot
    return (np.arange(n_heads)[:, None] * head_dim + per_head[None, :]).reshape(-1)


class _SDict:
    """State-dict view with prefix stripping + access tracking."""

    def __init__(self, sd: Dict[str, Any], strip: Tuple[str, ...] = ()):
        self._sd = {}
        for k, v in sd.items():
            for p in strip:
                if k.startswith(p):
                    k = k[len(p):]
                    break
            self._sd[k] = v
        self.used: set[str] = set()

    def __contains__(self, k):
        return k in self._sd

    def take(self, k: str) -> np.ndarray:
        self.used.add(k)
        return _to_numpy(self._sd[k])

    def get(self, k: str):
        return self.take(k) if k in self._sd else None

    def unused(self) -> list[str]:
        return sorted(set(self._sd) - self.used)


def _stack(layers: list[dict[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Per-layer dicts → one dict of (L, ...)-stacked arrays."""
    keys = layers[0].keys()
    return {k: np.stack([lyr[k] for lyr in layers]) for k in keys}


# ------------------------------------------------------------- family: gpt2
def _gpt2_config(hf: dict) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["n_layer"],
        n_head=hf["n_head"],
        d_model=hf["n_embd"],
        d_ff=hf.get("n_inner") or 4 * hf["n_embd"],
        max_seq=hf.get("n_positions", 1024),
        pos_embedding="learned", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True,
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )


def _gpt2_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """GPT-2: Conv1D stores weights as (in, out) — no transpose; fused
    ``c_attn`` (d, 3d) splits into wq/wk/wv."""
    d = cfg.d_model
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"h.{i}."
        ca_w = sd.take(h + "attn.c_attn.weight")          # (d, 3d)
        ca_b = sd.take(h + "attn.c_attn.bias")            # (3d,)
        wq, wk, wv = ca_w[:, :d], ca_w[:, d:2 * d], ca_w[:, 2 * d:]
        bq, bk, bv = ca_b[:d], ca_b[d:2 * d], ca_b[2 * d:]
        per_layer.append({
            "ln1_scale": sd.take(h + "ln_1.weight"),
            "ln1_bias": sd.take(h + "ln_1.bias"),
            "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
            "wo": sd.take(h + "attn.c_proj.weight"),
            "bo": sd.take(h + "attn.c_proj.bias"),
            "ln2_scale": sd.take(h + "ln_2.weight"),
            "ln2_bias": sd.take(h + "ln_2.bias"),
            "w_in": sd.take(h + "mlp.c_fc.weight"),
            "b_in": sd.take(h + "mlp.c_fc.bias"),
            "w_out": sd.take(h + "mlp.c_proj.weight"),
            "b_out": sd.take(h + "mlp.c_proj.bias"),
        })
    params = {
        "tok_embed": sd.take("wte.weight"),
        "pos_embed": sd.take("wpe.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd.take("lm_head.weight").T
    return params


# ------------------------------------------------------ family: llama-like
def _llama_config(hf: dict) -> TransformerConfig:
    if hf.get("rope_scaling"):
        raise ValueError(
            "checkpoint uses rope_scaling (extended-context RoPE remap); the "
            "native trunk applies plain rope_theta positions — importing "
            "would silently change long-range attention. Unsupported.")
    if hf.get("sliding_window") and hf.get("use_sliding_window", True):
        log_dist("importer: checkpoint declares sliding_window="
                 f"{hf['sliding_window']} — the native trunk runs full causal "
                 "attention, so outputs diverge from HF beyond the window")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_head=hf.get("num_key_value_heads") or hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf["intermediate_size"],
        max_seq=hf.get("max_position_embeddings", 4096),
        pos_embedding="rope", norm="rmsnorm", activation="silu_glu",
        use_bias=False, tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        rope_theta=hf.get("rope_theta", 10000.0),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        num_experts=hf.get("num_local_experts", 1),
        moe_top_k=hf.get("num_experts_per_tok", 2),
    )


def _llama_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Llama/Mistral/Mixtral: torch Linear (out, in) → transpose; absorb the
    RoPE basis change into wq/wk columns; Mixtral expert banks stacked."""
    hd = cfg.head_dim
    q_perm = _rope_interleave_perm(cfg.n_head, hd)
    kv_perm = _rope_interleave_perm(cfg.kv_heads, hd)
    moe = cfg.num_experts > 1
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"layers.{i}."
        lyr = {
            "ln1_scale": sd.take(h + "input_layernorm.weight"),
            "wq": sd.take(h + "self_attn.q_proj.weight").T[:, q_perm],
            "wk": sd.take(h + "self_attn.k_proj.weight").T[:, kv_perm],
            "wv": sd.take(h + "self_attn.v_proj.weight").T,
            "wo": sd.take(h + "self_attn.o_proj.weight").T,
            "ln2_scale": sd.take(h + "post_attention_layernorm.weight"),
        }
        if moe:
            m = h + "block_sparse_moe."
            lyr["router"] = sd.take(m + "gate.weight").T          # (d, E)
            # Mixtral expert order: w1=gate, w2=down, w3=up (all (out, in)).
            lyr["w_gate"] = np.stack([sd.take(f"{m}experts.{e}.w1.weight").T
                                      for e in range(cfg.num_experts)])
            lyr["w_out"] = np.stack([sd.take(f"{m}experts.{e}.w2.weight").T
                                     for e in range(cfg.num_experts)])
            lyr["w_in"] = np.stack([sd.take(f"{m}experts.{e}.w3.weight").T
                                    for e in range(cfg.num_experts)])
        else:
            lyr["w_gate"] = sd.take(h + "mlp.gate_proj.weight").T
            lyr["w_in"] = sd.take(h + "mlp.up_proj.weight").T
            lyr["w_out"] = sd.take(h + "mlp.down_proj.weight").T
        per_layer.append(lyr)
    params = {
        "tok_embed": sd.take("embed_tokens.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("norm.weight"),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd.take("lm_head.weight").T
    return params


# --------------------------------------------------------- family: internlm
def _internlm_config(hf: dict) -> TransformerConfig:
    """InternLM v1 (reference ``module_inject/containers/internlm.py``): a
    Llama block whose attention projections carry biases (config
    ``"bias": true``)."""
    cfg = _llama_config(hf)
    if hf.get("bias", True):
        cfg = dataclasses.replace(cfg, use_bias=True)
    return cfg


def _internlm_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Llama mapping + attention biases. The q/k biases feed pre-RoPE
    activations, so they get the same interleave basis change as the wq/wk
    columns. The trunk's use_bias is all-or-nothing; InternLM has no
    rmsnorm/FFN biases, so those leaves are zeros (numeric no-ops)."""
    params = _llama_convert(sd, cfg)
    if not cfg.use_bias:
        return params
    hd = cfg.head_dim
    perms = {"q_proj": _rope_interleave_perm(cfg.n_head, hd),
             "k_proj": _rope_interleave_perm(cfg.kv_heads, hd)}
    layers = params["layers"]
    for name, leaf in (("q_proj", "bq"), ("k_proj", "bk"),
                       ("v_proj", "bv"), ("o_proj", "bo")):
        rows = np.stack([sd.take(f"layers.{i}.self_attn.{name}.bias")
                         for i in range(cfg.n_layer)])
        perm = perms.get(name)
        layers[leaf] = rows[:, perm] if perm is not None else rows
    L, d, f = cfg.n_layer, cfg.d_model, cfg.ffn_dim
    layers["ln1_bias"] = np.zeros((L, d), np.float32)
    layers["ln2_bias"] = np.zeros((L, d), np.float32)
    layers["b_in"] = np.zeros((L, f), np.float32)
    layers["b_out"] = np.zeros((L, d), np.float32)
    params["lnf_bias"] = np.zeros((d,), np.float32)
    return params


# -------------------------------------------------------------- family: opt
def _opt_config(hf: dict) -> TransformerConfig:
    if hf.get("word_embed_proj_dim", hf["hidden_size"]) != hf["hidden_size"]:
        raise ValueError("OPT variants with word_embed_proj_dim != "
                         "hidden_size (350m) are not supported")
    if not hf.get("do_layer_norm_before", True):
        raise ValueError("OPT-350m's post-norm layout is not supported")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf["ffn_dim"],
        max_seq=hf.get("max_position_embeddings", 2048),
        pos_embedding="learned", norm="layernorm",
        activation=hf.get("activation_function", "relu"),
        use_bias=True, tie_embeddings=True,
    )


def _opt_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """OPT: torch Linear (out, in) → transpose; embed_positions rows are
    offset by 2 (HF quirk: positions 0.. use rows 2..)."""
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"layers.{i}."
        per_layer.append({
            "ln1_scale": sd.take(h + "self_attn_layer_norm.weight"),
            "ln1_bias": sd.take(h + "self_attn_layer_norm.bias"),
            "wq": sd.take(h + "self_attn.q_proj.weight").T,
            "wk": sd.take(h + "self_attn.k_proj.weight").T,
            "wv": sd.take(h + "self_attn.v_proj.weight").T,
            "bq": sd.take(h + "self_attn.q_proj.bias"),
            "bk": sd.take(h + "self_attn.k_proj.bias"),
            "bv": sd.take(h + "self_attn.v_proj.bias"),
            "wo": sd.take(h + "self_attn.out_proj.weight").T,
            "bo": sd.take(h + "self_attn.out_proj.bias"),
            "ln2_scale": sd.take(h + "final_layer_norm.weight"),
            "ln2_bias": sd.take(h + "final_layer_norm.bias"),
            "w_in": sd.take(h + "fc1.weight").T,
            "b_in": sd.take(h + "fc1.bias"),
            "w_out": sd.take(h + "fc2.weight").T,
            "b_out": sd.take(h + "fc2.bias"),
        })
    return {
        "tok_embed": sd.take("embed_tokens.weight"),
        "pos_embed": sd.take("embed_positions.weight")[2:],   # offset-2 rows
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("final_layer_norm.weight"),
        "lnf_bias": sd.take("final_layer_norm.bias"),
    }



# ------------------------------------------------------------- family: gptj
def _gptj_config(hf: dict) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["n_layer"],
        n_head=hf["n_head"],
        d_model=hf["n_embd"],
        d_ff=hf.get("n_inner") or 4 * hf["n_embd"],
        max_seq=hf.get("n_positions", 2048),
        pos_embedding="rope", rotary_dim=hf.get("rotary_dim"),
        norm="layernorm", activation="gelu",   # gelu_new = tanh approx
        use_bias=True, tie_embeddings=False, lm_head_bias=True,
        parallel_residual=True, parallel_shared_ln=True,
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )


def _gptj_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """GPT-J: parallel residual, ONE layernorm, separate unbiased q/k/v,
    partial interleaved rotary (native basis — no permutation)."""
    d, hh = cfg.d_model, cfg.n_head * cfg.head_dim
    zeros_h = np.zeros((hh,), np.float32)
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"h.{i}."
        per_layer.append({
            "ln1_scale": sd.take(h + "ln_1.weight"),
            "ln1_bias": sd.take(h + "ln_1.bias"),
            "wq": sd.take(h + "attn.q_proj.weight").T,
            "wk": sd.take(h + "attn.k_proj.weight").T,
            "wv": sd.take(h + "attn.v_proj.weight").T,
            "bq": zeros_h, "bk": zeros_h, "bv": zeros_h,
            "wo": sd.take(h + "attn.out_proj.weight").T,
            "bo": np.zeros((d,), np.float32),
            "w_in": sd.take(h + "mlp.fc_in.weight").T,
            "b_in": sd.take(h + "mlp.fc_in.bias"),
            "w_out": sd.take(h + "mlp.fc_out.weight").T,
            "b_out": sd.take(h + "mlp.fc_out.bias"),
        })
    return {
        "tok_embed": sd.take("wte.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
        "lm_head": sd.take("lm_head.weight").T,
        "lm_head_bias": sd.take("lm_head.bias"),
    }


# ---------------------------------------------------------- family: gpt_neo
def _gptneo_config(hf: dict) -> TransformerConfig:
    """EleutherAI GPT-Neo (reference ``module_inject/containers/gptneo.py``).

    HF alternates global/local attention per layer (``attention_types``);
    the native trunk runs full causal attention everywhere, which is exact
    for sequences up to ``window_size`` (default 256) and diverges beyond it
    on the local layers — same policy as the Mistral sliding-window import.
    """
    att = hf.get("attention_types") or []
    if any("local" in str(block).lower() for block in att):
        log_dist("importer: gpt_neo declares local-attention layers "
                 f"(window_size={hf.get('window_size', 256)}) — the native "
                 "trunk runs full causal attention, so outputs diverge from "
                 "HF beyond the window on those layers")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_layers"],
        n_head=hf["num_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf.get("intermediate_size") or 4 * hf["hidden_size"],
        max_seq=hf.get("max_position_embeddings", 2048),
        pos_embedding="learned", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True,
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )


def _gptneo_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """GPT-Neo: torch Linear (out, in) → transpose; q/k/v carry no bias
    (zeros, GPT-J pattern) but out_proj and the MLP do.  GPT-Neo applies NO
    1/sqrt(head_dim) attention scale (trained that way) — fold sqrt(hd) into
    wq to cancel the trunk's scaling exactly."""
    hh = cfg.n_head * cfg.head_dim
    q_scale = math.sqrt(cfg.head_dim)
    zeros_h = np.zeros((hh,), np.float32)
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"h.{i}."
        a = h + "attn.attention."
        per_layer.append({
            "ln1_scale": sd.take(h + "ln_1.weight"),
            "ln1_bias": sd.take(h + "ln_1.bias"),
            "wq": sd.take(a + "q_proj.weight").T * q_scale,
            "wk": sd.take(a + "k_proj.weight").T,
            "wv": sd.take(a + "v_proj.weight").T,
            "bq": zeros_h, "bk": zeros_h, "bv": zeros_h,
            "wo": sd.take(a + "out_proj.weight").T,
            "bo": sd.take(a + "out_proj.bias"),
            "ln2_scale": sd.take(h + "ln_2.weight"),
            "ln2_bias": sd.take(h + "ln_2.bias"),
            "w_in": sd.take(h + "mlp.c_fc.weight").T,
            "b_in": sd.take(h + "mlp.c_fc.bias"),
            "w_out": sd.take(h + "mlp.c_proj.weight").T,
            "b_out": sd.take(h + "mlp.c_proj.bias"),
        })
    return {
        "tok_embed": sd.take("wte.weight"),
        "pos_embed": sd.take("wpe.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
    }


# --------------------------------------------------------- family: gpt_neox
def _neox_config(hf: dict) -> TransformerConfig:
    hd = hf["hidden_size"] // hf["num_attention_heads"]
    if not hf.get("use_parallel_residual", True):
        raise ValueError("gpt_neox with use_parallel_residual=False: use the "
                         "sequential trunk via a custom config")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf["intermediate_size"],
        max_seq=hf.get("max_position_embeddings", 2048),
        pos_embedding="rope",
        rotary_dim=int(hd * hf.get("rotary_pct", 0.25)),
        rope_theta=hf.get("rotary_emb_base", 10000.0),
        norm="layernorm", activation="gelu_exact",
        use_bias=True, tie_embeddings=False,
        parallel_residual=True, parallel_shared_ln=False,
        norm_eps=hf.get("layer_norm_eps", 1e-5),
    )


def _split_fused_qkv_per_head(w, n_head, head_dim, d):
    """(3*h*hd, d) torch weight with per-head [q|k|v] interleave →
    three (d, h*hd) matmul weights (NeoX/Bloom layout)."""
    w = w.reshape(n_head, 3, head_dim, d)
    return tuple(w[:, j].reshape(n_head * head_dim, d).T for j in range(3))


def _split_fused_qkv_bias_per_head(b, n_head, head_dim):
    """Bias sibling of :func:`_split_fused_qkv_per_head`: (3*h*hd,) with
    per-head [q|k|v] interleave → three (h*hd,) bias vectors."""
    b = b.reshape(n_head, 3, head_dim)
    return tuple(b[:, j].reshape(-1) for j in range(3))


def _neox_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """GPT-NeoX: parallel residual with TWO layernorms, fused per-head-
    interleaved qkv, partial rotate-half rotary → permute rotary columns."""
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    perm = _rope_interleave_perm(h, hd, cfg.rotary_dim)
    per_layer = []
    for i in range(cfg.n_layer):
        p = f"layers.{i}."
        wq, wk, wv = _split_fused_qkv_per_head(
            sd.take(p + "attention.query_key_value.weight"), h, hd, d)
        bq, bk, bv = _split_fused_qkv_bias_per_head(
            sd.take(p + "attention.query_key_value.bias"), h, hd)
        per_layer.append({
            "ln1_scale": sd.take(p + "input_layernorm.weight"),
            "ln1_bias": sd.take(p + "input_layernorm.bias"),
            "ln2_scale": sd.take(p + "post_attention_layernorm.weight"),
            "ln2_bias": sd.take(p + "post_attention_layernorm.bias"),
            "wq": wq[:, perm], "wk": wk[:, perm], "wv": wv,
            "bq": bq[perm], "bk": bk[perm], "bv": bv,
            "wo": sd.take(p + "attention.dense.weight").T,
            "bo": sd.take(p + "attention.dense.bias"),
            "w_in": sd.take(p + "mlp.dense_h_to_4h.weight").T,
            "b_in": sd.take(p + "mlp.dense_h_to_4h.bias"),
            "w_out": sd.take(p + "mlp.dense_4h_to_h.weight").T,
            "b_out": sd.take(p + "mlp.dense_4h_to_h.bias"),
        })
    return {
        "tok_embed": sd.take("embed_in.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("final_layer_norm.weight"),
        "lnf_bias": sd.take("final_layer_norm.bias"),
        "lm_head": sd.take("embed_out.weight").T,
    }


# ------------------------------------------------------------ family: falcon
def _falcon_config(hf: dict) -> TransformerConfig:
    new_arch = hf.get("new_decoder_architecture", False)
    if not hf.get("parallel_attn", True):
        raise ValueError("falcon with parallel_attn=False is not supported")
    if hf.get("alibi", False):
        raise ValueError(
            "falcon with alibi=True (falcon-rw style): the converter maps "
            "the falcon family to rotary positions; importing would silently "
            "change attention. Unsupported.")
    if new_arch:
        n_kv = hf.get("num_kv_heads") or hf["num_attention_heads"]
    else:
        n_kv = 1 if hf.get("multi_query", True) else hf["num_attention_heads"]
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_head=n_kv,
        d_model=hf["hidden_size"],
        d_ff=hf.get("ffn_hidden_size") or 4 * hf["hidden_size"],
        max_seq=hf.get("max_position_embeddings", 2048),
        pos_embedding="rope", rope_theta=hf.get("rope_theta", 10000.0),
        norm="layernorm", activation="gelu_exact",
        use_bias=True,
        tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
        parallel_residual=True,
        parallel_shared_ln=not new_arch,   # 7B: one ln; 40B: ln_attn+ln_mlp
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )


def _falcon_split_qkv(w, n_head, n_kv, head_dim):
    """Falcon fused qkv → (wq, wk, wv) matmul weights.

    multi_query (7B): rows = [h*hd q | hd k | hd v].
    new_decoder_architecture (40B): rows grouped per kv head:
    [group0: q*(h/kv)·hd, k·hd, v·hd | group1: ...]."""
    d = w.shape[1]
    if n_kv == n_head:   # grouped layout degenerates per-head
        w = w.reshape(n_head, 3, head_dim, d)
        return tuple(w[:, j].reshape(-1, d).T for j in range(3))
    if n_kv == 1:
        hh = n_head * head_dim
        return (w[:hh].T, w[hh:hh + head_dim].T, w[hh + head_dim:].T)
    q_per = n_head // n_kv
    w = w.reshape(n_kv, q_per + 2, head_dim, d)
    wq = w[:, :q_per].reshape(-1, d).T
    wk = w[:, q_per].reshape(-1, d).T
    wv = w[:, q_per + 1].reshape(-1, d).T
    return wq, wk, wv


def _falcon_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    d, h, kv, hd = cfg.d_model, cfg.n_head, cfg.kv_heads, cfg.head_dim
    q_perm = _rope_interleave_perm(h, hd)
    kv_perm = _rope_interleave_perm(kv, hd)

    def bias_or_zeros(key, size):
        got = sd.get(key)     # falcon-rw ships biases; mainline has none
        return got if got is not None else np.zeros((size,), np.float32)

    per_layer = []
    for i in range(cfg.n_layer):
        p = f"h.{i}."
        wq, wk, wv = _falcon_split_qkv(
            sd.take(p + "self_attention.query_key_value.weight"), h, kv, hd)
        qkv_b = sd.get(p + "self_attention.query_key_value.bias")
        if qkv_b is not None:
            bq, bk, bv = (b.reshape(-1) for b in _falcon_split_qkv(
                qkv_b[:, None], h, kv, hd))
            bq, bk, bv = bq[q_perm], bk[kv_perm], bv
        else:
            bq = np.zeros((h * hd,), np.float32)
            bk = np.zeros((kv * hd,), np.float32)
            bv = np.zeros((kv * hd,), np.float32)
        lyr = {
            "wq": wq[:, q_perm], "wk": wk[:, kv_perm], "wv": wv,
            "bq": bq, "bk": bk, "bv": bv,
            "bo": bias_or_zeros(p + "self_attention.dense.bias", d),
            "b_in": bias_or_zeros(p + "mlp.dense_h_to_4h.bias", cfg.ffn_dim),
            "b_out": bias_or_zeros(p + "mlp.dense_4h_to_h.bias", d),
            "wo": sd.take(p + "self_attention.dense.weight").T,
            "w_in": sd.take(p + "mlp.dense_h_to_4h.weight").T,
            "w_out": sd.take(p + "mlp.dense_4h_to_h.weight").T,
        }
        if cfg.parallel_shared_ln:   # 7B: single input_layernorm
            lyr["ln1_scale"] = sd.take(p + "input_layernorm.weight")
            lyr["ln1_bias"] = sd.take(p + "input_layernorm.bias")
        else:                        # 40B: ln_attn (attn) + ln_mlp (mlp)
            lyr["ln1_scale"] = sd.take(p + "ln_attn.weight")
            lyr["ln1_bias"] = sd.take(p + "ln_attn.bias")
            lyr["ln2_scale"] = sd.take(p + "ln_mlp.weight")
            lyr["ln2_bias"] = sd.take(p + "ln_mlp.bias")
        per_layer.append(lyr)
    params = {
        "tok_embed": sd.take("word_embeddings.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd.take("lm_head.weight").T
    return params


# ------------------------------------------------------------- family: bloom
def _bloom_config(hf: dict) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf.get("n_layer") or hf["num_hidden_layers"],
        n_head=hf.get("n_head") or hf["num_attention_heads"],
        d_model=hf.get("hidden_size") or hf["n_embed"],
        d_ff=4 * (hf.get("hidden_size") or hf["n_embed"]),
        max_seq=hf.get("seq_length", 2048),
        pos_embedding="alibi", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True, embed_norm=True,
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )


def _bloom_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Bloom-HF: sequential residual, ALiBi, word-embedding layernorm,
    fused per-head-interleaved qkv (same layout as NeoX)."""
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    per_layer = []
    for i in range(cfg.n_layer):
        p = f"h.{i}."
        wq, wk, wv = _split_fused_qkv_per_head(
            sd.take(p + "self_attention.query_key_value.weight"), h, hd, d)
        bq, bk, bv = _split_fused_qkv_bias_per_head(
            sd.take(p + "self_attention.query_key_value.bias"), h, hd)
        per_layer.append({
            "ln1_scale": sd.take(p + "input_layernorm.weight"),
            "ln1_bias": sd.take(p + "input_layernorm.bias"),
            "ln2_scale": sd.take(p + "post_attention_layernorm.weight"),
            "ln2_bias": sd.take(p + "post_attention_layernorm.bias"),
            "wq": wq, "wk": wk, "wv": wv,
            "bq": bq, "bk": bk, "bv": bv,
            "wo": sd.take(p + "self_attention.dense.weight").T,
            "bo": sd.take(p + "self_attention.dense.bias"),
            "w_in": sd.take(p + "mlp.dense_h_to_4h.weight").T,
            "b_in": sd.take(p + "mlp.dense_h_to_4h.bias"),
            "w_out": sd.take(p + "mlp.dense_4h_to_h.weight").T,
            "b_out": sd.take(p + "mlp.dense_4h_to_h.bias"),
        })
    return {
        "tok_embed": sd.take("word_embeddings.weight"),
        "embed_ln_scale": sd.take("word_embeddings_layernorm.weight"),
        "embed_ln_bias": sd.take("word_embeddings_layernorm.bias"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
    }



# ------------------------------------------------------------- family: qwen2
def _qwen2_config(hf: dict) -> TransformerConfig:
    cfg = _llama_config(hf)
    # Qwen2 = llama trunk + attention-projection biases (q/k/v only; the
    # remaining bias slots import as zeros)
    return dataclasses.replace(cfg, use_bias=True)


def _qwen2_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Llama layout + q/k/v biases (RoPE basis permutation applies to the
    bias vectors exactly as to the projection columns)."""
    params = _llama_convert(sd, cfg)
    hd = cfg.head_dim
    q_perm = _rope_interleave_perm(cfg.n_head, hd)
    kv_perm = _rope_interleave_perm(cfg.kv_heads, hd)
    d, f = cfg.d_model, cfg.ffn_dim
    L = cfg.n_layer
    zeros = {
        "ln1_bias": np.zeros((L, d), np.float32),
        "ln2_bias": np.zeros((L, d), np.float32),
        "bo": np.zeros((L, d), np.float32),
        "b_in": np.zeros((L, f), np.float32),
        "b_out": np.zeros((L, d), np.float32),
    }
    bq = np.stack([sd.take(f"layers.{i}.self_attn.q_proj.bias")[q_perm]
                   for i in range(L)])
    bk = np.stack([sd.take(f"layers.{i}.self_attn.k_proj.bias")[kv_perm]
                   for i in range(L)])
    bv = np.stack([sd.take(f"layers.{i}.self_attn.v_proj.bias")
                   for i in range(L)])
    params["layers"].update({"bq": bq, "bk": bk, "bv": bv, **zeros})
    params["lnf_bias"] = np.zeros((d,), np.float32)
    return params


# --------------------------------------------------------------- family: phi
def _phi_config(hf: dict) -> TransformerConfig:
    if hf.get("qk_layernorm"):
        raise ValueError(
            "phi with qk_layernorm=True: the trunk has no per-head Q/K "
            "normalization — importing would silently change attention. "
            "Unsupported.")
    hd = hf["hidden_size"] // hf["num_attention_heads"]
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_head=hf.get("num_key_value_heads") or hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf["intermediate_size"],
        max_seq=hf.get("max_position_embeddings", 2048),
        pos_embedding="rope",
        rotary_dim=int(hd * hf.get("partial_rotary_factor", 0.5)),
        rope_theta=hf.get("rope_theta", 10000.0),
        norm="layernorm", activation="gelu",   # gelu_new = tanh approx
        use_bias=True, tie_embeddings=False, lm_head_bias=True,
        parallel_residual=True, parallel_shared_ln=True,
        norm_eps=hf.get("layer_norm_eps", 1e-5),
    )


def _phi_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Phi: parallel residual with ONE layernorm, separate biased q/k/v,
    partial rotate-half rotary → permuted rotary columns + bias entries."""
    hd = cfg.head_dim
    q_perm = _rope_interleave_perm(cfg.n_head, hd, cfg.rotary_dim)
    kv_perm = _rope_interleave_perm(cfg.kv_heads, hd, cfg.rotary_dim)
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"layers.{i}."
        per_layer.append({
            "ln1_scale": sd.take(h + "input_layernorm.weight"),
            "ln1_bias": sd.take(h + "input_layernorm.bias"),
            "wq": sd.take(h + "self_attn.q_proj.weight").T[:, q_perm],
            "bq": sd.take(h + "self_attn.q_proj.bias")[q_perm],
            "wk": sd.take(h + "self_attn.k_proj.weight").T[:, kv_perm],
            "bk": sd.take(h + "self_attn.k_proj.bias")[kv_perm],
            "wv": sd.take(h + "self_attn.v_proj.weight").T,
            "bv": sd.take(h + "self_attn.v_proj.bias"),
            "wo": sd.take(h + "self_attn.dense.weight").T,
            "bo": sd.take(h + "self_attn.dense.bias"),
            "w_in": sd.take(h + "mlp.fc1.weight").T,
            "b_in": sd.take(h + "mlp.fc1.bias"),
            "w_out": sd.take(h + "mlp.fc2.weight").T,
            "b_out": sd.take(h + "mlp.fc2.bias"),
        })
    return {
        "tok_embed": sd.take("embed_tokens.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("final_layernorm.weight"),
        "lnf_bias": sd.take("final_layernorm.bias"),
        "lm_head": sd.take("lm_head.weight").T,
        "lm_head_bias": sd.take("lm_head.bias"),
    }



# ----------------------------------------------------------- family: codegen
def _codegen_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """CodeGen = GPT-J block with a TPU-blocked fused qkv: the projection is
    stored as mp_num=4 blocks, each [q | v | k] over n_head/4 heads
    (HF ``CodeGenAttention._split_heads``). Rotary is natively interleaved
    (no basis permutation), like GPT-J."""
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    mp = 4
    local = h * hd // mp
    zeros_h = np.zeros((h * hd,), np.float32)
    per_layer = []
    for i in range(cfg.n_layer):
        p = f"h.{i}."
        w = sd.take(p + "attn.qkv_proj.weight").reshape(mp, 3 * local, d)
        wq = w[:, :local].reshape(h * hd, d).T
        wv = w[:, local:2 * local].reshape(h * hd, d).T
        wk = w[:, 2 * local:].reshape(h * hd, d).T
        per_layer.append({
            "ln1_scale": sd.take(p + "ln_1.weight"),
            "ln1_bias": sd.take(p + "ln_1.bias"),
            "wq": wq, "wk": wk, "wv": wv,
            "bq": zeros_h, "bk": zeros_h, "bv": zeros_h,
            "wo": sd.take(p + "attn.out_proj.weight").T,
            "bo": np.zeros((d,), np.float32),
            "w_in": sd.take(p + "mlp.fc_in.weight").T,
            "b_in": sd.take(p + "mlp.fc_in.bias"),
            "w_out": sd.take(p + "mlp.fc_out.weight").T,
            "b_out": sd.take(p + "mlp.fc_out.bias"),
        })
    return {
        "tok_embed": sd.take("wte.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
        "lm_head": sd.take("lm_head.weight").T,
        "lm_head_bias": sd.take("lm_head.bias"),
    }


# ------------------------------------------------------ family: gpt_bigcode
def _bigcode_config(hf: dict) -> TransformerConfig:
    if not hf.get("multi_query", True):
        raise ValueError("gpt_bigcode with multi_query=False is untested; "
                         "refusing a silent mis-split of the fused qkv")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["n_layer"],
        n_head=hf["n_head"],
        n_kv_head=1,
        d_model=hf["n_embd"],
        d_ff=hf.get("n_inner") or 4 * hf["n_embd"],
        max_seq=hf.get("n_positions", 8192),
        pos_embedding="learned", norm="layernorm", activation="gelu",
        use_bias=True,
        tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )


def _bigcode_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """GPT-BigCode (StarCoder): GPT-2 block shape but torch Linear (out, in)
    layout and MQA — fused c_attn rows are [d q | hd k | hd v]."""
    d, hd = cfg.d_model, cfg.head_dim
    per_layer = []
    for i in range(cfg.n_layer):
        p = f"h.{i}."
        w = sd.take(p + "attn.c_attn.weight")           # (d + 2hd, d)
        b = sd.take(p + "attn.c_attn.bias")
        per_layer.append({
            "ln1_scale": sd.take(p + "ln_1.weight"),
            "ln1_bias": sd.take(p + "ln_1.bias"),
            "wq": w[:d].T, "wk": w[d:d + hd].T, "wv": w[d + hd:].T,
            "bq": b[:d], "bk": b[d:d + hd], "bv": b[d + hd:],
            "wo": sd.take(p + "attn.c_proj.weight").T,
            "bo": sd.take(p + "attn.c_proj.bias"),
            "ln2_scale": sd.take(p + "ln_2.weight"),
            "ln2_bias": sd.take(p + "ln_2.bias"),
            "w_in": sd.take(p + "mlp.c_fc.weight").T,
            "b_in": sd.take(p + "mlp.c_fc.bias"),
            "w_out": sd.take(p + "mlp.c_proj.weight").T,
            "b_out": sd.take(p + "mlp.c_proj.bias"),
        })
    params = {
        "tok_embed": sd.take("wte.weight"),
        "pos_embed": sd.take("wpe.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd.take("lm_head.weight").T
    return params



# -------------------------------------------------------------- family: bert
_HF_ACT = {"gelu": "gelu_exact", "gelu_new": "gelu",
           "gelu_pytorch_tanh": "gelu", "relu": "relu", "silu": "silu",
           "swish": "silu"}


def _bert_config(hf: dict) -> TransformerConfig:
    act = hf.get("hidden_act", "gelu")
    if act not in _HF_ACT:
        raise ValueError(f"bert hidden_act {act!r} has no native mapping")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf["intermediate_size"],
        max_seq=hf.get("max_position_embeddings", 512),
        pos_embedding="learned", norm="layernorm",
        activation=_HF_ACT[act],
        use_bias=True, tie_embeddings=True, lm_head_bias=True,
        causal=False, objective="mlm",
        post_ln=True, embed_norm=True, mlm_transform=True,
        norm_eps=hf.get("layer_norm_eps", 1e-12),
    )


def _bert_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """BERT encoder (post-LN, embedding LayerNorm, MLM transform head).

    ``token_type_embeddings``: only segment A (type 0) is representable —
    its row folds into every position embedding (x = tok + pos + type[0]);
    the converter refuses checkpoints only through the unused-keys log,
    since all public MLM usage with a single segment passes type 0.
    """
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"encoder.layer.{i}."
        per_layer.append({
            "wq": sd.take(h + "attention.self.query.weight").T,
            "bq": sd.take(h + "attention.self.query.bias"),
            "wk": sd.take(h + "attention.self.key.weight").T,
            "bk": sd.take(h + "attention.self.key.bias"),
            "wv": sd.take(h + "attention.self.value.weight").T,
            "bv": sd.take(h + "attention.self.value.bias"),
            "wo": sd.take(h + "attention.output.dense.weight").T,
            "bo": sd.take(h + "attention.output.dense.bias"),
            "ln1_scale": sd.take(h + "attention.output.LayerNorm.weight"),
            "ln1_bias": sd.take(h + "attention.output.LayerNorm.bias"),
            "w_in": sd.take(h + "intermediate.dense.weight").T,
            "b_in": sd.take(h + "intermediate.dense.bias"),
            "w_out": sd.take(h + "output.dense.weight").T,
            "b_out": sd.take(h + "output.dense.bias"),
            "ln2_scale": sd.take(h + "output.LayerNorm.weight"),
            "ln2_bias": sd.take(h + "output.LayerNorm.bias"),
        })
    pos = sd.take("embeddings.position_embeddings.weight")
    type0 = sd.take("embeddings.token_type_embeddings.weight")[0]
    return {
        "tok_embed": sd.take("embeddings.word_embeddings.weight"),
        "pos_embed": pos + type0[None, :],    # segment-A fold
        "embed_ln_scale": sd.take("embeddings.LayerNorm.weight"),
        "embed_ln_bias": sd.take("embeddings.LayerNorm.bias"),
        "layers": _stack(per_layer),
        "mlm_dense_w": sd.take("cls.predictions.transform.dense.weight").T,
        "mlm_dense_b": sd.take("cls.predictions.transform.dense.bias"),
        "mlm_ln_scale": sd.take("cls.predictions.transform.LayerNorm.weight"),
        "mlm_ln_bias": sd.take("cls.predictions.transform.LayerNorm.bias"),
        "lm_head_bias": sd.take("cls.predictions.bias"),
    }


# -------------------------------------------------------- family: distilbert
def _distilbert_config(hf: dict) -> TransformerConfig:
    act = hf.get("activation", "gelu")
    if act not in _HF_ACT:
        raise ValueError(f"distilbert activation {act!r} has no native mapping")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["n_layers"],
        n_head=hf["n_heads"],
        d_model=hf["dim"],
        d_ff=hf["hidden_dim"],
        max_seq=hf.get("max_position_embeddings", 512),
        pos_embedding="learned", norm="layernorm",
        activation=_HF_ACT[act],
        use_bias=True, tie_embeddings=True, lm_head_bias=True,
        causal=False, objective="mlm",
        post_ln=True, embed_norm=True, mlm_transform=True,
        norm_eps=1e-12,
    )


def _distilbert_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """DistilBERT: BERT block without token types, flat layer names."""
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"transformer.layer.{i}."
        per_layer.append({
            "wq": sd.take(h + "attention.q_lin.weight").T,
            "bq": sd.take(h + "attention.q_lin.bias"),
            "wk": sd.take(h + "attention.k_lin.weight").T,
            "bk": sd.take(h + "attention.k_lin.bias"),
            "wv": sd.take(h + "attention.v_lin.weight").T,
            "bv": sd.take(h + "attention.v_lin.bias"),
            "wo": sd.take(h + "attention.out_lin.weight").T,
            "bo": sd.take(h + "attention.out_lin.bias"),
            "ln1_scale": sd.take(h + "sa_layer_norm.weight"),
            "ln1_bias": sd.take(h + "sa_layer_norm.bias"),
            "w_in": sd.take(h + "ffn.lin1.weight").T,
            "b_in": sd.take(h + "ffn.lin1.bias"),
            "w_out": sd.take(h + "ffn.lin2.weight").T,
            "b_out": sd.take(h + "ffn.lin2.bias"),
            "ln2_scale": sd.take(h + "output_layer_norm.weight"),
            "ln2_bias": sd.take(h + "output_layer_norm.bias"),
        })
    return {
        "tok_embed": sd.take("embeddings.word_embeddings.weight"),
        "pos_embed": sd.take("embeddings.position_embeddings.weight"),
        "embed_ln_scale": sd.take("embeddings.LayerNorm.weight"),
        "embed_ln_bias": sd.take("embeddings.LayerNorm.bias"),
        "layers": _stack(per_layer),
        "mlm_dense_w": sd.take("vocab_transform.weight").T,
        "mlm_dense_b": sd.take("vocab_transform.bias"),
        "mlm_ln_scale": sd.take("vocab_layer_norm.weight"),
        "mlm_ln_bias": sd.take("vocab_layer_norm.bias"),
        "lm_head_bias": sd.take("vocab_projector.bias"),
    }



# ------------------------------------------------------ family: megatron_gpt
def _megatron_config(hf: dict) -> TransformerConfig:
    """Megatron-LM GPT checkpoint (reference
    ``module_inject/containers/megatron_gpt.py``).  Megatron has no HF
    config.json; callers pass the training args as a dict with
    ``model_type='megatron_gpt'``.  Default activation is the tanh-approx
    gelu (Megatron's fused bias-gelu); pass ``activation='gelu_exact'``
    for checkpoints trained with the unfused erf gelu."""
    return TransformerConfig(
        vocab_size=hf["vocab_size"] if "vocab_size" in hf
        else hf["padded_vocab_size"],
        n_layer=hf["num_layers"],
        n_head=hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf.get("ffn_hidden_size") or 4 * hf["hidden_size"],
        max_seq=hf.get("max_position_embeddings", 1024),
        pos_embedding="learned", norm="layernorm",
        activation=hf.get("activation", "gelu"),
        use_bias=True, tie_embeddings=True,
        norm_eps=hf.get("layernorm_epsilon", 1e-5),
    )


def _megatron_attn_layer(sd: _SDict, p: str, cfg: TransformerConfig) -> dict:
    """Shared attention/LN half of a Megatron layer (dense and MoE)."""
    h, hd, d = cfg.n_head, cfg.head_dim, cfg.d_model
    wq, wk, wv = _split_fused_qkv_per_head(
        sd.take(p + "self_attention.query_key_value.weight"), h, hd, d)
    bq, bk, bv = _split_fused_qkv_bias_per_head(
        sd.take(p + "self_attention.query_key_value.bias"), h, hd)
    return {
        "ln1_scale": sd.take(p + "input_layernorm.weight"),
        "ln1_bias": sd.take(p + "input_layernorm.bias"),
        "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
        "wo": sd.take(p + "self_attention.dense.weight").T,
        "bo": sd.take(p + "self_attention.dense.bias"),
        "ln2_scale": sd.take(p + "post_attention_layernorm.weight"),
        "ln2_bias": sd.take(p + "post_attention_layernorm.bias"),
    }


def _megatron_embed_head(sd: _SDict, per_layer: list) -> dict:
    return {
        "tok_embed": sd.take("embedding.word_embeddings.weight"),
        "pos_embed": sd.take("embedding.position_embeddings.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("encoder.final_layernorm.weight"),
        "lnf_bias": sd.take("encoder.final_layernorm.bias"),
    }


def _megatron_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Megatron-LM GPT: sequential block, learned positions, fused
    per-head-interleaved qkv (the layout NeoX inherited), biased
    projections, word-embedding-tied head."""
    per_layer = []
    for i in range(cfg.n_layer):
        p = f"encoder.layers.{i}."
        lyr = _megatron_attn_layer(sd, p, cfg)
        lyr.update({
            "w_in": sd.take(p + "mlp.dense_h_to_4h.weight").T,
            "b_in": sd.take(p + "mlp.dense_h_to_4h.bias"),
            "w_out": sd.take(p + "mlp.dense_4h_to_h.weight").T,
            "b_out": sd.take(p + "mlp.dense_4h_to_h.bias"),
        })
        per_layer.append(lyr)
    return _megatron_embed_head(sd, per_layer)


# ------------------------------------------------- family: megatron_gpt_moe
def _megatron_moe_config(hf: dict) -> TransformerConfig:
    """Megatron-DeepSpeed MoE GPT (reference
    ``module_inject/containers/megatron_gpt_moe.py``): the dense Megatron
    block with the MLP replaced by ``deepspeed_moe`` (TopKGate + expert
    bank, ``moe/sharded_moe.py``). ``num_experts`` may arrive as the
    Megatron arg list form; top-k defaults to the reference TopKGate's
    k=1 (Switch-style) unless the args say otherwise."""
    cfg = _megatron_config(hf)
    E = hf["num_experts"]
    if isinstance(E, (list, tuple)):
        if len(set(E)) != 1:
            raise ValueError(
                f"per-layer expert counts {E} are not supported: the trunk "
                "routes a uniform expert bank (expert-interval checkpoints "
                "with dense layers mixed in cannot be imported)")
        E = E[0]
    if int(E) < 2:
        raise ValueError(
            "num_experts=1 deepspeed_moe checkpoint: the routed trunk needs "
            ">=2 experts (a 1-expert bank would import into shapes the dense "
            "model cannot consume) — import it as model_type='megatron_gpt' "
            "after renaming the expert MLP keys to the dense layout")
    return dataclasses.replace(
        cfg, num_experts=int(E),
        moe_top_k=int(hf.get("moe_top_k", hf.get("topk", 1))))


def _megatron_moe_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Megatron-DS MoE: router = ``gate.wg.weight`` (E, d) → (d, E);
    experts ``deepspeed_experts.{e}.dense_*`` stacked into (E, d, f) /
    (E, f, d) banks with per-expert biases."""
    E = cfg.num_experts
    per_layer = []
    for i in range(cfg.n_layer):
        p = f"encoder.layers.{i}."
        moe = p + "mlp.deepspeed_moe."
        if moe + "gate.wg.weight" not in sd:
            raise ValueError(
                f"layer {i} has no deepspeed_moe gate: mixed dense/MoE "
                "(expert-interval > 1) checkpoints are not importable — the "
                "trunk routes every layer")
        lyr = _megatron_attn_layer(sd, p, cfg)
        ex = moe + "experts.deepspeed_experts."
        lyr.update({
            "router": sd.take(moe + "gate.wg.weight").T,          # (d, E)
            "w_in": np.stack([sd.take(f"{ex}{e}.dense_h_to_4h.weight").T
                              for e in range(E)]),                # (E, d, f)
            "b_in": np.stack([sd.take(f"{ex}{e}.dense_h_to_4h.bias")
                              for e in range(E)]),                # (E, f)
            "w_out": np.stack([sd.take(f"{ex}{e}.dense_4h_to_h.weight").T
                               for e in range(E)]),               # (E, f, d)
            "b_out": np.stack([sd.take(f"{ex}{e}.dense_4h_to_h.bias")
                               for e in range(E)]),               # (E, d)
        })
        per_layer.append(lyr)
    return _megatron_embed_head(sd, per_layer)


# -------------------------------------------------------------- family: clip
def _clip_config(hf: dict) -> TransformerConfig:
    """CLIP text tower (reference ``module_inject/containers/clip.py`` —
    the Stable-Diffusion text conditioner).  Accepts a full CLIPConfig
    (nested ``text_config``) or a standalone CLIPTextConfig.  The tower is
    a pre-LN *causal* encoder whose product is final-norm hidden states,
    so it imports as ``objective='feature'`` (no unembedding)."""
    txt = hf.get("text_config") or hf
    return TransformerConfig(
        vocab_size=txt["vocab_size"],
        n_layer=txt["num_hidden_layers"],
        n_head=txt["num_attention_heads"],
        d_model=txt["hidden_size"],
        d_ff=txt["intermediate_size"],
        max_seq=txt.get("max_position_embeddings", 77),
        pos_embedding="learned", norm="layernorm",
        activation=txt.get("hidden_act", "quick_gelu"),
        use_bias=True, tie_embeddings=False, causal=True,
        objective="feature",
        norm_eps=txt.get("layer_norm_eps", 1e-5),
    )


def _clip_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """CLIP text encoder: torch Linear (out, in) → transpose; all
    projections biased; learned positions; final layernorm, no head."""
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"encoder.layers.{i}."
        per_layer.append({
            "ln1_scale": sd.take(h + "layer_norm1.weight"),
            "ln1_bias": sd.take(h + "layer_norm1.bias"),
            "wq": sd.take(h + "self_attn.q_proj.weight").T,
            "bq": sd.take(h + "self_attn.q_proj.bias"),
            "wk": sd.take(h + "self_attn.k_proj.weight").T,
            "bk": sd.take(h + "self_attn.k_proj.bias"),
            "wv": sd.take(h + "self_attn.v_proj.weight").T,
            "bv": sd.take(h + "self_attn.v_proj.bias"),
            "wo": sd.take(h + "self_attn.out_proj.weight").T,
            "bo": sd.take(h + "self_attn.out_proj.bias"),
            "ln2_scale": sd.take(h + "layer_norm2.weight"),
            "ln2_bias": sd.take(h + "layer_norm2.bias"),
            "w_in": sd.take(h + "mlp.fc1.weight").T,
            "b_in": sd.take(h + "mlp.fc1.bias"),
            "w_out": sd.take(h + "mlp.fc2.weight").T,
            "b_out": sd.take(h + "mlp.fc2.bias"),
        })
    return {
        "tok_embed": sd.take("embeddings.token_embedding.weight"),
        "pos_embed": sd.take("embeddings.position_embedding.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("final_layer_norm.weight"),
        "lnf_bias": sd.take("final_layer_norm.bias"),
    }


# ---------------------------------------------------------------- family: t5
def _t5_config(hf: dict):
    from .t5 import T5Config

    proj = hf.get("feed_forward_proj", "relu")
    if proj not in ("relu", "gated-gelu"):
        raise ValueError(f"t5 feed_forward_proj {proj!r} unsupported")
    return T5Config(
        vocab_size=hf["vocab_size"],
        d_model=hf["d_model"],
        d_kv=hf["d_kv"],
        d_ff=hf["d_ff"],
        n_layer=hf["num_layers"],
        n_dec_layer=hf.get("num_decoder_layers") or hf["num_layers"],
        n_head=hf["num_heads"],
        rel_buckets=hf.get("relative_attention_num_buckets", 32),
        rel_max_distance=hf.get("relative_attention_max_distance", 128),
        gated_ffn=proj == "gated-gelu",
        tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
        pad_token_id=hf.get("pad_token_id", 0),
        norm_eps=hf.get("layer_norm_epsilon", 1e-6),
    )


def _t5_convert(sd: _SDict, cfg) -> dict:
    """T5 encoder-decoder: relative-bias tables live on block 0 only;
    DenseReluDense wi/wo (or wi_0/wi_1 gated); all torch Linear (out, in)."""
    def stack(prefix, n, cross):
        per = []
        for i in range(n):
            b = f"{prefix}.block.{i}."
            ff = 2 if cross else 1
            lyr = {
                "ln1": sd.take(b + "layer.0.layer_norm.weight"),
                "wq": sd.take(b + "layer.0.SelfAttention.q.weight").T,
                "wk": sd.take(b + "layer.0.SelfAttention.k.weight").T,
                "wv": sd.take(b + "layer.0.SelfAttention.v.weight").T,
                "wo": sd.take(b + "layer.0.SelfAttention.o.weight").T,
                "ln_ffn": sd.take(b + f"layer.{ff}.layer_norm.weight"),
                "w_out": sd.take(b + f"layer.{ff}.DenseReluDense.wo.weight").T,
            }
            if cfg.gated_ffn:
                lyr["w_gate"] = sd.take(
                    b + f"layer.{ff}.DenseReluDense.wi_0.weight").T
                lyr["w_in"] = sd.take(
                    b + f"layer.{ff}.DenseReluDense.wi_1.weight").T
            else:
                lyr["w_in"] = sd.take(
                    b + f"layer.{ff}.DenseReluDense.wi.weight").T
            if cross:
                lyr.update({
                    "ln_cross": sd.take(b + "layer.1.layer_norm.weight"),
                    "cq": sd.take(b + "layer.1.EncDecAttention.q.weight").T,
                    "ck": sd.take(b + "layer.1.EncDecAttention.k.weight").T,
                    "cv": sd.take(b + "layer.1.EncDecAttention.v.weight").T,
                    "co": sd.take(b + "layer.1.EncDecAttention.o.weight").T,
                })
            per.append(lyr)
        return _stack(per)

    params = {
        "shared": sd.take("shared.weight"),
        "enc": {
            "layers": stack("encoder", cfg.n_layer, cross=False),
            "rel_bias": sd.take("encoder.block.0.layer.0.SelfAttention."
                                "relative_attention_bias.weight"),
            "final_ln": sd.take("encoder.final_layer_norm.weight"),
        },
        "dec": {
            "layers": stack("decoder", cfg.n_dec_layer, cross=True),
            "rel_bias": sd.take("decoder.block.0.layer.0.SelfAttention."
                                "relative_attention_bias.weight"),
            "final_ln": sd.take("decoder.final_layer_norm.weight"),
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd.take("lm_head.weight").T
    return params


_FAMILIES: dict[str, tuple[Callable, Callable, tuple[str, ...]]] = {
    # model_type → (config_fn, convert_fn, state-dict prefixes to strip)
    "gpt2": (_gpt2_config, _gpt2_convert, ("transformer.",)),
    "llama": (_llama_config, _llama_convert, ("model.",)),
    "internlm": (_internlm_config, _internlm_convert, ("model.",)),
    "mistral": (_llama_config, _llama_convert, ("model.",)),
    "mixtral": (_llama_config, _llama_convert, ("model.",)),
    "opt": (_opt_config, _opt_convert, ("model.decoder.", "decoder.")),
    "gptj": (_gptj_config, _gptj_convert, ("transformer.",)),
    "gpt_neo": (_gptneo_config, _gptneo_convert, ("transformer.",)),
    "gpt_neox": (_neox_config, _neox_convert, ("gpt_neox.",)),
    "falcon": (_falcon_config, _falcon_convert, ("transformer.",)),
    "bloom": (_bloom_config, _bloom_convert, ("transformer.",)),
    "qwen2": (_qwen2_config, _qwen2_convert, ("model.",)),
    "phi": (_phi_config, _phi_convert, ("model.",)),
    # CodeGen is a GPT-J block family: same config mapping, own qkv split
    "codegen": (_gptj_config, _codegen_convert, ("transformer.",)),
    "gpt_bigcode": (_bigcode_config, _bigcode_convert, ("transformer.",)),
    "bert": (_bert_config, _bert_convert, ("bert.",)),
    "distilbert": (_distilbert_config, _distilbert_convert,
                   ("distilbert.",)),
    "t5": (_t5_config, _t5_convert, ()),
    "clip": (_clip_config, _clip_convert, ("text_model.",)),
    "clip_text_model": (_clip_config, _clip_convert, ("text_model.",)),
    "megatron_gpt": (_megatron_config, _megatron_convert,
                     ("model.language_model.", "language_model.")),
    "megatron_gpt_moe": (_megatron_moe_config, _megatron_moe_convert,
                         ("model.language_model.", "language_model.")),
}


def _detect_family(state_dict: Dict[str, Any]) -> str:
    keys = state_dict.keys()
    for k in keys:
        if "attn.c_attn.weight" in k:
            # gpt2 and gpt_bigcode share every key NAME; only the fused-qkv
            # shape tells them apart (Conv1D (d, 3d) vs Linear (d+2hd, d))
            shape = tuple(state_dict[k].shape)
            return "gpt2" if shape[1] == 3 * shape[0] else "gpt_bigcode"
    if any("block_sparse_moe" in k for k in keys):
        return "mixtral"
    if any("decoder.layers" in k and "fc1" in k for k in keys):
        return "opt"
    if any("attn.qkv_proj" in k for k in keys):
        return "codegen"
    if any("attn.attention.q_proj" in k for k in keys):
        return "gpt_neo"
    if any("mlp.fc_in" in k for k in keys):
        return "gptj"

    if any("self_attn.dense" in k for k in keys) and \
            any("mlp.fc1" in k for k in keys):
        return "phi"
    if any("self_attn.q_proj.bias" in k for k in keys) and \
            any("mlp.gate_proj" in k for k in keys):
        # qwen2 biases q/k/v only; internlm v1 also biases o_proj
        return ("internlm"
                if any("self_attn.o_proj.bias" in k for k in keys)
                else "qwen2")
    if any("language_model" in k for k in keys) and \
            any("self_attention.query_key_value" in k for k in keys):
        # both anchors: multimodal HF checkpoints (LLaVA-style) also prefix
        # llama-layout keys with "language_model."
        return ("megatron_gpt_moe"
                if any("deepspeed_moe" in k for k in keys)
                else "megatron_gpt")
    if any("gpt_neox" in k or "embed_in" in k for k in keys):
        return "gpt_neox"
    if any("word_embeddings_layernorm" in k for k in keys):
        return "bloom"
    if any("self_attention.query_key_value" in k for k in keys):
        return "falcon"
    if any("EncDecAttention" in k for k in keys):
        return "t5"
    if any("attention.self.query" in k for k in keys):
        return "bert"
    if any("attention.q_lin" in k for k in keys):
        return "distilbert"
    if any("token_embedding" in k for k in keys) and \
            any("layer_norm1" in k for k in keys):
        return "clip_text_model"
    if any("self_attn.q_proj" in k for k in keys):
        return "llama"
    raise ValueError("cannot detect model family from checkpoint keys; "
                     f"sample: {sorted(keys)[:8]}")


# ------------------------------------------------------------- public entry
def config_from_hf(hf_config: dict) -> TransformerConfig:
    """HF ``config.json`` dict → native :class:`TransformerConfig`."""
    family = hf_config.get("model_type")
    if family not in _FAMILIES:
        raise ValueError(f"unsupported model_type {family!r}; "
                         f"supported: {sorted(_FAMILIES)}")
    return _FAMILIES[family][0](hf_config)


def import_state_dict(state_dict: Dict[str, Any],
                      config: TransformerConfig | None = None,
                      family: str | None = None,
                      hf_config: dict | None = None) -> Tuple[TransformerConfig, dict]:
    """Convert an HF-format state dict (torch/numpy tensors) into the native
    param pytree. Returns ``(config, params)`` with fp32 numpy leaves
    (the engine/inference cast to compute dtype and shard on device_put)."""
    family = family or (hf_config or {}).get("model_type") or _detect_family(state_dict)
    if family not in _FAMILIES:
        raise ValueError(f"unsupported model family {family!r}")
    if family == "mixtral":
        # Static-capacity routing can drop over-capacity tokens that HF's
        # dropless top-k would route; raise the factor for serving fidelity
        # (still overridable via a caller-supplied config).
        log_dist("importer: mixtral uses static-capacity expert routing — "
                 "over-capacity tokens are dropped; raise "
                 "moe_capacity_factor if imported outputs must match HF")
    config_fn, convert_fn, strip = _FAMILIES[family]
    if config is None:
        if hf_config is None:
            raise ValueError("need either a TransformerConfig or the HF "
                             "config.json dict to size the model")
        config = config_fn(hf_config)
    sd = _SDict(state_dict, strip=strip)
    params = convert_fn(sd, config)
    if (getattr(config, "pos_embedding", None) == "learned"
            and config.max_seq > params["pos_embed"].shape[0]):
        raise ValueError(
            f"max_seq={config.max_seq} exceeds the checkpoint's learned "
            f"position table ({params['pos_embed'].shape[0]} rows); "
            "positions past the table would silently clamp")
    leftovers = [k for k in sd.unused()
                 if not k.endswith((
                     "rotary_emb.inv_freq", "attn.bias", "attn.masked_bias",
                     # GPT-Neo nests the causal-mask buffers one level deeper
                     "attention.bias", "attention.masked_bias",
                     "lm_head.weight",
                     # tied-decoder duplicates + buffers (BERT/DistilBERT)
                     "cls.predictions.decoder.weight",
                     "cls.predictions.decoder.bias",
                     "vocab_projector.weight", "vocab_projector.bias",
                     "embeddings.position_ids",
                     # T5 per-stack duplicates of shared.weight
                     "encoder.embed_tokens.weight",
                     "decoder.embed_tokens.weight"))]
    if leftovers:
        log_dist(f"importer: {len(leftovers)} unused checkpoint keys "
                 f"(first 5: {leftovers[:5]})")
    return config, params


def _load_files(path: str) -> Dict[str, Any]:
    """Load all weight shards under an HF checkpoint directory."""
    def _safetensors(fp):
        import jax

        try:  # bf16-capable path — pinned to host so shards never touch HBM
            from safetensors.flax import load_file as lf
            with jax.default_device(jax.devices("cpu")[0]):
                return dict(lf(fp))
        except Exception:
            from safetensors.torch import load_file as lf
            return dict(lf(fp))

    candidates = [
        ("model.safetensors.index.json", _safetensors, "model.safetensors"),
        ("pytorch_model.bin.index.json", None, "pytorch_model.bin"),
    ]
    for index_name, loader, single_name in candidates:
        index_fp = os.path.join(path, index_name)
        single_fp = os.path.join(path, single_name)
        if loader is None:
            import torch

            def loader(fp):
                return torch.load(fp, map_location="cpu", weights_only=True)
        if os.path.exists(index_fp):
            with open(index_fp) as f:
                index = json.load(f)
            sd: Dict[str, Any] = {}
            for shard in sorted(set(index["weight_map"].values())):
                sd.update(loader(os.path.join(path, shard)))
            return sd
        if os.path.exists(single_fp):
            return loader(single_fp)
    raise FileNotFoundError(f"no model.safetensors / pytorch_model.bin under {path}")


def load_hf_checkpoint(path: str,
                       config: TransformerConfig | None = None,
                       **overrides) -> Tuple[TransformerConfig, dict]:
    """Load an HF checkpoint directory (config.json + safetensors/bin shards)
    onto the native trunk.

    >>> cfg, params = load_hf_checkpoint("/ckpts/llama-2-7b")
    >>> engine = ds.initialize(ds_config, build_model(cfg), params=params)

    ``overrides`` are applied to the derived TransformerConfig (e.g.
    ``max_seq=8192`` to serve longer than the checkpoint's default)."""
    hf_config = None
    cfg_fp = os.path.join(path, "config.json")
    if os.path.exists(cfg_fp):
        with open(cfg_fp) as f:
            hf_config = json.load(f)
    sd = _load_files(path)
    cfg, params = import_state_dict(sd, config=config, hf_config=hf_config)
    if overrides:
        # type(cfg): works for TransformerConfig AND T5Config alike
        cfg = type(cfg)(**{**cfg.__dict__, **overrides})
        if (getattr(cfg, "pos_embedding", None) == "learned"
                and cfg.max_seq > params["pos_embed"].shape[0]):
            # same guard as import_state_dict, re-checked post-override
            raise ValueError(
                f"max_seq={cfg.max_seq} exceeds the checkpoint's learned "
                f"position table ({params['pos_embed'].shape[0]} rows); "
                "positions past the table would silently clamp")
    n = sum(int(np.prod(p.shape)) for p in
            __import__("jax").tree.leaves(params))
    log_dist(f"importer: loaded {n / 1e6:.1f}M params from {path} "
             f"({hf_config.get('model_type') if hf_config else 'detected'})")
    return cfg, params
