"""HF-checkpoint importer: load pretrained weights onto the native trunk.

This is the TPU-native answer to the reference's kernel-injection / AutoTP
machinery (``module_inject/replace_module.py:182``, ``auto_tp.py:175``,
``module_inject/load_checkpoint.py``): instead of walking a live torch module
graph and swapping layers for fused replacements, we map a *checkpoint* —
HF-format ``safetensors`` / ``pytorch_model.bin`` plus ``config.json`` — onto
the native :class:`TransformerLM` parameter pytree.  The trunk's
``param_specs()`` then plays the role of the ~20 per-architecture injection
policies: sharding is a property of the destination, not a rewrite of the
source, so TP/ZeRO/offload all apply to imported models for free.

Per-architecture mapping lives in small ``_Family`` converters (the analog of
``module_inject/containers/*``): name mapping, per-layer stacking into the
scan-friendly ``(L, ...)`` layout, qkv handling (GPT-2's fused ``c_attn`` is
split; Llama's separate projections are transposed from torch's ``(out, in)``
to matmul ``(in, out)``), and the RoPE basis permutation (HF "rotate-half"
→ interleaved pairs) absorbed into the q/k projection weights.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .transformer import TransformerConfig

__all__ = ["load_hf_checkpoint", "import_state_dict", "config_from_hf"]


# ----------------------------------------------------------- tensor plumbing
def _to_numpy(t) -> np.ndarray:
    """torch / jax / numpy tensor → numpy, preserving the storage dtype.

    bf16 checkpoints stay bf16 (``ml_dtypes.bfloat16`` numpy arrays — the
    stack/transpose/permute ops all work on them), so a 70B import costs
    ~1× the checkpoint size in host RAM, not 3×; fp32 master creation
    upcasts leaf-by-leaf downstream in the engine."""
    if isinstance(t, np.ndarray):
        return t
    if isinstance(t, jnp.ndarray):
        return np.asarray(t)          # bf16 → ml_dtypes.bfloat16 view
    import torch

    if isinstance(t, torch.Tensor):
        t = t.detach().cpu()
        if t.dtype == torch.bfloat16:
            import ml_dtypes

            return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
        return t.numpy()
    raise TypeError(f"unsupported tensor type {type(t)!r}")


def _rope_interleave_perm(n_heads: int, head_dim: int) -> np.ndarray:
    """Column permutation converting HF rotate-half q/k projections to the
    trunk's interleaved-pair RoPE basis.

    HF rotates dim ``j`` with dim ``j + hd/2`` (shared freq_j); the trunk
    rotates dims ``(2j, 2j+1)``.  Mapping output column ``2j ← j`` and
    ``2j+1 ← j + hd/2`` per head makes both compute identical attention
    scores (the permutation is applied to q AND k, so dot products are
    invariant and ``wo`` needs no change)."""
    half = head_dim // 2
    per_head = np.empty((head_dim,), dtype=np.int64)
    per_head[0::2] = np.arange(half)
    per_head[1::2] = np.arange(half) + half
    return (np.arange(n_heads)[:, None] * head_dim + per_head[None, :]).reshape(-1)


class _SDict:
    """State-dict view with prefix stripping + access tracking."""

    def __init__(self, sd: Dict[str, Any], strip: Tuple[str, ...] = ()):
        self._sd = {}
        for k, v in sd.items():
            for p in strip:
                if k.startswith(p):
                    k = k[len(p):]
                    break
            self._sd[k] = v
        self.used: set[str] = set()

    def __contains__(self, k):
        return k in self._sd

    def take(self, k: str) -> np.ndarray:
        self.used.add(k)
        return _to_numpy(self._sd[k])

    def get(self, k: str):
        return self.take(k) if k in self._sd else None

    def unused(self) -> list[str]:
        return sorted(set(self._sd) - self.used)


def _stack(layers: list[dict[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Per-layer dicts → one dict of (L, ...)-stacked arrays."""
    keys = layers[0].keys()
    return {k: np.stack([lyr[k] for lyr in layers]) for k in keys}


# ------------------------------------------------------------- family: gpt2
def _gpt2_config(hf: dict) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["n_layer"],
        n_head=hf["n_head"],
        d_model=hf["n_embd"],
        d_ff=hf.get("n_inner") or 4 * hf["n_embd"],
        max_seq=hf.get("n_positions", 1024),
        pos_embedding="learned", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True,
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )


def _gpt2_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """GPT-2: Conv1D stores weights as (in, out) — no transpose; fused
    ``c_attn`` (d, 3d) splits into wq/wk/wv."""
    d = cfg.d_model
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"h.{i}."
        ca_w = sd.take(h + "attn.c_attn.weight")          # (d, 3d)
        ca_b = sd.take(h + "attn.c_attn.bias")            # (3d,)
        wq, wk, wv = ca_w[:, :d], ca_w[:, d:2 * d], ca_w[:, 2 * d:]
        bq, bk, bv = ca_b[:d], ca_b[d:2 * d], ca_b[2 * d:]
        per_layer.append({
            "ln1_scale": sd.take(h + "ln_1.weight"),
            "ln1_bias": sd.take(h + "ln_1.bias"),
            "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
            "wo": sd.take(h + "attn.c_proj.weight"),
            "bo": sd.take(h + "attn.c_proj.bias"),
            "ln2_scale": sd.take(h + "ln_2.weight"),
            "ln2_bias": sd.take(h + "ln_2.bias"),
            "w_in": sd.take(h + "mlp.c_fc.weight"),
            "b_in": sd.take(h + "mlp.c_fc.bias"),
            "w_out": sd.take(h + "mlp.c_proj.weight"),
            "b_out": sd.take(h + "mlp.c_proj.bias"),
        })
    return {
        "tok_embed": sd.take("wte.weight"),
        "pos_embed": sd.take("wpe.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("ln_f.weight"),
        "lnf_bias": sd.take("ln_f.bias"),
    }


# ------------------------------------------------------ family: llama-like
def _llama_config(hf: dict) -> TransformerConfig:
    if hf.get("rope_scaling"):
        raise ValueError(
            "checkpoint uses rope_scaling (extended-context RoPE remap); the "
            "native trunk applies plain rope_theta positions — importing "
            "would silently change long-range attention. Unsupported.")
    if hf.get("sliding_window"):
        log_dist("importer: checkpoint declares sliding_window="
                 f"{hf['sliding_window']} — the native trunk runs full causal "
                 "attention, so outputs diverge from HF beyond the window")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_head=hf.get("num_key_value_heads") or hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf["intermediate_size"],
        max_seq=hf.get("max_position_embeddings", 4096),
        pos_embedding="rope", norm="rmsnorm", activation="silu_glu",
        use_bias=False, tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        rope_theta=hf.get("rope_theta", 10000.0),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        num_experts=hf.get("num_local_experts", 1),
        moe_top_k=hf.get("num_experts_per_tok", 2),
    )


def _llama_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """Llama/Mistral/Mixtral: torch Linear (out, in) → transpose; absorb the
    RoPE basis change into wq/wk columns; Mixtral expert banks stacked."""
    hd = cfg.head_dim
    q_perm = _rope_interleave_perm(cfg.n_head, hd)
    kv_perm = _rope_interleave_perm(cfg.kv_heads, hd)
    moe = cfg.num_experts > 1
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"layers.{i}."
        lyr = {
            "ln1_scale": sd.take(h + "input_layernorm.weight"),
            "wq": sd.take(h + "self_attn.q_proj.weight").T[:, q_perm],
            "wk": sd.take(h + "self_attn.k_proj.weight").T[:, kv_perm],
            "wv": sd.take(h + "self_attn.v_proj.weight").T,
            "wo": sd.take(h + "self_attn.o_proj.weight").T,
            "ln2_scale": sd.take(h + "post_attention_layernorm.weight"),
        }
        if moe:
            m = h + "block_sparse_moe."
            lyr["router"] = sd.take(m + "gate.weight").T          # (d, E)
            # Mixtral expert order: w1=gate, w2=down, w3=up (all (out, in)).
            lyr["w_gate"] = np.stack([sd.take(f"{m}experts.{e}.w1.weight").T
                                      for e in range(cfg.num_experts)])
            lyr["w_out"] = np.stack([sd.take(f"{m}experts.{e}.w2.weight").T
                                     for e in range(cfg.num_experts)])
            lyr["w_in"] = np.stack([sd.take(f"{m}experts.{e}.w3.weight").T
                                    for e in range(cfg.num_experts)])
        else:
            lyr["w_gate"] = sd.take(h + "mlp.gate_proj.weight").T
            lyr["w_in"] = sd.take(h + "mlp.up_proj.weight").T
            lyr["w_out"] = sd.take(h + "mlp.down_proj.weight").T
        per_layer.append(lyr)
    params = {
        "tok_embed": sd.take("embed_tokens.weight"),
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("norm.weight"),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd.take("lm_head.weight").T
    return params


# -------------------------------------------------------------- family: opt
def _opt_config(hf: dict) -> TransformerConfig:
    if hf.get("word_embed_proj_dim", hf["hidden_size"]) != hf["hidden_size"]:
        raise ValueError("OPT variants with word_embed_proj_dim != "
                         "hidden_size (350m) are not supported")
    if not hf.get("do_layer_norm_before", True):
        raise ValueError("OPT-350m's post-norm layout is not supported")
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        d_model=hf["hidden_size"],
        d_ff=hf["ffn_dim"],
        max_seq=hf.get("max_position_embeddings", 2048),
        pos_embedding="learned", norm="layernorm",
        activation=hf.get("activation_function", "relu"),
        use_bias=True, tie_embeddings=True,
    )


def _opt_convert(sd: _SDict, cfg: TransformerConfig) -> dict:
    """OPT: torch Linear (out, in) → transpose; embed_positions rows are
    offset by 2 (HF quirk: positions 0.. use rows 2..)."""
    per_layer = []
    for i in range(cfg.n_layer):
        h = f"layers.{i}."
        per_layer.append({
            "ln1_scale": sd.take(h + "self_attn_layer_norm.weight"),
            "ln1_bias": sd.take(h + "self_attn_layer_norm.bias"),
            "wq": sd.take(h + "self_attn.q_proj.weight").T,
            "wk": sd.take(h + "self_attn.k_proj.weight").T,
            "wv": sd.take(h + "self_attn.v_proj.weight").T,
            "bq": sd.take(h + "self_attn.q_proj.bias"),
            "bk": sd.take(h + "self_attn.k_proj.bias"),
            "bv": sd.take(h + "self_attn.v_proj.bias"),
            "wo": sd.take(h + "self_attn.out_proj.weight").T,
            "bo": sd.take(h + "self_attn.out_proj.bias"),
            "ln2_scale": sd.take(h + "final_layer_norm.weight"),
            "ln2_bias": sd.take(h + "final_layer_norm.bias"),
            "w_in": sd.take(h + "fc1.weight").T,
            "b_in": sd.take(h + "fc1.bias"),
            "w_out": sd.take(h + "fc2.weight").T,
            "b_out": sd.take(h + "fc2.bias"),
        })
    return {
        "tok_embed": sd.take("embed_tokens.weight"),
        "pos_embed": sd.take("embed_positions.weight")[2:],   # offset-2 rows
        "layers": _stack(per_layer),
        "lnf_scale": sd.take("final_layer_norm.weight"),
        "lnf_bias": sd.take("final_layer_norm.bias"),
    }


_FAMILIES: dict[str, tuple[Callable, Callable, tuple[str, ...]]] = {
    # model_type → (config_fn, convert_fn, state-dict prefixes to strip)
    "gpt2": (_gpt2_config, _gpt2_convert, ("transformer.",)),
    "llama": (_llama_config, _llama_convert, ("model.",)),
    "mistral": (_llama_config, _llama_convert, ("model.",)),
    "mixtral": (_llama_config, _llama_convert, ("model.",)),
    "opt": (_opt_config, _opt_convert, ("model.decoder.", "decoder.")),
}


def _detect_family(state_dict: Dict[str, Any]) -> str:
    keys = state_dict.keys()
    if any("attn.c_attn" in k for k in keys):
        return "gpt2"
    if any("block_sparse_moe" in k for k in keys):
        return "mixtral"
    if any("decoder.layers" in k and "fc1" in k for k in keys):
        return "opt"
    if any("self_attn.q_proj" in k for k in keys):
        return "llama"
    raise ValueError("cannot detect model family from checkpoint keys; "
                     f"sample: {sorted(keys)[:8]}")


# ------------------------------------------------------------- public entry
def config_from_hf(hf_config: dict) -> TransformerConfig:
    """HF ``config.json`` dict → native :class:`TransformerConfig`."""
    family = hf_config.get("model_type")
    if family not in _FAMILIES:
        raise ValueError(f"unsupported model_type {family!r}; "
                         f"supported: {sorted(_FAMILIES)}")
    return _FAMILIES[family][0](hf_config)


def import_state_dict(state_dict: Dict[str, Any],
                      config: TransformerConfig | None = None,
                      family: str | None = None,
                      hf_config: dict | None = None) -> Tuple[TransformerConfig, dict]:
    """Convert an HF-format state dict (torch/numpy tensors) into the native
    param pytree. Returns ``(config, params)`` with fp32 numpy leaves
    (the engine/inference cast to compute dtype and shard on device_put)."""
    family = family or (hf_config or {}).get("model_type") or _detect_family(state_dict)
    if family not in _FAMILIES:
        raise ValueError(f"unsupported model family {family!r}")
    if family == "mixtral":
        # Static-capacity routing can drop over-capacity tokens that HF's
        # dropless top-k would route; raise the factor for serving fidelity
        # (still overridable via a caller-supplied config).
        log_dist("importer: mixtral uses static-capacity expert routing — "
                 "over-capacity tokens are dropped; raise "
                 "moe_capacity_factor if imported outputs must match HF")
    config_fn, convert_fn, strip = _FAMILIES[family]
    if config is None:
        if hf_config is None:
            raise ValueError("need either a TransformerConfig or the HF "
                             "config.json dict to size the model")
        config = config_fn(hf_config)
    sd = _SDict(state_dict, strip=strip)
    params = convert_fn(sd, config)
    if (config.pos_embedding == "learned"
            and config.max_seq > params["pos_embed"].shape[0]):
        raise ValueError(
            f"max_seq={config.max_seq} exceeds the checkpoint's learned "
            f"position table ({params['pos_embed'].shape[0]} rows); "
            "positions past the table would silently clamp")
    leftovers = [k for k in sd.unused()
                 if not k.endswith(("rotary_emb.inv_freq", "attn.bias",
                                    "attn.masked_bias", "lm_head.weight"))]
    if leftovers:
        log_dist(f"importer: {len(leftovers)} unused checkpoint keys "
                 f"(first 5: {leftovers[:5]})")
    return config, params


def _load_files(path: str) -> Dict[str, Any]:
    """Load all weight shards under an HF checkpoint directory."""
    def _safetensors(fp):
        import jax

        try:  # bf16-capable path — pinned to host so shards never touch HBM
            from safetensors.flax import load_file as lf
            with jax.default_device(jax.devices("cpu")[0]):
                return dict(lf(fp))
        except Exception:
            from safetensors.torch import load_file as lf
            return dict(lf(fp))

    candidates = [
        ("model.safetensors.index.json", _safetensors, "model.safetensors"),
        ("pytorch_model.bin.index.json", None, "pytorch_model.bin"),
    ]
    for index_name, loader, single_name in candidates:
        index_fp = os.path.join(path, index_name)
        single_fp = os.path.join(path, single_name)
        if loader is None:
            import torch

            def loader(fp):
                return torch.load(fp, map_location="cpu", weights_only=True)
        if os.path.exists(index_fp):
            with open(index_fp) as f:
                index = json.load(f)
            sd: Dict[str, Any] = {}
            for shard in sorted(set(index["weight_map"].values())):
                sd.update(loader(os.path.join(path, shard)))
            return sd
        if os.path.exists(single_fp):
            return loader(single_fp)
    raise FileNotFoundError(f"no model.safetensors / pytorch_model.bin under {path}")


def load_hf_checkpoint(path: str,
                       config: TransformerConfig | None = None,
                       **overrides) -> Tuple[TransformerConfig, dict]:
    """Load an HF checkpoint directory (config.json + safetensors/bin shards)
    onto the native trunk.

    >>> cfg, params = load_hf_checkpoint("/ckpts/llama-2-7b")
    >>> engine = ds.initialize(ds_config, build_model(cfg), params=params)

    ``overrides`` are applied to the derived TransformerConfig (e.g.
    ``max_seq=8192`` to serve longer than the checkpoint's default)."""
    hf_config = None
    cfg_fp = os.path.join(path, "config.json")
    if os.path.exists(cfg_fp):
        with open(cfg_fp) as f:
            hf_config = json.load(f)
    sd = _load_files(path)
    cfg, params = import_state_dict(sd, config=config, hf_config=hf_config)
    if overrides:
        cfg = TransformerConfig(**{**cfg.__dict__, **overrides})
        if (cfg.pos_embedding == "learned"
                and cfg.max_seq > params["pos_embed"].shape[0]):
            # same guard as import_state_dict, re-checked post-override
            raise ValueError(
                f"max_seq={cfg.max_seq} exceeds the checkpoint's learned "
                f"position table ({params['pos_embed'].shape[0]} rows); "
                "positions past the table would silently clamp")
    n = sum(int(np.prod(p.shape)) for p in
            __import__("jax").tree.leaves(params))
    log_dist(f"importer: loaded {n / 1e6:.1f}M params from {path} "
             f"({hf_config.get('model_type') if hf_config else 'detected'})")
    return cfg, params
