"""Pipeline parallelism: SPMD pipelining over the ``pipe`` mesh axis.

Reference: ``deepspeed/runtime/pipe/`` — ``PipelineModule`` partitions a
``LayerSpec`` list across stages (``pipe/module.py:86,370``) and
``PipelineEngine`` interprets an instruction schedule (1F1B ``TrainSchedule``,
``pipe/schedule.py:189``) with explicit p2p sends/recvs (``pipe/p2p.py:49``)
and tied-weight allreduces.

TPU-native design — one compiled program instead of a host-driven
interpreter (SURVEY §7 "hard parts"):

- **Stage assignment is a sharding**: layer weights keep the stacked
  ``(L, ...)`` layout and dim 0 is sharded over ``pipe`` — each device holds
  a contiguous slice of L/P layers (the ``PipelineModule`` uniform
  partitioner). No separate per-stage module objects.
- **The schedule is a scan**: under ``shard_map`` (manual only on ``pipe``;
  ``data``/``model``/``seq`` stay automatic so DP/TP/SP compose), a
  ``lax.scan`` runs M + P - 1 ticks. Each tick every stage applies its
  layer slice and hands its activation to the next stage with a
  non-cyclic ``ppermute`` — the p2p send/recv pair of ``pipe/p2p.py``
  compiled into the step. Stage 0 ingests microbatch t; the last P - 1
  tick outputs are the drained microbatches (GPipe fill/drain bubble).
- **The backward schedule is autodiff**: differentiating the scan yields
  the reversed pipeline (grads ppermute backwards) — no BackwardPass /
  SendGrad / RecvGrad instructions to hand-schedule.
- Loss is computed once over all drained microbatches (single big
  unembedding matmul) and ``psum``-masked to the last stage.

Tied embeddings: the tok_embed weight is replicated over ``pipe`` (spec
``P()``), so the first-stage embedding lookup and last-stage unembedding
read the same array and XLA psums its gradient across stages — the
reference's tied-weight allreduce (``pipe/engine.py:249``) for free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

from ..platform.mesh import current_mesh
from .transformer import TransformerConfig, TransformerLM


class PipelinedTransformerLM(TransformerLM):
    """TransformerLM whose layer stack executes as a ``pipe``-axis pipeline.

    Same param pytree/init as :class:`TransformerLM` — only ``param_specs``
    (dim 0 of layers → ``pipe``) and ``loss`` (pipelined schedule) differ, so
    checkpoints are interchangeable with the dense model.
    """

    def __init__(self, config: TransformerConfig, n_stages: int,
                 num_micro: int | None = None, attention_fn=None):
        super().__init__(config, attention_fn)
        assert config.n_layer % n_stages == 0, (
            f"n_layer {config.n_layer} not divisible by {n_stages} stages")
        assert config.num_experts == 1, "MoE + pipeline: not yet supported"
        self.n_stages = n_stages
        # Default 2 microbatches per stage: bubble fraction (P-1)/(M+P-1).
        self.num_micro = num_micro or 2 * n_stages

    def param_specs(self) -> dict:
        specs = super().param_specs()
        specs["layers"] = {
            k: P(*(("pipe",) + tuple(s)[1:]))
            for k, s in specs["layers"].items()
        }
        return specs

    # ------------------------------------------------------------- schedule
    def _pipeline_body(self, prm, ids_mb, lm_mb, am_mb, *, remat_policy):
        cfg = self.cfg
        Pn, M = self.n_stages, self.num_micro
        p = lax.axis_index("pipe")
        is_first = p == 0
        is_last = p == Pn - 1
        layers_local = prm["layers"]                  # (L/P, ...) slice
        _, Bm, S = ids_mb.shape
        T = M + Pn - 1
        perm = [(i, i + 1) for i in range(Pn - 1)]    # non-cyclic shift fwd

        def tick(x_recv, t):
            mb_i = jnp.clip(t, 0, M - 1)
            mb_ids = lax.dynamic_index_in_dim(ids_mb, mb_i, 0, keepdims=False)
            mb_am = (lax.dynamic_index_in_dim(am_mb, mb_i, 0, keepdims=False)
                     if am_mb is not None else None)
            emb, positions = self._embed(prm, mb_ids)
            x_in = jnp.where(is_first, emb, x_recv)
            y, _aux = self._scan_layers(x_in, layers_local, positions, mb_am,
                                        remat_policy)
            x_send = lax.ppermute(y, "pipe", perm)
            return x_send, y

        x0 = lax.pcast(jnp.zeros((Bm, S, cfg.d_model), cfg.dtype),
                       ("pipe",), to="varying")
        _, ys = lax.scan(tick, x0, jnp.arange(T))
        ys_out = ys[Pn - 1:]                          # (M, Bm, S, d) drained

        logits = self._head(prm, ys_out.reshape(M * Bm, S, cfg.d_model))
        ids_flat = ids_mb.reshape(M * Bm, S)
        targets = ids_flat[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        w = lm_mb.reshape(M * Bm, S)[:, 1:].astype(jnp.float32)
        # Only the last stage drained real activations; everything else is
        # bubble garbage — masked out by the select, then summed over pipe.
        loss_sum = lax.psum(jnp.where(is_last, jnp.sum(nll * w), 0.0), "pipe")
        tok_sum = lax.psum(jnp.where(is_last, jnp.sum(w), 0.0), "pipe")
        return loss_sum / jnp.maximum(tok_sum, 1.0)

    # ----------------------------------------------------------------- loss
    def loss(self, params, batch, *, remat_policy=None):
        mesh = current_mesh()
        Pn = self.n_stages
        if mesh is None or int(mesh.shape.get("pipe", 1)) == 1:
            # No pipe axis in context (single chip / eval): dense execution.
            return super().loss(params, batch, remat_policy=remat_policy)
        assert int(mesh.shape["pipe"]) == Pn, (
            f"model built for {Pn} stages but mesh has "
            f"{mesh.shape['pipe']} pipe ranks")
        if jax.default_backend() == "cpu":
            # XLA CPU bug workaround: any bf16<->f32 convert inside the
            # pipe-axis shard_map + scan + grad pattern CHECK-fails the CPU
            # compiler ("Invalid binary instruction opcode copy",
            # hlo_instruction.cc:1585 — float-normalization pass, which
            # native-bf16 TPUs don't run). Upcast params OUTSIDE the
            # shard_map and run the pipelined body through an fp32-config
            # clone (self.cfg stays untouched — dense fallback/eval numerics
            # are unchanged). Gated on actual dtypes at call time: the
            # engine's compute cast can hand us bf16 params even when the
            # model config says fp32.
            params = jax.tree.map(
                lambda p: p.astype(jnp.float32)
                if p.dtype == jnp.bfloat16 else p, params)
            if self.cfg.dtype == jnp.bfloat16:
                from ..inference.engine import model_with_dtype

                clone = model_with_dtype(self, jnp.float32)
                return clone.loss(params, batch, remat_policy=remat_policy)
        ids = batch["input_ids"]
        B, S = ids.shape
        M = self.num_micro
        assert B % M == 0, f"batch {B} not divisible by num_micro {M}"
        ids_mb = ids.reshape(M, B // M, S)
        lm = batch.get("loss_mask")
        lm_mb = (lm.reshape(M, B // M, S) if lm is not None
                 else jnp.ones_like(ids_mb))
        am = batch.get("attention_mask")

        pspecs = {k: (P("pipe") if k == "layers" else P()) for k in params}
        if am is not None:
            am_mb = am.reshape(M, B // M, S)
            f = shard_map(
                partial(self._pipeline_body, remat_policy=remat_policy),
                mesh=mesh, in_specs=(pspecs, P(), P(), P()), out_specs=P(),
                axis_names={"pipe"})
            return f(params, ids_mb, lm_mb, am_mb)
        f = shard_map(
            lambda prm, i_mb, l_mb: self._pipeline_body(
                prm, i_mb, l_mb, None, remat_policy=remat_policy),
            mesh=mesh, in_specs=(pspecs, P(), P()), out_specs=P(),
            axis_names={"pipe"})
        return f(params, ids_mb, lm_mb)


def build_pipeline_model(cfg: TransformerConfig, n_stages: int,
                         num_micro: int | None = None,
                         attention_fn=None) -> PipelinedTransformerLM:
    return PipelinedTransformerLM(cfg, n_stages, num_micro, attention_fn)
