"""Pipeline parallelism: SPMD pipelining over the ``pipe`` mesh axis.

Reference: ``deepspeed/runtime/pipe/`` — ``PipelineModule`` partitions a
``LayerSpec`` list across stages (``pipe/module.py:86,370``) and
``PipelineEngine`` interprets an instruction schedule (1F1B ``TrainSchedule``,
``pipe/schedule.py:189``) with explicit p2p sends/recvs (``pipe/p2p.py:49``)
and tied-weight allreduces.

TPU-native design — one compiled program instead of a host-driven
interpreter (SURVEY §7 "hard parts"):

- **Stage assignment is a sharding**: layer weights keep the stacked
  ``(L, ...)`` layout and dim 0 is sharded over ``pipe`` — each device holds
  a contiguous slice of L/P layers (the ``PipelineModule`` uniform
  partitioner). No separate per-stage module objects.
- **The schedule is a scan**: under ``shard_map`` (manual only on ``pipe``;
  ``data``/``model``/``seq`` stay automatic so DP/TP/SP compose), a
  ``lax.scan`` runs M + P - 1 ticks. Each tick every stage applies its
  layer slice and hands its activation to the next stage with a
  non-cyclic ``ppermute`` — the p2p send/recv pair of ``pipe/p2p.py``
  compiled into the step. Stage 0 ingests microbatch t; the last P - 1
  tick outputs are the drained microbatches (GPipe fill/drain bubble).
- **The backward schedule is autodiff**: differentiating the scan yields
  the reversed pipeline (grads ppermute backwards) — no BackwardPass /
  SendGrad / RecvGrad instructions to hand-schedule.
- Loss is computed once over all drained microbatches (single big
  unembedding matmul) and ``psum``-masked to the last stage.

Tied embeddings: the tok_embed weight is replicated over ``pipe`` (spec
``P()``), so the first-stage embedding lookup and last-stage unembedding
read the same array and XLA psums its gradient across stages — the
reference's tied-weight allreduce (``pipe/engine.py:249``) for free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

from ..platform.mesh import current_mesh
from .moe import MoETransformerLM
from .transformer import TransformerConfig, TransformerLM


class _PipelinedLMBase:
    """Pipeline-schedule mixin; must precede a :class:`TransformerLM`
    subclass in the MRO (``super()`` provides the trunk: dense or MoE).

    Same param pytree/init as the base trunk — only ``param_specs``
    (dim 0 of layers → ``pipe``) and ``loss`` (pipelined schedule) differ, so
    checkpoints are interchangeable with the dense model.
    """

    def __init__(self, config: TransformerConfig, n_stages: int,
                 num_micro: int | None = None, attention_fn=None,
                 tick_remat: bool = False, schedule: str = "gpipe"):
        if config.objective != "clm":
            raise ValueError(
                "the pipelined loss computes shifted next-token CE; "
                f"objective={config.objective!r} (MLM/encoder) is not "
                "supported under pipeline parallelism")
        super().__init__(config, attention_fn)
        assert config.n_layer % n_stages == 0, (
            f"n_layer {config.n_layer} not divisible by {n_stages} stages")
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        self.n_stages = n_stages
        # Default 2 microbatches per stage: bubble fraction (P-1)/(M+P-1).
        self.num_micro = num_micro or 2 * n_stages
        # tick_remat: checkpoint each pipeline tick — backward recomputes the
        # tick forward from its (Bm,S,d) input, so live activation memory is
        # O(in-flight microbatch inputs) like the reference's 1F1B window
        # (pipe/schedule.py:189) instead of O(M) full per-tick residuals.
        self.tick_remat = tick_remat
        # schedule="1f1b": memory-bounded execution. The tick scan runs in
        # windows of P ticks, each window wrapped in jax.checkpoint (and each
        # tick inside too), so the backward holds only window-boundary
        # carries + one recomputed tick — the O(P) in-flight activation
        # profile of the reference's 1F1B TrainSchedule
        # (pipe/schedule.py:189) instead of GPipe's O(M) stashes. Embeddings
        # are re-gathered per tick (cheap) rather than stashed (M,Bm,S,d).
        self.schedule = schedule

    def param_specs(self) -> dict:
        specs = super().param_specs()
        specs["layers"] = {
            k: P(*(("pipe",) + tuple(s)[1:]))
            for k, s in specs["layers"].items()
        }
        return specs

    # ------------------------------------------------------------- schedule
    def _pipeline_body(self, prm, ids_mb, lm_mb, am_mb, *, remat_policy):
        """One compiled pipeline schedule: M + P - 1 ticks.

        Efficiency structure (vs the naive all-stage head):
        - **vocab-sharded head**: each drained microbatch's unembedding runs
          with the vocab dim split over ``pipe`` — every stage computes a
          V/P logit slice and the cross-entropy is assembled with two scalar
          psums (max / sum-exp) + a psum'd target-logit lookup. Head FLOPs
          per stage drop P-fold; no stage computes the full vocab matmul.
        - **in-scan loss**: the drained tick's loss is accumulated in the
          scan carry, so no (M, Bm, S, d) activation stash survives the
          scan — live memory is the carry plus per-tick residuals
          (O(P)-class with ``tick_remat``).
        - **embeddings precomputed once** (gpipe schedule) for all M
          microbatches instead of re-gathered on every one of the T ticks by
          every stage; the 1f1b schedule deliberately inverts this trade —
          per-tick gathers are cheap, an (M, Bm, S, d) stash is not.
        """
        cfg = self.cfg
        Pn, M = self.n_stages, self.num_micro
        p = lax.axis_index("pipe")
        is_first = p == 0
        is_last = p == Pn - 1
        layers_local = prm["layers"]                  # (L/P, ...) slice
        _, Bm, S = ids_mb.shape
        T = M + Pn - 1
        perm = [(i, i + 1) for i in range(Pn - 1)]    # non-cyclic shift fwd
        memory_bound = self.schedule == "1f1b"

        if memory_bound:
            # per-tick embedding gather: nothing (M, Bm, S, d)-sized survives
            positions = self._positions(Bm, S)
            emb_all = None
        else:
            # ---- embeddings once, not per tick
            emb_all, positions_all = self._embed(prm, ids_mb.reshape(M * Bm, S))
            emb_all = emb_all.reshape(M, Bm, S, cfg.d_model)
            positions = positions_all[:Bm]

        # ---- vocab-sharded unembedding slice for this stage
        V = cfg.vocab_size
        Vp = -(-V // Pn)                              # padded per-stage chunk
        W = (prm["tok_embed"].astype(cfg.dtype).T if cfg.tie_embeddings
             else prm["lm_head"].astype(cfg.dtype))   # (d, V)
        Wpad = jnp.pad(W, ((0, 0), (0, Pn * Vp - V)))
        Wl = lax.dynamic_slice_in_dim(Wpad, p * Vp, Vp, axis=1)  # (d, Vp)
        v0 = p * Vp
        if cfg.lm_head_bias:
            # head bias slices with the vocab shard (GPT-J/CodeGen/Phi)
            bpad = jnp.pad(prm["lm_head_bias"].astype(jnp.float32),
                           (0, Pn * Vp - V))
            bias_l = lax.dynamic_slice_in_dim(bpad, p * Vp, Vp)
        else:
            bias_l = None

        def micro_loss(y, d_i):
            """CE of one drained microbatch; y is last-stage output,
            broadcast so all stages share the vocab-sharded matmul."""
            y_bc = lax.psum(jnp.where(is_last, y, jnp.zeros_like(y)), "pipe")
            z = self._head_norm(prm, y_bc)
            logits_l = (z @ Wl).astype(jnp.float32)   # (Bm, S, Vp)
            if bias_l is not None:
                logits_l = logits_l + bias_l
            # padded vocab tail must not win the max / contribute to sum-exp
            col = jnp.arange(Vp) + v0
            logits_l = jnp.where(col[None, None, :] < V, logits_l,
                                 jnp.float32(jnp.finfo(jnp.float32).min))
            # stability max only — gradient stopped (pmax has no JVP rule;
            # stop_gradient must wrap the OPERAND so the tangent entering
            # pmax is a symbolic zero and the rule is never invoked; the
            # log-sum-exp derivative is exact with the max held constant)
            mx = lax.pmax(jnp.max(lax.stop_gradient(logits_l), axis=-1),
                          "pipe")                                    # (Bm,S)
            se = lax.psum(jnp.sum(jnp.exp(logits_l - mx[..., None]),
                                  axis=-1), "pipe")                  # (Bm,S)
            ids_d = lax.dynamic_index_in_dim(ids_mb, d_i, 0, keepdims=False)
            w_d = lax.dynamic_index_in_dim(lm_mb, d_i, 0,
                                           keepdims=False)[:, 1:]
            tgt = ids_d[:, 1:]                                       # (Bm,S-1)
            in_range = (tgt >= v0) & (tgt < v0 + Vp)
            idx = jnp.clip(tgt - v0, 0, Vp - 1)
            tl_local = jnp.take_along_axis(logits_l[:, :-1], idx[..., None],
                                           axis=-1)[..., 0]
            wf = w_d.astype(jnp.float32)
            # Per-stage PARTIAL of sum(nll * w): each stage contributes its
            # vocab chunk's target logits; stage 0 alone adds the (already
            # globally-reduced) max/log-sum-exp term. One psum at schedule
            # end assembles the total — and keeps the output provably
            # replicated for shard_map's vma check.
            part = -jnp.sum(jnp.where(in_range, tl_local, 0.0) * wf)
            part += jnp.where(
                is_first,
                jnp.sum((mx[:, :-1] + jnp.log(se[:, :-1])) * wf), 0.0)
            tok_part = jnp.where(is_first, jnp.sum(wf), 0.0)
            return part, tok_part

        def tick(carry, t):
            x_recv, loss_acc, tok_acc, aux_acc = carry
            mb_i = jnp.clip(t, 0, M - 1)
            if memory_bound:
                ids_d = lax.dynamic_index_in_dim(ids_mb, mb_i, 0,
                                                 keepdims=False)
                emb, _ = self._embed(prm, ids_d)
            else:
                emb = lax.dynamic_index_in_dim(emb_all, mb_i, 0,
                                               keepdims=False)
            mb_am = (lax.dynamic_index_in_dim(am_mb, mb_i, 0, keepdims=False)
                     if am_mb is not None else None)
            x_in = jnp.where(is_first, emb, x_recv)
            y, aux = self._scan_layers(x_in, layers_local, positions, mb_am,
                                       remat_policy)
            d_i = jnp.clip(t - (Pn - 1), 0, M - 1)    # drained micro index
            # t >= T guards the 1f1b window padding: without it the last
            # drained microbatch would be double-counted on no-op ticks.
            valid = ((t >= Pn - 1) & (t < T)).astype(jnp.float32)
            # This stage holds real data (micro t - p) only for p <= t < p+M;
            # outside that window the trunk chews warmup/drain garbage and
            # its MoE aux contribution must not count.
            aux_valid = ((t >= p) & (t < p + M)).astype(jnp.float32)
            m_loss, m_tok = micro_loss(y, d_i)
            x_send = lax.ppermute(y, "pipe", perm)
            return (x_send, loss_acc + valid * m_loss,
                    tok_acc + valid * m_tok,
                    aux_acc + aux_valid * aux.astype(jnp.float32)), None

        if self.tick_remat or memory_bound:
            tick = jax.checkpoint(tick, prevent_cse=False)
        x0 = lax.pcast(jnp.zeros((Bm, S, cfg.d_model), cfg.dtype),
                       ("pipe",), to="varying")
        zero = lax.pcast(jnp.float32(0.0), ("pipe",), to="varying")
        carry0 = (x0, zero, zero, zero)
        if memory_bound:
            # Windowed scan: inner P ticks under one jax.checkpoint — the
            # backward stashes ceil(T/P) window-boundary carries and
            # recomputes one window (itself tick-checkpointed) at a time.
            Wn = Pn
            n_win = -(-T // Wn)
            ticks = jnp.arange(n_win * Wn).reshape(n_win, Wn)

            def window(carry, ts):
                carry, _ = lax.scan(tick, carry, ts)
                return carry, None

            window = jax.checkpoint(window, prevent_cse=False)
            (_, loss_part, tok_part, aux_part), _ = lax.scan(
                window, carry0, ticks)
        else:
            (_, loss_part, tok_part, aux_part), _ = lax.scan(
                tick, carry0, jnp.arange(T))
        loss_sum = lax.psum(loss_part, "pipe")
        tok_sum = lax.psum(tok_part, "pipe")
        loss = loss_sum / jnp.maximum(tok_sum, 1.0)
        if cfg.num_experts > 1:
            # Per-stage aux summed its local L/P layers over M real micros;
            # psum assembles the full depth, /M matches the dense trunk's
            # whole-batch mean (equal-sized micros: mean of means is exact).
            aux_total = lax.psum(aux_part, "pipe") / M
            loss = loss + cfg.moe_aux_loss_weight * aux_total
        return loss

    # ----------------------------------------------------------------- loss
    def loss(self, params, batch, *, remat_policy=None):
        mesh = current_mesh()
        Pn = self.n_stages
        if mesh is None or int(mesh.shape.get("pipe", 1)) == 1:
            # No pipe axis in context (single chip / eval): dense execution.
            return super().loss(params, batch, remat_policy=remat_policy)
        assert int(mesh.shape["pipe"]) == Pn, (
            f"model built for {Pn} stages but mesh has "
            f"{mesh.shape['pipe']} pipe ranks")
        if jax.default_backend() == "cpu":
            # XLA CPU bug workaround: any bf16<->f32 convert inside the
            # pipe-axis shard_map + scan + grad pattern CHECK-fails the CPU
            # compiler ("Invalid binary instruction opcode copy",
            # hlo_instruction.cc:1585 — AllReducePromotion cloning the bf16
            # grad all-reduces, a pass native-bf16 TPUs don't run;
            # re-reproduced on jax 0.9.0). The bf16 pipe body itself IS
            # covered: test_pipeline.py::test_bf16_pipe_body_traces_and_lowers
            # traces + lowers it with this workaround bypassed (only
            # .compile() hits the CPU backend pass). Upcast params OUTSIDE the
            # shard_map and run the pipelined body through an fp32-config
            # clone (self.cfg stays untouched — dense fallback/eval numerics
            # are unchanged). Gated on actual dtypes at call time: the
            # engine's compute cast can hand us bf16 params even when the
            # model config says fp32.
            params = jax.tree.map(
                lambda p: p.astype(jnp.float32)
                if p.dtype == jnp.bfloat16 else p, params)
            if self.cfg.dtype == jnp.bfloat16:
                from ..inference.engine import model_with_dtype

                clone = model_with_dtype(self, jnp.float32)
                return clone.loss(params, batch, remat_policy=remat_policy)
        ids = batch["input_ids"]
        B, S = ids.shape
        M = self.num_micro
        assert B % M == 0, f"batch {B} not divisible by num_micro {M}"
        ids_mb = ids.reshape(M, B // M, S)
        lm = batch.get("loss_mask")
        lm_mb = (lm.reshape(M, B // M, S) if lm is not None
                 else jnp.ones_like(ids_mb))
        am = batch.get("attention_mask")

        pspecs = {k: (P("pipe") if k == "layers" else P()) for k in params}
        if am is not None:
            am_mb = am.reshape(M, B // M, S)
            f = shard_map(
                partial(self._pipeline_body, remat_policy=remat_policy),
                mesh=mesh, in_specs=(pspecs, P(), P(), P()), out_specs=P(),
                axis_names={"pipe"})
            return f(params, ids_mb, lm_mb, am_mb)
        f = shard_map(
            lambda prm, i_mb, l_mb: self._pipeline_body(
                prm, i_mb, l_mb, None, remat_policy=remat_policy),
            mesh=mesh, in_specs=(pspecs, P(), P()), out_specs=P(),
            axis_names={"pipe"})
        return f(params, ids_mb, lm_mb)


class PipelinedTransformerLM(_PipelinedLMBase, TransformerLM):
    """Dense trunk under the ``pipe``-axis schedule."""


class PipelinedMoETransformerLM(_PipelinedLMBase, MoETransformerLM):
    """MoE trunk under the ``pipe``-axis schedule: the expert banks keep
    their ``expert``/``model`` sharding (GSPMD-managed inside the manual-pipe
    shard_map) and the GShard aux loss is accumulated per real microbatch,
    psum'd across stages — lifting the reference's MoE-on-pipe layer-list
    machinery (``pipe/module.py`` + ``moe/layer.py``) into one program."""


def build_pipeline_model(cfg: TransformerConfig, n_stages: int,
                         num_micro: int | None = None, attention_fn=None,
                         tick_remat: bool = False,
                         schedule: str = "gpipe"):
    cls = (PipelinedMoETransformerLM if cfg.num_experts > 1
           else PipelinedTransformerLM)
    return cls(cfg, n_stages, num_micro, attention_fn,
               tick_remat=tick_remat, schedule=schedule)
