"""T5 encoder-decoder (the reference registry's seq2seq family).

Reference: ``module_inject/replace_policy.py`` carries a T5 injection policy
among its ~20 architectures; here the family is a native trunk — fully
additive beside :class:`TransformerLM` (decoder-only) and sharing its
TPU-first shape: stacked ``(L, ...)`` weights scanned per stack, sharding
as ``param_specs``, one pure ``loss``.

T5-specific semantics implemented exactly (t5-v1.0, e.g. ``t5-small``):
- RMSNorm (no bias), pre-norm blocks, relu FFN, no linear biases;
- UNSCALED attention (no 1/sqrt(d_k) — absorbed into init by T5);
- bucketed relative position bias, parameters living on block 0 and
  applied in every layer (bidirectional buckets in the encoder, causal
  buckets in the decoder self-attention; none on cross-attention);
- tied shared embedding; when tied, decoder output scales by d_model^-0.5
  before the unembedding matmul;
- ``decoder_input_ids`` default to labels shifted right with the pad id.

Generation (autoregressive decode with cross-attention cache) is not wired
into the inference engine; the family covers import + training/eval.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..platform.mesh import BATCH_AXES, constrain, current_mesh
from .transformer import (_norm, _token_nll, fused_nll_sharded,
                          mesh_dp_world, vocab_parallel_lookup)

B_AXES = BATCH_AXES


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    n_layer: int = 6              # encoder layers
    n_dec_layer: int = 6
    n_head: int = 8
    rel_buckets: int = 32
    rel_max_distance: int = 128
    gated_ffn: bool = False       # v1.1 "gated-gelu"; v1.0 = relu
    tie_embeddings: bool = True
    pad_token_id: int = 0
    norm_eps: float = 1e-6
    # Fused Pallas softmax-xent over the tied shared embedding (see
    # TransformerConfig.fused_xent). None = auto: on for TPU when tied
    # and the model/seq/pipe axes are unsharded.
    fused_xent: Any = None
    dtype: Any = jnp.bfloat16
    # Nominal sequence lengths for FLOPs/MFU accounting only (runtime
    # shapes come from the batch): typical span-corruption pretraining.
    max_src: int = 512
    max_tgt: int = 114

    @property
    def inner_dim(self) -> int:
        return self.n_head * self.d_kv

    @property
    def max_seq(self) -> int:
        """Total counted tokens per sample (engine throughput accounting
        multiplies flops_per_token() by this)."""
        return self.max_src + self.max_tgt

    def flops_per_sample(self) -> float:
        """Fwd+bwd model FLOPs per (max_src, max_tgt) sample — Megatron
        convention, but split enc/dec: encoder params touch only source
        tokens, decoder params (and the logit projection) only target
        tokens, and attention counts self/self/cross separately."""
        d, inner, V = self.d_model, self.inner_dim, self.vocab_size
        S, T = self.max_src, self.max_tgt
        n_enc, n_dec = self._trunk_param_split()
        # cross-attention K/V projections (2*d*inner per decoder layer) run
        # over the S encoder outputs, not the T decoder positions — count
        # them at S and back them out of the T-scaled decoder trunk
        cross_kv = self.n_dec_layer * 2 * d * inner
        trunk = 6 * (n_enc * S + (n_dec - cross_kv) * T + cross_kv * S)
        attn = 12 * inner * (self.n_layer * S * S
                             + self.n_dec_layer * (T * T + S * T))
        head = 6 * d * V * T
        return trunk + attn + head

    def flops_per_token(self) -> float:
        return self.flops_per_sample() / self.max_seq

    def _trunk_param_split(self) -> tuple[int, int]:
        d, inner, ff = self.d_model, self.inner_dim, self.d_ff
        attn = 3 * d * inner + inner * d
        ffn = d * ff * (3 if self.gated_ffn else 2)
        enc = self.n_layer * (attn + ffn)
        dec = self.n_dec_layer * (2 * attn + ffn)
        return enc, dec

    def param_count(self, non_embedding: bool = False) -> int:
        enc, dec = self._trunk_param_split()
        emb = 0 if non_embedding else self.vocab_size * self.d_model
        return enc + dec + emb


def _rel_bucket(rel_pos, *, bidirectional: bool, num_buckets: int,
                max_distance: int):
    """HF ``T5Attention._relative_position_bucket``, vectorized."""
    ret = jnp.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def _position_bias(rel_table, q_len: int, k_len: int, *, bidirectional: bool,
                   num_buckets: int, max_distance: int):
    """(H, q_len, k_len) additive score bias from the (buckets, H) table."""
    ctx = jnp.arange(q_len)[:, None]
    mem = jnp.arange(k_len)[None, :]
    buckets = _rel_bucket(mem - ctx, bidirectional=bidirectional,
                          num_buckets=num_buckets, max_distance=max_distance)
    return jnp.transpose(rel_table[buckets], (2, 0, 1)).astype(jnp.float32)


def _t5_attention(q, k, v, *, bias=None, causal: bool = False, mask=None):
    """UNSCALED attention. q:(B,Sq,H,dk) k/v:(B,Sk,H,dk); bias (H,Sq,Sk)."""
    B, Sq, H, dk = q.shape
    Sk = k.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if bias is not None:
        scores = scores + bias[None]
    big_neg = jnp.finfo(jnp.float32).min
    if causal:
        keep = jnp.tril(jnp.ones((Sq, Sk), bool))
        scores = jnp.where(keep[None, None], scores, big_neg)
    if mask is not None:   # (B, Sk) key padding mask
        scores = jnp.where(mask[:, None, None, :].astype(bool), scores, big_neg)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class T5Model:
    """init / loss / param_specs over :class:`T5Config` (engine protocol)."""

    def __init__(self, config: T5Config):
        self.cfg = config
        self._remat_policy = None

    # ----------------------------------------------------------------- init
    def _stack(self, key, n, cross: bool):
        cfg = self.cfg
        d, inner, ff = cfg.d_model, cfg.inner_dim, cfg.d_ff
        k = iter(jax.random.split(key, 16))

        def w(shape, scale):
            return jax.random.normal(next(k), shape, jnp.float32) * scale

        layers = {
            "ln1": jnp.ones((n, d), jnp.float32),
            "wq": w((n, d, inner), (d * cfg.d_kv) ** -0.5),
            "wk": w((n, d, inner), d ** -0.5),
            "wv": w((n, d, inner), d ** -0.5),
            "wo": w((n, inner, d), inner ** -0.5),
            "ln_ffn": jnp.ones((n, d), jnp.float32),
            "w_in": w((n, d, ff), d ** -0.5),
            "w_out": w((n, ff, d), ff ** -0.5),
        }
        if cfg.gated_ffn:
            layers["w_gate"] = w((n, d, ff), d ** -0.5)
        if cross:
            layers.update({
                "ln_cross": jnp.ones((n, d), jnp.float32),
                "cq": w((n, d, inner), (d * cfg.d_kv) ** -0.5),
                "ck": w((n, d, inner), d ** -0.5),
                "cv": w((n, d, inner), d ** -0.5),
                "co": w((n, inner, d), inner ** -0.5),
            })
        return layers

    def init(self, rng) -> dict:
        cfg = self.cfg
        ke, kd, ks, keb, kdb, kh = jax.random.split(rng, 6)
        params = {
            "shared": jax.random.normal(
                ks, (cfg.vocab_size, cfg.d_model), jnp.float32),
            "enc": {
                "layers": self._stack(ke, cfg.n_layer, cross=False),
                "rel_bias": jax.random.normal(
                    keb, (cfg.rel_buckets, cfg.n_head), jnp.float32) * 0.1,
                "final_ln": jnp.ones((cfg.d_model,), jnp.float32),
            },
            "dec": {
                "layers": self._stack(kd, cfg.n_dec_layer, cross=True),
                "rel_bias": jax.random.normal(
                    kdb, (cfg.rel_buckets, cfg.n_head), jnp.float32) * 0.1,
                "final_ln": jnp.ones((cfg.d_model,), jnp.float32),
            },
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = jax.random.normal(
                kh, (cfg.d_model, cfg.vocab_size), jnp.float32)
        return params

    # ----------------------------------------------------------------- specs
    def param_specs(self) -> dict:
        def stack_specs(cross: bool):
            s = {
                "ln1": P(None, None),
                "wq": P(None, None, "model"), "wk": P(None, None, "model"),
                "wv": P(None, None, "model"), "wo": P(None, "model", None),
                "ln_ffn": P(None, None),
                "w_in": P(None, None, "model"), "w_out": P(None, "model", None),
            }
            if self.cfg.gated_ffn:
                s["w_gate"] = P(None, None, "model")
            if cross:
                s.update({"ln_cross": P(None, None),
                          "cq": P(None, None, "model"),
                          "ck": P(None, None, "model"),
                          "cv": P(None, None, "model"),
                          "co": P(None, "model", None)})
            return s

        specs = {
            "shared": P("model", None),   # vocab-sharded, like every trunk
            "enc": {"layers": stack_specs(False), "rel_bias": P(None, None),
                    "final_ln": P(None)},
            "dec": {"layers": stack_specs(True), "rel_bias": P(None, None),
                    "final_ln": P(None)},
        }
        if not self.cfg.tie_embeddings:
            specs["lm_head"] = P(None, "model")
        return specs

    def stacked_fn(self):
        cfg = self.cfg
        sizes = {cfg.n_layer, cfg.n_dec_layer}
        rel_shape = (cfg.rel_buckets, cfg.n_head)

        def is_stacked(shape) -> bool:
            # rel_bias (buckets, H) is NOT layer-stacked even when a stack
            # depth equals rel_buckets (e.g. 32-layer models)
            if tuple(shape) == rel_shape:
                return False
            return len(shape) >= 2 and shape[0] in sizes

        return is_stacked

    # ------------------------------------------------------------------ body
    def _heads(self, x, w):
        B, S, _ = x.shape
        return (x @ w.astype(x.dtype)).reshape(
            B, S, self.cfg.n_head, self.cfg.d_kv)

    def _ffn(self, y, p):
        cfg = self.cfg
        u = y @ p["w_in"].astype(y.dtype)
        if cfg.gated_ffn:
            u = jax.nn.gelu(y @ p["w_gate"].astype(y.dtype)) * u
        else:
            u = jax.nn.relu(u)
        u = constrain(u, P(B_AXES, None, "model"))
        return u @ p["w_out"].astype(y.dtype)

    def _encode(self, params, ids, mask):
        cfg = self.cfg
        x = vocab_parallel_lookup(params["shared"].astype(cfg.dtype), ids)
        S = ids.shape[1]
        bias = _position_bias(params["enc"]["rel_bias"], S, S,
                              bidirectional=True, num_buckets=cfg.rel_buckets,
                              max_distance=cfg.rel_max_distance)

        def layer(x, p):
            # same offload-policy anchors as the decoder trunk
            # (transformer.py _layer; engine OFFLOAD_ACTIVATION_NAMES)
            x = checkpoint_name(x, "layer_in")
            y = _norm(x, p["ln1"], None, "rmsnorm", cfg.norm_eps)
            o = _t5_attention(self._heads(y, p["wq"]), self._heads(y, p["wk"]),
                              self._heads(y, p["wv"]), bias=bias, mask=mask)
            o = checkpoint_name(o, "attn_out")
            x = x + (o.reshape(*o.shape[:2], -1) @ p["wo"].astype(x.dtype))
            y = _norm(x, p["ln_ffn"], None, "rmsnorm", cfg.norm_eps)
            x = x + self._ffn(y, p)
            return constrain(x, P(B_AXES, None, None)), None

        if self._remat_policy is not None:
            layer = jax.checkpoint(layer, policy=self._remat_policy,
                                   prevent_cse=False)
        x, _ = lax.scan(layer, x, params["enc"]["layers"])
        return _norm(x, params["enc"]["final_ln"], None, "rmsnorm",
                     cfg.norm_eps)

    def _decode(self, params, dec_ids, enc_out, enc_mask):
        cfg = self.cfg
        x = vocab_parallel_lookup(params["shared"].astype(cfg.dtype), dec_ids)
        S = dec_ids.shape[1]
        bias = _position_bias(params["dec"]["rel_bias"], S, S,
                              bidirectional=False,
                              num_buckets=cfg.rel_buckets,
                              max_distance=cfg.rel_max_distance)

        def layer(x, p):
            x = checkpoint_name(x, "layer_in")
            y = _norm(x, p["ln1"], None, "rmsnorm", cfg.norm_eps)
            o = _t5_attention(self._heads(y, p["wq"]), self._heads(y, p["wk"]),
                              self._heads(y, p["wv"]), bias=bias, causal=True)
            o = checkpoint_name(o, "attn_out")
            x = x + (o.reshape(*o.shape[:2], -1) @ p["wo"].astype(x.dtype))
            y = _norm(x, p["ln_cross"], None, "rmsnorm", cfg.norm_eps)
            o = _t5_attention(self._heads(y, p["cq"]),
                              self._heads(enc_out, p["ck"]),
                              self._heads(enc_out, p["cv"]), mask=enc_mask)
            x = x + (o.reshape(*o.shape[:2], -1) @ p["co"].astype(x.dtype))
            y = _norm(x, p["ln_ffn"], None, "rmsnorm", cfg.norm_eps)
            x = x + self._ffn(y, p)
            return constrain(x, P(B_AXES, None, None)), None

        if self._remat_policy is not None:
            layer = jax.checkpoint(layer, policy=self._remat_policy,
                                   prevent_cse=False)
        x, _ = lax.scan(layer, x, params["dec"]["layers"])
        return _norm(x, params["dec"]["final_ln"], None, "rmsnorm",
                     cfg.norm_eps)

    # ------------------------------------------------------------------ api
    def _features(self, params, input_ids, decoder_input_ids,
                  attention_mask, remat_policy):
        """Everything before the unembedding: (B, Sd, D) decoder output,
        already d_model^-0.5-rescaled when tied (the HF T5 rule). Shared
        by apply() and the fused loss path so they cannot drift."""
        self._remat_policy = remat_policy
        enc_out = self._encode(params, input_ids, attention_mask)
        x = self._decode(params, decoder_input_ids, enc_out, attention_mask)
        if self.cfg.tie_embeddings:
            x = x * (self.cfg.d_model ** -0.5)
        return x

    def apply(self, params, input_ids, decoder_input_ids, *,
              attention_mask=None, remat_policy=None, return_aux=False):
        """((B,Se), (B,Sd)) → (B, Sd, V) logits."""
        cfg = self.cfg
        x = self._features(params, input_ids, decoder_input_ids,
                           attention_mask, remat_policy)
        if cfg.tie_embeddings:
            logits = x @ params["shared"].astype(x.dtype).T
        else:
            logits = x @ params["lm_head"].astype(x.dtype)
        logits = constrain(logits, P(B_AXES, None, "model"))
        return (logits, jnp.float32(0.0)) if return_aux else logits

    def _shift_right(self, labels):
        start = jnp.full((labels.shape[0], 1), self.cfg.pad_token_id,
                         labels.dtype)
        shifted = jnp.concatenate([start, labels[:, :-1]], axis=1)
        return jnp.where(shifted == -100, self.cfg.pad_token_id, shifted)

    def loss(self, params, batch, *, remat_policy=None):
        labels = batch["labels"]
        dec_ids = batch.get("decoder_input_ids")
        if dec_ids is None:
            dec_ids = self._shift_right(labels)
        safe = jnp.maximum(labels, 0)
        if self._fused_xent_active(batch_size=labels.shape[0],
                                   compute_dtype=params["shared"].dtype):
            x = self._features(params, batch["input_ids"], dec_ids,
                               batch.get("attention_mask"), remat_policy)
            nll = fused_nll_sharded(x, safe,
                                    params["shared"].astype(x.dtype))
        else:
            logits = self.apply(params, batch["input_ids"], dec_ids,
                                attention_mask=batch.get("attention_mask"),
                                remat_policy=remat_policy)
            nll = _token_nll(logits, safe)
        mask = batch.get("loss_mask")
        w = (mask.astype(jnp.float32) if mask is not None
             else (labels != -100).astype(jnp.float32))
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)

    def _fused_xent_active(self, batch_size=None, compute_dtype=None) -> bool:
        """T5 fused-loss gate: tied shared embedding only (the kernel takes
        the (V, d) table), and conservatively NO model/seq/pipe sharding —
        the shared table's TP layout differs from the decoder trunk's, so
        T5 does not take the vocab-sharded variant. Batch must split on
        batch boundaries across the dp world (see the decoder gate)."""
        cfg = self.cfg
        if cfg.fused_xent is False or not cfg.tie_embeddings:
            return False
        # hardware eligibility (f16-on-TPU, VMEM at wide d): ops/xent.py
        from ..ops.xent import fused_xent_eligible

        if not fused_xent_eligible(cfg.dtype, compute_dtype, cfg.d_model):
            return False
        mesh = current_mesh()
        if mesh is not None and not mesh.empty:
            from ..platform.mesh import manual_axes_of
            if manual_axes_of(mesh):
                return False
            for ax in ("model", "seq", "pipe"):
                if ax in mesh.axis_names and mesh.shape[ax] != 1:
                    return False
            if batch_size is not None \
                    and batch_size % mesh_dp_world(mesh) != 0:
                return False
        if cfg.fused_xent:
            return True
        return jax.default_backend() == "tpu"


def t5(size: str = "small", **overrides) -> T5Config:
    table = {
        "small": dict(d_model=512, d_kv=64, d_ff=2048, n_layer=6,
                      n_dec_layer=6, n_head=8),
        "base": dict(d_model=768, d_kv=64, d_ff=3072, n_layer=12,
                     n_dec_layer=12, n_head=12),
        "large": dict(d_model=1024, d_kv=64, d_ff=4096, n_layer=24,
                      n_dec_layer=24, n_head=16),
    }
    base = dict(vocab_size=32128)
    base.update(table[size])
    base.update(overrides)
    return T5Config(**base)
