"""Mixture-of-Experts layer and MoE transformer trunk.

TPU-native analog of the reference MoE stack (``deepspeed/moe/layer.py:16``,
``sharded_moe.py:477-554`` — GShard top-1/top-2 gating with capacity factor,
all-to-all dispatch to experts, expert-parallel groups orthogonal to DP/TP,
``utils/groups.py:113``).

Design differences that make this TPU-idiomatic:

- **Grouped static-capacity dispatch**: tokens are grouped per batch row
  (the GShard "group" dim), each group gets a static per-expert capacity
  ``C = ceil(S * k * cf / E)``, and dispatch/combine are one-hot einsums —
  so the whole layer is a handful of large MXU matmuls, memory linear in
  batch, and XLA fuses the scatter/gather away.
- **Expert parallelism by sharding**: expert-stacked weights ``(E, d, f)``
  are sharded over the ``expert`` mesh axis; constraining the dispatched
  activations ``(B, E, C, d)`` to the same axis makes GSPMD emit exactly
  the all-to-all the reference hand-codes (``sharded_moe.py:_AllToAll``).
- **Gating in fp32**: router weights are exempted from the engine's bf16
  compute cast (``fp32_param_names``) so near-tie routing decisions don't
  flap across bf16 rounding, matching ``sharded_moe.py:top1gating``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..platform.mesh import BATCH_AXES, constrain
from .transformer import TransformerConfig, TransformerLM, _activation

B_AXES = BATCH_AXES


def _capacity(tokens_per_group: int, num_experts: int, capacity_factor: float,
              top_k: int, min_capacity: int = 4,
              drop_tokens: bool = True) -> int:
    """Static per-expert capacity (reference ``sharded_moe.py`` capacity calc).

    ``drop_tokens=False`` sizes the capacity to hold EVERY routed token
    (reference no-drop mode) — O(S) memory per expert, never drops."""
    if not drop_tokens:
        return tokens_per_group
    cap = int(math.ceil(tokens_per_group * top_k * capacity_factor / num_experts))
    return max(cap, min_capacity)


def topk_gating(logits: jnp.ndarray, top_k: int, capacity: int):
    """GShard-style top-k gating with static capacity, for ONE token group.

    Args:
      logits: (T, E) router logits (fp32) for a group of T tokens.
      top_k: 1 or 2 (reference ``top1gating``/``top2gating``).
      capacity: per-expert static capacity C.

    Returns:
      combine: (T, E, C) fp32 combine weights (0 for dropped tokens).
      dispatch: (T, E, C) bool dispatch mask.
      aux_loss: scalar load-balancing loss (GShard eq. 4).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)      # (T, E)

    combine = jnp.zeros((T, E, capacity), jnp.float32)
    dispatch = jnp.zeros((T, E, capacity), bool)
    remaining = probs
    # running per-expert fill count, advanced across the k passes
    fill = jnp.zeros((E,), jnp.int32)
    gates_sum = jnp.zeros((T,), jnp.float32)
    top1_mask = None

    for k in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                          # (T,)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.int32)                # (T, E)
        if k == 0:
            top1_mask = mask
        # position of each token within its chosen expert's buffer:
        # cumulative count of earlier tokens that chose the same expert,
        # offset by the fill left by previous k-passes.
        pos_in_expert = (jnp.cumsum(mask, axis=0) - mask) + fill[None, :]  # (T, E)
        pos = jnp.sum(pos_in_expert * mask, axis=-1)                  # (T,)
        kept = pos < capacity
        gate = jnp.sum(probs * mask, axis=-1) * kept                  # (T,)
        onehot_pos = jax.nn.one_hot(jnp.minimum(pos, capacity - 1), capacity,
                                    dtype=jnp.float32)                # (T, C)
        sel = (mask.astype(jnp.float32) * kept[:, None])              # (T, E)
        combine = combine + gate[:, None, None] * sel[:, :, None] * onehot_pos[:, None, :]
        dispatch = dispatch | (sel[:, :, None] * onehot_pos[:, None, :] > 0)
        gates_sum = gates_sum + gate
        fill = fill + jnp.sum(mask * kept[:, None].astype(jnp.int32), axis=0)
        # mask out the chosen expert for the next pass
        remaining = remaining * (1 - mask)

    # normalize combine weights over the selected experts (top2gating renorm)
    if top_k > 1:
        denom = jnp.maximum(gates_sum, 1e-9)
        combine = combine / denom[:, None, None]

    # aux loss: E * sum_e( mean_tokens(route_frac_e) * mean_tokens(prob_e) )
    me = jnp.mean(probs, axis=0)                                      # (E,)
    ce = jnp.mean(top1_mask.astype(jnp.float32), axis=0)              # (E,)
    aux_loss = jnp.sum(me * ce) * E
    return combine, dispatch, aux_loss


class MoETransformerLM(TransformerLM):
    """TransformerLM with the dense FFN replaced by an expert-parallel MoE
    bank in every layer (Mixtral-style; the reference interleaves dense/MoE
    via its layer list — here ``num_experts`` governs the whole trunk).
    Only the MLP half of the layer differs; attention is inherited."""

    # ------------------------------------------------------------- MoE MLP
    @jax.named_scope("moe_mlp")
    def _mlp_block(self, y, p):
        """y: (B, S, d) post-norm activations. Groups = batch rows."""
        cfg = self.cfg
        B, S, d = y.shape
        E = cfg.num_experts
        # Eval uses the (larger) eval capacity factor so fewer tokens drop
        # (reference ``eval_capacity_factor``); the flag is a trace-time
        # constant set by the engine's eval step.
        factor = cfg.moe_capacity_factor
        if getattr(self, "moe_eval_mode", False):
            factor = cfg.moe_eval_capacity_factor or 2.0 * factor
        C = _capacity(S, E, factor, cfg.moe_top_k,
                      min_capacity=cfg.moe_min_capacity,
                      drop_tokens=cfg.moe_drop_tokens)

        logits = y.astype(jnp.float32) @ p["router"].astype(jnp.float32)  # (B,S,E)
        gate = jax.vmap(lambda lg: topk_gating(lg, cfg.moe_top_k, C))
        combine, dispatch, aux = gate(logits)      # (B,S,E,C) x2, (B,)

        # dispatch: (B,S,E,C) x (B,S,d) -> (B,E,C,d). The batch dim enters
        # sharded over (data, expert); constraining it to 'data' and E to
        # 'expert' is the token all-to-all of the reference's _AllToAll
        # autograd fn (sharded_moe.py:299) — GSPMD emits it.
        xs = jnp.einsum("bsec,bsd->becd", dispatch.astype(y.dtype), y)
        xs = constrain(xs, P(("data", "zero"), "expert", None, None))

        u = jnp.einsum("becd,edf->becf", xs, p["w_in"].astype(y.dtype))
        u = self._expert_bias(u, p, "b_in")
        if cfg.is_glu:
            g = jnp.einsum("becd,edf->becf", xs, p["w_gate"].astype(y.dtype))
            u = jax.nn.silu(g) * u
        else:
            # same dispatch as the dense trunk: unknown names fail loudly
            # instead of silently running experts with the wrong nonlinearity
            # (gelu_exact Megatron-MoE imports reached this path)
            u = _activation(u, cfg.activation)
        u = constrain(u, P(("data", "zero"), "expert", None, "model"))
        out = jnp.einsum("becf,efd->becd", u, p["w_out"].astype(y.dtype))
        out = self._expert_bias(out, p, "b_out")
        out = constrain(out, P(("data", "zero"), "expert", None, None))

        # combine: (B,S,E,C) x (B,E,C,d) -> (B,S,d)  (the return all-to-all)
        res = jnp.einsum("bsec,becd->bsd", combine.astype(y.dtype), out)
        return res, jnp.mean(aux).astype(jnp.float32)

    def _expert_bias(self, u, p, name):
        if self.cfg.use_bias and name in p:
            return u + p[name][:, None, :].astype(u.dtype)  # (E,f) -> (E,1,f)
        return u

    @staticmethod
    def _bank(p, name, dtype):
        """Dense view of a (possibly int8/int4) expert bank at its point
        of consumption. The decode engine keeps expert banks quantized in
        HBM; the 3-D batched-expert einsum has no Pallas WOQ kernel (yet),
        so the dequant happens per-use inside the decode step — in-scan,
        never hoisted to a whole-bank bf16 copy across steps."""
        w = p[name]
        from ..inference.quantization import QuantizedTensor, dequantize

        if isinstance(w, QuantizedTensor):
            return dequantize(w, dtype)
        return w.astype(dtype)

    # -------------------------------------------------------- inference MoE
    @jax.named_scope("moe_mlp_infer")
    def _mlp_block_infer(self, y, p):
        """Single-group MoE dispatch for the T=1 KV-cache decode step
        (reference ``DeepSpeedMoEInference``,
        ``ops/transformer/inference/moe_inference.py:159``).

        The training dispatch groups tokens per batch row so each group's
        capacity is a static function of S — but at decode T=1 that
        degenerates to ``min_capacity`` slots per row on every expert
        (min_capacity·E× the ideal compute). Decode instead flattens the
        B·1 tokens into ONE routing group with capacity C = B: NO token is
        ever dropped (a decode drop silently zeroes that token's FFN
        contribution, with no training loss to compensate — a generation
        quality bug, not a throughput tradeoff). Compute is E·B·d·f, E/k×
        the routed ideal, but decode is HBM-bandwidth-bound on the expert
        bank read, so the slack compute is hidden; the bench's MBU row
        counts the full bank read for the same reason. Routing decisions
        are per-token and independent of grouping, so the output equals
        the training layer's exactly whenever the training path doesn't
        drop either (the decode parity test pins this). Prefill (T>1)
        keeps the training per-row dispatch — same memory profile as
        training, no B× inflation of the dispatch one-hots."""
        cfg = self.cfg
        B, T, d = y.shape
        E = cfg.num_experts
        tg = B * T
        C = tg
        yt = y.reshape(tg, d)
        logits = yt.astype(jnp.float32) @ p["router"].astype(jnp.float32)
        combine, dispatch, aux = topk_gating(logits, cfg.moe_top_k, C)

        # (tg,E,C) x (tg,d) -> (E,C,d); the expert axis carries the same
        # all-to-all the training path's constraint emits.
        xs = jnp.einsum("tec,td->ecd", dispatch.astype(y.dtype), yt)
        xs = constrain(xs, P("expert", None, None))
        u = jnp.einsum("ecd,edf->ecf", xs, self._bank(p, "w_in", y.dtype))
        u = self._expert_bias(u, p, "b_in")
        if cfg.is_glu:
            g = jnp.einsum("ecd,edf->ecf", xs, self._bank(p, "w_gate", y.dtype))
            u = jax.nn.silu(g) * u
        else:
            u = _activation(u, cfg.activation)
        u = constrain(u, P("expert", None, "model"))
        out = jnp.einsum("ecf,efd->ecd", u, self._bank(p, "w_out", y.dtype))
        out = self._expert_bias(out, p, "b_out")
        out = constrain(out, P("expert", None, None))
        res = jnp.einsum("tec,ecd->td", combine.astype(y.dtype), out)
        return res.reshape(B, T, d), aux.astype(jnp.float32)

    # ----------------------------------------------------------------- init
    def init(self, rng) -> dict:
        params = super().init(rng)
        cfg = self.cfg
        d, f, L, E = cfg.d_model, cfg.ffn_dim, cfg.n_layer, cfg.num_experts
        k = iter(jax.random.split(jax.random.fold_in(rng, 1), 8))
        layers = params["layers"]  # base init skips the dense FFN for E > 1

        def dense(key, shape, scale):
            return jax.random.normal(key, shape, jnp.float32) * scale

        layers["router"] = dense(next(k), (L, d, E), 0.02)
        layers["w_in"] = dense(next(k), (L, E, d, f), 1.0 / math.sqrt(d))
        layers["w_out"] = dense(next(k), (L, E, f, d), 1.0 / math.sqrt(2 * L * f))
        if cfg.is_glu:
            layers["w_gate"] = dense(next(k), (L, E, d, f), 1.0 / math.sqrt(d))
        if cfg.use_bias:
            layers["b_in"] = jnp.zeros((L, E, f), jnp.float32)
            layers["b_out"] = jnp.zeros((L, E, d), jnp.float32)
        return params

    # ---------------------------------------------------------------- specs
    def param_specs(self) -> dict:
        specs = super().param_specs()
        layers = specs["layers"]  # base specs skip the dense FFN for E > 1
        layers["router"] = P(None, None, None)
        layers["w_in"] = P(None, "expert", None, "model")
        layers["w_out"] = P(None, "expert", "model", None)
        if self.cfg.is_glu:
            layers["w_gate"] = P(None, "expert", None, "model")
        if self.cfg.use_bias:
            layers["b_in"] = P(None, "expert", "model")
            layers["b_out"] = P(None, "expert", None)
        return specs

    def fp32_param_names(self) -> tuple[str, ...]:
        """Leaf names kept in fp32 by the engine's compute cast (router
        precision governs tie-breaking stability)."""
        return ("router",)
