from .presets import build_model, gpt2, llama2, mixtral, tiny_test
from .transformer import TransformerConfig, TransformerLM

__all__ = ["TransformerConfig", "TransformerLM", "build_model", "gpt2",
           "llama2", "mixtral", "tiny_test"]
