from .pipeline import PipelinedTransformerLM, build_pipeline_model
from .presets import build_model, gpt2, llama2, mixtral, tiny_test
from .transformer import TransformerConfig, TransformerLM

__all__ = ["TransformerConfig", "TransformerLM", "PipelinedTransformerLM",
           "build_model", "build_pipeline_model", "gpt2", "llama2", "mixtral",
           "tiny_test"]
