from .exporter import export_hf_checkpoint, export_state_dict
from .importer import config_from_hf, import_state_dict, load_hf_checkpoint
from .pipeline import PipelinedTransformerLM, build_pipeline_model
from .presets import (bert, bloom, build_model, gpt2, llama2, mixtral, opt,
                      tiny_test)
from .t5 import T5Config, T5Model, t5
from .transformer import TransformerConfig, TransformerLM

__all__ = ["TransformerConfig", "TransformerLM", "PipelinedTransformerLM",
           "T5Config", "T5Model", "t5",
           "build_model", "build_pipeline_model", "gpt2", "llama2", "mixtral",
           "bert", "opt", "bloom", "tiny_test", "load_hf_checkpoint",
           "import_state_dict", "config_from_hf", "export_state_dict",
           "export_hf_checkpoint"]
