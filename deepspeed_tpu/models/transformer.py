"""Unified decoder-only transformer LM (GPT-2 and Llama families).

This is the flagship model the framework trains and serves. Functional style:
``init`` builds a param pytree, ``apply`` is a pure function, ``param_specs``
returns the TP/EP sharding rules as a matching pytree of ``PartitionSpec``.

Design choices that matter on TPU:
- **scan over stacked layers**: every per-layer weight carries a leading
  ``L`` dim and the block runs under ``lax.scan`` — one compiled layer body,
  remat-friendly, and the unit at which ZeRO-3 all-gathers params
  (the compiled analog of the reference fetch coordinator's per-submodule
  gather, ``partitioned_param_coordinator.py:256``).
- **parallelism by constraint**: batch dim sharded over ``(data, expert)``,
  sequence dim over ``seq``, heads/ffn over ``model``. Ulysses sequence
  parallelism (reference ``sequence/layer.py:15-85``, all-to-all that trades
  the sequence shard for a head shard around attention) is expressed as two
  resharding constraints — GSPMD emits the same all-to-alls.
- **MXU-friendly shapes**: weights live in (possibly stacked) 2-D matmul
  layouts, computation in bf16 with fp32 softmax/layernorm accumulations.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..platform.mesh import BATCH_AXES, constrain, current_mesh

B_AXES = BATCH_AXES  # ("data", "zero", "expert")


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    n_kv_head: Optional[int] = None       # < n_head => GQA/MQA (Llama-2-70B style)
    d_model: int = 768
    d_ff: Optional[int] = None            # default 4*d_model (gpt2) / from preset
    max_seq: int = 1024
    # family switches
    pos_embedding: str = "learned"        # "learned" (gpt2/opt) | "rope"
                                          # (llama) | "alibi" (bloom)
    norm: str = "layernorm"               # "layernorm" | "rmsnorm"
    norm_eps: float = 1e-5                # HF llama checkpoints vary (1e-5/1e-6)
    activation: str = "gelu"              # "gelu" | "silu_glu" (llama) | "relu" (opt)
    use_bias: bool = True                 # gpt2 yes, llama no
    tie_embeddings: bool = True
    causal: bool = True                   # False => encoder (BERT family)
    objective: str = "clm"                # "clm" next-token | "mlm" (BERT)
                                          # | "feature" (CLIP text encoder:
                                          # apply() returns hidden states)
    rope_theta: float = 10000.0
    rotary_dim: Optional[int] = None      # partial rotary (GPT-J/NeoX):
                                          # rotate only the first N dims/head
    # parallel residual: x + attn(norm1(x)) + mlp(norm_mlp(x)) in one hop
    # (GPT-J / GPT-NeoX / Falcon) instead of the sequential two-hop block.
    parallel_residual: bool = False
    # GPT-J / Falcon-7B share ONE layernorm for both branches (norm_mlp =
    # norm1); NeoX / Falcon-40B keep a second one.
    parallel_shared_ln: bool = False
    embed_norm: bool = False              # Bloom word_embeddings_layernorm
    lm_head_bias: bool = False            # GPT-J lm_head has a bias
    # >1: compute the unembedding matmul as a scan over that many vocab
    # column tiles (ops/tiled.py; reference zero/tiling.py TiledLinear) —
    # bounds the logits working set of a giant-vocab head on the XLA loss
    # path. The fused-xent path never materializes logits and ignores this.
    tiled_head: int = 1
    # post-LN block (BERT family): x = LN(x + attn(x)); x = LN(x + mlp(x)).
    # The norm params keep their pre-LN names: ln1 = post-attention LN,
    # ln2 = post-FFN LN; no final lnf exists.
    post_ln: bool = False
    # BERT MLM head transform: LN(gelu(x @ W + b)) before the tied decoder
    # (+ output bias). Only meaningful with objective="mlm".
    mlm_transform: bool = False
    # Fused Pallas softmax-xent over the unembedding (ops/xent.py): never
    # materializes (B,S,V) logits. None = auto (on for TPU when eligible:
    # tied embeddings, clm/mlm, seq/pipe axes unsharded; data-parallel
    # and vocab-sharded TP meshes both supported via shard_map).
    fused_xent: Optional[bool] = None
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16             # compute dtype
    # MoE (dense when num_experts == 1); see models/moe.py
    num_experts: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: Optional[float] = None  # eval default: 2x train
    moe_min_capacity: int = 4
    moe_drop_tokens: bool = True          # False: capacity covers ALL tokens
    moe_aux_loss_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head

    @property
    def ffn_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def is_glu(self) -> bool:
        return self.activation.endswith("glu")

    def flops_per_token(self) -> float:
        """Fwd+bwd model FLOPs per token for MFU accounting (Megatron
        convention): 6*N_active trunk matmul FLOPs + the attention
        score/value term (12*L*d*S) + the output-logit projection
        (6*d*V) — the unembedding is a real (B*S, d) x (d, V) matmul on
        the MXU, so omitting it (as pure-6N accounting does) under-reports
        achieved FLOPs; Megatron's model-FLOPs formula includes the logit
        layer explicitly. The token-embedding *lookup* is a gather, not a
        matmul, and stays excluded.

        For MoE only the ``moe_top_k`` routed experts do work per token, so
        FLOPs use the *active* parameter count, not the total bank size."""
        n_params = self.param_count(non_embedding=True, active_only=True)
        attn = 12 * self.n_layer * self.d_model * self.max_seq
        head = (0 if self.objective == "feature"
                else 6 * self.d_model * self.vocab_size)
        return 6 * n_params + attn + head

    def _ffn_params_per_layer(self, active_only: bool = False) -> int:
        d, f, E = self.d_model, self.ffn_dim, self.num_experts
        per_expert = d * f * (3 if self.is_glu else 2)
        if E == 1:
            return per_expert
        router = d * E
        mult = min(self.moe_top_k, E) if active_only else E
        return router + mult * per_expert

    def param_count(self, non_embedding: bool = False,
                    active_only: bool = False) -> int:
        d, L = self.d_model, self.n_layer
        h, kv, hd = self.n_head, self.kv_heads, self.head_dim
        per_layer = d * (h * hd) + 2 * d * (kv * hd) + (h * hd) * d
        per_layer += self._ffn_params_per_layer(active_only=active_only)
        emb = self.vocab_size * d
        total = L * per_layer + (emb if not non_embedding else 0)
        if (not self.tie_embeddings and not non_embedding
                and self.objective != "feature"):
            total += emb
        return total


# ------------------------------------------------------------------ helpers
def _norm(x, scale, bias, kind: str, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    if kind == "rmsnorm":
        y = xf * lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    else:
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
        y = (xf - mu) * lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def _rope(q, k, positions, theta: float, rotary_dim: int | None = None):
    """Rotary embeddings on (B, S, H, hd) q/k (interleaved-pair basis).

    ``rotary_dim`` < head_dim rotates only the leading dims of each head
    (GPT-J's ``rotary_dim``, NeoX's ``rotary_pct``); the tail passes through.
    Frequencies are computed over ``rotary_dim``, matching those models.
    """
    hd = q.shape[-1]
    rd = rotary_dim or hd
    freqs = 1.0 / (theta ** (jnp.arange(0, rd, 2, dtype=jnp.float32) / rd))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, rd/2)
    cos, sin = jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]

    def rot(x):
        xr, xp = x[..., :rd], x[..., rd:]
        x1, x2 = xr[..., ::2], xr[..., 1::2]
        xr1 = x1 * cos - x2 * sin
        xr2 = x2 * cos + x1 * sin
        out = jnp.stack([xr1, xr2], axis=-1).reshape(xr.shape)
        return jnp.concatenate([out, xp], axis=-1) if rd < hd else out

    return (rot(q.astype(jnp.float32)).astype(q.dtype),
            rot(k.astype(jnp.float32)).astype(k.dtype))


def _activation(u, name: str):
    """Named activation; unknown names fail loudly (a silent silu fallback
    once imported gelu_new checkpoints with the wrong nonlinearity)."""
    if name == "gelu":
        return jax.nn.gelu(u)                      # tanh approx (gelu_new)
    if name == "gelu_exact":
        return jax.nn.gelu(u, approximate=False)   # erf gelu
    if name == "relu":
        return jax.nn.relu(u)
    if name in ("silu", "swish"):
        return jax.nn.silu(u)
    if name == "quick_gelu":
        return u * jax.nn.sigmoid(1.702 * u)       # CLIP's sigmoid approx
    raise ValueError(f"unknown activation {name!r}")


def vocab_parallel_lookup(table, ids):
    """Vocab-parallel embedding lookup (shared by every trunk).

    Embedding tables are vocab-sharded over ``model`` (``param_specs``); a
    plain gather there makes GSPMD replicate the whole table
    ("involuntary full rematerialization", ``spmd_partitioner.cc:652`` —
    the round-2 dryrun regression). The TPU-native fix is Megatron's
    vocab-parallel lookup: each shard gathers its own vocab range, masks
    foreign ids to zero, and one psum over ``model`` assembles the rows —
    activation-sized traffic instead of table-sized.
    """
    ctx = current_mesh()
    from ..platform.mesh import manual_axes_of
    manual = manual_axes_of(ctx) if ctx is not None else frozenset()
    if (ctx is None or "model" not in getattr(ctx, "axis_names", ())
            or ctx.shape["model"] == 1 or manual):
        return table[ids]

    def lookup(tbl, idx):
        v_local = tbl.shape[0]
        local = idx - lax.axis_index("model") * v_local
        ok = (local >= 0) & (local < v_local)
        rows = tbl[jnp.clip(local, 0, v_local - 1)]
        rows = jnp.where(ok[..., None], rows, jnp.zeros((), rows.dtype))
        return lax.psum(rows, "model")

    # Fully-manual region (partial-manual psum trips an XLA partitioner
    # CHECK on composed meshes): batch/seq stay sharded as in the trunk,
    # the table enters model-sharded on vocab with full embedding rows.
    fn = jax.shard_map(lookup, mesh=ctx,
                       in_specs=(P("model", None), P(B_AXES, "seq")),
                       out_specs=P(B_AXES, "seq", None))
    return fn(table, ids)


def alibi_slopes(n_head: int) -> jnp.ndarray:
    """Standard ALiBi per-head slopes (Bloom; geometric in 2^(-8/n))."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_head).is_integer():
        slopes = pow2_slopes(n_head)
    else:
        closest = 2 ** math.floor(math.log2(n_head))
        slopes = pow2_slopes(closest)
        extra = pow2_slopes(2 * closest)[0::2][:n_head - closest]
        slopes += extra
    return jnp.asarray(slopes, jnp.float32)


def alibi_bias(slopes, S: int) -> jnp.ndarray:
    """Dense (H, S, S) ALiBi distance bias: slope·(key_pos − query_pos).
    ONE definition of the ramp convention — the flash/ring/decode kernels
    rebuild the same ramp from positions instead of taking this tensor
    (it is O(S²); only the dense fallbacks materialize it)."""
    rel = (jnp.arange(S)[None, :] - jnp.arange(S)[:, None])
    return (jnp.asarray(slopes, jnp.float32)[:, None, None]
            * rel[None].astype(jnp.float32))


def _token_nll_impl(logits, targets):
    """Per-token NLL in fp32 without materializing a (B, S, V) fp32 tensor:
    nll = logsumexp(logits) - logit[target]. The bf16→fp32 cast and exp
    fuse into a single reduction pass over V (log_softmax + take_along_axis
    instead writes the full fp32 log-probability cube — ~2x the head's HBM
    traffic at GPT-2 vocab sizes)."""
    m = jnp.max(logits, axis=-1, keepdims=True)
    se = jnp.sum(jnp.exp((logits - m).astype(jnp.float32)), axis=-1)
    lse = jnp.log(se) + m[..., 0].astype(jnp.float32)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return lse - tgt.astype(jnp.float32)


# checkpoint: the backward recomputes exp(shifted) fused into the
# d_logits = softmax - onehot epilogue instead of saving it as a resident
# (B, S, V) fp32 tensor between forward and backward.
_token_nll = jax.checkpoint(_token_nll_impl)


def mesh_dp_world(mesh) -> int:
    """Product of the batch (token-sharding) axes of a mesh."""
    return int(math.prod(mesh.shape[a] for a in BATCH_AXES
                         if a in mesh.axis_names))


def fused_nll_sharded(feats, targets, table, bias=None):
    """(B, S', D) features + (B, S') targets → (B, S') fp32 NLL via the
    fused Pallas kernel (ops/xent.py), shard_mapped over the batch axes
    when data-parallel and over the model axis (vocab-sharded variant)
    when tensor-parallel. ``table`` is the (V, D) unembedding in
    embedding layout; shared by the decoder trunk's and T5's loss paths."""
    from ..ops.xent import fused_token_nll, fused_token_nll_tp

    B, S, dm = feats.shape
    h2 = feats.reshape(B * S, dm)
    t2 = targets.reshape(B * S).astype(jnp.int32)
    mesh = current_mesh()
    in_mesh = mesh is not None and not mesh.empty
    dp = mesh_dp_world(mesh) if in_mesh else 1
    tp = int(mesh.shape.get("model", 1)) if in_mesh else 1
    if dp > 1 or tp > 1:
        has_b = bias is not None

        def body(h, w, *rest):
            b, t = rest if has_b else (None, rest[0])
            if tp > 1:
                return fused_token_nll_tp(h, w, b, t, "model")
            return fused_token_nll(h, w, b, t)

        # Specs name only axes the mesh actually carries: a user-built
        # mesh with, say, just a "data" axis still takes the fused path
        # instead of crashing on an unknown axis name (advisor r3). tp > 1
        # implies "model" exists (tp is read off the mesh above).
        b_axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names) or None
        mdl = "model" if "model" in mesh.axis_names else None
        in_specs = ((P(b_axes, None), P(mdl, None))
                    + ((P(mdl),) if has_b else ()) + (P(b_axes),))
        args = (h2, table) + ((bias,) if has_b else ()) + (t2,)
        nll2 = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=P(b_axes), check_vma=False)(*args)
    else:
        nll2 = fused_token_nll(h2, table, bias, t2)
    return nll2.reshape(B, S)


def causal_attention(q, k, v, *, mask: jnp.ndarray | None = None,
                     causal: bool = True, bias: jnp.ndarray | None = None):
    """Plain attention, fp32 softmax. q:(B,S,H,hd) k/v:(B,S,KV,hd).

    ``causal=False`` = bidirectional (encoder); ``bias`` is an additive
    score bias, shape (S, S), (H, S, S) (ALiBi) or (B|1, H|1, S, S)
    (evoformer pair bias) — broadcast gradients flow correctly through the
    ``broadcast_to``. Heads are grouped for GQA by repeating kv. The
    Pallas flash kernel (ops/flash_attention.py) replaces this on TPU for
    long sequences.
    """
    B, S, H, hd = q.shape
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) / math.sqrt(hd)
    if bias is not None:
        b4 = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
        scores = scores + jnp.broadcast_to(b4, scores.shape).astype(jnp.float32)
    big_neg = jnp.finfo(jnp.float32).min
    if causal:
        tri = jnp.tril(jnp.ones((S, S), dtype=bool))
        scores = jnp.where(tri[None, None, :, :], scores, big_neg)
    if mask is not None:  # (B, S) padding mask on keys
        scores = jnp.where(mask[:, None, None, :].astype(bool), scores, big_neg)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


# -------------------------------------------------------------------- model
class TransformerLM:
    """init/apply/param_specs over a :class:`TransformerConfig`."""

    def __init__(self, config: TransformerConfig, attention_fn=None):
        self.cfg = config
        if attention_fn is not None and not config.causal:
            raise ValueError(
                "encoder (causal=False) configs require the default "
                "attention: the flash/sparse/Ulysses attention_fns apply a "
                "causal mask and would silently break bidirectionality")
        if attention_fn is not None and config.pos_embedding == "alibi" \
                and not (getattr(attention_fn, "accepts_bias", False)
                         or getattr(attention_fn, "accepts_alibi_slopes",
                                    False)):
            raise ValueError(
                "alibi needs an additive score bias; this attention_fn "
                "accepts neither a bias nor alibi slopes (flash and ring "
                "attention do; sparse/Ulysses still do not)")
        self.attention_fn = attention_fn or partial(causal_attention,
                                                    causal=config.causal)

    # ----------------------------------------------------------------- init
    def init(self, rng) -> dict:
        cfg = self.cfg
        d, f, L = cfg.d_model, cfg.ffn_dim, cfg.n_layer
        h, kv, hd = cfg.n_head, cfg.kv_heads, cfg.head_dim
        k = iter(jax.random.split(rng, 16))

        def dense(key, shape, scale=None):
            scale = scale or (1.0 / math.sqrt(shape[-2] if len(shape) > 1 else shape[-1]))
            return (jax.random.normal(key, shape, jnp.float32) * scale)

        dense_ffn = cfg.num_experts == 1  # MoE trunks build expert banks instead
        two_ln = not (cfg.parallel_residual and cfg.parallel_shared_ln)
        layers = {
            "ln1_scale": jnp.ones((L, d), jnp.float32),
            "wq": dense(next(k), (L, d, h * hd)),
            "wk": dense(next(k), (L, d, kv * hd)),
            "wv": dense(next(k), (L, d, kv * hd)),
            "wo": dense(next(k), (L, h * hd, d), scale=1.0 / math.sqrt(2 * L * d)),
        }
        if two_ln:
            layers["ln2_scale"] = jnp.ones((L, d), jnp.float32)
        if dense_ffn:
            layers["w_in"] = dense(next(k), (L, d, f))
            layers["w_out"] = dense(next(k), (L, f, d), scale=1.0 / math.sqrt(2 * L * f))
            if cfg.is_glu:
                layers["w_gate"] = dense(next(k), (L, d, f))
        if cfg.use_bias:
            layers.update({
                "ln1_bias": jnp.zeros((L, d), jnp.float32),
                "bq": jnp.zeros((L, h * hd), jnp.float32),
                "bk": jnp.zeros((L, kv * hd), jnp.float32),
                "bv": jnp.zeros((L, kv * hd), jnp.float32),
                "bo": jnp.zeros((L, d), jnp.float32),
            })
            if two_ln:
                layers["ln2_bias"] = jnp.zeros((L, d), jnp.float32)
            if dense_ffn:
                layers["b_in"] = jnp.zeros((L, f), jnp.float32)
                layers["b_out"] = jnp.zeros((L, d), jnp.float32)
        params = {
            "tok_embed": jax.random.normal(next(k), (cfg.vocab_size, d), jnp.float32) * 0.02,
            "layers": layers,
        }
        if not cfg.post_ln:
            params["lnf_scale"] = jnp.ones((d,), jnp.float32)
        if cfg.pos_embedding == "learned":
            params["pos_embed"] = jax.random.normal(next(k), (cfg.max_seq, d),
                                                    jnp.float32) * 0.02
        if cfg.use_bias and not cfg.post_ln:
            params["lnf_bias"] = jnp.zeros((d,), jnp.float32)
        if cfg.mlm_transform:
            params["mlm_dense_w"] = dense(next(k), (d, d))
            params["mlm_dense_b"] = jnp.zeros((d,), jnp.float32)
            params["mlm_ln_scale"] = jnp.ones((d,), jnp.float32)
            params["mlm_ln_bias"] = jnp.zeros((d,), jnp.float32)
        if cfg.embed_norm:
            params["embed_ln_scale"] = jnp.ones((d,), jnp.float32)
            if cfg.use_bias:
                params["embed_ln_bias"] = jnp.zeros((d,), jnp.float32)
        if cfg.lm_head_bias:
            params["lm_head_bias"] = jnp.zeros((cfg.vocab_size,), jnp.float32)
        if not cfg.tie_embeddings and cfg.objective != "feature":
            params["lm_head"] = dense(next(k), (d, cfg.vocab_size), scale=0.02)
        return params

    # ---------------------------------------------------------------- specs
    def param_specs(self) -> dict:
        """TP (Megatron-style) sharding over the ``model`` axis:
        qkv/w_in column-split, wo/w_out row-split, embeddings vocab-split."""
        cfg = self.cfg
        dense_ffn = cfg.num_experts == 1
        two_ln = not (cfg.parallel_residual and cfg.parallel_shared_ln)
        layers = {
            "ln1_scale": P(None, None),
            "wq": P(None, None, "model"),
            "wk": P(None, None, "model"),
            "wv": P(None, None, "model"),
            "wo": P(None, "model", None),
        }
        if two_ln:
            layers["ln2_scale"] = P(None, None)
        if dense_ffn:
            layers["w_in"] = P(None, None, "model")
            layers["w_out"] = P(None, "model", None)
            if cfg.is_glu:
                layers["w_gate"] = P(None, None, "model")
        if cfg.use_bias:
            layers.update({
                "ln1_bias": P(None, None),
                "bq": P(None, "model"), "bk": P(None, "model"), "bv": P(None, "model"),
                "bo": P(None, None),
            })
            if two_ln:
                layers["ln2_bias"] = P(None, None)
            if dense_ffn:
                layers["b_in"] = P(None, "model")
                layers["b_out"] = P(None, None)
        specs = {
            "tok_embed": P("model", None),
            "layers": layers,
        }
        if not cfg.post_ln:
            specs["lnf_scale"] = P(None)
        if cfg.pos_embedding == "learned":
            specs["pos_embed"] = P(None, None)
        if cfg.use_bias and not cfg.post_ln:
            specs["lnf_bias"] = P(None)
        if cfg.mlm_transform:
            specs["mlm_dense_w"] = P(None, None)
            specs["mlm_dense_b"] = P(None)
            specs["mlm_ln_scale"] = P(None)
            specs["mlm_ln_bias"] = P(None)
        if cfg.embed_norm:
            specs["embed_ln_scale"] = P(None)
            if cfg.use_bias:
                specs["embed_ln_bias"] = P(None)
        if not cfg.tie_embeddings and cfg.objective != "feature":
            specs["lm_head"] = P(None, "model")
        if cfg.lm_head_bias:
            specs["lm_head_bias"] = P("model")
        return specs

    def stacked_fn(self):
        """Which param shapes are layer-stacked (leading scan dim)."""
        L = self.cfg.n_layer

        def is_stacked(shape) -> bool:
            return len(shape) >= 2 and shape[0] == L

        return is_stacked

    # ---------------------------------------------------------------- apply
    def _maybe_bias(self, y, p, name):
        return y + p[name].astype(y.dtype) if self.cfg.use_bias and name in p else y

    @jax.named_scope("attn")
    def _attention_block(self, x, p, positions, attn_mask):
        """Shared attention half of a layer (dense and MoE trunks)."""
        cfg = self.cfg
        B, S, d = x.shape
        h, kv, hd = cfg.n_head, cfg.kv_heads, cfg.head_dim
        y = x if cfg.post_ln else _norm(x, p["ln1_scale"], p.get("ln1_bias"),
                                        cfg.norm, cfg.norm_eps)
        q = self._maybe_bias(y @ p["wq"].astype(y.dtype), p, "bq").reshape(B, S, h, hd)
        kk = self._maybe_bias(y @ p["wk"].astype(y.dtype), p, "bk").reshape(B, S, kv, hd)
        vv = self._maybe_bias(y @ p["wv"].astype(y.dtype), p, "bv").reshape(B, S, kv, hd)
        if cfg.pos_embedding == "rope":
            q, kk = _rope(q, kk, positions, cfg.rope_theta, cfg.rotary_dim)
        attn_kw = {}
        if cfg.pos_embedding == "alibi":
            # ALiBi (Bloom): linear distance bias on the scores instead of
            # any positional embedding. Attention fns that take slopes
            # build the ramp themselves (flash: in-kernel from block
            # indices; ring: from the global ring-step positions) — no
            # (H, S, S) bias ever materializes, which is what makes ALiBi
            # long-context viable; the dense path gets the explicit bias.
            if getattr(self.attention_fn, "accepts_alibi_slopes", False):
                attn_kw["alibi_slopes"] = alibi_slopes(h)
            else:
                attn_kw["bias"] = alibi_bias(alibi_slopes(h), S)
        if getattr(self.attention_fn, "handles_sharding", False):
            # Explicit-collective attention (sequence/layer.py Ulysses or
            # ring): the wrapper does its own shard_map resharding.
            o = self.attention_fn(q, kk, vv, mask=attn_mask, **attn_kw)
        else:
            # Ulysses via GSPMD: trade the sequence shard for a head shard
            # around attention (reference sequence/layer.py all_to_all pair).
            qs = constrain(q, P(B_AXES, None, ("model", "seq"), None))
            ks = constrain(kk, P(B_AXES, None, None, None)) \
                if kv < h else constrain(kk, P(B_AXES, None, ("model", "seq"), None))
            vs = constrain(vv, P(B_AXES, None, None, None)) \
                if kv < h else constrain(vv, P(B_AXES, None, ("model", "seq"), None))
            o = self.attention_fn(qs, ks, vs, mask=attn_mask, **attn_kw)
            o = constrain(o, P(B_AXES, "seq", "model", None))
        o = self._maybe_bias(o.reshape(B, S, h * hd) @ p["wo"].astype(x.dtype), p, "bo")
        return o

    def _proj(self, y, p, name):
        """``y @ p[name]`` whether the weight is dense or int8/int4
        (inference WOQ: the engine keeps weights quantized end-to-end and
        the decode step consumes them at the point of use — via the fused
        Pallas GEMM when ``self.woq_kernel`` is set, else a per-use XLA
        dequant). Training trees never carry quantized leaves, so this is
        a plain matmul there."""
        w = p[name]
        from ..inference.quantization import QuantizedTensor, woq_dot

        if isinstance(w, QuantizedTensor):
            return woq_dot(y, w, use_kernel=getattr(self, "woq_kernel",
                                                    False))
        return y @ w.astype(y.dtype)

    @jax.named_scope("mlp")
    def _mlp_block(self, y, p):
        """FFN half. Returns (out, aux_loss); MoE trunks override this.

        NOTE: ``inference/decode.py _mlp_tp_quant`` mirrors this math
        with the w_out psum quantized (tp_comm_quant) — a change to the
        activation/gate/bias sequence here must be mirrored there or the
        quantized-TP greedy-parity oracle breaks for knob-on users."""
        cfg = self.cfg
        u = self._maybe_bias(self._proj(y, p, "w_in"), p, "b_in")
        if cfg.is_glu:
            # GLU: tag the gated product — bwd still recomputes the gate
            # matmul for the silu grad, but w_out's input is saved
            u = jax.nn.silu(self._proj(y, p, "w_gate")) * u
            u = checkpoint_name(u, "mlp_h")
        else:
            # Tag the PRE-activation: under save_names_mlp the bwd then
            # recomputes only the elementwise nonlinearity (for both the
            # activation grad and w_out's input) — the w_in matmul, the
            # largest single dot in the layer, is never recomputed
            u = checkpoint_name(u, "mlp_h")
            u = _activation(u, cfg.activation)
        u = constrain(u, P(B_AXES, "seq", "model"))
        out = self._maybe_bias(self._proj(u, p, "w_out"), p, "b_out")
        return out, jnp.float32(0.0)

    def _layer(self, x, layer_params, positions, attn_mask):
        cfg = self.cfg
        p = layer_params
        # Remat-policy anchors (reference cpu_checkpointing,
        # activation_checkpointing/checkpointing.py:1036): under the
        # engine's "offload_dots" policy these two names — the residual
        # stream entering the layer and the projected attention output —
        # are offloaded to pinned host memory during the forward and
        # fetched back in the backward, instead of being kept in HBM
        # (dots_saveable) or recomputed (full remat: for attn_out that
        # means redoing the whole S^2 attention). Under any other policy
        # checkpoint_name is an identity.
        x = checkpoint_name(x, "layer_in")
        o = self._attention_block(x, p, positions, attn_mask)
        o = checkpoint_name(o, "attn_out")
        if cfg.post_ln:
            # BERT block: norms AFTER each residual; FFN input is the
            # post-attention-LN output directly
            x = _norm(x + o, p["ln1_scale"], p.get("ln1_bias"),
                      cfg.norm, cfg.norm_eps)
            out, aux = self._mlp_block(x, p)
            x = _norm(x + out, p["ln2_scale"], p.get("ln2_bias"),
                      cfg.norm, cfg.norm_eps)
            return constrain(x, P(B_AXES, "seq", None)), aux
        if cfg.parallel_residual:
            # x + attn(n1(x)) + mlp(n(x)) — GPT-J/NeoX/Falcon block shape;
            # shared_ln reuses n1 (XLA CSEs the recompute with the one
            # inside the attention branch).
            ln = ("ln1" if cfg.parallel_shared_ln else "ln2")
            y = _norm(x, p[f"{ln}_scale"], p.get(f"{ln}_bias"),
                      cfg.norm, cfg.norm_eps)
            out, aux = self._mlp_block(y, p)
            x = x + o + out
        else:
            x = x + o
            y = _norm(x, p["ln2_scale"], p.get("ln2_bias"),
                      cfg.norm, cfg.norm_eps)
            out, aux = self._mlp_block(y, p)
            x = x + out
        return constrain(x, P(B_AXES, "seq", None)), aux

    def _tok_lookup(self, table, ids):
        return vocab_parallel_lookup(table, ids)

    @staticmethod
    def _positions(B: int, S: int):
        return jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))

    @jax.named_scope("embed")
    def _embed(self, params, input_ids):
        """(B, S) int32 → ((B, S, D) embeddings, (B, S) positions)."""
        cfg = self.cfg
        B, S = input_ids.shape
        x = self._tok_lookup(params["tok_embed"].astype(cfg.dtype), input_ids)
        positions = self._positions(B, S)
        if cfg.pos_embedding == "learned":
            x = x + params["pos_embed"].astype(cfg.dtype)[positions[0]][None]
        if cfg.embed_norm:
            # Bloom word_embeddings_layernorm
            x = _norm(x, params["embed_ln_scale"], params.get("embed_ln_bias"),
                      cfg.norm, cfg.norm_eps)
        return constrain(x, P(B_AXES, "seq", None)), positions

    def _scan_layers(self, x, layers, positions, attn_mask, remat_policy):
        """Scan the (remat-wrapped) layer body over a stacked layer pytree.

        ``layers`` may be the full stack or (under pipeline shard_map) the
        local stage's slice. Returns (x, summed aux losses).

        When ``self.params_on_host`` is set (ZeRO-Infinity param offload,
        reference ``runtime/swap_tensor/partitioned_param_swapper.py:36``),
        the stacked weights live in pinned host memory and each scan step
        copies its layer slice into device HBM right before use — XLA's
        latency-hiding scheduler overlaps the next slice's DMA with the
        current layer's compute, so HBM only ever holds ~2 layers of weights.
        """
        body = partial(self._layer, positions=positions, attn_mask=attn_mask)
        if remat_policy is not None:
            body = jax.checkpoint(body, policy=remat_policy, prevent_cse=False)
        stream = getattr(self, "params_on_host", False)
        if stream:
            from ..platform.mesh import to_device_memory

            specs = self.param_specs()["layers"]
            slice_specs = jax.tree.map(
                lambda s: P(*tuple(s)[1:]), specs,
                is_leaf=lambda x: isinstance(x, P))

        def scan_fn(carry, layer_params):
            if stream:
                layer_params = to_device_memory(layer_params, slice_specs)
            new_x, aux = body(carry, layer_params)
            return new_x, aux

        x, aux_losses = lax.scan(scan_fn, x, layers)
        return x, jnp.sum(aux_losses)

    def _head_norm(self, params, x):
        """Final layernorm only (the pipeline's vocab-sharded head applies
        its own unembedding slice). Post-LN trunks have no final norm —
        each block already ends normalized."""
        if self.cfg.post_ln:
            return x
        return _norm(x, params["lnf_scale"], params.get("lnf_bias"),
                     self.cfg.norm, self.cfg.norm_eps)

    def _pre_head(self, params, x):
        """Final norm + (BERT) MLM transform: everything before the
        unembedding matmul — shared by the logits head and the fused-xent
        loss path."""
        cfg = self.cfg
        x = self._head_norm(params, x)
        if cfg.mlm_transform:
            # BERT cls.predictions.transform: dense + hidden_act + LN before
            # the tied decoder (HF uses config.hidden_act here too); output
            # bias added by the head / fused kernel via lm_head_bias
            x = _activation(x @ params["mlm_dense_w"].astype(x.dtype)
                            + params["mlm_dense_b"].astype(x.dtype),
                            cfg.activation)
            x = _norm(x, params["mlm_ln_scale"], params.get("mlm_ln_bias"),
                      cfg.norm, cfg.norm_eps)
        return x

    @jax.named_scope("lm_head")
    def _head(self, params, x):
        """Final norm + unembedding: (B, S, D) → (B, S, V) logits."""
        cfg = self.cfg
        x = self._pre_head(params, x)
        w = (params["tok_embed"].astype(x.dtype).T if cfg.tie_embeddings
             else params["lm_head"].astype(x.dtype))
        if cfg.tiled_head > 1 and w.shape[1] % cfg.tiled_head == 0:
            from ..ops.tiled import tiled_matmul

            logits = tiled_matmul(x, w, cfg.tiled_head)
        else:
            logits = x @ w
        if cfg.lm_head_bias:
            logits = logits + params["lm_head_bias"].astype(logits.dtype)
        return constrain(logits, P(B_AXES, "seq", "model"))

    def sparse_grad_names(self) -> tuple[str, ...]:
        """Param leaves whose gradient is row-sparse in the batch's tokens
        (the engine's ``sparse_gradients`` offload-D2H compression,
        reference ``sparse_allreduce`` engine.py:2427). ONLY the untied
        input embedding qualifies: a tied table also receives the
        unembedding's softmax gradient, which is dense over the vocab —
        top-k row selection there would silently drop gradient mass."""
        return () if self.cfg.tie_embeddings else ("tok_embed",)

    def _trunk(self, params, input_ids, attn_mask, remat_policy):
        """Embed + layer stack: (B, S) → ((B, S, D) pre-final-norm, aux)."""
        x, positions = self._embed(params, input_ids)
        return self._scan_layers(x, params["layers"], positions, attn_mask,
                                 remat_policy)

    def apply(self, params, input_ids, *, attn_mask=None, remat_policy=None,
              return_aux: bool = False):
        """Forward: (B, S) int32 → (B, S, V) logits (compute dtype), or
        (B, S, D) final-norm hidden states for ``objective='feature'``."""
        x, aux = self._trunk(params, input_ids, attn_mask, remat_policy)
        if self.cfg.objective == "feature":
            # Feature extractor (CLIP text tower): no unembedding exists;
            # the product is the final-norm hidden states (B, S, D).
            out = self._head_norm(params, x)
        else:
            out = self._head(params, x)
        if return_aux:
            return out, aux
        return out

    # ----------------------------------------------------------------- loss
    def loss(self, params, batch, *, remat_policy=None):
        """Objective-dependent cross-entropy, fp32, mean over counted tokens,
        plus the MoE load-balancing aux loss when the trunk routes.

        ``clm``: next-token over (possibly loss-masked) positions.
        ``mlm`` (encoder / BERT): predict ``batch['labels']`` at the
        positions marked by ``batch['loss_mask']`` — no shift."""
        if self.cfg.objective == "feature":
            raise ValueError(
                "objective='feature' models have no unembedding/LM loss; "
                "train them under a task head (apply() gives hidden states)")
        ids = batch["input_ids"]
        mlm = self.cfg.objective == "mlm"
        B, S = ids.shape
        if self._fused_xent_active(
                batch_size=B, compute_dtype=params["tok_embed"].dtype):
            x, aux = self._trunk(params, ids, batch.get("attention_mask"),
                                 remat_policy)
            feats = self._pre_head(params, x)
            if mlm:
                nll = self._fused_nll(params, feats, batch["labels"])
            else:
                nll = self._fused_nll(params, feats[:, :-1], ids[:, 1:])
        else:
            logits, aux = self.apply(params, ids,
                                     attn_mask=batch.get("attention_mask"),
                                     remat_policy=remat_policy,
                                     return_aux=True)
            if mlm:
                nll = _token_nll(logits, batch["labels"])
            else:
                nll = _token_nll(logits[:, :-1], ids[:, 1:])
        mask = batch["loss_mask"] if mlm else batch.get("loss_mask")
        if mask is not None:
            mask = (mask if mlm else mask[:, 1:]).astype(jnp.float32)
            ce = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        else:
            ce = jnp.mean(nll)
        if self.cfg.num_experts > 1:
            ce = ce + self.cfg.moe_aux_loss_weight * aux
        return ce

    def _fused_xent_active(self, batch_size: Optional[int] = None,
                           compute_dtype=None) -> bool:
        """Route the loss through the fused Pallas softmax-xent kernel?
        Auto (fused_xent=None): on for TPU when the head is expressible —
        tied embeddings (W stays in (V, d) table layout, no transpose) and
        no seq/pipe sharding (the kernel runs per data shard under
        shard_map; a seq-sharded head keeps the XLA path; model-axis
        sharding takes the vocab-sharded TP kernel). A batch size not
        divisible by the data-parallel world also keeps the XLA path:
        shard_map would split the flattened rows mid-sequence, which is
        numerically fine (the kernel is per-token) but forces a resharding
        gather against the batch-sharded feature layout right in the hot
        loss path — and partial eval batches must not start erroring
        because the fused path auto-activated."""
        cfg = self.cfg
        if cfg.fused_xent is False or not cfg.tie_embeddings \
                or cfg.objective not in ("clm", "mlm"):
            return False
        # hardware eligibility (f16-on-TPU, VMEM at wide d): ops/xent.py
        from ..ops.xent import fused_xent_eligible

        if not fused_xent_eligible(cfg.dtype, compute_dtype, cfg.d_model):
            return False
        mesh = current_mesh()
        if mesh is not None and not mesh.empty:
            from ..platform.mesh import manual_axes_of
            if manual_axes_of(mesh):
                return False
            for ax in ("seq", "pipe"):
                if ax in mesh.axis_names and mesh.shape[ax] != 1:
                    return False
            # model-axis sharding IS supported (vocab-sharded TP kernel:
            # per-shard partials + two collectives) when the vocab splits
            # evenly across the axis
            tp = int(mesh.shape.get("model", 1))
            if tp > 1 and cfg.vocab_size % tp != 0:
                return False
            if batch_size is not None \
                    and batch_size % self._dp_world(mesh) != 0:
                return False
        if cfg.fused_xent:
            return True
        return jax.default_backend() == "tpu"

    @staticmethod
    def _dp_world(mesh) -> int:
        return mesh_dp_world(mesh)

    def _fused_nll(self, params, feats, targets):
        cfg = self.cfg
        bias = (params["lm_head_bias"].astype(feats.dtype)
                if cfg.lm_head_bias else None)
        return fused_nll_sharded(feats, targets,
                                 params["tok_embed"].astype(feats.dtype),
                                 bias)
