from .logging import log_dist, logger, print_rank_0
from .timer import ThroughputTimer, WallClockTimers, peak_flops_for

__all__ = ["logger", "log_dist", "print_rank_0", "WallClockTimers",
           "ThroughputTimer", "peak_flops_for"]
