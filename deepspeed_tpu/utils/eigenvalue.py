"""Hessian eigenvalue estimation by power iteration.

Analog of the reference's ``runtime/eigenvalue.py:149`` (power iteration on
the loss curvature, used to rank layers for MoQ precision switching —
``engine.py:2116-2127``). The torch version differentiates twice through
retained graphs; in JAX the Hessian-vector product is one
``jvp``-of-``grad`` composition, jittable end to end.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def _tree_dot(a, b):
    return sum(jnp.vdot(x, y) for x, y in
               zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _tree_norm(a):
    return jnp.sqrt(jnp.real(_tree_dot(a, a)))


def max_eigenvalue(loss_fn: Callable, params, *, iters: int = 10,
                   seed: int = 0, tol: float = 0.0):
    """Dominant Hessian eigenvalue of ``loss_fn(params)`` via power
    iteration. Returns (eigenvalue, eigenvector pytree)."""
    grad_fn = jax.grad(loss_fn)

    def hvp(v):
        return jax.jvp(grad_fn, (params,), (v,))[1]

    keys = jax.random.split(jax.random.PRNGKey(seed),
                            len(jax.tree.leaves(params)))
    flat, treedef = jax.tree.flatten(params)
    v = treedef.unflatten([jax.random.normal(k, p.shape, jnp.float32)
                           for k, p in zip(keys, flat)])
    n0 = _tree_norm(v)
    v = jax.tree.map(lambda x: x / n0, v)

    eig = jnp.float32(0.0)
    for _ in range(iters):
        hv = hvp(v)
        new_eig = jnp.real(_tree_dot(v, hv))
        norm = _tree_norm(hv)
        v = jax.tree.map(lambda x: x / jnp.maximum(norm, 1e-12), hv)
        if tol and abs(float(new_eig) - float(eig)) < tol:
            eig = new_eig
            break
        eig = new_eig
    return eig, v


def layer_eigenvalues(loss_fn: Callable, params, layer_key: str = "layers",
                      **kw) -> jnp.ndarray:
    """Per-layer dominant eigenvalues over the stacked (L, ...) layer pytree
    (the reference ranks modules this way for MoQ). Restricts the power
    iteration to one layer's slice at a time, other params frozen."""
    L = jax.tree.leaves(params[layer_key])[0].shape[0]
    eigs = []
    for i in range(L):
        def layer_loss(layer_i, i=i):
            stitched = {**params, layer_key: jax.tree.map(
                lambda full, one: full.at[i].set(one),
                params[layer_key], layer_i)}
            return loss_fn(stitched)

        layer_params = jax.tree.map(lambda a: a[i], params[layer_key])
        eig, _ = max_eigenvalue(layer_loss, layer_params, **kw)
        eigs.append(eig)
    return jnp.stack(eigs)
