"""Wall-clock + throughput timers.

Analog of the reference ``deepspeed/utils/timer.py`` (``SynchronizedWallClockTimer:43``,
``ThroughputTimer:198``). On TPU there are no CUDA events; synchronization is
``jax.block_until_ready`` on the step outputs, which the engine does at timer
boundaries when ``wall_clock_breakdown`` is on.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from .logging import log_dist


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self.elapsed_total = 0.0
        self.count = 0
        # most recent completed interval, as absolute perf_counter instants
        # — the observability span layer re-emits timer windows as spans
        # without adding clock reads of its own
        self.last_start = 0.0
        self.last_stop = 0.0

    def start(self) -> None:
        self._start = time.perf_counter()
        self.started = True

    def stop(self) -> None:
        if not self.started:
            return
        now = time.perf_counter()
        self.elapsed_total += now - self._start
        self.count += 1
        self.last_start = self._start
        self.last_stop = now
        self.started = False

    def elapsed(self, reset: bool = True) -> float:
        e = self.elapsed_total
        if reset:
            self.reset()
        return e

    def mean(self) -> float:
        return self.elapsed_total / max(1, self.count)

    def reset(self) -> None:
        self.elapsed_total = 0.0
        self.count = 0
        self.started = False


class WallClockTimers:
    """Named timer registry (reference ``SynchronizedWallClockTimer``)."""

    def __init__(self, synchronize_fn: Optional[Callable[[], None]] = None):
        self._timers: dict[str, _Timer] = {}
        self._synchronize = synchronize_fn

    def __call__(self, name: str) -> _Timer:
        if name not in self._timers:
            self._timers[name] = _Timer(name)
        return self._timers[name]

    def start(self, name: str) -> None:
        if self._synchronize:
            self._synchronize()
        self(name).start()

    def stop(self, name: str) -> None:
        if self._synchronize:
            self._synchronize()
        self(name).stop()

    def log(self, names: list[str] | None = None, reset: bool = True) -> dict[str, float]:
        names = names or list(self._timers)
        out = {}
        for n in names:
            if n in self._timers:
                out[n] = self._timers[n].elapsed(reset=reset) * 1000.0
        if out:
            msg = " | ".join(f"{k}: {v:.2f}ms" for k, v in out.items())
            log_dist(f"time (ms) | {msg}", ranks=[0])
        return out


class ThroughputTimer:
    """samples/s + TFLOPS/MFU reporting (reference ``utils/timer.py:198``).

    ``flops_per_sample`` comes from the model's cost analysis (see
    ``profiling/flops.py``); ``peak_flops`` from the platform table.
    """

    def __init__(self, batch_size: int, steps_per_output: int = 10,
                 flops_per_sample: float = 0.0, peak_flops: float = 0.0,
                 monitor=None):
        self.batch_size = batch_size
        self.steps_per_output = steps_per_output
        self.flops_per_sample = flops_per_sample
        self.peak_flops = peak_flops
        self.monitor = monitor
        self.epoch_count = 0
        self.global_steps = 0
        self.total_elapsed = 0.0
        self._start = None

    def start(self) -> None:
        self._start = time.perf_counter()

    def stop(self, report: bool = True) -> Optional[dict]:
        if self._start is None:
            return None
        dt = time.perf_counter() - self._start
        self._start = None
        self.global_steps += 1
        self.total_elapsed += dt
        if report and self.global_steps % self.steps_per_output == 0:
            return self.report(dt)
        return None

    def report(self, step_time: float) -> dict:
        samples_per_sec = self.batch_size / max(step_time, 1e-9)
        stats = {"samples_per_sec": samples_per_sec, "step_time_s": step_time}
        if self.flops_per_sample:
            tflops = samples_per_sec * self.flops_per_sample / 1e12
            stats["tflops"] = tflops
            if self.peak_flops:
                stats["mfu"] = tflops * 1e12 / self.peak_flops
        msg = (f"step {self.global_steps}: {samples_per_sec:.1f} samples/s, "
               f"{step_time * 1000:.1f} ms/step")
        if "tflops" in stats:
            msg += f", {stats['tflops']:.1f} TFLOPS"
        if "mfu" in stats:
            msg += f", MFU {stats['mfu'] * 100:.1f}%"
        log_dist(msg, ranks=[0])
        return stats


# Peak dense bf16 FLOPS per chip, for MFU accounting.
PEAK_FLOPS_BY_PLATFORM = {
    "tpu": {
        "v4": 275e12,
        "v5 lite": 197e12,  # v5e
        "v5": 459e12,       # v5p
        "v6 lite": 918e12,  # trillium
        "default": 197e12,
    },
    "cpu": {"default": 1e12},
    "gpu": {"default": 312e12},
}


# Peak HBM bandwidth per chip (bytes/s), for decode MBU accounting
# (autoregressive decode is bandwidth-bound: every generated token re-reads
# the weights, so tokens/s * bytes-read-per-token / peak-BW is the honest
# utilization metric — the decode analog of MFU).
PEAK_HBM_BW_BY_PLATFORM = {
    "tpu": {
        "v4": 1228e9,
        "v5 lite": 819e9,   # v5e
        "v5": 2765e9,       # v5p
        "v6 lite": 1640e9,  # trillium
    },
    "cpu": {"default": 50e9},
    "gpu": {"default": 2039e9},
}


def _peak_lookup(device, tables: dict, env_var: str, what: str) -> float:
    """Shared per-chip peak lookup for utilization accounting. MFU/MBU are
    the product's headline numbers, so an unknown TPU generation must fail
    loudly rather than silently divide by a guessed peak; override with the
    named env var when running on hardware the table predates."""
    override = os.environ.get(env_var)
    if override:
        return float(override)
    table = tables.get(device.platform)
    if table is None:
        raise ValueError(
            f"no {what} entry for platform {device.platform!r}; set "
            f"{env_var}=<per-chip value> to report utilization honestly")
    kind = getattr(device, "device_kind", "").lower()
    for key, val in table.items():
        if key != "default" and key in kind:
            return val
    if device.platform == "tpu":
        raise ValueError(
            f"unknown TPU generation {kind!r} — refusing to guess {what}; "
            f"set {env_var}=<per-chip value>")
    return table["default"]


def peak_hbm_bw_for(device) -> float:
    """Per-chip peak HBM bandwidth (bytes/s) for decode-MBU accounting.
    Override: ``DSTPU_PEAK_HBM_BW``."""
    return _peak_lookup(device, PEAK_HBM_BW_BY_PLATFORM,
                        "DSTPU_PEAK_HBM_BW", "HBM bandwidth")


def peak_flops_for(device) -> float:
    """Per-chip peak bf16 FLOP/s for MFU accounting.
    Override: ``DSTPU_PEAK_FLOPS``."""
    return _peak_lookup(device, PEAK_FLOPS_BY_PLATFORM,
                        "DSTPU_PEAK_FLOPS", "peak FLOPs")


# Aggregate per-chip interconnect (ICI) bandwidth (bytes/s, all links),
# for the collective bus-bandwidth roofline (observability/commscope.py:
# achieved busbw / this peak is the collective analog of the decode MBU).
# Published aggregates: v4 six 50 GB/s links, v5e four 50 GB/s (1600
# Gbps), v5p 600 GB/s (4800 Gbps), Trillium ~448 GB/s (3584 Gbps).
PEAK_ICI_BW_BY_PLATFORM = {
    "tpu": {
        "v4": 300e9,
        "v5 lite": 200e9,   # v5e
        "v5": 600e9,        # v5p
        "v6 lite": 448e9,   # trillium
    },
    # CPU "interconnect" is host memory; GPU default is NVLink-class.
    "cpu": {"default": 10e9},
    "gpu": {"default": 900e9},
}


def peak_ici_bw_for(device) -> float:
    """Per-chip aggregate ICI bandwidth (bytes/s) for the collective
    roofline. Override: ``DSTPU_PEAK_ICI_BW``. Raises ValueError on an
    unknown TPU generation like the other peaks — commscope catches it
    and degrades the roofline ratio to null."""
    return _peak_lookup(device, PEAK_ICI_BW_BY_PLATFORM,
                        "DSTPU_PEAK_ICI_BW", "ICI bandwidth")
