"""Rank-aware logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (logger +
``log_dist`` rank filtering). In a multi-host JAX job the "rank" is
``jax.process_index()``; inside a single-process SPMD program all devices share
one Python process, so rank filtering is per *host*, not per chip.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu", level: int | None = None) -> logging.Logger:
    if level is None:
        level = getattr(logging, os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper(), logging.INFO)
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(logging.Formatter(LOG_FORMAT))
        lg.addHandler(handler)
    return lg


logger = _create_logger()


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: list[int] | None = None,
             level: int | str = logging.INFO) -> None:
    """Log ``message`` only on the listed process ranks (``[-1]`` or None = all).

    Mirrors the behavior of the reference ``log_dist`` but keyed on
    ``jax.process_index()``. ``level`` accepts a name ("WARNING") or an
    int — ``logging.Logger.log`` raises on strings, and callers pass both.
    """
    my_rank = _process_index()
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        logger.info(message)


def warning_once(message: str, _seen: set = set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
