from .flops_profiler import (FlopsProfiler, compiled_cost_analysis,
                             compiled_memory_analysis, model_flops_tree,
                             profile_model)

__all__ = ["FlopsProfiler", "compiled_cost_analysis",
           "compiled_memory_analysis", "model_flops_tree", "profile_model"]
