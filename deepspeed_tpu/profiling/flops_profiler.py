"""Flops profiler: per-module FLOPs/params tree + measured XLA cost analysis.

Analog of the reference flops profiler (``profiling/flops_profiler/
profiler.py:28,65-131``), which installs forward hooks on every ``nn.Module``
to count MACs and latency and prints an indented per-module tree at
``profile_step``.  Under jit there are no module hooks — and none are needed:

- the **measured** side comes from the compiled executable itself:
  ``jax.stages.Compiled.cost_analysis()`` reports the post-fusion FLOPs and
  bytes-accessed XLA actually scheduled — more truthful than hook counting,
  which can't see fusion or rematerialisation;
- the **per-module breakdown** is computed analytically from the
  :class:`TransformerConfig` (the model is a closed family, so the tree is
  exact), matching the reference report's params/MACs/% columns;
- latency is a real timed step, so the report ends with achieved TFLOPS and
  MFU against the chip's peak (the reference prints samples/s + TFLOPS).
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax

from ..utils.logging import log_dist
from ..utils.timer import peak_flops_for


# ------------------------------------------------------------ measured side
def _compiled(jitted, *args, **kwargs):
    compiled = jitted
    if hasattr(compiled, "lower"):
        compiled = compiled.lower(*args, **kwargs)
    if hasattr(compiled, "compile"):
        compiled = compiled.compile()
    return compiled


def compiled_cost_analysis(jitted, *args, **kwargs) -> dict:
    """FLOPs/bytes the compiler scheduled for one call of ``jitted(*args)``.

    Works on a ``jax.jit`` wrapper (traces + hits the compile cache) or an
    already-lowered/compiled object."""
    cost = _compiled(jitted, *args, **kwargs).cost_analysis()
    if isinstance(cost, (list, tuple)):   # older jax: one dict per device
        cost = cost[0] if cost else {}
    return dict(cost or {})


def compiled_memory_analysis(jitted, *args, **kwargs) -> dict:
    """Buffer-assignment byte summary (``*_in_bytes`` fields) of one
    compiled call — the compiler's own temp/argument/output/generated
    sizes. Same calling convention as :func:`compiled_cost_analysis`;
    the field set varies across jax versions and backends, so every
    available numeric field is returned and absent ones are simply
    missing (callers treat missing as unknown). Raises when the backend
    has no ``memory_analysis`` at all — capacity census wraps this in
    its degradation guard."""
    ma = _compiled(jitted, *args, **kwargs).memory_analysis()
    out = {}
    if ma is None:
        return out
    for k in dir(ma):
        if k.endswith("_in_bytes"):
            try:
                out[k] = int(getattr(ma, k))
            except Exception:
                pass   # field probe: names vary across jax versions
    return out


# ------------------------------------------------------------ analytic side
def model_flops_tree(cfg, batch: int, seq: int) -> list[dict]:
    """Per-component rows: name, params, fwd MACs for a (batch, seq) step.

    Mirrors the reference tree's structure (embedding / per-layer attention
    and FFN / head) for the native trunk family."""
    d, L, V = cfg.d_model, cfg.n_layer, cfg.vocab_size
    h, kv, hd, f = cfg.n_head, cfg.kv_heads, cfg.head_dim, cfg.ffn_dim
    E, k = cfg.num_experts, min(cfg.moe_top_k, cfg.num_experts)
    tokens = batch * seq

    bias = cfg.use_bias
    qkv_params = d * h * hd + 2 * d * kv * hd + (bias * (h * hd + 2 * kv * hd))
    out_params = h * hd * d + bias * d
    ln_params = 2 * d if cfg.norm == "layernorm" and bias else d
    per_expert = d * f * (3 if cfg.is_glu else 2) + bias * (f + d)
    ffn_params = per_expert if E == 1 else d * E + E * per_expert
    per_expert_macs = d * f * (3 if cfg.is_glu else 2)
    ffn_active = (per_expert_macs if E == 1
                  else d * E + k * per_expert_macs)

    rows = [{
        "name": "embedding",
        "params": V * d + (cfg.max_seq * d if cfg.pos_embedding == "learned" else 0),
        "macs": 0,   # gathers, no matmul
    }]
    for comp, params, macs_tok in [
        ("attention.qkv_proj", L * qkv_params, L * qkv_params),
        ("attention.scores+context", 0, L * 2 * seq * h * hd),
        ("attention.out_proj", L * out_params, L * out_params),
        ("norms", L * 2 * ln_params + ln_params, 0),
        (f"ffn{'' if E == 1 else f'.moe(E={E},top{k})'}",
         L * ffn_params, L * ffn_active),
    ]:
        rows.append({"name": comp, "params": params, "macs": macs_tok * tokens})
    if getattr(cfg, "objective", "clm") != "feature":   # feature towers
        head_params = 0 if cfg.tie_embeddings else d * V  # have no unembed
        rows.append({"name": "lm_head", "params": head_params,
                     "macs": d * V * tokens})
    return rows


def profile_model(cfg, batch: int, seq: int) -> dict:
    """Whole-model summary (reference ``get_model_profile`` analog)."""
    rows = model_flops_tree(cfg, batch, seq)
    fwd_macs = sum(r["macs"] for r in rows)
    return {
        "params": sum(r["params"] for r in rows),
        "fwd_macs": fwd_macs,
        "fwd_flops": 2 * fwd_macs,
        "train_step_flops": 6 * fwd_macs,   # fwd + bwd (2x fwd)
        "rows": rows,
    }


def _fmt(n: float) -> str:
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n:.0f} "


# ------------------------------------------------------------------ the hook
class FlopsProfiler:
    """Engine-attached profiler; fires once at ``profile_step``.

    ``clock`` is the injectable timestamp seam (same discipline as the
    observability stack: default to ``time.perf_counter`` WITHOUT calling
    it, so fake-clock tests can drive the timed step deterministically)."""

    def __init__(self, config, engine, clock=time.perf_counter):
        self.cfg = config
        self.engine = engine
        self.clock = clock
        self.done = False

    def should_fire(self) -> bool:
        return (self.cfg.enabled and not self.done
                and self.engine.global_steps >= self.cfg.profile_step)

    def profile(self, batch: dict) -> str:
        """Build + emit the report. ``batch`` is a live global batch (used to
        re-time one real step and to size the analytic tree)."""
        self.done = True
        eng = self.engine
        ids = batch["input_ids"]
        global_batch, seq = int(ids.shape[0]), int(ids.shape[-1])
        if ids.ndim == 3:   # (gas, local, seq) micro-stepped layout
            global_batch = int(ids.shape[0]) * int(ids.shape[1])

        # measured: compiled cost + one timed step
        step_fn = eng._grad_step if eng.offload else eng._train_step
        step_args = ((eng.compute_params, batch) if eng.offload
                     else (eng.state, batch))
        try:
            with eng.mesh:
                cost = compiled_cost_analysis(step_fn, *step_args)
        except Exception as e:  # cost analysis is best-effort per backend
            cost = {}
            log_dist(f"flops_profiler: cost_analysis unavailable ({e})")
        # The timed step is a REAL engine step (train_batch: includes the
        # host optimizer update in offload mode — timing only _grad_step
        # would overstate MFU — and commits state/global_steps normally;
        # self.done is already True so this cannot recurse).
        t0 = self.clock()
        eng.train_batch(batch)
        jax.block_until_ready(
            jax.tree.leaves(eng.compute_params if eng.offload
                            else eng.state.master_params)[0])
        dt = self.clock() - t0

        lines = [f"-------- deepspeed_tpu flops profiler "
                 f"(step {eng.global_steps}) --------",
                 f"global batch: {global_batch}  seq: {seq}  "
                 f"devices: {len(jax.devices())}"]
        model_cfg = getattr(eng.model, "cfg", None)
        total_flops: Optional[float] = None
        if model_cfg is not None:
            prof = profile_model(model_cfg, global_batch, seq)
            total_flops = float(prof["train_step_flops"])
            lines.append(f"params: {_fmt(prof['params'])} "
                         f"| fwd MACs/step: {_fmt(prof['fwd_macs'])} "
                         f"| train FLOPs/step: {_fmt(total_flops)}")
            if self.cfg.detailed:
                macs_total = max(1, prof["fwd_macs"])
                for r in prof["rows"]:
                    pct = 100.0 * r["macs"] / macs_total
                    lines.append(f"  {r['name']:<28} params {_fmt(r['params']):>9} "
                                 f"MACs {_fmt(r['macs']):>9} ({pct:4.1f}%)")
        measured = cost.get("flops")
        if measured:
            lines.append(f"XLA-scheduled FLOPs/step (post-fusion, this "
                         f"device): {_fmt(measured)}")
            if total_flops is None:
                total_flops = float(measured) * len(jax.devices())
        lines.append(f"step latency: {dt * 1e3:.1f} ms")
        if total_flops:
            achieved = total_flops / dt
            lines.append(f"achieved: {achieved / 1e12:.2f} TFLOPS")
            try:
                peak = peak_flops_for(jax.devices()[0]) * len(jax.devices())
                lines[-1] += f" ({100.0 * achieved / peak:.1f}% of peak)"
            except ValueError:
                pass  # unknown hardware: report TFLOPS without a peak ratio
        lines.append("-" * 58)
        report = "\n".join(lines)
        log_dist(report, ranks=[0])
        if self.cfg.output_file and jax.process_index() == 0:
            with open(self.cfg.output_file, "w") as fh:
                fh.write(report + "\n")
        return report
