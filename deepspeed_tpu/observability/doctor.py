"""Ops triage CLI: ``python -m deepspeed_tpu.observability.doctor``.

Pretty-prints the artifacts the runbooks point at, from files alone (no
running engine, no device):

- the newest Prometheus textfile (``*.prom``) — current gauges;
- the newest per-request log (``*.requests.jsonl``) — last requests,
  grouped by terminal status;
- the newest flight record (``flight_*/``) — reason, markers, the
  slowest spans, and where the trace.json lives for Perfetto;
- the newest incident dir (``incident_*/`` — the fleet's correlated
  cross-replica capture) — which replicas dumped, the merged
  cross-replica timeline, the route-audit summary, and where the merged
  Perfetto trace lives;
- the newest capacity report (``CAPACITY_REPORT*.json``) — HBM ledger
  totals and the advisor's ranked levers (docs/OPERATIONS.md
  capacity-planning runbook);
- ``[replay]`` — the newest traffic trace (``*traffic_trace*.jsonl``,
  the record half of record→replay, bundled into flight/incident dumps)
  schema-validated, plus the last replay parity verdict
  (``REPLAY_REPORT*.json`` — ``observability/replay.py``);
- ``[perf]`` — the cross-PR perf ledger (``PERF_LEDGER.json``,
  ``observability/perf_ledger.py``): trajectory summary and the
  regression gate vs each series' rolling best;
- ``[comm]`` — the communication observatory
  (``observability/commscope.py``): exposed/overlap collective
  fractions, per-kind achieved bus bandwidth, and the per-device skew
  table, from the latest .prom; a BURNING straggler gauge gates.
- ``[kv]`` — the KV residency observatory
  (``observability/kvscope.py``): eviction-regret rate, session heat,
  hottest evicted sessions, and the ``tiered_kv`` lever verdict from
  the newest capacity report; RUNAWAY regret (regret_frac above
  ``--kv-regret-max``) gates.

Exit code is the CI/cron gate: **nonzero** when the newest flight record
contains a why-marker (watchdog stall, SLO breach, anomaly, compile
storm — something fired since the record was cut), when any
``dstpu_*_burn`` SLO gauge in the latest .prom is above zero, when
the newest incident dir is UNRECONCILED (per-replica dumps from fewer
replicas than the fleet had live — the post-mortem is incomplete), when
the newest traffic trace is invalid or the last replay verdict is a
parity FAILURE, when the perf ledger holds a series worse than its
rolling best beyond the margin, or when a straggler gauge is burning
(``dstpu_train_straggler_active`` > 0); 0 on a clean replica. ``--no-gate``
restores the always-0 report-only behavior. ``--targets`` combined with
``--flight-dir`` runs the incident gate alongside fleet triage.

``--url http://host:port`` switches to **live mode**: instead of files,
the doctor scrapes a running engine's telemetry plane
(``observability/server.py``) — ``/metrics``, ``/healthz``, ``/readyz``,
``/goodput``, the newest flight manifest via ``/flight`` — with the
same gate semantics (burning SLO gauges or why-markers in the newest
flight record exit nonzero). Endpoints the engine doesn't expose (no
goodput ledger, no flight recorder, a training engine's missing
``/requests``) degrade to a note, never an error; an entirely
unreachable target is itself a gate finding.

Usage::

    python -m deepspeed_tpu.observability.doctor [--dir ./monitor]
        [--flight-dir <dir>] [--requests N] [--no-gate]
        [--url http://host:port] [--timeout S]

Stdout is this module's interface (it is a CLI report tool, exempt from
the bare-print lint like ``env_report.py``).
"""

from __future__ import annotations

import argparse
import json
import math
from collections import Counter as _Counter
from pathlib import Path
from typing import Optional


def _newest(dirpath: Path, pattern: str):
    cands = sorted(dirpath.glob(pattern),
                   key=lambda p: (p.stat().st_mtime, p.name))
    return cands[-1] if cands else None




def _fmt(v: float) -> str:
    if isinstance(v, float) and not math.isfinite(v):
        from .sinks import format_prometheus_value

        return format_prometheus_value(v)     # the NaN/+Inf/-Inf spellings
    if isinstance(v, float) and v and abs(v) < 1e-3:
        return f"{v:.3e}"
    return f"{v:g}" if isinstance(v, float) else str(v)


def _print_metrics(vals: dict, where: str) -> list:
    """Shared by file and live modes: print every metric (serving
    first, then training, then the rest — a process that both trains
    and serves shows both halves) and return the gate findings: every
    SLO burn gauge currently above zero. One implementation so the two
    modes cannot drift on what gates."""
    shown: set[str] = set()
    for prefix in ("dstpu_serve_", "dstpu_train_", ""):
        for k, v in sorted(vals.items()):
            if k.startswith(prefix) and k not in shown:
                shown.add(k)
                print(f"  {k:<44s} {_fmt(v)}")
    return [f"SLO burn gauge {k} = {_fmt(v)} {where}"
            for k, v in sorted(vals.items())
            if k.endswith("_burn") and "_slo_" in k
            and isinstance(v, float) and v > 0]


def report_prometheus(d: Path) -> list:
    """Print the latest .prom; returns gate findings — every SLO burn
    gauge (``dstpu_*_burn``) currently above zero."""
    from .sinks import parse_prometheus_textfile

    prom = _newest(d, "*.prom")
    if prom is None:
        print(f"[prom] no *.prom under {d}")
        return []
    vals = parse_prometheus_textfile(prom.read_text())
    print(f"[prom] {prom} ({len(vals)} metrics)")
    return _print_metrics(vals, f"in {prom.name}")


def report_requests(d: Path, limit: int) -> None:
    log = _newest(d, "*.requests.jsonl")
    if log is None:
        print(f"[requests] no *.requests.jsonl under {d}")
        return
    from .flight import load_jsonl_tolerant

    rows, skipped = load_jsonl_tolerant(log)
    by_status = _Counter(r.get("status", "?") for r in rows)
    torn = f" {skipped} torn line(s) skipped" if skipped else ""
    print(f"[requests] {log} ({len(rows)} records){torn} "
          + " ".join(f"{k}={n}" for k, n in sorted(by_status.items())))
    for r in rows[-limit:]:
        ttft = r.get("ttft_s")
        qw = r.get("queue_wait_s")
        print(f"  rid={str(r.get('rid')):<6} {r.get('status', '?'):<10} "
              f"tokens={r.get('tokens')} "
              f"ttft={_fmt(ttft) if ttft is not None else '-'} "
              f"queue_wait={_fmt(qw) if qw is not None else '-'}"
              + (f" error={r['error']}" if r.get("error") else ""))


def report_flight(d: Path, slow: int = 5) -> list:
    """Print the newest flight record; returns gate findings — the
    why-markers it contains (a record with markers means something
    fired: watchdog stall, SLO breach, anomaly, compile storm)."""
    from .flight import newest_flight_record, read_flight_record

    rec_dir = newest_flight_record(d)
    if rec_dir is None:
        print(f"[flight] no flight_* record under {d}")
        return []
    rec = read_flight_record(rec_dir)
    mf = rec["manifest"]
    print(f"[flight] {rec_dir}")
    print(f"  reason={mf.get('reason')} at {mf.get('wall_time')} "
          f"events={mf.get('events')} requests={mf.get('requests')}")
    markers = [e for e in rec["events"] if e.get("kind") == "marker"]
    for m in markers[-8:]:
        meta = dict(m.get("meta", {}))
        name = meta.pop("name", "?")
        extra = " ".join(f"{k}={_fmt(v) if isinstance(v, float) else v}"
                         for k, v in meta.items())
        print(f"  marker t={m['t0']:.6g} {name} {extra}".rstrip())
    spans = [e for e in rec["events"] if "t1" in e]
    spans.sort(key=lambda e: e["t1"] - e["t0"], reverse=True)
    if spans:
        print(f"  slowest spans (of {len(spans)}):")
        for e in spans[:slow]:
            who = " ".join(f"{k}={e[k]}" for k in ("rid", "slot", "step")
                           if k in e)
            print(f"    {e['kind']:<14s} {e['t1'] - e['t0']:.6g}s {who}")
    if rec.get("trace") is not None:
        print(f"  perfetto: load {rec_dir}/trace.json at "
              "https://ui.perfetto.dev")
    names = sorted({str(dict(m.get("meta", {})).get("name", "?"))
                    for m in markers})
    if names:
        return [f"flight record {rec_dir.name} contains why-marker(s): "
                + ", ".join(names)]
    return []


def newest_incident_dir(d: Path) -> Optional[Path]:
    """Most recent ``incident_*`` directory (the fleet's correlated
    cross-replica capture — serving/fleet.py) under ``d``, or None."""
    if not d.is_dir():
        return None
    cands = [p for p in d.iterdir()
             if p.is_dir() and p.name.startswith("incident_")]
    if not cands:
        return None
    return max(cands, key=lambda p: (p.stat().st_mtime, p.name))


def report_incidents(d: Path, events: int = 12) -> list:
    """Print the newest incident dir and reconstruct the cross-replica
    timeline (every replica's dumped events + the fleet ring, merged by
    timestamp — all rings share the fleet's injectable clock). Gate
    finding: an UNRECONCILED incident — per-replica dumps from fewer
    replicas than the fleet had live when it opened (a replica's
    recorder hit max_dumps, an unwritable disk, or a crash mid-fan-out:
    the post-mortem is incomplete and someone should know)."""
    from .flight import load_jsonl_tolerant

    inc = newest_incident_dir(d)
    if inc is None:
        return []
    findings: list = []
    try:
        mf = json.loads((inc / "incident.json").read_text(errors="replace"))
    except (OSError, json.JSONDecodeError):
        mf = {}
    if not isinstance(mf, dict):
        mf = {}
    live = mf.get("replicas_live")
    expected = mf.get("replicas") if isinstance(mf.get("replicas"), list) \
        else []
    # a replica's dump is real only when its subdir carries a manifest —
    # an empty directory left by a crashed dump does not reconcile
    sub = sorted(p.name for p in inc.iterdir()
                 if p.is_dir() and p.name != "fleet"
                 and (p / "manifest.json").exists())
    print(f"[incident] {inc}")
    print(f"  id={mf.get('incident_id', inc.name)} "
          f"reason={mf.get('reason')} "
          f"trigger={mf.get('trigger_replica')} at {mf.get('wall_time')}")
    print(f"  replica dumps: {len(sub)}/{live if live is not None else '?'}"
          f" live ({', '.join(sub) or 'none'})")
    if isinstance(live, int) and len(sub) < live:
        missing = sorted(set(str(n) for n in expected) - set(sub))
        findings.append(
            f"unreconciled incident {inc.name}: dumps from {len(sub)} of "
            f"{live} live replicas"
            + (f" (missing: {', '.join(missing)})" if missing else ""))
    # cross-replica timeline: merge the dumped rings by t0 (one shared
    # injectable clock), label each event with where it happened
    rows: list = []
    for name in sub:
        p = inc / name / "events.jsonl"
        if p.exists():
            evs, _ = load_jsonl_tolerant(p)
            rows += [(e.get("t0", 0.0), name, e) for e in evs
                     if isinstance(e, dict)]
    fev = inc / "fleet" / "events.jsonl"
    if fev.exists():
        evs, _ = load_jsonl_tolerant(fev)
        rows += [(e.get("t0", 0.0), "fleet", e) for e in evs
                 if isinstance(e, dict)]
    rows.sort(key=lambda r: r[0])
    if rows:
        print(f"  timeline (last {min(events, len(rows))} of {len(rows)} "
              "events across replicas):")
        for t0, who, e in rows[-events:]:
            kind = e.get("kind", "?")
            if kind == "marker":
                kind = f"marker:{dict(e.get('meta', {})).get('name', '?')}"
            extra = " ".join(f"{k}={e[k]}" for k in ("rid", "slot", "step")
                             if k in e)
            meta = dict(e.get("meta", {}))
            status = meta.get("status")
            if status:
                extra = (extra + f" status={status}").strip()
            print(f"    t={t0:<12.6g} [{who:>8s}] {kind:<18s} "
                  f"{extra}".rstrip())
    audit = inc / "fleet" / "route_audit.jsonl"
    if audit.exists():
        entries, _ = load_jsonl_tolerant(audit)
        by_ev = _Counter(e.get("event", "?") for e in entries)
        print("  route audit: " + " ".join(f"{k}={n}" for k, n
                                           in sorted(by_ev.items())))
    tr = inc / "fleet" / "trace_merged.json"
    if tr.exists():
        print(f"  perfetto: load {tr} at https://ui.perfetto.dev "
              "(replicas as processes, requests as flows)")
    return findings


def _newest_trace_file(dirs) -> Optional[Path]:
    """Newest traffic-trace JSONL across the given dirs, searched
    recursively — traces live beside the monitor artifacts AND inside
    flight/incident dumps (the capture ring's tail)."""
    cands: list[Path] = []
    seen: set = set()
    for d in dirs:
        d = Path(d)
        if not d.is_dir() or d in seen:
            continue
        seen.add(d)
        cands += [p for p in d.rglob("*traffic_trace*.jsonl")
                  if p.is_file()]
    if not cands:
        return None
    return max(cands, key=lambda p: (p.stat().st_mtime, str(p)))


def report_replay(dirs) -> list:
    """Print the ``[replay]`` picture: the newest traffic trace
    (present/valid, event counts) and the last replay parity verdict.
    Gate findings: an INVALID trace (the incident is not replayable as
    recorded) and a parity-FAILED replay report (same traffic, different
    bits — the regression the replay loop exists to catch)."""
    from .replay import TrafficTrace

    findings: list = []
    tr_path = _newest_trace_file(dirs)
    if tr_path is None:
        print(f"[replay] no traffic trace under {', '.join(map(str, dirs))}")
    else:
        tr = TrafficTrace.read(tr_path)
        problems = tr.validate()
        torn = f" {tr.torn_lines} torn line(s)" if tr.torn_lines else ""
        print(f"[replay] {tr_path}")
        print(f"  requests={len(tr.requests)} results={len(tr.results)} "
              f"chaos={len(tr.chaos_events)}"
              f" dropped={tr.meta.get('dropped_events', 0)}{torn}")
        if problems:
            for p in problems[:4]:
                print(f"  INVALID: {p}")
            findings.append(
                f"traffic trace {tr_path.name} is invalid "
                f"({len(problems)} schema problems)")
    rep_path = None
    for d in dirs:
        cand = _newest(Path(d), "REPLAY_REPORT*.json") \
            if Path(d).is_dir() else None
        if cand is not None and (rep_path is None
                                 or cand.stat().st_mtime
                                 > rep_path.stat().st_mtime):
            rep_path = cand
    if rep_path is None:
        print("[replay] no REPLAY_REPORT*.json (no replay run yet — see "
              "docs/OPERATIONS.md incident-replay runbook)")
        return findings
    try:
        rep = json.loads(rep_path.read_text(errors="replace"))
    except (OSError, json.JSONDecodeError) as e:
        print(f"[replay] {rep_path} unreadable ({e!r})")
        return findings
    rep = rep if isinstance(rep, dict) else {}
    parity = rep.get("parity")
    verdict = {True: "PARITY", False: "DIVERGED",
               None: "no oracle (trace carried no recorded outputs)"}
    print(f"[replay] last replay {rep_path.name}: "
          f"{verdict.get(parity, parity)} — "
          f"matched {rep.get('matched')}/{rep.get('requests')}, "
          f"{len(rep.get('diverged') or [])} diverged, "
          f"chaos applied {rep.get('chaos_applied')}")
    if parity is False:
        div = rep.get("diverged") or []
        rids = ", ".join(str(x.get("rid")) for x in div[:8]
                         if isinstance(x, dict))
        findings.append(
            f"replay parity FAILED in {rep_path.name}: "
            f"{len(div)} request(s) diverged"
            + (f" (rids {rids})" if rids else ""))
    return findings


def report_perf(ledger_path: Path, margin: float = 0.2) -> list:
    """Print the ``[perf]`` trajectory summary; gate findings are every
    series whose newest point is worse than its rolling best beyond the
    margin (``perf_ledger.check_regressions``)."""
    from .perf_ledger import check_regressions, load_ledger, summarize

    if not Path(ledger_path).is_file():
        print(f"[perf] no ledger at {ledger_path} (run "
              "python -m deepspeed_tpu.observability.perf_ledger)")
        return []
    led = load_ledger(ledger_path)
    s = summarize(led)
    print(f"[perf] {ledger_path}: {s['series']} series "
          f"({s['directed_series']} directed, "
          f"{s['series_with_history']} with history) over {s['runs']} "
          f"run(s), last {s['last_run']}")
    regs = check_regressions(led, margin=margin)
    for r in regs[:8]:
        print(f"  REGRESSION {r['series']} [{r['direction']}] "
              f"best {r['best']:g} -> {r['last']:g} at {r['last_run']}")
    return [f"perf regression: {r['series']} best {r['best']:g} -> "
            f"{r['last']:g} ({r['direction']}, margin {margin:g})"
            for r in regs]


def report_capacity(d: Path, levers: int = 4) -> None:
    """Print the newest capacity report's ledger totals + ranked advisor
    levers (informational — the advisor ranks levers, it doesn't gate)."""
    import json

    from .capacity import validate_capacity_report

    rep_path = _newest(d, "CAPACITY_REPORT*.json")
    if rep_path is None:
        print(f"[capacity] no CAPACITY_REPORT*.json under {d}")
        return
    try:
        rep = json.loads(rep_path.read_text(errors="replace"))
    except (OSError, json.JSONDecodeError) as e:
        print(f"[capacity] {rep_path} unreadable ({e!r})")
        return
    errs = validate_capacity_report(rep)
    valid = "" if not errs else f" INVALID ({len(errs)} schema problems)"
    print(f"[capacity] {rep_path}{valid}")
    if not isinstance(rep, dict):
        return
    led = rep.get("ledger")
    led = led if isinstance(led, dict) else {}
    gib = 1 << 30
    for k in ("weights_bytes", "kv_bytes", "temp_bytes", "total_bytes",
              "limit_bytes", "headroom_bytes"):
        v = led.get(k)
        print(f"  {k:<28s} "
              + (f"{v / gib:.3f} GiB" if isinstance(v, (int, float))
                 else "unknown"))
    for k in ("projected_max_slots", "projected_max_context"):
        print(f"  {k:<28s} {led.get(k)}")
    adv = rep.get("advisor")
    lvs = adv.get("levers") if isinstance(adv, dict) else None
    for i, lv in enumerate((lvs if isinstance(lvs, list) else [])[:levers]):
        # an INVALID report's levers still print, field by field — the
        # triage contract is degrade, never crash on a torn artifact
        lv = lv if isinstance(lv, dict) else {}
        score = lv.get("score")
        if isinstance(score, (int, float)):
            score = _fmt(float(score))
        print(f"  #{i + 1} {str(lv.get('name')):<22s} "
              f"score={score}  {lv.get('why') or ''}")


def report_comm(d: Path) -> list:
    """Print the ``[comm]`` picture from the latest .prom — the
    communication observatory's gauges (``observability/commscope.py``):
    exposed/overlap fractions, per-kind achieved bus bandwidth, and the
    per-device skew table. Gate finding: a BURNING straggler gauge
    (``dstpu_train_straggler_active`` > 0 — a device is currently
    dragging every step; docs/OPERATIONS.md "diagnosing a slow multichip
    step")."""
    from .sinks import parse_prometheus_textfile

    prom = _newest(d, "*.prom")
    if prom is None:
        return []
    vals = parse_prometheus_textfile(prom.read_text())
    comm = {k: v for k, v in vals.items() if k.startswith("dstpu_comm_")}
    strag = {k: v for k, v in vals.items()
             if k.startswith("dstpu_train_straggler_")}
    if not comm and not strag:
        return []          # no observatory ran: no section, no gate
    print(f"[comm] {prom.name}")
    for key, label in (("dstpu_comm_exposed_frac", "exposed_comm_frac"),
                       ("dstpu_comm_overlap_frac", "overlap_frac"),
                       ("dstpu_comm_exposed_s", "exposed_s"),
                       ("dstpu_comm_collective_s", "collective_s")):
        if key in comm:
            print(f"  {label:<24s} {_fmt(comm[key])}")
    for k in sorted(comm):
        if k.endswith(("_busbw_gbps", "_algbw_gbps", "_roofline")):
            print(f"  {k.replace('dstpu_comm_', ''):<34s} {_fmt(comm[k])}")
    findings: list = []
    active = strag.get("dstpu_train_straggler_active")
    skews = sorted((k, v) for k, v in strag.items()
                   if "_skew_s_d" in k)
    if skews:
        print("  per-device skew (s):")
        for k, v in skews:
            dev = k.rsplit("_d", 1)[-1]
            print(f"    device {dev:<6s} {_fmt(v)}")
    if isinstance(active, float) and active > 0:
        dev = strag.get("dstpu_train_straggler_device")
        worst = strag.get("dstpu_train_straggler_skew_s")
        print(f"  STRAGGLER burning: device={_fmt(dev) if dev is not None else '?'} "
              f"skew={_fmt(worst) if worst is not None else '?'}s")
        findings.append(
            "straggler gauge burning in " + prom.name
            + (f": device {_fmt(dev)}" if dev is not None else "")
            + (f" skew {_fmt(worst)}s" if worst is not None else ""))
    eps = strag.get("dstpu_train_straggler_episodes")
    if eps:
        print(f"  straggler episodes (lifetime): {_fmt(eps)}")
    return findings


def report_kv(d: Path, regret_max: float = 0.5) -> list:
    """Print the ``[kv]`` picture — the KV residency observatory
    (``observability/kvscope.py``): eviction-regret rate, session heat,
    the hottest evicted sessions, and the ``tiered_kv`` lever verdict
    from the newest capacity report. Gate finding: RUNAWAY REGRET — the
    regretted share of prefill work (``dstpu_serve_eviction_regret_frac``
    in the latest .prom) above ``regret_max``: the pool is thrashing and
    every resume re-pays its prefill (docs/OPERATIONS.md "sizing the
    host KV tier from the regret ledger")."""
    from .sinks import parse_prometheus_textfile

    prom = _newest(d, "*.prom")
    if prom is None:
        return []
    vals = parse_prometheus_textfile(prom.read_text())
    kv = {k: v for k, v in vals.items()
          if k.startswith(("dstpu_serve_eviction_regret",
                           "dstpu_serve_kv_", "dstpu_serve_session",
                           "dstpu_serve_host_tier",
                           "dstpu_serve_nvme_", "dstpu_serve_demote_ahead",
                           "dstpu_fleet_affinity_regret",
                           "dstpu_fleet_resume_regret"))}
    if not kv:
        return []          # no observatory ran: no section, no gate
    print(f"[kv] {prom.name}")
    for key, label in (
            ("dstpu_serve_eviction_regret_tokens", "regret_tokens"),
            ("dstpu_serve_eviction_regret_frac", "regret_frac"),
            ("dstpu_serve_kv_ghost_entries", "ghost_entries"),
            ("dstpu_serve_sessions_active", "sessions_active"),
            ("dstpu_serve_sessions_idle", "sessions_idle"),
            ("dstpu_serve_sessions_dead", "sessions_dead"),
            ("dstpu_serve_session_resumed", "session_resumes"),
            ("dstpu_serve_session_regret_resumes", "regret_resumes"),
            ("dstpu_serve_session_idle_kv_byte_s", "idle_kv_byte_s"),
            ("dstpu_fleet_affinity_regret", "fleet_affinity_regret"),
            ("dstpu_serve_host_tier_pages", "host_tier_pages"),
            ("dstpu_serve_host_tier_bytes", "host_tier_bytes"),
            ("dstpu_serve_host_tier_occupancy", "host_tier_occupancy"),
            ("dstpu_serve_host_tier_restores", "host_tier_restores"),
            ("dstpu_serve_host_tier_restored_tokens",
             "host_restored_tokens"),
            ("dstpu_serve_host_tier_prunes", "host_tier_prunes"),
            ("dstpu_serve_host_tier_fallbacks", "host_tier_fallbacks"),
            ("dstpu_serve_session_host_restored_resumes",
             "host_restored_resumes"),
            ("dstpu_serve_host_tier_staged_ahead", "staged_ahead_pages"),
            ("dstpu_serve_host_tier_demote_wait_s", "demote_wait_s"),
            ("dstpu_serve_demote_ahead_staged", "demote_ahead_staged"),
            ("dstpu_serve_demote_ahead_fastfrees",
             "demote_ahead_fastfrees"),
            ("dstpu_serve_nvme_tier_pages", "nvme_tier_pages"),
            ("dstpu_serve_nvme_tier_bytes", "nvme_tier_bytes"),
            ("dstpu_serve_nvme_tier_occupancy", "nvme_tier_occupancy"),
            ("dstpu_serve_nvme_tier_promotions", "nvme_promotions"),
            ("dstpu_serve_host_tier_spills", "nvme_spilled_in"),
            ("dstpu_serve_nvme_tier_fallbacks", "nvme_tier_fallbacks"),
            ("dstpu_serve_nvme_aio_errors", "nvme_aio_errors")):
        if key in kv:
            print(f"  {label:<24s} {_fmt(kv[key])}")
    # host-tier verdict: restores without fallbacks is the tier working;
    # pressure means the next demotion prunes cold history
    if "dstpu_serve_host_tier_pages" in kv:
        pressed = kv.get("dstpu_serve_host_tier_pressure")
        fb = kv.get("dstpu_serve_host_tier_fallbacks") or 0
        verdict = ("DEGRADED: lost/corrupt host copies" if fb
                   else "under pressure (next demotion prunes)"
                   if pressed else "clean")
        print(f"  host tier verdict: {verdict}")
    # NVMe rung verdict beside it: promotions without fallbacks/errors
    # is the disk rung working (host prune spills instead of losing
    # history); aio errors mean the transport itself is failing
    if "dstpu_serve_nvme_tier_pages" in kv:
        nfb = kv.get("dstpu_serve_nvme_tier_fallbacks") or 0
        nae = kv.get("dstpu_serve_nvme_aio_errors") or 0
        npr = kv.get("dstpu_serve_nvme_tier_pressure")
        verdict = ("DEGRADED: aio transport errors" if nae
                   else "DEGRADED: torn/corrupt disk copies" if nfb
                   else "under pressure (next spill prunes)"
                   if npr else "clean")
        print(f"  nvme tier verdict: {verdict}")
    # hottest evicted sessions + the lever verdict come from the newest
    # capacity report's kvscope section (per-session data never lands in
    # the scalar exposition)
    rep_path = _newest(d, "CAPACITY_REPORT*.json")
    if rep_path is not None:
        try:
            rep = json.loads(rep_path.read_text(errors="replace"))
        except (OSError, json.JSONDecodeError):
            rep = {}
        rep = rep if isinstance(rep, dict) else {}
        ks = rep.get("kvscope")
        ks = ks if isinstance(ks, dict) else {}
        hot = (ks.get("sessions") or {}).get("hottest") or []
        if hot:
            print("  hottest evicted sessions (regretted tokens):")
            for h in hot[:5]:
                h = h if isinstance(h, dict) else {}
                print(f"    {str(h.get('session')):<16s} "
                      f"regret={h.get('regret_tokens')} "
                      f"resumes={h.get('resumes')} "
                      f"state={h.get('state')}")
        adv = rep.get("advisor")
        lvs = adv.get("levers") if isinstance(adv, dict) else None
        for lv in (lvs if isinstance(lvs, list) else []):
            lv = lv if isinstance(lv, dict) else {}
            if lv.get("name") == "tiered_kv":
                score = lv.get("score")
                print(f"  tiered_kv lever: score="
                      f"{_fmt(float(score)) if isinstance(score, (int, float)) else score}"
                      f"  {lv.get('why') or ''}")
    findings: list = []
    frac = kv.get("dstpu_serve_eviction_regret_frac")
    if isinstance(frac, float) and frac > regret_max:
        print(f"  RUNAWAY REGRET: {_fmt(frac)} of prefill work re-paid "
              f"because of evictions (gate at {regret_max:g})")
        findings.append(
            f"runaway eviction regret in {prom.name}: regret_frac "
            f"{_fmt(frac)} > {regret_max:g} — the KV pool is thrashing; "
            "see the tiered_kv lever / host-tier sizing runbook")
    fb = kv.get("dstpu_serve_host_tier_fallbacks")
    if isinstance(fb, (int, float)) and fb > 0:
        print(f"  HOST-TIER FALLBACKS: {_fmt(fb)} lost/corrupt host "
              "copies degraded to recompute")
        findings.append(
            f"host-tier fallbacks in {prom.name}: {_fmt(fb)} demoted KV "
            "copies failed verification and were recomputed — host "
            "memory corruption or a torn demotion; serving degraded "
            "safely but the tier is not trustworthy")
    nfb = kv.get("dstpu_serve_nvme_tier_fallbacks")
    if isinstance(nfb, (int, float)) and nfb > 0:
        print(f"  NVME-TIER FALLBACKS: {_fmt(nfb)} torn/corrupt/missing "
              "disk copies degraded to recompute")
        findings.append(
            f"nvme-tier fallbacks in {prom.name}: {_fmt(nfb)} disk KV "
            "copies failed CRC/read verification and were recomputed — "
            "torn writes or a failing device; serving degraded safely "
            "but the disk rung is not trustworthy")
    nae = kv.get("dstpu_serve_nvme_aio_errors")
    if isinstance(nae, (int, float)) and nae > 0:
        print(f"  NVME AIO ERRORS: {_fmt(nae)} async I/O "
              "submit/wait failures (ds_aio_errors)")
        findings.append(
            f"nvme aio errors in {prom.name}: {_fmt(nae)} async I/O "
            "operations failed on the swap files — check the "
            "serving.nvme_path mount (space, permissions, device "
            "health); the tier degrades to recompute but disk "
            "bandwidth is being wasted")
    return findings


def report_load(d: Path, rho_max: float = 0.9) -> list:
    """Print the ``[load]`` picture — the arrival & scaling observatory
    (``observability/loadscope.py``): arrival rate / burstiness / trend,
    utilization ρ per engine, the SLO time-to-violation horizon, and
    the ``scaling`` lever verdict from the newest capacity report. Gate
    finding: SUSTAINED OVERLOAD — utilization at or above ``rho_max``
    with queue pressure (a non-empty queue or a rising arrival trend)
    and a finite time-to-violation: the fleet is trending into SLO burn
    and needs a scale-out (docs/OPERATIONS.md "deciding when to
    scale")."""
    from .sinks import parse_prometheus_textfile

    prom = _newest(d, "*.prom")
    if prom is None:
        return []
    vals = parse_prometheus_textfile(prom.read_text())
    load = {k: v for k, v in vals.items()
            if k.startswith(("dstpu_serve_arrival_",
                             "dstpu_serve_offered_tokens_per_s",
                             "dstpu_serve_utilization",
                             "dstpu_serve_predicted_queue_wait_s",
                             "dstpu_serve_slo_ttv_s",
                             "dstpu_fleet_arrival_",
                             "dstpu_fleet_offered_",
                             "dstpu_fleet_utilization_max",
                             "dstpu_fleet_slo_ttv_min_s"))}
    if not load:
        return []          # no observatory ran: no section, no gate
    print(f"[load] {prom.name}")
    for key, label in (
            ("dstpu_serve_arrival_rate_per_s", "arrival_rate_per_s"),
            ("dstpu_serve_arrival_cv", "interarrival_cv"),
            ("dstpu_serve_arrival_trend_per_s2", "arrival_trend_per_s2"),
            ("dstpu_serve_offered_tokens_per_s", "offered_tokens_per_s"),
            ("dstpu_serve_utilization", "utilization_rho"),
            ("dstpu_serve_predicted_queue_wait_s", "pred_queue_wait_s"),
            ("dstpu_serve_slo_ttv_s", "slo_ttv_s"),
            ("dstpu_fleet_arrival_rate_per_s", "fleet_arrival_per_s"),
            ("dstpu_fleet_offered_tokens_per_s", "fleet_offered_tok_s"),
            ("dstpu_fleet_utilization_max", "fleet_utilization_max"),
            ("dstpu_fleet_slo_ttv_min_s", "fleet_slo_ttv_min_s")):
        if key in load:
            print(f"  {label:<24s} {_fmt(load[key])}")
    # per-replica ρ table + the advisor verdict come from the newest
    # capacity report's loadscope section / scaling lever
    rep_path = _newest(d, "CAPACITY_REPORT*.json")
    if rep_path is not None:
        try:
            rep = json.loads(rep_path.read_text(errors="replace"))
        except (OSError, json.JSONDecodeError):
            rep = {}
        rep = rep if isinstance(rep, dict) else {}
        ls = rep.get("loadscope")
        ls = ls if isinstance(ls, dict) else {}
        reps = ls.get("replicas")
        if isinstance(reps, dict) and reps:
            print("  per-replica utilization:")
            for name, row in sorted(reps.items()):
                row = row if isinstance(row, dict) else {}
                u = row.get("utilization") or {}
                rho = u.get("rho")
                print(f"    {str(name):<12s} "
                      f"rho={_fmt(rho) if isinstance(rho, (int, float)) else 'unmeasured'} "
                      f"wait={u.get('predicted_queue_wait_s')}")
        adv = rep.get("advisor")
        lvs = adv.get("levers") if isinstance(adv, dict) else None
        for lv in (lvs if isinstance(lvs, list) else []):
            lv = lv if isinstance(lv, dict) else {}
            if lv.get("name") == "scaling":
                score = lv.get("score")
                rec = (lv.get("estimate") or {}).get("recommendation") \
                    if isinstance(lv.get("estimate"), dict) else None
                print(f"  scaling lever: score="
                      f"{_fmt(float(score)) if isinstance(score, (int, float)) else score}"
                      + (f"  recommends {rec}" if rec else "")
                      + f"  {lv.get('why') or ''}")
    findings: list = []
    rho = max((v for k, v in load.items()
               if k in ("dstpu_serve_utilization",
                        "dstpu_fleet_utilization_max")
               and isinstance(v, float)), default=None)
    ttv = min((v for k, v in load.items()
               if k in ("dstpu_serve_slo_ttv_s",
                        "dstpu_fleet_slo_ttv_min_s")
               and isinstance(v, float)), default=None)
    trend = load.get("dstpu_serve_arrival_trend_per_s2")
    qd = vals.get("dstpu_serve_queue_depth")
    pressure = (isinstance(qd, float) and qd > 0) \
        or (isinstance(trend, float) and trend > 0)
    if rho is not None and rho >= rho_max and pressure \
            and ttv is not None:
        print(f"  SUSTAINED OVERLOAD: rho {_fmt(rho)} >= {rho_max:g} "
              f"with queue pressure and TTV {_fmt(ttv)}s")
        findings.append(
            f"sustained overload in {prom.name}: utilization {_fmt(rho)} "
            f">= {rho_max:g} with queue pressure and a finite "
            f"time-to-violation ({_fmt(ttv)}s) — trending into SLO burn; "
            "see the scaling lever / deciding-when-to-scale runbook")
    return findings


def report_autoscale(d: Path, frozen_max: float = 900.0) -> list:
    """Print the ``[autoscale]`` picture — the elastic autoscaler's
    control-loop state (``serving/autoscaler.py``) from the newest
    ``Fleet/autoscale_*`` gauges. Gate findings: FLAP BUDGET EXHAUSTED
    (the loop hit its reversal budget and froze itself — traffic is
    oscillating around a threshold; widen the hysteresis or cooldowns,
    docs/OPERATIONS.md "running the autoscaler") and FROZEN STALE (the
    loop has been frozen longer than ``frozen_max`` seconds — a deploy
    freeze somebody forgot to lift, or a flap freeze nobody triaged)."""
    from .sinks import parse_prometheus_textfile

    prom = _newest(d, "*.prom")
    if prom is None:
        return []
    vals = parse_prometheus_textfile(prom.read_text())
    auto = {k: v for k, v in vals.items()
            if k.startswith("dstpu_fleet_autoscale_")}
    if not auto:
        return []          # no autoscaler ran: no section, no gate
    print(f"[autoscale] {prom.name}")
    for key, label in (
            ("dstpu_fleet_autoscale_evals", "evaluations"),
            ("dstpu_fleet_autoscale_adds", "adds"),
            ("dstpu_fleet_autoscale_removes", "removes"),
            ("dstpu_fleet_autoscale_rebalances", "rebalances"),
            ("dstpu_fleet_autoscale_drains", "drains_started"),
            ("dstpu_fleet_autoscale_drain_aborts", "drain_aborts"),
            ("dstpu_fleet_autoscale_alarms", "alarms"),
            ("dstpu_fleet_autoscale_suppressed", "suppressed"),
            ("dstpu_fleet_autoscale_flaps", "flaps"),
            ("dstpu_fleet_autoscale_flap_budget_remaining",
             "flap_budget_remaining"),
            ("dstpu_fleet_autoscale_frozen", "frozen"),
            ("dstpu_fleet_autoscale_frozen_stale_s", "frozen_stale_s"),
            ("dstpu_fleet_autoscale_incident_latched",
             "incident_latched"),
            ("dstpu_fleet_autoscale_draining", "drain_in_flight")):
        if key in auto:
            print(f"  {label:<24s} {_fmt(auto[key])}")
    findings: list = []
    remaining = auto.get("dstpu_fleet_autoscale_flap_budget_remaining")
    frozen = auto.get("dstpu_fleet_autoscale_frozen")
    stale = auto.get("dstpu_fleet_autoscale_frozen_stale_s")
    if isinstance(remaining, float) and remaining <= 0 \
            and isinstance(frozen, float) and frozen >= 1:
        print("  FLAP BUDGET EXHAUSTED: the loop froze itself after "
              "too many scale reversals")
        findings.append(
            f"autoscaler flap budget exhausted in {prom.name}: the "
            "control loop froze itself — traffic oscillates around a "
            "threshold; widen hysteresis/cooldowns and unfreeze via "
            "POST /autoscale (docs/OPERATIONS.md)")
    elif isinstance(frozen, float) and frozen >= 1 \
            and isinstance(stale, float) and stale > frozen_max:
        print(f"  FROZEN STALE: frozen {_fmt(stale)}s "
              f"> {frozen_max:g}s")
        findings.append(
            f"autoscaler frozen-stale in {prom.name}: frozen for "
            f"{_fmt(stale)}s (> {frozen_max:g}s) — a forgotten deploy "
            "freeze or untriaged flap freeze; the fleet is not "
            "elastic while frozen")
    return findings


def report_tenants(d: Path, fairness_min: float = 0.0) -> list:
    """Print the ``[tenants]`` picture — the per-tenant cost attribution
    observatory (``observability/tenantscope.py``) from the newest
    .prom's labeled ``dstpu_serve_tenant_*`` series: top consumers by
    completed tokens, the Jain fairness index, and any active
    noisy-neighbor episode. Gate finding: FAIRNESS FLOOR BREACHED —
    the fairness index below ``fairness_min`` (0 disables; Jain's
    index is 1.0 when every tenant gets an equal token share,
    approaching 1/n under full capture by one tenant)."""
    from .expfmt import parse_labels, split_series
    from .sinks import parse_prometheus_textfile

    prom = _newest(d, "*.prom")
    if prom is None:
        return []
    vals = parse_prometheus_textfile(prom.read_text())
    tnt = {k: v for k, v in vals.items()
           if k.startswith("dstpu_serve_tenant_")}
    if not tnt:
        return []          # no tenantscope ran: no section, no gate
    # fold the labeled series into per-tenant rows
    per: dict = {}
    for k, v in tnt.items():
        base, block = split_series(k)
        if not block:
            continue
        tid = parse_labels(block).get("tenant")
        if tid is None:
            continue
        per.setdefault(tid, {})[base] = v
    print(f"[tenants] {prom.name} ({len(per)} tenant(s))")
    top = sorted(per.items(),
                 key=lambda kv: kv[1].get(
                     "dstpu_serve_tenant_completed_tokens", 0.0),
                 reverse=True)
    for tid, row in top[:8]:
        toks = row.get("dstpu_serve_tenant_completed_tokens")
        share = row.get("dstpu_serve_tenant_goodput_share")
        dom = row.get("dstpu_serve_tenant_dominant_share")
        ps = row.get("dstpu_serve_tenant_page_seconds")
        sheds = row.get("dstpu_serve_tenant_sheds")
        print(f"  {tid:<16s} "
              f"tokens={_fmt(toks) if toks is not None else '-'} "
              f"share={_fmt(share) if share is not None else '-'} "
              f"dominant={_fmt(dom) if dom is not None else '-'} "
              f"page_s={_fmt(ps) if ps is not None else '-'}"
              + (f" sheds={_fmt(sheds)}" if sheds else ""))
    jain = tnt.get("dstpu_serve_tenant_fairness_jain")
    if jain is not None:
        print(f"  fairness_jain          {_fmt(jain)}")
    episodes = tnt.get("dstpu_serve_tenant_noisy_episodes")
    active = tnt.get("dstpu_serve_tenant_noisy_active")
    if episodes:
        state = "ACTIVE" if isinstance(active, float) and active >= 1 \
            else "ended"
        print(f"  noisy_neighbor         {_fmt(episodes)} episode(s), "
              f"{state} (triage: docs/OPERATIONS.md)")
    findings: list = []
    if fairness_min > 0 and isinstance(jain, float) \
            and jain < fairness_min:
        print(f"  FAIRNESS FLOOR BREACHED: jain {_fmt(jain)} "
              f"< {fairness_min:g}")
        findings.append(
            f"tenant fairness floor breached in {prom.name}: Jain "
            f"index {_fmt(jain)} < {fairness_min:g} — one tenant is "
            "capturing the fleet; see the noisy-neighbor runbook "
            "(docs/OPERATIONS.md)")
    return findings


# ----------------------------------------------------------- live (--url)
def _http_get(url: str, timeout: float) -> "tuple[Optional[int], str]":
    """(status, body) for a GET; (None, error-repr) when the target is
    unreachable. 4xx/5xx return their status — live-mode triage treats
    a 404 as "endpoint absent", not a failure."""
    from urllib.error import HTTPError, URLError
    from urllib.request import urlopen

    try:
        with urlopen(url, timeout=timeout) as r:
            return int(r.status), r.read().decode("utf-8",
                                                  errors="replace")
    except HTTPError as e:
        try:
            return int(e.code), e.read().decode("utf-8", errors="replace")
        except OSError:
            return int(e.code), ""
    except (URLError, OSError) as e:
        return None, repr(e)


def report_live(url: str, timeout: float = 3.0,
                fairness_min: float = 0.0) -> list:
    """Triage one LIVE engine over its telemetry endpoints; returns gate
    findings with the same semantics as the file mode (burning SLO
    gauges, a breached tenant-fairness floor, why-markers in the newest
    flight record, plus: target unreachable)."""
    from .expfmt import parse_prometheus_textfile

    url = url.rstrip("/")
    findings: list = []
    # ---- /metrics: the live analog of the newest .prom
    code, body = _http_get(url + "/metrics", timeout)
    if code is None:
        print(f"[live] {url} unreachable ({body})")
        return [f"telemetry target {url} unreachable"]
    if code != 200:
        print(f"[live] {url}/metrics -> {code}")
    else:
        vals = parse_prometheus_textfile(body)
        print(f"[live] {url}/metrics ({len(vals)} metrics)")
        findings += _print_metrics(vals, f"at {url}")
    # ---- probes
    for ep in ("/healthz", "/readyz"):
        code, body = _http_get(url + ep, timeout)
        if code is None:
            print(f"[live] {ep} unreachable")
            continue
        try:
            h = json.loads(body)
        except json.JSONDecodeError:
            h = {}
        keys = ("state", "ready", "degraded", "queue_depth", "occupancy",
                "pool_pressure", "global_steps")
        brief = " ".join(f"{k}={h[k]}" for k in keys if k in h)
        print(f"[live] {ep} -> {code} {brief}".rstrip())
    # ---- /goodput: the wall-time decomposition
    code, body = _http_get(url + "/goodput", timeout)
    if code == 200:
        try:
            g = json.loads(body)
        except json.JSONDecodeError:
            g = {}
        wall = g.get("wall_s")
        frac = g.get("goodput_frac")
        print(f"[goodput] wall={_fmt(wall) if wall is not None else '?'}s "
              f"productive={_fmt(g.get('productive_s', 0.0))}s "
              f"frac={_fmt(frac) if frac is not None else '?'}")
        for b, v in sorted((g.get("badput_s") or {}).items()):
            if v:
                print(f"  badput_{b:<12s} {_fmt(v)}s")
    elif code is not None:
        print(f"[goodput] endpoint absent ({code}) — goodput ledger "
              "disabled on this engine")
    # ---- /tenants: the live analog of the [tenants] file section
    code, body = _http_get(url + "/tenants", timeout)
    if code == 200:
        try:
            tr = json.loads(body)
        except json.JSONDecodeError:
            tr = {}
        rows = tr.get("tenants")
        rows = rows if isinstance(rows, dict) else {}
        print(f"[tenants] {len(rows)} tenant(s)")
        top = sorted(rows.items(),
                     key=lambda kv: (kv[1] or {}).get(
                         "completed_tokens", 0) or 0, reverse=True)
        for tid, row in top[:8]:
            row = row if isinstance(row, dict) else {}
            share = row.get("goodput_share")
            print(f"  {str(tid):<16s} "
                  f"tokens={row.get('completed_tokens')} "
                  f"share={_fmt(share) if isinstance(share, float) else '-'} "
                  f"sheds={row.get('sheds')}")
        fair = tr.get("fairness")
        jain = fair.get("jain") if isinstance(fair, dict) else None
        if jain is not None:
            print(f"  fairness_jain          {_fmt(float(jain))}")
        noisy = tr.get("noisy")
        noisy = noisy if isinstance(noisy, dict) else {}
        if noisy.get("episodes"):
            state = "ACTIVE" if noisy.get("active") else "ended"
            print(f"  noisy_neighbor         {noisy['episodes']} "
                  f"episode(s), {state}")
        if fairness_min > 0 and isinstance(jain, (int, float)) \
                and jain < fairness_min:
            print(f"  FAIRNESS FLOOR BREACHED: jain {_fmt(float(jain))} "
                  f"< {fairness_min:g}")
            findings.append(
                f"tenant fairness floor breached at {url}: Jain index "
                f"{_fmt(float(jain))} < {fairness_min:g} — one tenant "
                "is capturing the fleet; see the noisy-neighbor "
                "runbook (docs/OPERATIONS.md)")
    elif code is not None:
        print(f"[tenants] endpoint absent ({code}) — tenantscope "
              "disabled on this engine (set serving.tenantscope)")
    # ---- /flight: newest manifest + why-markers (the live flight gate)
    code, body = _http_get(url + "/flight", timeout)
    if code == 200:
        try:
            fl = json.loads(body)
        except json.JSONDecodeError:
            fl = {}
        newest = fl.get("newest")
        if newest:
            mf = newest.get("manifest") or {}
            print(f"[flight] newest {newest.get('path')} "
                  f"reason={mf.get('reason')} events={mf.get('events')}")
            names = [str(n) for n in newest.get("markers", [])]
            if names:
                findings.append(
                    f"flight record at {url} contains why-marker(s): "
                    + ", ".join(sorted(names)))
        else:
            print(f"[flight] recorder configured, no dumps yet "
                  f"({len(fl.get('dumps', []))} taken)")
    elif code is not None:
        print(f"[flight] endpoint absent ({code}) — no flight recorder "
              "on this engine")
    return findings


def report_fleet(targets: list, timeout: float = 3.0) -> list:
    """Fleet triage (``--targets a,b,c``): one
    :class:`~.fleet_scrape.FleetScraper` pass over N engine telemetry
    endpoints plus each live target's ``/flight`` manifest, with the
    SAME gate semantics as single-engine triage — findings are every
    DOWN replica, every burning SLO gauge anywhere, and every flight
    record carrying why-markers. A dead target is a finding, never an
    exception (the scraper's degradation contract)."""
    from .fleet_scrape import FleetScraper

    findings: list = []
    scraper = FleetScraper(targets, timeout=timeout)
    snap = scraper.scrape()
    fl = snap["fleet"]
    print(f"[fleet] {fl['up']}/{fl['engines']} up, "
          f"{fl['ready']} ready"
          + (f", goodput_frac={_fmt(fl['goodput_frac'])}"
             if fl["goodput_frac"] is not None else "")
          + (f", slo_burn_max={_fmt(fl['slo_burn_max'])}"
             if fl["slo_burn_max"] is not None else ""))
    for e in snap["engines"]:
        if not e["up"]:
            print(f"[fleet] {e['engine']} ({e['target']}) DOWN "
                  f"({e['error']})")
            findings.append(f"replica {e['engine']} at {e['target']} "
                            "is down")
            continue
        vals = e["metrics"]
        keys = ("dstpu_serve_ready", "dstpu_serve_draining",
                "dstpu_serve_degraded", "dstpu_serve_queue_depth",
                "dstpu_serve_slot_occupancy", "dstpu_serve_goodput_frac")
        brief = " ".join(f"{k.replace('dstpu_serve_', '')}={_fmt(vals[k])}"
                         for k in keys if k in vals)
        ready = {True: "ready", False: "NOT-ready", None: "ready?"}
        print(f"[fleet] {e['engine']} up ({ready[e['ready']]}, "
              f"{len(vals)} metrics) {brief}".rstrip())
        findings += [f"SLO burn gauge {k} = {_fmt(v)} on {e['engine']}"
                     for k, v in sorted(vals.items())
                     if k.endswith("_burn") and "_slo_" in k
                     and isinstance(v, float) and v > 0]
        # the live flight gate, per replica: why-markers in the newest
        # record mean something fired there since it was cut
        code, body = _http_get(e["target"] + "/flight", timeout)
        if code == 200:
            try:
                flr = json.loads(body)
            except json.JSONDecodeError:
                flr = {}
            newest = flr.get("newest")
            if newest and newest.get("markers"):
                names = sorted(str(n) for n in newest["markers"])
                print(f"[fleet]   flight why-markers: {', '.join(names)}")
                findings.append(
                    f"flight record on {e['engine']} contains "
                    "why-marker(s): " + ", ".join(names))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.observability.doctor",
        description="Pretty-print the latest .prom, request log, flight "
                    "record, and capacity report for ops triage; exit "
                    "nonzero when something fired (see --no-gate).")
    ap.add_argument("--dir", default="./monitor",
                    help="monitor output directory (default ./monitor)")
    ap.add_argument("--flight-dir", default=None,
                    help="flight-record / incident directory (default: "
                         "--dir); with --targets, enables the "
                         "unreconciled-incident gate alongside live "
                         "triage")
    ap.add_argument("--requests", type=int, default=8,
                    help="recent request rows to show (default 8)")
    ap.add_argument("--no-gate", action="store_true",
                    help="always exit 0 (report-only; the default exits "
                         "1 on why-markers / burning SLOs so CI and cron "
                         "can gate on this command)")
    ap.add_argument("--url", default=None,
                    help="triage a LIVE engine at this base URL "
                         "(http://host:port) via its telemetry "
                         "endpoints instead of reading files")
    ap.add_argument("--targets", default=None,
                    help="fleet triage: comma-separated telemetry base "
                         "URLs (http://host:port,...) scraped via the "
                         "fleet aggregator; any down replica, burning "
                         "SLO gauge, or flight why-marker gates")
    ap.add_argument("--timeout", type=float, default=3.0,
                    help="per-endpoint timeout in live mode (default 3s)")
    ap.add_argument("--ledger", default=None,
                    help="perf ledger path for the [perf] section "
                         "(default <dir>/PERF_LEDGER.json)")
    ap.add_argument("--perf-margin", type=float, default=0.2,
                    help="relative regression margin for the [perf] gate "
                         "(default 0.2)")
    ap.add_argument("--kv-regret-max", type=float, default=0.5,
                    help="[kv] gate: regretted share of prefill work "
                         "above this trips (default 0.5)")
    ap.add_argument("--load-rho-max", type=float, default=0.9,
                    help="[load] gate: utilization rho at/above this "
                         "with queue pressure and a finite TTV trips "
                         "(default 0.9)")
    ap.add_argument("--autoscale-frozen-max", type=float, default=900.0,
                    help="[autoscale] gate: a control loop frozen "
                         "longer than this (seconds) trips "
                         "(default 900)")
    ap.add_argument("--tenant-fairness-min", type=float, default=0.0,
                    help="[tenants] gate: a Jain fairness index below "
                         "this floor trips (default 0 = disabled; 1.0 "
                         "is perfectly even token shares)")
    args = ap.parse_args(argv)
    if args.targets:
        findings = report_fleet(
            [t for t in args.targets.split(",") if t],
            timeout=args.timeout)
        if args.flight_dir:
            # fleet triage + a shared flight dir: the incident gate runs
            # too — an unreconciled incident (dumps from fewer replicas
            # than were live) trips CI even when every target is up
            findings += report_incidents(Path(args.flight_dir))
    elif args.url:
        findings = report_live(args.url, timeout=args.timeout,
                               fairness_min=args.tenant_fairness_min)
    else:
        d = Path(args.dir)
        findings = report_prometheus(d)
        report_requests(d, args.requests)
        fdir = Path(args.flight_dir) if args.flight_dir else d
        findings += report_flight(fdir)
        findings += report_incidents(fdir)
        report_capacity(d)
        findings += report_comm(d)
        findings += report_kv(d, regret_max=args.kv_regret_max)
        findings += report_load(d, rho_max=args.load_rho_max)
        findings += report_autoscale(
            d, frozen_max=args.autoscale_frozen_max)
        findings += report_tenants(
            d, fairness_min=args.tenant_fairness_min)
        findings += report_replay([d] if fdir == d else [d, fdir])
        ledger = Path(args.ledger) if args.ledger \
            else d / "PERF_LEDGER.json"
        findings += report_perf(ledger, margin=args.perf_margin)
    if findings:
        print(f"[gate] {len(findings)} finding(s):")
        for f in findings:
            print(f"  - {f}")
        return 0 if args.no_gate else 1
    print("[gate] clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
