"""Ops triage CLI: ``python -m deepspeed_tpu.observability.doctor``.

Pretty-prints the three artifacts the runbooks point at, from files
alone (no running engine, no device):

- the newest Prometheus textfile (``*.prom``) — current gauges;
- the newest per-request log (``*.requests.jsonl``) — last requests,
  grouped by terminal status;
- the newest flight record (``flight_*/``) — reason, markers, the
  slowest spans, and where the trace.json lives for Perfetto.

Usage::

    python -m deepspeed_tpu.observability.doctor [--dir ./monitor]
        [--flight-dir <dir>] [--requests N]

Stdout is this module's interface (it is a CLI report tool, exempt from
the bare-print lint like ``env_report.py``).
"""

from __future__ import annotations

import argparse
import math
from collections import Counter as _Counter
from pathlib import Path


def _newest(dirpath: Path, pattern: str):
    cands = sorted(dirpath.glob(pattern),
                   key=lambda p: (p.stat().st_mtime, p.name))
    return cands[-1] if cands else None




def _fmt(v: float) -> str:
    if isinstance(v, float) and not math.isfinite(v):
        from .sinks import format_prometheus_value

        return format_prometheus_value(v)     # the NaN/+Inf/-Inf spellings
    if isinstance(v, float) and v and abs(v) < 1e-3:
        return f"{v:.3e}"
    return f"{v:g}" if isinstance(v, float) else str(v)


def report_prometheus(d: Path) -> None:
    from .sinks import parse_prometheus_textfile

    prom = _newest(d, "*.prom")
    if prom is None:
        print(f"[prom] no *.prom under {d}")
        return
    vals = parse_prometheus_textfile(prom.read_text())
    print(f"[prom] {prom} ({len(vals)} metrics)")
    # every metric, serving first, then training, then the rest — a
    # process that both trains and serves shows both halves
    shown: set[str] = set()
    for prefix in ("dstpu_serve_", "dstpu_train_", ""):
        for k, v in sorted(vals.items()):
            if k.startswith(prefix) and k not in shown:
                shown.add(k)
                print(f"  {k:<44s} {_fmt(v)}")


def report_requests(d: Path, limit: int) -> None:
    log = _newest(d, "*.requests.jsonl")
    if log is None:
        print(f"[requests] no *.requests.jsonl under {d}")
        return
    from .flight import load_jsonl_tolerant

    rows, skipped = load_jsonl_tolerant(log)
    by_status = _Counter(r.get("status", "?") for r in rows)
    torn = f" {skipped} torn line(s) skipped" if skipped else ""
    print(f"[requests] {log} ({len(rows)} records){torn} "
          + " ".join(f"{k}={n}" for k, n in sorted(by_status.items())))
    for r in rows[-limit:]:
        ttft = r.get("ttft_s")
        qw = r.get("queue_wait_s")
        print(f"  rid={str(r.get('rid')):<6} {r.get('status', '?'):<10} "
              f"tokens={r.get('tokens')} "
              f"ttft={_fmt(ttft) if ttft is not None else '-'} "
              f"queue_wait={_fmt(qw) if qw is not None else '-'}"
              + (f" error={r['error']}" if r.get("error") else ""))


def report_flight(d: Path, slow: int = 5) -> None:
    from .flight import newest_flight_record, read_flight_record

    rec_dir = newest_flight_record(d)
    if rec_dir is None:
        print(f"[flight] no flight_* record under {d}")
        return
    rec = read_flight_record(rec_dir)
    mf = rec["manifest"]
    print(f"[flight] {rec_dir}")
    print(f"  reason={mf.get('reason')} at {mf.get('wall_time')} "
          f"events={mf.get('events')} requests={mf.get('requests')}")
    markers = [e for e in rec["events"] if e.get("kind") == "marker"]
    for m in markers[-8:]:
        meta = dict(m.get("meta", {}))
        name = meta.pop("name", "?")
        extra = " ".join(f"{k}={_fmt(v) if isinstance(v, float) else v}"
                         for k, v in meta.items())
        print(f"  marker t={m['t0']:.6g} {name} {extra}".rstrip())
    spans = [e for e in rec["events"] if "t1" in e]
    spans.sort(key=lambda e: e["t1"] - e["t0"], reverse=True)
    if spans:
        print(f"  slowest spans (of {len(spans)}):")
        for e in spans[:slow]:
            who = " ".join(f"{k}={e[k]}" for k in ("rid", "slot", "step")
                           if k in e)
            print(f"    {e['kind']:<14s} {e['t1'] - e['t0']:.6g}s {who}")
    if rec.get("trace") is not None:
        print(f"  perfetto: load {rec_dir}/trace.json at "
              "https://ui.perfetto.dev")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.observability.doctor",
        description="Pretty-print the latest .prom, request log, and "
                    "flight record for ops triage.")
    ap.add_argument("--dir", default="./monitor",
                    help="monitor output directory (default ./monitor)")
    ap.add_argument("--flight-dir", default=None,
                    help="flight-record directory (default: --dir)")
    ap.add_argument("--requests", type=int, default=8,
                    help="recent request rows to show (default 8)")
    args = ap.parse_args(argv)
    d = Path(args.dir)
    report_prometheus(d)
    report_requests(d, args.requests)
    report_flight(Path(args.flight_dir) if args.flight_dir else d)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
