"""Crash/stall flight recorder: the post-mortem artifact.

When the PR-4 guards fire — a watchdog stall, a ``NonFiniteLossError``
halt, a ``PreemptionGuard`` SIGTERM — the operator today gets a gauge
flip and nothing else. The flight recorder holds the last-N lifecycle
events (the span ring), the most recent retired-request records, and a
set of metric snapshot providers; :meth:`dump` freezes all of it into a
timestamped directory:

- ``manifest.json`` — reason, wall time, event/record counts;
- ``events.jsonl``  — the span ring, one event per line;
- ``metrics.json``  — every registered snapshot provider's output;
- ``requests.jsonl``— recent retired requests (serving engines);
- ``trace.json``    — the Chrome-trace/Perfetto export of the ring.

Recording cost follows the span discipline: host-side floats in bounded
deques, zero device syncs, zero new programs. ``note()`` markers are the
"why" trail — every SLO burn / anomaly / watchdog firing writes one, so
the dump explains the action that was taken. Dumping is capped
(``max_dumps``) so a stall storm cannot fill the disk.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, Optional

from ..utils.logging import log_dist
from . import spans as S


def sanitize_reason(reason: str, fallback: str = "manual") -> str:
    """A dump/incident reason as a filesystem-safe directory-name part
    (shared by the flight recorder and the fleet's incident capture so
    the two artifact families cannot drift on naming)."""
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in reason)[:48] or fallback


def unique_dir(base: Path) -> Path:
    """``base``, or ``base.k`` for the first k that doesn't exist yet
    (same second + same reason collide on the strftime stamp)."""
    d = base
    k = 0
    while d.exists():
        k += 1
        d = base.with_name(f"{base.name}.{k}")
    return d


def _json_default(o):
    # numpy values reach dumps() from metric snapshots: scalars via
    # .item(), arrays via .tolist() (.item() RAISES on size != 1, and a
    # serializer crash here would lose the dump on the very failure path
    # it exists to record)
    if getattr(o, "size", 1) == 1:
        f = getattr(o, "item", None)
        if callable(f):
            return f()
    f = getattr(o, "tolist", None)
    if callable(f):
        return f()
    return str(o)


class FlightRecorder:
    """Bounded black box + dump-to-directory.

    ``spans`` is the engine's :class:`~.spans.SpanRecorder` (or None —
    markers and snapshots still dump without the timeline).
    ``snapshots`` maps name → zero-arg callable returning a JSON-able
    dict; providers are called at dump time only. ``clock`` stamps
    marker events (injectable, like every other observability clock);
    directory names use wall time via ``time.strftime`` because they
    are operator-facing filenames, not measured intervals. ``registry``
    (the owner's MetricsRegistry) makes silent dump degradation visible:
    every failed artifact write counts in ``Flight/write_errors``
    (``dstpu_flight_write_errors`` in the .prom) instead of only
    warning."""

    def __init__(self, dump_dir, spans: Optional[S.SpanRecorder] = None,
                 snapshots: Optional[dict[str, Callable[[], dict]]] = None,
                 recent_requests: int = 64, max_dumps: int = 8,
                 clock: Optional[Callable[[], float]] = None,
                 job_name: str = "deepspeed_tpu", registry=None):
        self.registry = registry
        self.dump_dir = Path(dump_dir)
        self.spans = spans
        self.snapshots: dict[str, Callable[[], dict]] = dict(snapshots or {})
        self.clock = clock if clock is not None else (
            spans.clock if spans is not None else time.perf_counter)
        self.job_name = job_name
        self.max_dumps = int(max_dumps)
        self.dumps: list[Path] = []
        # incident-correlation seam (serving/fleet.py): when set, every
        # dump asks ``redirect(reason)`` for a target directory FIRST —
        # the fleet's handler opens a shared incident dir, fans the dump
        # out to every sibling recorder, and returns this recorder's
        # subdirectory, so one replica's trigger becomes one correlated
        # cross-replica capture. None (default) = dumps land under
        # ``dump_dir`` exactly as before.
        self.redirect: Optional[Callable[[str], Optional[Path]]] = None
        # extra dump artifacts: name -> zero-arg callable returning the
        # file's TEXT (e.g. the traffic capture's traffic_trace.jsonl
        # tail — observability/replay.py — so every dump is replayable
        # standing alone). Called at dump time only, under the same
        # per-artifact write guards as the built-in artifacts.
        self.artifacts: dict[str, Callable[[], str]] = {}
        self._markers = S.SpanRecorder(capacity=256, clock=self.clock)
        self._requests: deque[dict] = deque(maxlen=int(recent_requests))
        # RLock for the same reason as SpanRecorder: dump() runs inside
        # signal handlers (PreemptionGuard) on the main thread, which may
        # have been interrupted while holding this lock in on_request()
        self._lock = threading.RLock()

    def _count_write_error(self) -> None:
        """A dump artifact failed to land on disk — count it so the .prom
        shows the degradation (``dstpu_flight_write_errors``); the
        warning alone disappears with the process."""
        if self.registry is not None:
            self.registry.counter("Flight/write_errors").inc()

    # ------------------------------------------------------------ recording
    def add_snapshot_provider(self, name: str,
                              fn: Callable[[], dict]) -> None:
        self.snapshots[name] = fn

    def add_artifact_provider(self, name: str,
                              fn: Callable[[], str]) -> None:
        """Register an extra dump artifact: ``fn()`` returns the text
        written as ``<dump_dir>/<name>`` on every dump."""
        self.artifacts[name] = fn

    def note(self, name: str, t: Optional[float] = None,
             **meta) -> S.SpanEvent:
        """Record a "why" marker — into the engine span ring too (when
        present), so the Perfetto timeline shows the firing in place."""
        if self.spans is not None:
            return self.spans.marker(name, t=t, **meta)
        return self._markers.marker(name, t=t, **meta)

    def on_request(self, record: dict) -> None:
        """Keep one retired request's record (bounded)."""
        with self._lock:
            self._requests.append(record)

    # ---------------------------------------------------------------- dump
    def _events(self) -> list[S.SpanEvent]:
        evs = self._markers.events()
        if self.spans is not None:
            evs += self.spans.events()
        evs.sort(key=lambda e: e.t0)
        return evs

    def dump(self, reason: str = "manual",
             into: "Optional[Path]" = None) -> Optional[Path]:
        """Freeze the black box into ``<dump_dir>/flight_<stamp>_<reason>``.
        Returns the directory, or None once ``max_dumps`` is reached (the
        rings keep recording; only new directories stop). ``into`` dumps
        to that EXACT directory instead (the fleet's incident fan-out
        targets ``<incident_dir>/<replica>``); when unset, an installed
        :attr:`redirect` hook is asked for one first."""
        with self._lock:
            if self.max_dumps and len(self.dumps) >= self.max_dumps:
                # checked BEFORE the redirect hook: a dump-capped
                # recorder must not keep opening fleet incidents (the
                # cap bounds disk for the whole correlated capture too)
                return None
        if into is None and self.redirect is not None:
            try:
                into = self.redirect(reason)
            except Exception as e:
                # the correlation plumbing must never cost the LOCAL
                # post-mortem: fall back to a plain dump
                log_dist(f"flight recorder: incident redirect failed "
                         f"({e!r}); dumping locally", ranks=[0],
                         level="WARNING")
                into = None
        with self._lock:
            if self.max_dumps and len(self.dumps) >= self.max_dumps:
                return None          # raced a dump during the redirect
            stamp = time.strftime("%Y%m%d-%H%M%S")
            safe = sanitize_reason(reason)
            try:
                d = unique_dir(Path(into) if into is not None
                               else self.dump_dir
                               / f"flight_{stamp}_{safe}")
                d.mkdir(parents=True)
            except OSError as e:
                # full/read-only disk: losing the dump is acceptable;
                # raising OSError out of the watchdog, the nonfinite
                # halt, or the SIGTERM handler — replacing the error the
                # resilience layer is watching for — is not
                self._count_write_error()
                log_dist(f"flight recorder: dump to {self.dump_dir} "
                         f"failed ({e!r})", ranks=[0], level="WARNING")
                return None
            self.dumps.append(d)
            requests = list(self._requests)
        events = self._events()
        snaps: dict[str, object] = {}
        for name, fn in self.snapshots.items():
            try:
                snaps[name] = fn()
            except Exception as e:   # a broken provider must not lose the
                snaps[name] = {"error": repr(e)}   # rest of the dump
        # per-artifact guards: dump() runs on failure paths (watchdog
        # stall, SIGTERM) — one unserializable artifact must not raise out
        # of the serving loop and lose the rest of the post-mortem
        def _write(name, write):
            try:
                write()
            except Exception as e:
                self._count_write_error()
                try:
                    (d / (name + ".error")).write_text(repr(e),
                                                       encoding="utf-8")
                except OSError:
                    pass

        def _w_manifest():
            (d / "manifest.json").write_text(json.dumps({
                "reason": reason, "job": self.job_name,
                "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "clock_now": self.clock(),
                "events": len(events), "requests": len(requests),
                "snapshot_providers": sorted(snaps),
            }, indent=2, default=_json_default), encoding="utf-8")

        def _w_events():
            with open(d / "events.jsonl", "w", encoding="utf-8") as f:
                for ev in events:
                    f.write(json.dumps(ev.as_dict(), separators=(",", ":"),
                                       default=_json_default) + "\n")

        def _w_metrics():
            (d / "metrics.json").write_text(
                json.dumps(snaps, indent=2, default=_json_default),
                encoding="utf-8")

        def _w_requests():
            with open(d / "requests.jsonl", "w", encoding="utf-8") as f:
                for rec in requests:
                    f.write(json.dumps(rec, separators=(",", ":"),
                                       default=_json_default) + "\n")

        def _w_trace():
            from .export import write_chrome_trace

            write_chrome_trace(events, d / "trace.json", self.job_name)

        _write("manifest.json", _w_manifest)
        _write("events.jsonl", _w_events)
        _write("metrics.json", _w_metrics)
        _write("requests.jsonl", _w_requests)
        _write("trace.json", _w_trace)
        for name, fn in list(self.artifacts.items()):
            _write(name, lambda name=name, fn=fn:
                   (d / name).write_text(fn(), encoding="utf-8"))
        log_dist(f"flight recorder: dumped {len(events)} events to {d} "
                 f"(reason: {reason})", ranks=[0], level="WARNING")
        return d


def load_jsonl_tolerant(path) -> tuple[list, int]:
    """Parse a JSONL file, SKIPPING torn lines — ``(rows, skipped)``.

    The artifacts the triage tools read are left by crashed processes; a
    half-written trailing record is the expected state, not a reason to
    abort. Shared by :func:`read_flight_record` and the doctor CLI so
    both agree on what a torn artifact parses to."""
    rows: list = []
    skipped = 0
    for line in Path(path).read_text(errors="replace").splitlines():
        if not line.strip():
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            skipped += 1
    return rows, skipped


def newest_flight_record(dump_dir) -> Optional[Path]:
    """Most recent ``flight_*`` directory under ``dump_dir`` (mtime order),
    or None — the doctor CLI's and the runbook's entry point."""
    d = Path(dump_dir)
    if not d.is_dir():
        return None
    cands = [p for p in d.iterdir()
             if p.is_dir() and p.name.startswith("flight_")]
    if not cands:
        return None
    return max(cands, key=lambda p: (p.stat().st_mtime, p.name))


def read_flight_record(record_dir) -> dict:
    """Load one flight record back into a dict (doctor CLI + tests):
    ``{"manifest", "events", "metrics", "requests"}``.

    Torn artifacts — a dump interrupted by the very crash it was
    recording — degrade instead of raising: unparseable whole-file JSON
    reads back empty/None, torn JSONL lines are skipped and counted in
    ``torn_lines``. The triage path must survive every half-written
    state a dying process can leave."""

    def _json_or(path: Path, default):
        try:
            return json.loads(path.read_text(errors="replace"))
        except (OSError, json.JSONDecodeError):
            return default

    d = Path(record_dir)
    out = {"path": str(d), "torn_lines": 0}
    mf = d / "manifest.json"
    out["manifest"] = _json_or(mf, {}) if mf.exists() else {}
    mx = d / "metrics.json"
    out["metrics"] = _json_or(mx, {}) if mx.exists() else {}
    for name in ("events", "requests"):
        p = d / f"{name}.jsonl"
        rows: list = []
        if p.exists():
            rows, skipped = load_jsonl_tolerant(p)
            out["torn_lines"] += skipped
        out[name] = rows
    tr = d / "trace.json"
    out["trace"] = _json_or(tr, None) if tr.exists() else None
    return out
