"""Metrics core: counters / gauges / histograms with rolling reservoirs.

The serving/training analog of the reference's monitor + flops-profiler
numbers, unified: every component records into a :class:`MetricsRegistry`
(``Train/*`` from the training engine, ``Serve/*`` from the inference
engine, ``Comm/*`` from the collective census, ``Memory/*`` from the HBM
watermark), and ``snapshot()`` / ``to_events()`` expose one coherent
namespace to callers and to the :class:`~..monitor.monitor.MonitorMaster`
sinks (CSV / TensorBoard / WandB / JSONL / Prometheus).

Everything here is host-side Python over already-materialized floats —
recording never touches a device buffer, so instrumentation cannot add
host↔device synchronization. In a multi-host job each process keeps its
own registry; emission is process-0's business (``MonitorMaster`` already
gates on ``jax.process_index() == 0``), which is the reference monitor's
rank-0 aggregation contract.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, Optional

# Percentiles every histogram reports (nearest-rank over the rolling window).
DEFAULT_PERCENTILES = (50, 90, 99)


class Reservoir:
    """Rolling window of the most recent ``size`` observations.

    A plain ring buffer, not Vitter sampling: serving percentiles should
    reflect *recent* traffic (a latency regression must show up in p99 now,
    not diluted by the whole process history), and the window is small
    enough that keeping every recent sample exactly is cheaper than being
    clever."""

    def __init__(self, size: int = 1024):
        if size <= 0:
            raise ValueError(f"reservoir size must be positive, got {size}")
        self.size = int(size)
        self._buf: list[float] = []
        self._idx = 0          # next write slot once the buffer is full
        # sort cache, invalidated on add(): a publish pass reads the same
        # window several times (SLO scoring + event export), and re-sorting
        # up to 1024 samples per histogram per read doubles the lock-held
        # work for nothing. Guarded by the owning Histogram's lock.
        self._sorted: Optional[list[float]] = None

    def add(self, value: float) -> None:
        v = float(value)
        if len(self._buf) < self.size:
            self._buf.append(v)
        else:
            self._buf[self._idx] = v
            self._idx = (self._idx + 1) % self.size
        self._sorted = None

    def _sorted_buf(self) -> list[float]:
        if self._sorted is None:
            self._sorted = sorted(self._buf)
        return self._sorted

    def __len__(self) -> int:
        return len(self._buf)

    def values(self) -> list[float]:
        return list(self._buf)

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the window (q in [0, 100])."""
        if not self._buf:
            return math.nan
        s = self._sorted_buf()
        rank = max(1, math.ceil(q / 100.0 * len(s)))
        return s[min(rank, len(s)) - 1]

    def percentiles(self, qs: Iterable[float] = DEFAULT_PERCENTILES) -> dict:
        if not self._buf:
            return {f"p{_fmt_q(q)}": math.nan for q in qs}
        s = self._sorted_buf()
        out = {}
        for q in qs:
            rank = max(1, math.ceil(q / 100.0 * len(s)))
            out[f"p{_fmt_q(q)}"] = s[min(rank, len(s)) - 1]
        return out


def _fmt_q(q: float) -> str:
    return str(int(q)) if float(q).is_integer() else str(q).replace(".", "_")


class Counter:
    """Monotonic accumulator (requests served, tokens generated, bytes)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: Optional[threading.RLock] = None):
        self.name = name
        self.value = 0.0
        self._lock = lock if lock is not None else threading.RLock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:   # += is a read-modify-write, not atomic
            self.value += n


class Gauge:
    """Last-write-wins scalar (loss, lr, MFU, bytes in use)."""

    __slots__ = ("name", "value", "updated", "_lock")

    def __init__(self, name: str, lock: Optional[threading.RLock] = None):
        self.name = name
        self.value = math.nan
        self.updated = False
        self._lock = lock if lock is not None else threading.RLock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)
            self.updated = True


class Histogram:
    """Distribution summary: count/sum/last + rolling-window percentiles."""

    def __init__(self, name: str, reservoir_size: int = 1024,
                 percentiles: tuple = DEFAULT_PERCENTILES,
                 lock: Optional[threading.RLock] = None):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.last = math.nan
        self.percentiles = tuple(percentiles)
        self.reservoir = Reservoir(reservoir_size)
        self._lock = lock if lock is not None else threading.RLock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:   # count/sum/reservoir must move together
            self.count += 1
            self.sum += v
            self.last = v
            self.reservoir.add(v)

    def summary(self) -> dict:
        with self._lock:
            out = {"count": self.count,
                   "mean": (self.sum / self.count) if self.count else math.nan,
                   "last": self.last}
            out.update(self.reservoir.percentiles(self.percentiles))
            return out


class MetricsRegistry:
    """Named counters/gauges/histograms behind one shared lock.

    The registry's RLock is handed to every instrument it creates, so
    mutators (``inc``/``set``/``observe``) and readers
    (``snapshot``/``to_events``) serialize against each other — two server
    threads recording concurrently can't lose increments or tear a
    histogram's count/reservoir pair (reentrant because ``snapshot`` holds
    the lock while calling ``Histogram.summary``).

    ``snapshot()`` is the machine-readable read API (nested dict);
    ``to_events(step)`` flattens to the ``(name, value, step)`` tuples the
    monitor fan-out consumes — histograms emit ``<name>/p50`` etc. so every
    sink sees plain scalars."""

    def __init__(self, default_reservoir: int = 1024):
        self._lock = threading.RLock()
        self._default_reservoir = int(default_reservoir)
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    # ------------------------------------------------------------ accessors
    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, lock=self._lock)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, lock=self._lock)
            return g

    def histogram(self, name: str, reservoir_size: Optional[int] = None,
                  percentiles: tuple = DEFAULT_PERCENTILES) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(
                    name, reservoir_size or self._default_reservoir,
                    percentiles, lock=self._lock)
            return h

    # ----------------------------------------------------------- shorthands
    def set_gauges(self, values: dict[str, float]) -> None:
        for k, v in values.items():
            self.gauge(k).set(v)

    # -------------------------------------------------------------- readout
    def snapshot(self) -> dict:
        """Nested machine-readable view of everything recorded so far."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()
                           if g.updated},
                "histograms": {n: h.summary()
                               for n, h in self._histograms.items()},
            }

    def to_events(self, step: int) -> list[tuple]:
        """Flat ``(name, value, step)`` list for MonitorMaster.write_events.

        NaNs (empty gauges/histograms) are dropped rather than written: a
        NaN row poisons CSV plots and Prometheus scrapes alike."""
        events: list[tuple] = []
        with self._lock:
            for n, c in self._counters.items():
                events.append((n, c.value, step))
            for n, g in self._gauges.items():
                if g.updated and not math.isnan(g.value):
                    events.append((n, g.value, step))
            for n, h in self._histograms.items():
                for k, v in h.summary().items():
                    if isinstance(v, float) and math.isnan(v):
                        continue
                    events.append((f"{n}/{k}", v, step))
        return events

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


def publish_registry(registry: MetricsRegistry, monitor,
                     step: Optional[int] = None,
                     default_step_counter: Optional[str] = None) -> int:
    """Push a registry through a monitor fan-out — a ``MonitorMaster`` or
    anything with ``write_events([(name, value, step)])`` — flushing if the
    monitor supports it. ``step`` defaults to the value of
    ``default_step_counter`` (e.g. requests served): serving loops have no
    universal step cadence, so the caller names the clock. Returns the
    number of events written. The single implementation behind both
    engines' ``publish_metrics``."""
    if step is None:
        step = int(registry.counter(default_step_counter).value) \
            if default_step_counter else 0
    events = registry.to_events(step)
    monitor.write_events(events)
    fl = getattr(monitor, "flush", None)
    if fl is not None:
        fl()
    return len(events)


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """Process-wide default registry (engines default to their own private
    registries; this one is for ad-hoc instrumentation and scripts)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT
