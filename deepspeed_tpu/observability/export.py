"""Span export: Chrome-trace-event JSON (Perfetto) + per-request JSONL log.

Machine-readable views of the span ring (``spans.py``):

- :func:`to_chrome_trace` renders the events in the Chrome trace-event
  format Perfetto loads directly: the serving process as one pid with
  the queue, the prefill lane, the decode step, and every slot as its
  own named track; requests as complete (``X``) spans nested on their
  tracks; queue depth / slot occupancy as counter (``C``) tracks; SLO /
  anomaly / watchdog markers as instant (``i``) events. Training spans
  land under a second pid. ``ts`` is microseconds relative to the
  earliest event, per the spec.
- :func:`merge_fleet_trace` stitches a FLEET of rings into ONE trace:
  every replica's serving ring becomes its own pid (named after the
  replica), the fleet-level ring (router decisions, handoff hops —
  serving/fleet.py) lands under a ``router`` pid, and each request that
  crossed replicas gets a flow (``s``/``t``/``f`` arrows, id = rid)
  connecting its hops — the Dapper-style end-to-end timeline of a
  distributed request.
- :func:`hop_trace` is the per-request hop-latency decomposition
  (queue_wait/prefill/handoff_wait/import/decode/e2e) derived from the
  host timestamps the schedulers and the fleet stamp on the request —
  no span ring needed, which is why the request log can carry it.
- :class:`RequestLogSink` is a MonitorMaster-compatible writer that
  additionally accepts whole request records (one JSON object per
  retired request) — the request-level ground truth the scalar
  ``(name, value, step)`` event contract cannot carry.

:func:`validate_chrome_trace` is the schema gate the tests (and the
flight recorder's own smoke assertion) run over every generated trace:
required keys, known phases, non-negative durations, sorted timestamps,
matched B/E nesting, matched flow ids, and (for traces that name their
processes) no events under an unnamed pid.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Optional

import numpy as np

from . import spans as S
from .sinks import JsonlSink

# pids in the exported trace: one "process" per engine kind.
PID_SERVING = 1
PID_TRAIN = 2

# merged fleet traces: the router/handoff ring fronts the trace, replicas
# follow in fleet order (pid 10 + i, each named after its replica).
PID_FLEET = 1
_PID_REPLICA0 = 10
_FLEET_TID_ROUTER = 1
_FLEET_TID_HANDOFF = 2
_FLEET_TID_MARKERS = 3

# Fixed serving tids; slots start at _TID_SLOT0 (slot k → tid k + 10).
_TID_QUEUE = 1
_TID_PREFILL = 2
_TID_STEP = 3
_TID_MARKERS = 4
_TID_SLOT0 = 10
# per-session residency tracks (kvscope lifecycle spans) allocate from
# here in first-seen order — high enough that slot tids can never reach
_TID_SESSION0 = 1000

_TRAIN_TIDS = {"train_step": 1}   # phases allocate 2.. in first-seen order

# the communication observatory's tracks (observability/commscope.py),
# fixed high so dynamically-allocated phase tids can never collide:
# collective ops in flight, and the exposed gaps (collective time NOT
# hidden behind compute — the T3 number, visible as a track)
_TID_COMM = 98
_TID_COMM_EXPOSED = 99


def _sec_to_us(t: float, origin: float) -> float:
    return max(0.0, (t - origin) * 1e6)


def _slot_tid(slot) -> int:
    return _TID_SLOT0 + int(slot)


def to_chrome_trace(events: Iterable[S.SpanEvent],
                    job_name: str = "deepspeed_tpu",
                    origin: Optional[float] = None) -> dict:
    """Span events → a Chrome trace-event JSON object (Perfetto-loadable).

    Events are emitted sorted by ``ts`` and every span uses the complete
    (``X``) phase — no B/E pairing for a ring buffer whose head may have
    evicted a B while keeping its E. ``origin`` pins the t=0 reference
    (``merge_fleet_trace`` passes one shared origin so every replica's
    timestamps land on the same axis); None = this ring's earliest event."""
    evs = list(events)
    if not evs:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"job": job_name}}
    if origin is None:
        origin = min(e.t0 for e in evs)
    out: list[dict] = []
    used_tids: dict[int, set] = {PID_SERVING: set(), PID_TRAIN: set()}
    train_tids = dict(_TRAIN_TIDS)
    session_tids: dict[str, int] = {}    # residency tracks, first-seen

    def add(pid, tid, ph, name, ts, dur=None, args=None):
        ev = {"name": name, "ph": ph, "pid": pid, "tid": tid,
              "ts": round(ts, 3)}
        if dur is not None:
            ev["dur"] = round(max(0.0, dur), 3)
        if ph == "i":
            ev["s"] = "p"             # process-scoped instant
        if args:
            ev["args"] = args
        used_tids[pid].add(tid)
        out.append(ev)

    for e in evs:
        ts = _sec_to_us(e.t0, origin)
        dur = None if e.t1 is None else (e.t1 - e.t0) * 1e6
        args = {k: v for k, v in e.meta.items()}
        if e.rid is not None:
            args["rid"] = e.rid
        if e.step is not None:
            args["step"] = e.step
        if e.kind == S.QUEUED:
            add(PID_SERVING, _TID_QUEUE, "X", f"queued rid={e.rid}", ts,
                dur or 0.0, args)
        elif e.kind == S.PREFILL_CHUNK:
            add(PID_SERVING, _TID_PREFILL, "X",
                f"prefill rid={e.rid} chunk={e.meta.get('chunk', '?')}",
                ts, dur or 0.0, args)
        elif e.kind == S.PLACED:
            add(PID_SERVING, _slot_tid(e.slot), "i",
                f"placed rid={e.rid}", ts, None, args)
        elif e.kind == S.DECODE_RESIDENCY:
            add(PID_SERVING, _slot_tid(e.slot), "X",
                f"decode rid={e.rid}", ts, dur or 0.0, args)
        elif e.kind == S.RETIRED:
            add(PID_SERVING,
                _slot_tid(e.slot) if e.slot is not None and e.slot >= 0
                else _TID_QUEUE, "i",
                f"retired rid={e.rid} [{e.meta.get('status', '?')}]",
                ts, None, args)
        elif e.kind == S.DECODE_STEP:
            add(PID_SERVING, _TID_STEP, "X", "decode_step", ts,
                dur or 0.0, args)
        elif e.kind == S.OCCUPANCY:
            # one counter track per sample name — Perfetto draws them as
            # stacked value timelines
            for k, v in e.meta.items():
                out.append({"name": k, "ph": "C", "pid": PID_SERVING,
                            "tid": 0, "ts": round(ts, 3),
                            "args": {k: v}})
        elif e.kind == S.MARKER:
            nm = e.meta.get("name", "marker")
            add(PID_SERVING, _TID_MARKERS, "i", f"marker:{nm}", ts, None,
                args)
        elif e.kind == S.TRAIN_STEP:
            add(PID_TRAIN, train_tids["train_step"], "X", "train_step",
                ts, dur or 0.0, args)
        elif e.kind == S.TRAIN_PHASE:
            phase = e.meta.get("phase", "phase")
            tid = train_tids.setdefault(phase, len(train_tids) + 1)
            add(PID_TRAIN, tid, "X", phase, ts, dur or 0.0, args)
        elif e.kind in (S.SESSION_ACTIVE, S.SESSION_IDLE):
            # per-session residency track (kvscope): active bursts and
            # the idle gaps between them on one line per session — the
            # host-tier trade (idle HBM vs regretted recompute) readable
            # straight off the timeline
            sid = str(e.meta.get("session", "?"))
            tid = session_tids.setdefault(
                sid, _TID_SESSION0 + len(session_tids))
            nm = "active" if e.kind == S.SESSION_ACTIVE else "idle"
            add(PID_SERVING, tid, "X", nm, ts, dur or 0.0, args)
        elif e.kind == S.COMM_OP:
            add(PID_TRAIN, _TID_COMM, "X",
                str(e.meta.get("collective", "collective")), ts,
                dur or 0.0, args)
        elif e.kind == S.COMM_EXPOSED:
            add(PID_TRAIN, _TID_COMM_EXPOSED, "X", "exposed", ts,
                dur or 0.0, args)
        else:   # unknown kind: keep it visible rather than dropping it
            add(PID_SERVING, _TID_MARKERS, "i", f"event:{e.kind}", ts,
                None, args)

    out.sort(key=lambda ev: ev["ts"])
    meta: list[dict] = []

    def name_meta(pid, name):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "ts": 0.0, "args": {"name": name}})

    def thread_meta(pid, tid, name):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "ts": 0.0, "args": {"name": name}})

    if used_tids[PID_SERVING] or any(ev["pid"] == PID_SERVING
                                     for ev in out):
        name_meta(PID_SERVING, f"{job_name}:serving")
        for tid, nm in ((_TID_QUEUE, "queue"), (_TID_PREFILL, "prefill"),
                        (_TID_STEP, "decode-step"),
                        (_TID_MARKERS, "markers")):
            if tid in used_tids[PID_SERVING]:
                thread_meta(PID_SERVING, tid, nm)
        for tid in sorted(t for t in used_tids[PID_SERVING]
                          if _TID_SLOT0 <= t < _TID_SESSION0):
            thread_meta(PID_SERVING, tid, f"slot {tid - _TID_SLOT0}")
        for sid, tid in session_tids.items():
            thread_meta(PID_SERVING, tid, f"session {sid}")
    if used_tids[PID_TRAIN]:
        name_meta(PID_TRAIN, f"{job_name}:train")
        for phase, tid in train_tids.items():
            if tid in used_tids[PID_TRAIN]:
                thread_meta(PID_TRAIN, tid, phase)
        for tid, nm in ((_TID_COMM, "comm"),
                        (_TID_COMM_EXPOSED, "comm-exposed")):
            if tid in used_tids[PID_TRAIN]:
                thread_meta(PID_TRAIN, tid, nm)
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"job": job_name}}


def write_chrome_trace(events: Iterable[S.SpanEvent], path,
                       job_name: str = "deepspeed_tpu") -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(to_chrome_trace(events, job_name)),
                    encoding="utf-8")
    return path


# ------------------------------------------------------------- fleet merge
def merge_fleet_trace(replica_events: "dict[str, Iterable[S.SpanEvent]]",
                      fleet_events: Optional[Iterable[S.SpanEvent]] = None,
                      job_name: str = "fleet") -> dict:
    """Merge N replica span rings + the fleet-level ring into ONE
    Chrome/Perfetto trace.

    Every replica renders exactly as :func:`to_chrome_trace` would —
    queue/prefill/decode-step/slot tracks — but under its OWN pid
    (``10 + i`` in fleet order, process-named ``{job}:{replica}``),
    against one shared time origin so all timelines align. The fleet
    ring (router decisions, requeues, handoff export/pending/import —
    ``serving/fleet.py``) fronts the trace as a ``{job}:router`` process.
    Each request whose ``X`` slices land on more than one pid is
    stitched into a flow (``s``/``t``/``f``, ``id`` = rid): Perfetto
    draws the arrows that make the cross-replica causal chain —
    admission on the prefill replica, the handoff hop on the router
    track, decode residency on the decode replica — readable as one
    request."""
    fleet_evs = list(fleet_events or [])
    rings = {str(name): list(evs) for name, evs in replica_events.items()}
    all_evs = fleet_evs + [e for evs in rings.values() for e in evs]
    if not all_evs:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"job": job_name, "replicas": list(rings)}}
    origin = min(e.t0 for e in all_evs)
    meta: list[dict] = []
    out: list[dict] = []
    # ---- replicas: the single-engine exporter, remapped to a fleet pid
    for i, (name, evs) in enumerate(rings.items()):
        pid = _PID_REPLICA0 + i
        sub = to_chrome_trace(evs, job_name=job_name, origin=origin)
        for ev in sub["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = pid        # serving AND (unexpected) train events
            if ev.get("ph") == "M":
                if ev["name"] == "process_name":
                    ev["args"] = {"name": f"{job_name}:{name}"}
                meta.append(ev)
            else:
                args = dict(ev.get("args") or {})
                args["replica"] = name
                ev["args"] = args
                out.append(ev)
    # ---- fleet ring: router decisions + handoff hops under PID_FLEET
    used_fleet: set = set()

    def fadd(tid, ph, nm, ts, dur=None, args=None):
        ev = {"name": nm, "ph": ph, "pid": PID_FLEET, "tid": tid,
              "ts": round(ts, 3)}
        if dur is not None:
            ev["dur"] = round(max(0.0, dur), 3)
        if ph == "i":
            ev["s"] = "p"
        if args:
            ev["args"] = args
        used_fleet.add(tid)
        out.append(ev)

    for e in fleet_evs:
        ts = _sec_to_us(e.t0, origin)
        dur = None if e.t1 is None else (e.t1 - e.t0) * 1e6
        args = dict(e.meta)
        if e.rid is not None:
            args["rid"] = e.rid
        if e.kind in (S.ROUTE, S.REQUEUE):
            fadd(_FLEET_TID_ROUTER, "i",
                 f"{e.kind} rid={e.rid} -> {e.meta.get('replica', '?')}",
                 ts, None, args)
        elif e.kind in (S.HANDOFF_EXPORT, S.HANDOFF_PENDING,
                        S.HANDOFF_IMPORT):
            fadd(_FLEET_TID_HANDOFF, "X",
                 f"{e.kind.replace('handoff_', '')} rid={e.rid}",
                 ts, dur or 0.0, args)
        elif e.kind == S.MARKER:
            fadd(_FLEET_TID_MARKERS, "i",
                 f"marker:{e.meta.get('name', 'marker')}", ts, None, args)
        else:
            fadd(_FLEET_TID_MARKERS, "i", f"event:{e.kind}", ts, None,
                 args)
    if used_fleet:
        meta.append({"name": "process_name", "ph": "M", "pid": PID_FLEET,
                     "tid": 0, "ts": 0.0,
                     "args": {"name": f"{job_name}:router"}})
        for tid, nm in ((_FLEET_TID_ROUTER, "router"),
                        (_FLEET_TID_HANDOFF, "handoff"),
                        (_FLEET_TID_MARKERS, "markers")):
            if tid in used_fleet:
                meta.append({"name": "thread_name", "ph": "M",
                             "pid": PID_FLEET, "tid": tid, "ts": 0.0,
                             "args": {"name": nm}})
    # ---- flows: one arrow chain per request that crossed pids
    anchors: dict = {}
    for ev in out:
        if ev.get("ph") == "X":
            rid = (ev.get("args") or {}).get("rid")
            if rid is not None:
                anchors.setdefault(rid, []).append(
                    (ev["ts"], ev["pid"], ev["tid"]))
    for rid in sorted(anchors):
        pts = anchors[rid]
        if len({p for _, p, _ in pts}) < 2:
            continue      # never left one replica: no arrow to draw
        pts.sort()
        for j, (ts, pid, tid) in enumerate(pts):
            ph = "s" if j == 0 else ("f" if j == len(pts) - 1 else "t")
            fe = {"name": f"rid {rid}", "cat": "request", "ph": ph,
                  "id": int(rid), "pid": pid, "tid": tid, "ts": ts}
            if ph != "s":
                fe["bp"] = "e"     # bind to the ENCLOSING slice
            out.append(fe)
    # flows sort behind slices at the same ts ("f" last), so the
    # validator's per-id s→f order holds even on coincident stamps
    rank = {"s": 1, "t": 1, "f": 2}
    out.sort(key=lambda ev: (ev["ts"], rank.get(ev["ph"], 0)))
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"job": job_name, "replicas": list(rings)}}


# ----------------------------------------------------------------- validator
_KNOWN_PHASES = {"X", "B", "E", "i", "I", "C", "M", "b", "e", "n", "s",
                 "t", "f"}


def validate_chrome_trace(trace: dict) -> list[str]:
    """Schema gate over a trace-event JSON object; returns the list of
    problems (empty = valid). Checks: the ``traceEvents`` envelope,
    per-event required keys, known phases, non-negative ``ts``/``dur``,
    timestamps sorted among non-metadata events, matched B/E nesting
    per (pid, tid), matched flow chains per id (``s`` first, ``f``
    present — a dangling flow draws no arrow in Perfetto), and — when
    the trace names any process — no timeline event under an unnamed
    pid (merged fleet traces name every replica; an unknown pid means
    a ring was merged without its identity)."""
    problems: list[str] = []
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["missing or non-list traceEvents"]
    last_ts: Optional[float] = None
    stacks: dict[tuple, list] = {}
    named_pids: set = set()
    seen_pids: set = set()
    flows: dict = {}
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        missing = [k for k in ("name", "ph", "pid", "tid", "ts")
                   if k not in ev]
        if missing:
            problems.append(f"event {i}: missing keys {missing}")
            continue
        if ph not in _KNOWN_PHASES:
            problems.append(f"event {i}: unknown phase {ph!r}")
            continue
        ts = ev["ts"]
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        if ph == "M":
            if ev["name"] == "process_name":
                named_pids.add(ev["pid"])
            continue                  # metadata: outside the timeline
        seen_pids.add(ev["pid"])
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i}: ts {ts} < previous {last_ts} "
                            "(events must be sorted)")
        last_ts = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: X event needs dur >= 0, "
                                f"got {dur!r}")
        elif ph == "B":
            stacks.setdefault((ev["pid"], ev["tid"]), []).append(ev["name"])
        elif ph == "E":
            stack = stacks.get((ev["pid"], ev["tid"]), [])
            if not stack:
                problems.append(f"event {i}: E without matching B on "
                                f"(pid={ev['pid']}, tid={ev['tid']})")
            else:
                stack.pop()
        elif ph in ("s", "t", "f"):
            fid = ev.get("id")
            if fid is None:
                problems.append(f"event {i}: flow event without id")
                continue
            seq = flows.setdefault(fid, [])
            if not seq and ph != "s":
                problems.append(f"event {i}: flow id {fid!r} {ph} "
                                "without a preceding s")
            seq.append(ph)
    for (pid, tid), stack in stacks.items():
        if stack:
            problems.append(f"unclosed B events on (pid={pid}, tid={tid}): "
                            f"{stack}")
    for fid in sorted(flows, key=str):
        seq = flows[fid]
        if "s" in seq and "f" not in seq:
            problems.append(f"dangling flow id {fid!r}: s without f")
    if named_pids:
        for pid in sorted(seen_pids - named_pids, key=str):
            problems.append(f"unknown pid {pid}: events under a pid with "
                            "no process_name metadata")
    return problems


# --------------------------------------------------------------- hop trace
# the hop names, in causal order; hop_trace() keys are these + "_s"
HOP_NAMES = ("queue_wait", "prefill", "handoff_wait", "import", "decode")


def hop_trace(req) -> dict:
    """Per-request hop-latency decomposition, derived from the host
    timestamps the schedulers and the fleet stamp on the request — no
    span ring required (which is why the request log carries it).

    Hops, on the owner's injectable clock:

    - ``queue_wait_s``   — submit → admission (covers EVERY earlier
      attempt plus the requeue delay when the request was failed over);
    - ``prefill_s``      — admission → first token (chunked prefill);
    - ``handoff_wait_s`` — first token → the start of the import that
      seated it on a decode replica (page export + host-held pending);
      a request that DIED in the handoff buffer (deadline, cancel)
      closes this hop at its finish instead — the wait is a handoff
      wait, never decode time; None outside disaggregated serving;
    - ``import_s``       — the import program's wall window; None
      outside disaggregated serving;
    - ``decode_s``       — decode residency → retirement; None for a
      request that never reached a decode slot after its handoff;
    - ``e2e_s``          — submit → retirement.

    The non-null hops TILE ``[submit_t, finish_t]`` — their sum equals
    ``e2e_s`` exactly (the fake-clock tests pin it to within 1% as the
    documented invariant). ``requeue_delay_s`` (kill → re-admission,
    None unless the request was requeued) OVERLAPS ``queue_wait_s`` —
    it separates TTFT from failover cost, it is not an extra hop."""
    st = req.submit_t
    at = getattr(req, "admit_t", None)
    ft = req.first_token_t
    fin = req.finish_t
    ex = getattr(req, "export_t", None)
    i0 = getattr(req, "import_t0", None)
    i1 = getattr(req, "import_t1", None)
    out: dict = {f"{h}_s": None for h in HOP_NAMES}
    out["e2e_s"] = None
    if at is not None:
        out["queue_wait_s"] = at - st
        if ft is not None:
            out["prefill_s"] = ft - at
    if ft is not None:
        if i0 is not None:
            out["handoff_wait_s"] = i0 - ft
            if i1 is not None:
                out["import_s"] = i1 - i0
            if fin is not None:
                out["decode_s"] = fin - (i1 if i1 is not None else i0)
        elif ex is not None:
            # exported but never imported: the request died in the
            # handoff buffer — that time is handoff wait, NOT decode
            if fin is not None:
                out["handoff_wait_s"] = fin - ft
        elif fin is not None:
            out["decode_s"] = fin - ft
    if fin is not None:
        out["e2e_s"] = fin - st
    out["attempts"] = int(getattr(req, "attempts", 0))
    rq = getattr(req, "requeue_t", None)
    out["requeue_delay_s"] = (at - rq) if (rq is not None
                                          and at is not None) else None
    return out


# ------------------------------------------------------------- request log
# v2 grew the fields deterministic replay needs (observability/replay.py
# trace_from_request_log): prompt token ids, sampling seed, session id,
# and the per-request deadline BUDGETS (relative seconds, recomputed from
# the absolute stamps) — an existing request log upgrades cleanly into a
# TrafficTrace. v3 adds `tenant_id` (the cost-attribution dimension,
# observability/tenantscope.py). Old rows still parse everywhere: v2 rows
# upgrade with tenant_id="default" (counted, never a crash); v1 rows (no
# schema key) just cannot replay.
REQUEST_RECORD_SCHEMA = "dstpu.request_record.v3"


def request_record(req, queue_wait_s: Optional[float] = None) -> dict:
    """One retired serving request → a flat JSON-able record (the
    per-request row of the request log and of flight dumps)."""
    status = getattr(req.status, "value", str(req.status))
    admit_t = getattr(req, "admit_t", None)
    if queue_wait_s is None and admit_t is not None:
        queue_wait_s = admit_t - req.submit_t
    ttft = (req.first_token_t - req.submit_t
            if req.first_token_t is not None else None)
    tpot = None
    n = len(req.tokens)
    if (req.finish_t is not None and req.first_token_t is not None
            and n > 1):
        tpot = (req.finish_t - req.first_token_t) / (n - 1)
    dl_ttft = getattr(req, "deadline_ttft", None)
    dl_total = getattr(req, "deadline_total", None)
    prompt = getattr(req, "prompt", None)
    # session ids are opaque hashables (fleet affinity); the record must
    # stay json.dumps-able by every sink, so exotic types stringify
    sid = getattr(req, "session_id", None)
    if sid is not None and not isinstance(sid, (str, int, float, bool)):
        sid = str(sid)
    return {
        "schema": REQUEST_RECORD_SCHEMA,
        "rid": req.rid, "status": status, "prompt_len": req.prompt_len,
        # replay fields: the (prompt, seed) pair IS the request's bit
        # stream (per-request RNG folds from the seed), session_id keys
        # fleet affinity, the deadline budgets are the submit overrides
        "prompt": ([int(t) for t in np.asarray(prompt).reshape(-1)
                    .tolist()] if prompt is not None else None),
        "seed": int(getattr(req, "seed", 0)),
        "session_id": sid,
        "tenant_id": str(getattr(req, "tenant_id", "default") or "default"),
        "ttft_deadline_s": (dl_ttft - req.submit_t
                            if dl_ttft is not None else None),
        "total_deadline_s": (dl_total - req.submit_t
                             if dl_total is not None else None),
        "max_new": req.max_new, "tokens": n, "slot": req.slot,
        "submit_t": req.submit_t, "first_token_t": req.first_token_t,
        "finish_t": req.finish_t, "ttft_s": ttft, "tpot_s": tpot,
        "queue_wait_s": queue_wait_s, "error": req.error or None,
        # failover visibility: >0 means the fleet router moved this
        # request to a surviving replica (REQUEUED transitions)
        "attempts": getattr(req, "attempts", 0),
        # the hop-latency decomposition (hop_trace): offline analysis of
        # where a request's wall time went — queue / prefill / handoff /
        # import / decode — needs no span ring. Handoff hops are null
        # outside disaggregated serving.
        "trace": hop_trace(req),
    }


class RequestLogSink(JsonlSink):
    """Per-request JSONL log riding the MonitorMaster fan-out.

    A :class:`~.sinks.JsonlSink` whose payload is whole request records
    (engines call :meth:`log_request`), not scalar events — so it
    inherits the persistent handle, flush boundaries, and ``rotate_mb``
    rotation. Implements the writer contract so MonitorMaster owns its
    lifecycle like every other sink."""

    SUFFIX = ".requests.jsonl"
    FLUSH_EVERY = 16

    def log_request(self, record: dict) -> None:
        self._write_line(json.dumps(record, separators=(",", ":")))

    def write_events(self, events) -> None:
        """Scalar metric events are not this sink's payload (the JSONL
        event log already carries them) — accept and drop."""
