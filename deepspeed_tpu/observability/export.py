"""Span export: Chrome-trace-event JSON (Perfetto) + per-request JSONL log.

Two machine-readable views of the span ring (``spans.py``):

- :func:`to_chrome_trace` renders the events in the Chrome trace-event
  format Perfetto loads directly: the serving process as one pid with
  the queue, the prefill lane, the decode step, and every slot as its
  own named track; requests as complete (``X``) spans nested on their
  tracks; queue depth / slot occupancy as counter (``C``) tracks; SLO /
  anomaly / watchdog markers as instant (``i``) events. Training spans
  land under a second pid. ``ts`` is microseconds relative to the
  earliest event, per the spec.
- :class:`RequestLogSink` is a MonitorMaster-compatible writer that
  additionally accepts whole request records (one JSON object per
  retired request) — the request-level ground truth the scalar
  ``(name, value, step)`` event contract cannot carry.

:func:`validate_chrome_trace` is the schema gate the tests (and the
flight recorder's own smoke assertion) run over every generated trace:
required keys, known phases, non-negative durations, sorted timestamps,
matched B/E nesting.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Optional

from . import spans as S
from .sinks import JsonlSink

# pids in the exported trace: one "process" per engine kind.
PID_SERVING = 1
PID_TRAIN = 2

# Fixed serving tids; slots start at _TID_SLOT0 (slot k → tid k + 10).
_TID_QUEUE = 1
_TID_PREFILL = 2
_TID_STEP = 3
_TID_MARKERS = 4
_TID_SLOT0 = 10

_TRAIN_TIDS = {"train_step": 1}   # phases allocate 2.. in first-seen order


def _sec_to_us(t: float, origin: float) -> float:
    return max(0.0, (t - origin) * 1e6)


def _slot_tid(slot) -> int:
    return _TID_SLOT0 + int(slot)


def to_chrome_trace(events: Iterable[S.SpanEvent],
                    job_name: str = "deepspeed_tpu") -> dict:
    """Span events → a Chrome trace-event JSON object (Perfetto-loadable).

    Events are emitted sorted by ``ts`` and every span uses the complete
    (``X``) phase — no B/E pairing for a ring buffer whose head may have
    evicted a B while keeping its E."""
    evs = list(events)
    if not evs:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"job": job_name}}
    origin = min(e.t0 for e in evs)
    out: list[dict] = []
    used_tids: dict[int, set] = {PID_SERVING: set(), PID_TRAIN: set()}
    train_tids = dict(_TRAIN_TIDS)

    def add(pid, tid, ph, name, ts, dur=None, args=None):
        ev = {"name": name, "ph": ph, "pid": pid, "tid": tid,
              "ts": round(ts, 3)}
        if dur is not None:
            ev["dur"] = round(max(0.0, dur), 3)
        if ph == "i":
            ev["s"] = "p"             # process-scoped instant
        if args:
            ev["args"] = args
        used_tids[pid].add(tid)
        out.append(ev)

    for e in evs:
        ts = _sec_to_us(e.t0, origin)
        dur = None if e.t1 is None else (e.t1 - e.t0) * 1e6
        args = {k: v for k, v in e.meta.items()}
        if e.rid is not None:
            args["rid"] = e.rid
        if e.step is not None:
            args["step"] = e.step
        if e.kind == S.QUEUED:
            add(PID_SERVING, _TID_QUEUE, "X", f"queued rid={e.rid}", ts,
                dur or 0.0, args)
        elif e.kind == S.PREFILL_CHUNK:
            add(PID_SERVING, _TID_PREFILL, "X",
                f"prefill rid={e.rid} chunk={e.meta.get('chunk', '?')}",
                ts, dur or 0.0, args)
        elif e.kind == S.PLACED:
            add(PID_SERVING, _slot_tid(e.slot), "i",
                f"placed rid={e.rid}", ts, None, args)
        elif e.kind == S.DECODE_RESIDENCY:
            add(PID_SERVING, _slot_tid(e.slot), "X",
                f"decode rid={e.rid}", ts, dur or 0.0, args)
        elif e.kind == S.RETIRED:
            add(PID_SERVING,
                _slot_tid(e.slot) if e.slot is not None and e.slot >= 0
                else _TID_QUEUE, "i",
                f"retired rid={e.rid} [{e.meta.get('status', '?')}]",
                ts, None, args)
        elif e.kind == S.DECODE_STEP:
            add(PID_SERVING, _TID_STEP, "X", "decode_step", ts,
                dur or 0.0, args)
        elif e.kind == S.OCCUPANCY:
            # one counter track per sample name — Perfetto draws them as
            # stacked value timelines
            for k, v in e.meta.items():
                out.append({"name": k, "ph": "C", "pid": PID_SERVING,
                            "tid": 0, "ts": round(ts, 3),
                            "args": {k: v}})
        elif e.kind == S.MARKER:
            nm = e.meta.get("name", "marker")
            add(PID_SERVING, _TID_MARKERS, "i", f"marker:{nm}", ts, None,
                args)
        elif e.kind == S.TRAIN_STEP:
            add(PID_TRAIN, train_tids["train_step"], "X", "train_step",
                ts, dur or 0.0, args)
        elif e.kind == S.TRAIN_PHASE:
            phase = e.meta.get("phase", "phase")
            tid = train_tids.setdefault(phase, len(train_tids) + 1)
            add(PID_TRAIN, tid, "X", phase, ts, dur or 0.0, args)
        else:   # unknown kind: keep it visible rather than dropping it
            add(PID_SERVING, _TID_MARKERS, "i", f"event:{e.kind}", ts,
                None, args)

    out.sort(key=lambda ev: ev["ts"])
    meta: list[dict] = []

    def name_meta(pid, name):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "ts": 0.0, "args": {"name": name}})

    def thread_meta(pid, tid, name):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "ts": 0.0, "args": {"name": name}})

    if used_tids[PID_SERVING] or any(ev["pid"] == PID_SERVING
                                     for ev in out):
        name_meta(PID_SERVING, f"{job_name}:serving")
        for tid, nm in ((_TID_QUEUE, "queue"), (_TID_PREFILL, "prefill"),
                        (_TID_STEP, "decode-step"),
                        (_TID_MARKERS, "markers")):
            if tid in used_tids[PID_SERVING]:
                thread_meta(PID_SERVING, tid, nm)
        for tid in sorted(t for t in used_tids[PID_SERVING]
                          if t >= _TID_SLOT0):
            thread_meta(PID_SERVING, tid, f"slot {tid - _TID_SLOT0}")
    if used_tids[PID_TRAIN]:
        name_meta(PID_TRAIN, f"{job_name}:train")
        for phase, tid in train_tids.items():
            if tid in used_tids[PID_TRAIN]:
                thread_meta(PID_TRAIN, tid, phase)
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"job": job_name}}


def write_chrome_trace(events: Iterable[S.SpanEvent], path,
                       job_name: str = "deepspeed_tpu") -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(to_chrome_trace(events, job_name)),
                    encoding="utf-8")
    return path


# ----------------------------------------------------------------- validator
_KNOWN_PHASES = {"X", "B", "E", "i", "I", "C", "M", "b", "e", "n", "s",
                 "t", "f"}


def validate_chrome_trace(trace: dict) -> list[str]:
    """Schema gate over a trace-event JSON object; returns the list of
    problems (empty = valid). Checks: the ``traceEvents`` envelope,
    per-event required keys, known phases, non-negative ``ts``/``dur``,
    timestamps sorted among non-metadata events, and matched B/E nesting
    per (pid, tid)."""
    problems: list[str] = []
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["missing or non-list traceEvents"]
    last_ts: Optional[float] = None
    stacks: dict[tuple, list] = {}
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        missing = [k for k in ("name", "ph", "pid", "tid", "ts")
                   if k not in ev]
        if missing:
            problems.append(f"event {i}: missing keys {missing}")
            continue
        if ph not in _KNOWN_PHASES:
            problems.append(f"event {i}: unknown phase {ph!r}")
            continue
        ts = ev["ts"]
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        if ph == "M":
            continue                  # metadata: outside the timeline
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i}: ts {ts} < previous {last_ts} "
                            "(events must be sorted)")
        last_ts = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: X event needs dur >= 0, "
                                f"got {dur!r}")
        elif ph == "B":
            stacks.setdefault((ev["pid"], ev["tid"]), []).append(ev["name"])
        elif ph == "E":
            stack = stacks.get((ev["pid"], ev["tid"]), [])
            if not stack:
                problems.append(f"event {i}: E without matching B on "
                                f"(pid={ev['pid']}, tid={ev['tid']})")
            else:
                stack.pop()
    for (pid, tid), stack in stacks.items():
        if stack:
            problems.append(f"unclosed B events on (pid={pid}, tid={tid}): "
                            f"{stack}")
    return problems


# ------------------------------------------------------------- request log
def request_record(req, queue_wait_s: Optional[float] = None) -> dict:
    """One retired serving request → a flat JSON-able record (the
    per-request row of the request log and of flight dumps)."""
    status = getattr(req.status, "value", str(req.status))
    admit_t = getattr(req, "admit_t", None)
    if queue_wait_s is None and admit_t is not None:
        queue_wait_s = admit_t - req.submit_t
    ttft = (req.first_token_t - req.submit_t
            if req.first_token_t is not None else None)
    tpot = None
    n = len(req.tokens)
    if (req.finish_t is not None and req.first_token_t is not None
            and n > 1):
        tpot = (req.finish_t - req.first_token_t) / (n - 1)
    return {
        "rid": req.rid, "status": status, "prompt_len": req.prompt_len,
        "max_new": req.max_new, "tokens": n, "slot": req.slot,
        "submit_t": req.submit_t, "first_token_t": req.first_token_t,
        "finish_t": req.finish_t, "ttft_s": ttft, "tpot_s": tpot,
        "queue_wait_s": queue_wait_s, "error": req.error or None,
        # failover visibility: >0 means the fleet router moved this
        # request to a surviving replica (REQUEUED transitions)
        "attempts": getattr(req, "attempts", 0),
    }


class RequestLogSink(JsonlSink):
    """Per-request JSONL log riding the MonitorMaster fan-out.

    A :class:`~.sinks.JsonlSink` whose payload is whole request records
    (engines call :meth:`log_request`), not scalar events — so it
    inherits the persistent handle, flush boundaries, and ``rotate_mb``
    rotation. Implements the writer contract so MonitorMaster owns its
    lifecycle like every other sink."""

    SUFFIX = ".requests.jsonl"
    FLUSH_EVERY = 16

    def log_request(self, record: dict) -> None:
        self._write_line(json.dumps(record, separators=(",", ":")))

    def write_events(self, events) -> None:
        """Scalar metric events are not this sink's payload (the JSONL
        event log already carries them) — accept and drop."""
