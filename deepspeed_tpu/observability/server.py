"""Live telemetry & control plane: per-engine HTTP ops surface.

Every observability layer before this one was file-based — ``.prom``
textfiles, JSONL logs, flight-record directories read after the fact.
A fleet needs engines that are *live* targets: scrapeable metrics,
machine-readable probes, and remote drain/dump control. This module is
that surface, dependency-free on the stdlib ``http.server``:

Read endpoints (GET):

- ``/metrics``  — Prometheus exposition, byte-compatible with the
  textfile sink (both render through ``expfmt.render_exposition``);
- ``/healthz``  — liveness JSON (200 while the process serves requests);
- ``/readyz``   — readiness JSON, **503** when not ready (draining /
  queue full) — the k8s-style probe contract;
- ``/requests`` — live in-flight table (rid, state, slot, tokens,
  deadlines) straight from the scheduler;
- ``/capacity`` — the capacity report (PR 6); ``?census=1`` adds the
  AOT program census (expensive — off by default per scrape);
- ``/goodput``  — the goodput/badput decomposition (``goodput.py``);
- ``/tenants``  — per-tenant cost/fairness breakdown (``tenantscope.py``
  report: attribution rows, Jain index, noisy-neighbor state);
- ``/flight``   — newest flight-record summary (manifest + why-marker
  names), the live analog of the doctor's file-mode flight section;
- ``/trace``    — the engine's span ring as a Chrome/Perfetto trace
  (save and load at ui.perfetto.dev); ``?rid=N`` returns that request's
  hop-latency decomposition (queue_wait/prefill/handoff_wait/import/
  decode/e2e) instead.

Control endpoints (POST, token-gated — see below):

- ``/drain``       — begin a graceful drain (body ``{"end": true}``
  reopens intake);
- ``/flight/dump`` — freeze the flight recorder now, why-marker
  ``manual``;
- ``/slo/reload``  — swap the SLO config live (JSON body = the new
  ``SLOConfig`` dict).

Security posture: the server binds **loopback by default**; exposing it
beyond localhost is an explicit config/call-site decision. Control
POSTs additionally require the configured bearer token
(``Authorization: Bearer <token>`` or ``X-DSTPU-Token``) when one is
set; without a token they are accepted from loopback peers only.

Cost discipline: config-gated, off by default — a disabled engine
builds no server object, spawns **zero threads**, compiles zero
programs, and adds zero host syncs (the ``bench_serving.py --smoke``
compile-freeze gate is the oracle). Enabled, request handling runs on
daemon threads and only ever touches host-side Python state (registry
snapshots under their own locks, scheduler tables copied defensively).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from ..utils.logging import log_dist
from .expfmt import exposition_from_events

_JSON = "application/json; charset=utf-8"
# the content type Prometheus' scraper advertises/expects for text format
_PROM = "text/plain; version=0.0.4; charset=utf-8"


@dataclasses.dataclass
class TelemetryConfig:
    """Config block gating the per-engine telemetry server (serving:
    ``serving.telemetry``, training: ``observability.telemetry``). Off
    (``enabled=False`` / block absent) builds nothing — zero threads."""

    enabled: bool = False
    port: int = 0                  # 0 = ephemeral (bound port returned)
    host: str = "127.0.0.1"        # loopback-bound by default
    token: str = ""                # control-POST bearer token ("" = only
                                   # loopback peers may POST)

    def __post_init__(self):
        if not 0 <= int(self.port) <= 65535:
            raise ValueError(f"telemetry port must be in [0, 65535], "
                             f"got {self.port}")

    @classmethod
    def from_any(cls, cfg) -> "Optional[TelemetryConfig]":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown telemetry config keys: {sorted(unknown)}")
        return cls(**cfg)


@dataclasses.dataclass
class TelemetryHooks:
    """What an engine exposes to its telemetry server. Every hook is
    optional except the registry: an absent hook makes its endpoint a
    clean 404 (the doctor's ``--url`` mode degrades on exactly that),
    so one server class fronts both engine types."""

    registry: object                              # MetricsRegistry
    prefix: str = "dstpu"
    step_fn: Optional[Callable[[], int]] = None
    # called before every /metrics render: refresh derived gauges
    # (health mirror, goodput export) so scrapes are always current
    refresh_fn: Optional[Callable[[], None]] = None
    health_fn: Optional[Callable[[], dict]] = None
    requests_fn: Optional[Callable[[], list]] = None
    capacity_fn: Optional[Callable[[bool], dict]] = None   # (census) ->
    goodput_fn: Optional[Callable[[], dict]] = None
    flight_fn: Optional[Callable[[], dict]] = None
    # (rid | None) -> chrome trace dict / hop decomposition / None(→404)
    trace_fn: Optional[Callable[[Optional[int]], object]] = None
    drain_fn: Optional[Callable[[bool], dict]] = None      # (end) ->
    dump_fn: Optional[Callable[[], Optional[str]]] = None
    slo_reload_fn: Optional[Callable[[dict], dict]] = None
    # arrival & scaling observatory readout (loadscope.py): the scaling
    # report JSON — unmeasured inputs arrive as nulls with reasons, the
    # endpoint stays 200 (degraded-null contract); absent hook → 404
    scaling_fn: Optional[Callable[[], dict]] = None
    # per-tenant observatory readout (tenantscope.py): the per-tenant
    # breakdown — cost attribution rows, fairness block, noisy-neighbor
    # state (the doctor's --url [tenants] section); absent hook → 404
    tenants_fn: Optional[Callable[[], dict]] = None
    # autoscaler control loop (serving/autoscaler.py): GET status +
    # decision audit tail; POST freeze/pin override (token-gated like
    # every control POST; ValueError → 400)
    autoscale_fn: Optional[Callable[[], dict]] = None
    autoscale_control_fn: Optional[Callable[[dict], dict]] = None


def flight_summary(flight) -> dict:
    """Live flight-record summary for ``GET /flight`` and the doctor's
    ``--url`` gate: the newest dump's manifest plus the why-marker names
    it contains — the same facts the file-mode doctor derives from the
    dump directory."""
    from .flight import newest_flight_record, read_flight_record

    out: dict = {"dump_dir": str(flight.dump_dir),
                 "dumps": [str(p) for p in flight.dumps],
                 "max_dumps": flight.max_dumps,
                 "newest": None, "markers": []}
    rec_dir = newest_flight_record(flight.dump_dir)
    if rec_dir is not None:
        rec = read_flight_record(rec_dir)
        names = sorted({str(dict(m.get("meta", {})).get("name", "?"))
                        for m in rec["events"]
                        if m.get("kind") == "marker"})
        out["newest"] = {"path": str(rec_dir), "manifest": rec["manifest"],
                         "markers": names}
        out["markers"] = names
    return out


class TelemetryServer:
    """One engine's HTTP ops surface; start with :meth:`start`, stop
    with :meth:`close`. ``port`` holds the bound port after start (pass
    0 for an ephemeral one — the bench and tests do)."""

    def __init__(self, hooks: TelemetryHooks, host: str = "127.0.0.1",
                 port: int = 0, token: str = ""):
        self.hooks = hooks
        self.host = host
        self.port = int(port)
        self.token = token or ""
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self.port
        handler = _make_handler(self)
        httpd = ThreadingHTTPServer((self.host, self.port), handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = int(httpd.server_address[1])
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="dstpu-telemetry",
            daemon=True)
        self._thread.start()
        log_dist(f"telemetry server listening on "
                 f"http://{self.host}:{self.port}", ranks=[0])
        return self.port

    def close(self) -> None:
        """Shut the listener down (idempotent). Worker threads are
        daemonic; in-flight handlers finish or die with the process."""
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------- renders
    def metrics_text(self) -> str:
        """The /metrics body — also the byte-compat oracle the tests
        compare against the textfile sink."""
        h = self.hooks
        if h.refresh_fn is not None:
            h.refresh_fn()
        step = int(h.step_fn()) if h.step_fn is not None else 0
        return exposition_from_events(h.registry.to_events(step), h.prefix)


def _make_handler(server: TelemetryServer):
    """Handler class closed over the server (BaseHTTPRequestHandler is
    instantiated per request by the socket server — state lives on the
    TelemetryServer)."""

    class Handler(BaseHTTPRequestHandler):
        # keep noisy per-request lines out of stderr; failures surface
        # through status codes and the engine's own logging
        def log_message(self, fmt, *args):   # noqa: D102
            pass

        # ------------------------------------------------------- plumbing
        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json(self, code: int, obj) -> None:
            self._send(code, json.dumps(obj, indent=2, default=str)
                       .encode("utf-8") + b"\n", _JSON)

        def _authorized(self) -> bool:
            """Control-POST gate: bearer token when configured, else
            loopback peers only (the server binds loopback by default;
            a re-bound server without a token still refuses remote
            control)."""
            if server.token:
                auth = self.headers.get("Authorization", "")
                tok = auth[len("Bearer "):] if auth.startswith("Bearer ") \
                    else self.headers.get("X-DSTPU-Token", "")
                return tok == server.token
            return self.client_address[0] in ("127.0.0.1", "::1")

        def _body_json(self) -> Optional[dict]:
            """POST body → dict; an EMPTY body is a valid {} (bare
            ``POST /drain`` / ``/flight/dump``), but a NON-EMPTY body
            that fails to parse returns None → 400. A garbled
            ``/slo/reload`` must not silently read as "disable SLOs",
            nor a garbled ``/drain {"end": true}`` as "begin"."""
            try:
                n = int(self.headers.get("Content-Length", 0) or 0)
            except ValueError:
                return None
            if n <= 0:
                return {}
            try:
                obj = json.loads(self.rfile.read(n).decode("utf-8"))
                return obj if isinstance(obj, dict) else None
            except (ValueError, UnicodeDecodeError):
                return None

        # ------------------------------------------------------------- GET
        def do_GET(self):   # noqa: N802 (http.server API)
            try:
                self._get()
            except BrokenPipeError:
                pass        # client went away mid-response; nothing to do
            except Exception as e:   # a handler bug must not kill the
                # listener thread — degrade to a 500 the scraper sees
                try:
                    self._json(500, {"error": repr(e)})
                except Exception:
                    return

        def _get(self):
            h = server.hooks
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/") or "/"
            if path == "/metrics":
                self._send(200, server.metrics_text().encode("utf-8"),
                           _PROM)
            elif path == "/healthz":
                health = h.health_fn() if h.health_fn is not None \
                    else {"alive": True}
                # liveness: the process is up and answering — 200 even
                # while degraded/draining (that's /readyz's business)
                self._json(200, {"alive": True, **health})
            elif path == "/readyz":
                health = h.health_fn() if h.health_fn is not None \
                    else {"ready": True}
                ready = bool(health.get("ready", True))
                self._json(200 if ready else 503, health)
            elif path == "/requests":
                if h.requests_fn is None:
                    self._json(404, {"error": "no request table "
                                              "(training engine?)"})
                else:
                    rows = h.requests_fn()
                    self._json(200, {"requests": rows,
                                     "in_flight": len(rows)})
            elif path == "/capacity":
                if h.capacity_fn is None:
                    self._json(404, {"error": "no capacity hook"})
                else:
                    q = parse_qs(parsed.query)
                    census = q.get("census", ["0"])[0] in ("1", "true")
                    self._json(200, h.capacity_fn(census))
            elif path == "/goodput":
                if h.goodput_fn is None:
                    self._json(404, {"error": "goodput ledger disabled "
                                              "(set goodput=True)"})
                else:
                    self._json(200, h.goodput_fn())
            elif path == "/flight":
                if h.flight_fn is None:
                    self._json(404, {"error": "no flight recorder "
                                              "configured"})
                else:
                    self._json(200, h.flight_fn())
            elif path == "/scaling":
                if h.scaling_fn is None:
                    self._json(404, {"error": "loadscope disabled "
                                              "(set serving.loadscope)"})
                else:
                    self._json(200, h.scaling_fn())
            elif path == "/tenants":
                if h.tenants_fn is None:
                    self._json(404, {"error": "tenantscope disabled "
                                              "(set serving.tenantscope)"})
                else:
                    self._json(200, h.tenants_fn())
            elif path == "/autoscale":
                if h.autoscale_fn is None:
                    self._json(404, {"error": "no autoscaler "
                                              "(set serving.autoscale)"})
                else:
                    self._json(200, h.autoscale_fn())
            elif path == "/trace":
                if h.trace_fn is None:
                    self._json(404, {"error": "no trace hook"})
                    return
                q = parse_qs(parsed.query)
                rid_s = q.get("rid", [None])[0]
                try:
                    rid = None if rid_s is None else int(rid_s)
                except ValueError:
                    self._json(400, {"error": f"bad rid {rid_s!r}"})
                    return
                obj = h.trace_fn(rid)
                if obj is None:
                    self._json(404, {"error":
                                     f"unknown rid {rid}" if rid is not None
                                     else "span ring disabled "
                                          "(set serving.spans)"})
                else:
                    self._json(200, obj)
            elif path == "/":
                eps = {"/metrics": h.registry is not None,
                       "/healthz": True, "/readyz": True,
                       "/requests": h.requests_fn is not None,
                       "/capacity": h.capacity_fn is not None,
                       "/goodput": h.goodput_fn is not None,
                       "/flight": h.flight_fn is not None,
                       "/scaling": h.scaling_fn is not None,
                       "/tenants": h.tenants_fn is not None,
                       "/autoscale": h.autoscale_fn is not None,
                       "/trace": h.trace_fn is not None,
                       "POST /drain": h.drain_fn is not None,
                       "POST /flight/dump": h.dump_fn is not None,
                       "POST /slo/reload": h.slo_reload_fn is not None,
                       "POST /autoscale":
                           h.autoscale_control_fn is not None}
                self._json(200, {"endpoints": {k: v for k, v in eps.items()
                                               if v}})
            else:
                self._json(404, {"error": f"unknown endpoint {path!r}"})

        # ------------------------------------------------------------ POST
        def do_POST(self):   # noqa: N802
            try:
                self._post()
            except BrokenPipeError:
                pass        # client went away mid-response; nothing to do
            except Exception as e:
                try:
                    self._json(500, {"error": repr(e)})
                except Exception:
                    return

        def _post(self):
            h = server.hooks
            path = urlparse(self.path).path.rstrip("/")
            if path not in ("/drain", "/flight/dump", "/slo/reload",
                            "/autoscale"):
                self._json(404, {"error": f"unknown endpoint {path!r}"})
                return
            if not self._authorized():
                self._json(403, {"error": "control endpoint: missing or "
                                          "wrong token (Authorization: "
                                          "Bearer <token>)"})
                return
            body = self._body_json()
            if body is None:
                self._json(400, {"error": "request body is not a JSON "
                                          "object (send {} or omit the "
                                          "body)"})
                return
            if path == "/drain":
                if h.drain_fn is None:
                    self._json(404, {"error": "no drain hook "
                                              "(training engine?)"})
                    return
                self._json(200, h.drain_fn(bool(body.get("end", False))))
            elif path == "/flight/dump":
                if h.dump_fn is None:
                    self._json(404, {"error": "no flight recorder "
                                              "configured"})
                    return
                d = h.dump_fn()
                self._json(200 if d is not None else 409,
                           {"dumped": d is not None,
                            "dir": None if d is None else str(d),
                            "why": None if d is not None else
                            "max_dumps reached (or recorder refused)"})
            elif path == "/slo/reload":
                if h.slo_reload_fn is None:
                    self._json(404, {"error": "no SLO machinery on this "
                                              "engine"})
                    return
                try:
                    self._json(200, h.slo_reload_fn(body))
                except (ValueError, TypeError) as e:
                    self._json(400, {"error": str(e)})
            elif path == "/autoscale":
                if h.autoscale_control_fn is None:
                    self._json(404, {"error": "no autoscaler "
                                              "(set serving.autoscale)"})
                    return
                try:
                    self._json(200, h.autoscale_control_fn(body))
                except (ValueError, TypeError) as e:
                    self._json(400, {"error": str(e)})

    return Handler
