"""Traffic capture & deterministic replay: record → replay → validate.

Every signal the observability stack produces today is *live-only*: an
incident dir tells you what happened but nothing can re-run it, and the
capacity advisor's what-if rankings have never been scored against a
real outcome. This module closes the loop, in the measurement discipline
the DeepSpeed-FastGen/ZeRO papers anchor every optimization claim to —
a reproducible workload:

- **Capture** (:class:`TrafficCapture`): a schema-versioned
  :class:`TrafficTrace` recording, per admitted request, the relative
  submit time on the injectable clock, the prompt token ids (or a
  generator seed for synthetic traffic), the sampling seed and
  per-request deadline overrides, the session id, plus every chaos
  event (replica kills/joins, drains) and every terminal result (the
  parity oracle's recorded outputs). Written live from hooks on
  ``ServingEngine.submit`` / ``FleetEngine.submit`` into a bounded
  host-side ring — zero device syncs, zero new programs; ``capture``
  off (the default) builds none of it.
- **Replay** (:class:`ReplayDriver`): re-runs a trace against a fresh
  :class:`~..serving.engine.ServingEngine` or
  :class:`~..serving.fleet.FleetEngine` under ANY config, on the
  injectable fake clock (time-compressed jumps or paced ticks),
  co-replaying the recorded chaos script (kills/joins/drains land at
  their recorded positions). Greedy/fp replay is bit-identical to the
  recorded outputs — the parity oracle — and divergence is reported
  per-request in the :class:`ReplayReport`, never raised as a crash.
- **Backtest** (:func:`advisor_backtest`): replays the same trace under
  what-if configs (prefix sharing on/off, int8 KV) and scores the
  capacity advisor's predictions (``CAPACITY_REPORT.json`` levers)
  against achieved prefill-tokens-saved / TTFT / goodput into a
  prediction-error report — the advisor finally gets a report card.

The request log upgrades into a trace too
(:func:`trace_from_request_log`): v2 request records carry the fields
replay needs (prompt ids, seed, session, deadline overrides), so an
existing ``*.requests.jsonl`` replays — without recorded outputs, the
parity oracle degrades to ``parity=None`` instead of lying.

``python -m deepspeed_tpu.observability.doctor`` grew a ``[replay]``
section (trace present/valid + the last replay parity verdict) and
flight/incident dumps bundle ``traffic_trace.jsonl`` (the capture ring's
tail), so every incident is replayable standing alone — see
docs/OPERATIONS.md "Reproducing an incident from its trace".
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from collections import OrderedDict, deque
from pathlib import Path
from typing import Callable, Iterable, Optional

import numpy as np

TRACE_SCHEMA = "dstpu.traffic_trace.v1"

# event kinds a trace line may carry ("header" is the first line only)
_KIND_REQUEST = "request"
_KIND_RESULT = "result"
_KIND_CHAOS = "chaos"
_KINDS = frozenset({_KIND_REQUEST, _KIND_RESULT, _KIND_CHAOS})

# chaos events the replay driver knows how to co-replay
_CHAOS_EVENTS = frozenset({"kill_replica", "remove_replica", "add_replica",
                           "begin_drain", "end_drain"})


class ReplayClock:
    """Settable fake clock for deterministic replay.

    Engines under replay and the :class:`ReplayDriver` share ONE of
    these: the driver jumps it to each event's recorded relative time
    (time-compressed replay), so deadline sweeps and goodput windows see
    the recorded timeline without any real waiting. ``dt`` (optional)
    makes every read tick forward — spans and goodput ledgers then see
    nonzero intervals, like the test suites' TickClock."""

    def __init__(self, t0: float = 0.0, dt: float = 0.0):
        self.t = float(t0)
        self.dt = float(dt)

    def __call__(self) -> float:
        t = self.t
        self.t += self.dt
        return t

    def advance(self, s: float) -> None:
        self.t += float(s)

    def advance_to(self, t: float) -> None:
        """Jump forward to ``t`` (never backward — a trace with jittered
        stamps must not rewind deadlines under a live engine)."""
        if t > self.t:
            self.t = float(t)


def resolve_prompt(entry: dict) -> np.ndarray:
    """An entry's prompt tokens: the recorded ids, or the deterministic
    regeneration of a synthetic ``gen`` spec (``{"seed", "len",
    "vocab"?}`` — the compact form benches record instead of shipping
    token arrays)."""
    if entry.get("prompt") is not None:
        return np.asarray(entry["prompt"], np.int32)
    gen = entry.get("gen")
    if not isinstance(gen, dict):
        raise ValueError(f"trace entry rid={entry.get('rid')} has neither "
                         "prompt ids nor a gen spec")
    rng = np.random.default_rng(int(gen["seed"]))
    return rng.integers(0, int(gen.get("vocab", 256)),
                        (int(gen["len"]),)).astype(np.int32)


class TrafficTrace:
    """One recorded traffic stream: a header (schema + capture meta) and
    an ordered event list (requests, results, chaos) — the JSONL form is
    one JSON object per line, header first.

    Construction is either programmatic (``add_request`` /
    ``add_result`` / ``add_chaos`` — synthetic traces for benches and
    tests) or from a capture ring (:meth:`TrafficCapture.trace`) or disk
    (:meth:`read`, torn-line tolerant like every other triage artifact).
    """

    def __init__(self, meta: Optional[dict] = None,
                 events: Optional[list] = None):
        self.meta = dict(meta or {})
        self.events: list[dict] = list(events or [])
        self.torn_lines = 0

    # ------------------------------------------------------------ building
    def add_request(self, rid: int, t_rel: float, prompt=None,
                    gen: Optional[dict] = None, max_new: int = 1,
                    seed: int = 0, session_id=None, tenant_id=None,
                    ttft_deadline_s: Optional[float] = None,
                    total_deadline_s: Optional[float] = None) -> dict:
        ev: dict = {"kind": _KIND_REQUEST, "t_rel": float(t_rel),
                    "rid": int(rid), "max_new": int(max_new),
                    "seed": int(seed)}
        if prompt is not None:
            ev["prompt"] = [int(t) for t in
                            np.asarray(prompt).reshape(-1).tolist()]
        elif gen is not None:
            ev["gen"] = {k: int(v) for k, v in gen.items()}
        if session_id is not None:
            ev["session_id"] = session_id
        if tenant_id is not None and str(tenant_id) != "default":
            # stored only when attribution is real: tenant-free traces
            # (and their byte layout) are unchanged
            ev["tenant_id"] = str(tenant_id)
        if ttft_deadline_s is not None:
            ev["ttft_deadline_s"] = float(ttft_deadline_s)
        if total_deadline_s is not None:
            ev["total_deadline_s"] = float(total_deadline_s)
        self.events.append(ev)
        return ev

    def add_result(self, rid: int, t_rel: float, status: str = "ok",
                   tokens: Iterable = (), attempts: int = 0) -> dict:
        ev = {"kind": _KIND_RESULT, "t_rel": float(t_rel), "rid": int(rid),
              "status": str(status),
              "tokens": [int(t) for t in tokens],
              "attempts": int(attempts)}
        self.events.append(ev)
        return ev

    def add_chaos(self, event: str, t_rel: float, replica: str = "",
                  role: str = "") -> dict:
        ev = {"kind": _KIND_CHAOS, "t_rel": float(t_rel),
              "event": str(event), "replica": str(replica)}
        if role:
            # disaggregated joins record the phase so an autoscaled run
            # replays its add_replica edges into the right role
            ev["role"] = str(role)
        self.events.append(ev)
        return ev

    # ------------------------------------------------------------- readout
    @property
    def requests(self) -> list[dict]:
        return [e for e in self.events if e.get("kind") == _KIND_REQUEST]

    @property
    def chaos_events(self) -> list[dict]:
        return [e for e in self.events if e.get("kind") == _KIND_CHAOS]

    @property
    def results(self) -> dict:
        """rid → result entry (the recorded outputs — the parity oracle's
        reference). Last write wins, matching the capture dedupe."""
        return {e["rid"]: e for e in self.events
                if e.get("kind") == _KIND_RESULT}

    def validate(self) -> list[str]:
        """Schema gate; returns the list of problems (empty = valid) —
        the same degrade-don't-crash contract every triage artifact
        follows. Checks the schema version, known event kinds, required
        request fields (prompt ids XOR gen spec, max_new >= 1), unique
        request rids, results referencing known rids, and non-decreasing
        ``t_rel`` (capture appends in clock order; a shuffled trace
        would replay a different scenario than it claims to record)."""
        problems: list[str] = []
        schema = self.meta.get("schema", TRACE_SCHEMA)
        if schema != TRACE_SCHEMA:
            problems.append(f"unknown trace schema {schema!r} "
                            f"(this build reads {TRACE_SCHEMA})")
        seen_rids: set = set()
        last_t = None
        for i, ev in enumerate(self.events):
            if not isinstance(ev, dict):
                problems.append(f"event {i}: not an object")
                continue
            kind = ev.get("kind")
            if kind not in _KINDS:
                problems.append(f"event {i}: unknown kind {kind!r}")
                continue
            t = ev.get("t_rel")
            if not isinstance(t, (int, float)) or t < 0:
                problems.append(f"event {i}: bad t_rel {t!r}")
                continue
            if last_t is not None and t < last_t:
                problems.append(f"event {i}: t_rel {t} < previous {last_t} "
                                "(events must be in capture order)")
            last_t = t
            if kind == _KIND_REQUEST:
                rid = ev.get("rid")
                if rid in seen_rids:
                    problems.append(f"event {i}: duplicate request "
                                    f"rid {rid}")
                seen_rids.add(rid)
                has_prompt = isinstance(ev.get("prompt"), list) \
                    and len(ev["prompt"]) > 0
                gen = ev.get("gen")
                has_gen = isinstance(gen, dict) and "seed" in gen \
                    and "len" in gen
                if not has_prompt and not has_gen:
                    problems.append(f"event {i}: request rid {rid} needs "
                                    "prompt ids or a gen{seed,len} spec")
                if not isinstance(ev.get("max_new"), int) \
                        or ev["max_new"] < 1:
                    problems.append(f"event {i}: request rid {rid} needs "
                                    f"max_new >= 1, got {ev.get('max_new')!r}")
            elif kind == _KIND_RESULT:
                if ev.get("rid") not in seen_rids:
                    problems.append(f"event {i}: result for unknown "
                                    f"rid {ev.get('rid')}")
                if not isinstance(ev.get("tokens"), list):
                    problems.append(f"event {i}: result rid {ev.get('rid')} "
                                    "needs a tokens list")
            elif kind == _KIND_CHAOS:
                if ev.get("event") not in _CHAOS_EVENTS:
                    problems.append(f"event {i}: unknown chaos event "
                                    f"{ev.get('event')!r}")
        return problems

    # ----------------------------------------------------------------- io
    def as_lines(self) -> list[str]:
        header = {"kind": "header", "schema": TRACE_SCHEMA,
                  **{k: v for k, v in self.meta.items() if k != "schema"}}
        return ([json.dumps(header, separators=(",", ":"), default=str)]
                + [json.dumps(ev, separators=(",", ":"), default=str)
                   for ev in self.events])

    def write(self, path) -> Path:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text("\n".join(self.as_lines()) + "\n", encoding="utf-8")
        return path

    @classmethod
    def read(cls, path) -> "TrafficTrace":
        """Load a trace from JSONL, skipping torn lines (the artifact may
        have been cut by the very crash it records — ``torn_lines``
        counts what was skipped)."""
        from .flight import load_jsonl_tolerant

        rows, skipped = load_jsonl_tolerant(path)
        meta: dict = {}
        events: list = []
        for row in rows:
            if not isinstance(row, dict):
                skipped += 1
                continue
            if row.get("kind") == "header":
                meta = {k: v for k, v in row.items() if k != "kind"}
            else:
                events.append(row)
        tr = cls(meta=meta, events=events)
        tr.torn_lines = skipped
        return tr


def capture_meta(cfg, engine: str = "serving", **extra) -> dict:
    """Trace-header meta from one :class:`ServingConfig` — the recorded
    config a faithful replay must match (sampling policy and ``max_len``
    are part of the sampled bit-stream; paging knobs size the what-if
    space). ONE builder shared by ``ServingEngine`` and ``FleetEngine``
    so the drift-check schema (:meth:`ReplayDriver._check_config`)
    cannot fork between the two surfaces. ``extra`` carries
    surface-specific fields (replica counts)."""
    return {"engine": engine, "slots": cfg.slots, "max_len": cfg.max_len,
            "prefill_chunk": cfg.prefill_chunk,
            "page_size": cfg.page_size,
            "kv_quant_bits": cfg.kv_quant_bits,
            "prefix_sharing": cfg.prefix_sharing,
            "sampling": {"temperature": cfg.temperature,
                         "top_k": cfg.top_k, "top_p": cfg.top_p,
                         "greedy": cfg.greedy},
            **extra}


class TrafficCapture:
    """The record half of record→replay: a bounded, thread-safe ring of
    trace events fed by the engine hooks.

    ``clock`` is the OWNER's injectable clock (the serving stats clock /
    the fleet clock), so capture timestamps, deadlines, and spans agree
    to the float; the first event anchors ``t_rel = 0``. ``ring`` bounds
    host memory — on overflow the oldest events drop and ``dropped``
    counts them (the flight-dump artifact is explicitly the ring's TAIL;
    a full standalone trace comes from :meth:`trace` before overflow or
    from a request-log upgrade). Results dedupe by rid: a request's
    terminal outcome is recorded once even when fleet adoption paths
    visit it twice."""

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 ring: int = 4096, meta: Optional[dict] = None):
        if ring < 1:
            raise ValueError(f"capture ring must be >= 1, got {ring}")
        self.clock = clock if clock is not None else time.perf_counter
        self.meta = dict(meta or {})
        self._ring: deque[dict] = deque(maxlen=int(ring))
        self._lock = threading.RLock()
        self._t0: Optional[float] = None
        self._last_t = 0.0
        self._appended = 0
        # bounded result-rid dedupe window (the double-visit paths are
        # all within a few events of each other; 4x ring is generous)
        self._result_rids: OrderedDict = OrderedDict()
        self._result_cap = 4 * int(ring)

    # ------------------------------------------------------------ recording
    def _append(self, ev: dict) -> None:
        """Stamp ``t_rel`` and append under ONE lock acquisition: two
        threads (the serving loop vs a telemetry-thread drain/dump hook)
        must not interleave between reading the clock and appending, or
        the ring would hold out-of-order events and the trace would fail
        its own order check on a healthy engine. ``t_rel`` is also
        clamped monotone against the last event as a second line of
        defense (an injected clock that steps backward)."""
        with self._lock:
            now = self.clock()
            if self._t0 is None:
                self._t0 = now
            t = max(0.0, now - self._t0, self._last_t)
            self._last_t = t
            ev["t_rel"] = t
            self._ring.append(ev)
            self._appended += 1

    def on_submit(self, req, session_id=None,
                  ttft_deadline_s: Optional[float] = None,
                  total_deadline_s: Optional[float] = None) -> None:
        """One admitted request into the ring (shed submits never ran and
        are not part of the trace). ``ttft_deadline_s`` /
        ``total_deadline_s`` are the PER-REQUEST overrides as passed to
        ``submit`` (None = the config default applied) — replay resubmits
        them so deadline semantics reproduce under the same config."""
        ev: dict = {"kind": _KIND_REQUEST,
                    "rid": int(req.rid), "max_new": int(req.max_new),
                    "seed": int(req.seed),
                    "prompt": [int(t) for t in
                               np.asarray(req.prompt).reshape(-1).tolist()]}
        sid = session_id if session_id is not None \
            else getattr(req, "session_id", None)
        if sid is not None:
            ev["session_id"] = sid
        tid = getattr(req, "tenant_id", None)
        if tid is not None and str(tid) != "default":
            # verbatim tenant attribution; the inert value stays
            # unrecorded so pre-tenant captures are byte-identical
            ev["tenant_id"] = str(tid)
        if ttft_deadline_s is not None:
            ev["ttft_deadline_s"] = float(ttft_deadline_s)
        if total_deadline_s is not None:
            ev["total_deadline_s"] = float(total_deadline_s)
        self._append(ev)

    def on_result(self, req) -> None:
        """One terminal outcome (status + the output tokens — the parity
        oracle's reference bits). Deduped by rid."""
        with self._lock:
            if req.rid in self._result_rids:
                return
            self._result_rids[req.rid] = True
            while len(self._result_rids) > self._result_cap:
                self._result_rids.popitem(last=False)
        status = getattr(req.status, "value", str(req.status))
        self._append({"kind": _KIND_RESULT,
                      "rid": int(req.rid), "status": status,
                      "tokens": [int(t) for t in req.tokens],
                      "attempts": int(getattr(req, "attempts", 0))})

    def on_chaos(self, event: str, replica: str = "",
                 role: str = "") -> None:
        """One fleet chaos event (replica kill/join, drain edge) — the
        chaos script replay co-replays at the recorded position.
        ``role`` (joins on a disaggregated fleet) rides along so replay
        re-adds the replica into the right phase."""
        ev = {"kind": _KIND_CHAOS,
              "event": str(event), "replica": str(replica)}
        if role:
            ev["role"] = str(role)
        self._append(ev)

    # -------------------------------------------------------------- readout
    @property
    def dropped(self) -> int:
        """Events evicted from the ring so far (0 = the ring still holds
        the full capture and :meth:`trace` is the complete stream)."""
        with self._lock:
            return max(0, self._appended - len(self._ring))

    def trace(self) -> TrafficTrace:
        with self._lock:
            events = list(self._ring)
            dropped = max(0, self._appended - len(self._ring))
        if dropped:
            # an overflowed ring may hold results whose request events
            # were evicted; they can neither replay nor compare, and a
            # tail trace carrying them would fail validate() (and the
            # doctor's [replay] gate) on a perfectly healthy engine —
            # drop the orphans, count them with the evicted
            rids = {e["rid"] for e in events
                    if e.get("kind") == _KIND_REQUEST}
            kept = [e for e in events if e.get("kind") != _KIND_RESULT
                    or e.get("rid") in rids]
            dropped += len(events) - len(kept)
            events = kept
        meta = dict(self.meta)
        meta["captured_events"] = len(events)
        meta["dropped_events"] = dropped
        return TrafficTrace(meta=meta, events=events)

    def tail_text(self) -> str:
        """The ring's current tail as trace JSONL text — the flight/
        incident-dump artifact (``traffic_trace.jsonl``), so every
        incident dir is replayable standing alone (up to the ring
        bound)."""
        return "\n".join(self.trace().as_lines()) + "\n"

    def write(self, path) -> Path:
        return self.trace().write(path)


def trace_from_request_log(rows: Iterable[dict]) \
        -> "tuple[TrafficTrace, int]":
    """Upgrade request-log records into a replayable
    :class:`TrafficTrace` — ``(trace, skipped)``.

    v2+ request records (``observability/export.py``) carry the fields
    replay needs: prompt token ids, sampling seed, session id, and the
    per-request deadline budgets; v3 adds ``tenant_id``. Rows missing
    the replay fields (v1 logs, or torn lines parsed to partial
    objects) are SKIPPED and counted, never guessed at. v2 rows (no
    tenant_id) upgrade to ``"default"`` — counted in the trace meta
    (``tenantless_rows``), never a crash. The request log does not
    carry output token ids (only counts), so the upgraded trace has no
    recorded outputs — replay runs but the parity oracle reports
    ``parity=None``."""
    usable = []
    skipped = 0
    for r in rows:
        if (isinstance(r, dict) and isinstance(r.get("prompt"), list)
                and r["prompt"] and r.get("seed") is not None
                and r.get("submit_t") is not None
                and r.get("rid") is not None and r.get("max_new")):
            usable.append(r)
        else:
            skipped += 1
    usable.sort(key=lambda r: (r["submit_t"], r["rid"]))
    t0 = usable[0]["submit_t"] if usable else 0.0
    tenantless = sum(1 for r in usable if r.get("tenant_id") is None)
    tr = TrafficTrace(meta={"source": "request_log",
                            "upgraded_rows": len(usable),
                            "skipped_rows": skipped,
                            # v2 rows carrying no tenant dimension —
                            # upgraded to "default", never dropped
                            "tenantless_rows": tenantless})
    for r in usable:
        tr.add_request(rid=r["rid"], t_rel=r["submit_t"] - t0,
                       prompt=r["prompt"], max_new=int(r["max_new"]),
                       seed=int(r["seed"]), session_id=r.get("session_id"),
                       tenant_id=r.get("tenant_id", "default"),
                       ttft_deadline_s=r.get("ttft_deadline_s"),
                       total_deadline_s=r.get("total_deadline_s"))
    return tr, skipped


# ------------------------------------------------------------------- replay
@dataclasses.dataclass
class ReplayReport:
    """One replay's outcome, per-request — divergence is DATA here, not
    an exception (the whole point of a parity oracle is to tell you
    exactly which requests' bits moved and where).

    ``parity`` is True when every recorded-OK request replayed
    bit-identical (status OK, same tokens), False when any diverged, and
    None when the trace carried no recorded outputs to compare against
    (e.g. a request-log upgrade)."""

    schema: str = "dstpu.replay_report.v1"
    requests: int = 0                 # request entries in the trace
    replayed: int = 0                 # successfully submitted + finished
    matched: int = 0                  # bit-identical to the recorded output
    diverged: list = dataclasses.field(default_factory=list)
    skipped_non_ok: int = 0           # recorded non-OK: excluded from parity
    failed_submits: list = dataclasses.field(default_factory=list)
    chaos_applied: int = 0
    chaos_skipped: list = dataclasses.field(default_factory=list)
    notes: list = dataclasses.field(default_factory=list)
    parity: Optional[bool] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def write(self, path) -> Path:
        """Persist the verdict (``REPLAY_REPORT*.json`` is what the
        doctor's ``[replay]`` section reads)."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.as_dict(), indent=2, default=str),
                        encoding="utf-8")
        return path


class ReplayDriver:
    """Re-run one :class:`TrafficTrace` against a serving surface.

    ``engine`` is a :class:`~..serving.engine.ServingEngine` or
    :class:`~..serving.fleet.FleetEngine` built by the caller under
    WHATEVER config the experiment wants (the parity run uses the
    recorded config; a backtest run flips a lever). ``clock`` should be
    the SAME :class:`ReplayClock` the engine was built with: the driver
    advances it to each event's recorded ``t_rel`` (time-compressed —
    no waiting), or in ``paced_dt`` ticks with an engine step per tick
    (paced — deadline sweeps and watchdogs observe the recorded
    timeline). With no controllable clock the replay is order-only:
    events land in recorded order and time-derived behavior (deadlines)
    follows the engine's own clock.

    The recorded chaos script co-replays: ``kill_replica`` /
    ``remove_replica`` / ``add_replica`` / drain edges apply to a fleet
    engine at their recorded positions; on a single engine (or a fleet
    missing the named replica) they are counted in ``chaos_skipped``
    rather than failing the run — a what-if replay on a different
    topology is a legitimate experiment."""

    def __init__(self, engine, trace: TrafficTrace,
                 clock: Optional[ReplayClock] = None,
                 paced_dt: float = 0.0, max_iterations: int = 1_000_000):
        self.engine = engine
        self.trace = trace
        self.clock = clock
        self.paced_dt = float(paced_dt)
        self.max_iterations = int(max_iterations)
        self._fleet = hasattr(engine, "replicas")

    # ------------------------------------------------------------- helpers
    def _advance_to(self, t_rel: float, collected: dict) -> None:
        if self.clock is None:
            return
        if self.paced_dt > 0:
            # paced: tick toward the event time, stepping the engine so
            # the recorded inter-arrival gaps are really served
            while self.clock.t + self.paced_dt <= t_rel:
                self.clock.advance(self.paced_dt)
                self._pump(collected)
        self.clock.advance_to(t_rel)

    def _pump(self, collected: dict) -> None:
        for req in self.engine.step():
            if req.rid in collected or req.rid not in self._rid_map:
                continue
            collected[req.rid] = req
            self.engine.pop_result(req.rid)

    def _apply_chaos(self, ev: dict) -> None:
        event, name = ev.get("event"), ev.get("replica", "")
        try:
            if event in ("kill_replica", "remove_replica"):
                if not self._fleet or name not in self.engine.replicas:
                    raise LookupError(f"no replica {name!r} to remove")
                if event == "kill_replica":
                    self.engine.kill_replica(name)
                else:
                    self.engine.remove_replica(name)
            elif event == "add_replica":
                if not self._fleet:
                    raise LookupError("add_replica needs a fleet engine")
                # recorded role (disaggregated autoscaled joins) rides
                # along; a role the target fleet rejects is a topology
                # mismatch → counted-skip below
                self.engine.add_replica(name or None,
                                        role=ev.get("role") or None)
            elif event == "begin_drain":
                if name:
                    # replica-scoped drain edge (autoscaler-recorded):
                    # unknown name / non-fleet → counted-skip
                    if not self._fleet:
                        raise LookupError("replica drain needs a fleet")
                    self.engine.begin_drain_replica(name)
                else:
                    self.engine.begin_drain()
            elif event == "end_drain":
                if name:
                    if not self._fleet:
                        raise LookupError("replica drain needs a fleet")
                    self.engine.end_drain_replica(name)
                else:
                    self.engine.end_drain()
            else:
                raise LookupError(f"unknown chaos event {event!r}")
        except (LookupError, RuntimeError, KeyError, ValueError) as e:
            # a topology mismatch is an experiment, not a crash — the
            # report says which recorded faults could not be co-replayed
            self._report.chaos_skipped.append(
                {"event": event, "replica": name, "error": repr(e)})
            return
        self._report.chaos_applied += 1

    # ----------------------------------------------------------------- run
    def run(self) -> ReplayReport:
        from ..resilience.guards import QueueFullError

        rep = ReplayReport()
        self._report = rep
        self._rid_map: dict[int, int] = {}     # replay rid -> recorded rid
        recorded = self.trace.results
        timeline = sorted(
            [e for e in self.trace.events
             if e.get("kind") in (_KIND_REQUEST, _KIND_CHAOS)],
            key=lambda e: e.get("t_rel", 0.0))
        rep.requests = sum(1 for e in timeline
                           if e["kind"] == _KIND_REQUEST)
        self._check_config(rep)
        collected: dict[int, object] = {}
        for ev in timeline:
            self._advance_to(ev.get("t_rel", 0.0), collected)
            if ev["kind"] == _KIND_CHAOS:
                self._apply_chaos(ev)
                continue
            kw = {}
            if ev.get("ttft_deadline_s") is not None:
                kw["ttft_deadline_s"] = ev["ttft_deadline_s"]
            if ev.get("total_deadline_s") is not None:
                kw["total_deadline_s"] = ev["total_deadline_s"]
            if self._fleet and ev.get("session_id") is not None:
                kw["session_id"] = ev["session_id"]
            if ev.get("tenant_id") is not None:
                # engine and fleet submit both take tenant_id; absent
                # (pre-tenant trace) → scheduler default "default"
                kw["tenant_id"] = ev["tenant_id"]
            try:
                rid = self.engine.submit(resolve_prompt(ev),
                                         int(ev["max_new"]),
                                         seed=int(ev["seed"]), **kw)
            except (QueueFullError, ValueError) as e:
                # a shed (queue full / drained) OR a request the what-if
                # config cannot host at all (e.g. a smaller max_len) —
                # both are DATA about this replay, not a crash
                rep.failed_submits.append({"rid": ev["rid"],
                                           "error": str(e)})
                continue
            self._rid_map[rid] = ev["rid"]
            # one step per event: admission interleaves with intake the
            # way a live server's loop does
            self._pump(collected)
        it = 0
        while len(collected) < len(self._rid_map):
            self._pump(collected)
            it += 1
            if it > self.max_iterations:
                raise RuntimeError(
                    f"replay failed to finish in {self.max_iterations} "
                    f"iterations ({len(collected)}/{len(self._rid_map)} "
                    "collected) — engine wedged?")
        self._compare(rep, collected, recorded)
        return rep

    def _check_config(self, rep: ReplayReport) -> None:
        """Note (never fail on) engine-vs-trace config drift: a replay
        under a different sampling policy is a legitimate what-if, but
        the report must say why parity broke."""
        meta = self.trace.meta
        cfg = getattr(self.engine, "cfg", None)
        if cfg is None and self._fleet and self.engine.replicas:
            # a fleet holds no .cfg of its own; every replica carries
            # the same serving config — drift notes must not go silent
            # on exactly the multi-replica replays that need them
            cfg = next(iter(self.engine.replicas.values())).cfg
        if cfg is None:
            return
        rec = meta.get("sampling")
        if isinstance(rec, dict):
            live = {"temperature": cfg.temperature, "top_k": cfg.top_k,
                    "top_p": cfg.top_p, "greedy": cfg.greedy}
            drift = {k: (rec.get(k), v) for k, v in live.items()
                     if rec.get(k) is not None and rec.get(k) != v}
            if drift:
                rep.notes.append({"config_drift": {
                    k: {"recorded": a, "replay": b}
                    for k, (a, b) in drift.items()}})
        if meta.get("max_len") is not None and cfg.max_len != meta["max_len"]:
            # the cache width is part of the sampled bit-stream — this
            # drift breaks parity even at identical sampling knobs
            rep.notes.append({"config_drift": {"max_len": {
                "recorded": meta["max_len"], "replay": cfg.max_len}}})

    def _compare(self, rep: ReplayReport, collected: dict,
                 recorded: dict) -> None:
        had_oracle = False
        replayed_rec = set(self._rid_map.values())
        for rid, rec_rid in self._rid_map.items():
            req = collected.get(rid)
            if req is None:
                continue
            rep.replayed += 1
            want = recorded.get(rec_rid)
            if want is None:
                continue                    # no recorded output: no oracle
            had_oracle = True
            if want.get("status") != "ok":
                rep.skipped_non_ok += 1
                continue
            got = [int(t) for t in req.tokens]
            exp = [int(t) for t in want.get("tokens", [])]
            status = getattr(req.status, "value", str(req.status))
            if got == exp and status == "ok":
                rep.matched += 1
            else:
                first = next((i for i, (a, b) in enumerate(zip(got, exp))
                              if a != b), min(len(got), len(exp)))
                rep.diverged.append({
                    "rid": rec_rid, "first_diff": first,
                    "recorded_tokens": len(exp), "replayed_tokens": len(got),
                    "recorded_status": "ok", "replayed_status": status,
                })
        # a recorded-OK request that never replayed (submit failed/shed
        # under this config) is a parity failure, not a free pass: the
        # verdict must not claim "bit-identical" over requests that
        # never ran
        for e in self.trace.requests:
            rec_rid = e.get("rid")
            if rec_rid in replayed_rec:
                continue
            want = recorded.get(rec_rid)
            if want is None:
                continue
            had_oracle = True
            if want.get("status") != "ok":
                rep.skipped_non_ok += 1
                continue
            rep.diverged.append({
                "rid": rec_rid, "first_diff": None,
                "recorded_tokens": len(want.get("tokens", [])),
                "replayed_tokens": 0, "recorded_status": "ok",
                "replayed_status": "not_replayed",
            })
        rep.parity = (not rep.diverged) if had_oracle else None


# ----------------------------------------------------------------- backtest
BACKTEST_SCHEMA = "dstpu.advisor_backtest.v1"


def _lever_prediction(lever: str, capacity_report: Optional[dict],
                      trace: TrafficTrace, page_size: int) \
        -> "tuple[Optional[float], str]":
    """The advisor's prediction for one lever — from a
    ``CAPACITY_REPORT.json`` dict when given (the real report card),
    else recomputed from the trace through the PR-6 estimator (the
    standalone form benches use) — ``(predicted, source)``."""
    if isinstance(capacity_report, dict):
        levers = (capacity_report.get("advisor") or {}).get("levers") or []
        for lv in levers:
            if isinstance(lv, dict) and lv.get("name") == lever:
                est = lv.get("estimate") or {}
                if lever == "prefix_sharing":
                    v = est.get("shared_prefix_fraction")
                    if isinstance(v, (int, float)):
                        return float(v), "capacity_report"
                break
    if lever == "prefix_sharing":
        from .workload import WorkloadAnalyzer

        wl = WorkloadAnalyzer({"block": page_size})
        for e in trace.requests:
            wl.on_admit(resolve_prompt(e))
        return wl.prefix_overlap, "workload_estimator"
    return None, "none"


def _speculation_prediction(trace: TrafficTrace, ngram: int) \
        -> "tuple[Optional[float], str]":
    """Predicted first-draft acceptance for the self-speculation lever:
    the shared n-gram helper (the SAME implementation the live drafter
    runs) scored over each recorded request's prompt + reference output,
    restricted to the decode region and CONDITIONED on the table having
    a prediction — exactly what the live drafter's per-step first-draft
    accept rate measures (it only proposes when the table has an
    entry). Pooled over the trace. None when no recorded output is long
    enough to score."""
    from ..inference.speculation import acceptance_stats

    results = trace.results
    hits = predicted = 0
    for e in trace.requests:
        prompt = resolve_prompt(e).tolist()
        ref = (results.get(e["rid"]) or {}).get("tokens") or []
        if not ref:
            continue
        full = acceptance_stats(prompt + [int(t) for t in ref], ngram)
        if full is None:
            continue
        head = acceptance_stats(prompt, ngram) \
            or {"hits": 0, "predicted": 0}
        hits += full["hits"] - head["hits"]
        predicted += full["predicted"] - head["predicted"]
    if not predicted:
        return None, "ngram_estimator"
    return hits / predicted, "ngram_estimator"


def advisor_backtest(trace: TrafficTrace, engine, serving: dict,
                     levers=("prefix_sharing", "kv_quantization"),
                     capacity_report: Optional[dict] = None,
                     page_size: int = 8,
                     speculation: Optional[dict] = None) -> dict:
    """Score the capacity advisor against reality: replay ``trace``
    under each lever's what-if config and compare the advisor's
    prediction to the achieved outcome — the prediction-error report.

    ``engine`` is the shared :class:`InferenceEngine`; ``serving`` is
    the base ServingConfig dict (sampling knobs, slots, max_len) every
    run starts from — the backtest owns the paged/lever fields. Each run
    is a fresh ServingEngine on its own :class:`ReplayClock` (goodput
    ledger on, so achieved goodput/TTFT ride the report alongside
    prefill-tokens-saved).

    Levers scored:

    - ``prefix_sharing`` — predicted shared-prefix fraction (the
      ``CAPACITY_REPORT.json`` lever estimate when given, else the PR-6
      estimator on the trace) vs ACHIEVED prefill-tokens-saved fraction
      with the radix tree on; ``abs_error_pts`` is the headline number
      (the ±10-point acceptance band in ``bench_replay.py --smoke``).
    - ``kv_quantization`` — predicted int8/fp KV bytes-per-token ratio
      (the ledger math) vs the achieved ledger ratio in the int8 replay.
    - ``speculative_decoding`` — predicted first-draft acceptance (the
      shared n-gram helper scored over each recorded request's decode
      region, conditioned on the table proposing) vs the ACHIEVED live
      first-draft accept rate from the spec-on replay's engine
      snapshot. The what-if forces ``greedy: True`` (self-speculation
      requires it); ``speculation`` overrides the lever's config
      (default ``{"ngram": 3, "max_draft": 4}``).
    """
    from ..serving.engine import ServingEngine

    def run(extra: dict) -> "tuple[ReplayReport, dict]":
        clock = ReplayClock(dt=1e-4)
        srv = ServingEngine(engine, {**serving, "goodput": True,
                                     **extra}, clock=clock)
        rep = ReplayDriver(srv, trace, clock=clock).run()
        snap = srv.stats.snapshot()
        pool = srv.pool.snapshot() if srv.pool is not None else None
        ledger = srv.hbm_ledger()
        gp = srv.goodput.snapshot() if srv.goodput is not None else {}
        achieved = {
            "replayed": rep.replayed,
            "prefill_tokens_saved": (pool or {}).get(
                "prefill_tokens_saved", 0),
            "ttft_p50_s": (snap.get("ttft_s") or {}).get("p50"),
            "goodput_frac": gp.get("goodput_frac"),
            "kv_per_token_bytes": ledger.get("kv_per_token_bytes"),
            "speculation": srv.spec_snapshot(),
        }
        srv.close()
        return rep, achieved

    total_prompt = int(sum(
        len(resolve_prompt(e)) for e in trace.requests))
    out: dict = {"schema": BACKTEST_SCHEMA,
                 "trace": {"requests": len(trace.requests),
                           "prompt_tokens": total_prompt,
                           "chaos_events": len(trace.chaos_events)},
                 "levers": {}}
    base_rep, base = run({"page_size": page_size,
                          "prefix_sharing": False})
    out["baseline"] = {**base, "parity": base_rep.parity}
    if "prefix_sharing" in levers:
        predicted, source = _lever_prediction(
            "prefix_sharing", capacity_report, trace, page_size)
        rep, ach = run({"page_size": page_size, "prefix_sharing": True})
        achieved = (ach["prefill_tokens_saved"] / total_prompt
                    if total_prompt else 0.0)
        entry = {"predicted": predicted, "source": source,
                 "achieved": achieved, "what_if": ach,
                 "parity": rep.parity}
        if predicted is not None:
            entry["abs_error_pts"] = abs(predicted - achieved) * 100.0
        out["levers"]["prefix_sharing"] = entry
    if "kv_quantization" in levers:
        from ..inference.config import ServingConfig
        from .capacity import kv_cache_bytes

        # config validation alone resolves pool_pages=0 → auto; no
        # engine (and no device slot state) needed for the ledger math
        cfg_probe = ServingConfig.from_any({**serving,
                                            "page_size": page_size})
        fp = kv_cache_bytes(engine.model.cfg, cfg_probe.slots,
                            cfg_probe.max_len, engine.compute_dtype,
                            page_size=page_size,
                            pool_pages=cfg_probe.pool_pages)
        q8 = kv_cache_bytes(engine.model.cfg, cfg_probe.slots,
                            cfg_probe.max_len, engine.compute_dtype,
                            page_size=page_size,
                            pool_pages=cfg_probe.pool_pages,
                            kv_quant_bits=8)
        predicted = (q8["per_token_bytes"] / fp["per_token_bytes"]
                     if fp.get("per_token_bytes") else None)
        rep, ach = run({"page_size": page_size, "prefix_sharing": True,
                        "kv_quant_bits": 8})
        achieved = (ach["kv_per_token_bytes"]
                    / base["kv_per_token_bytes"]
                    if base.get("kv_per_token_bytes") else None)
        entry = {"predicted": predicted, "source": "ledger_math",
                 "achieved": achieved, "what_if": ach,
                 "parity": rep.parity}
        if predicted is not None and achieved is not None:
            entry["abs_error_pts"] = abs(predicted - achieved) * 100.0
        out["levers"]["kv_quantization"] = entry
    if "speculative_decoding" in levers:
        spec_cfg = dict(speculation or {"ngram": 3, "max_draft": 4})
        predicted, source = _speculation_prediction(
            trace, int(spec_cfg.get("ngram", 3)))
        rep, ach = run({"page_size": page_size, "prefix_sharing": True,
                        "greedy": True, "speculation": spec_cfg})
        spec_snap = ach.get("speculation") or {}
        achieved = spec_snap.get("first_accept_rate")
        entry = {"predicted": predicted, "source": source,
                 "achieved": achieved, "what_if": ach,
                 "parity": rep.parity}
        if predicted is not None and achieved is not None:
            entry["abs_error_pts"] = abs(predicted - achieved) * 100.0
        out["levers"]["speculative_decoding"] = entry
    return out


def write_backtest_report(report: dict, path) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report, indent=2, default=str),
                    encoding="utf-8")
    return path


# -------------------------------------------------- scaling backtest (PR 17)
SCALING_BACKTEST_SCHEMA = "dstpu.scaling_backtest.v1"


def make_diurnal_trace(*, duration_s: float, base_rate: float,
                       peak_rate: Optional[float] = None,
                       period_s: Optional[float] = None,
                       burst_factor: float = 1.0, burst_duty: float = 0.5,
                       burst_period_s: Optional[float] = None,
                       prompt_len: int = 8, max_new: int = 8,
                       vocab: int = 256, seed: int = 0) -> TrafficTrace:
    """Synthesize a schema-valid diurnal × bursty request stream.

    A non-homogeneous Poisson process (thinning against the rate
    envelope's peak) whose instantaneous rate is a diurnal sinusoid —
    ``base_rate`` at the trough, ``peak_rate`` at the crest, one full
    period per ``period_s`` (default: one period over the whole trace)
    — multiplied by an on/off burst square wave (``burst_factor`` for
    the first ``burst_duty`` of every ``burst_period_s``). The default
    ``burst_factor=1`` degenerates to the pure sinusoid; cranking it
    raises the interarrival CV above Poisson's 1.0, which is exactly
    what the loadscope burstiness estimator must detect. Requests carry
    compact ``gen`` specs (deterministic per-rid prompts), so the trace
    stays a few bytes per event at any scale. Fully deterministic in
    ``seed``."""
    import random as _random

    if duration_s <= 0 or base_rate <= 0:
        raise ValueError("make_diurnal_trace needs duration_s > 0 and "
                         f"base_rate > 0, got {duration_s}/{base_rate}")
    peak = float(peak_rate) if peak_rate is not None else float(base_rate)
    if peak < base_rate:
        raise ValueError(f"peak_rate {peak} < base_rate {base_rate}")
    period = float(period_s) if period_s is not None else float(duration_s)
    bperiod = float(burst_period_s) if burst_period_s is not None \
        else float(duration_s) / 6.0
    duty = min(max(float(burst_duty), 0.0), 1.0)

    def rate(t: float) -> float:
        diurnal = base_rate + (peak - base_rate) * 0.5 * (
            1.0 - math.cos(2.0 * math.pi * t / period))
        bursting = duty > 0 and (t % bperiod) < duty * bperiod
        return diurnal * (burst_factor if bursting else 1.0)

    lam_max = peak * max(1.0, float(burst_factor))
    rng = _random.Random(int(seed))
    tr = TrafficTrace(meta={
        "source": "make_diurnal_trace", "duration_s": float(duration_s),
        "base_rate": float(base_rate), "peak_rate": peak,
        "period_s": period, "burst_factor": float(burst_factor),
        "burst_duty": duty, "burst_period_s": bperiod, "seed": int(seed)})
    t, rid = 0.0, 0
    while True:
        t += rng.expovariate(lam_max)      # thinning: candidate at peak rate
        if t >= duration_s:
            break
        if rng.random() * lam_max > rate(t):
            continue                       # thinned out of the lull
        tr.add_request(rid, t,
                       gen={"seed": int(seed) * 100003 + rid,
                            "len": int(prompt_len), "vocab": int(vocab)},
                       max_new=int(max_new), seed=rid)
        rid += 1
    return tr


def _drive_timeline(engine, trace: TrafficTrace, clock: ReplayClock,
                    max_iterations: int = 2_000_000) \
        -> "tuple[dict, int]":
    """Replay ``trace`` on ``engine`` so fake time advances ONLY through
    the shared ticking clock (``dt`` per read) plus idle jumps to the
    next arrival. That makes the queueing timeline self-consistent with
    the span-measured service rates (a step's span duration IS the fake
    time the step consumed), which is the whole point of the scaling
    backtest: utilization ρ measured by loadscope and the achieved
    queue waits live on the same clock. Returns ``(rid → finished
    Request, shed_count)``."""
    from ..resilience.guards import QueueFullError

    pending = sorted(trace.requests, key=lambda e: e.get("t_rel", 0.0))
    done: dict = {}
    i = submitted = shed = it = 0
    while i < len(pending) or len(done) < submitted:
        while i < len(pending) and pending[i]["t_rel"] <= clock.t:
            ev = pending[i]
            i += 1
            try:
                engine.submit(resolve_prompt(ev), int(ev["max_new"]),
                              seed=int(ev["seed"]))
                submitted += 1
            except (QueueFullError, ValueError):
                shed += 1                  # a shed is data, not a crash
        for req in engine.step():
            done[req.rid] = req
            engine.pop_result(req.rid)
        if i < len(pending) and len(done) >= submitted:
            # nothing in flight and the next arrival is in the future:
            # jump there (underload must not burn iterations — or fake
            # seconds — spinning on an empty engine)
            clock.advance_to(pending[i]["t_rel"])
        it += 1
        if it > max_iterations:
            raise RuntimeError(
                f"scaling backtest wedged: {len(done)}/{submitted} "
                f"finished after {max_iterations} iterations")
    return done, shed


def _achieved(done: dict, trace: TrafficTrace, horizon_s: float) -> dict:
    """Measured outcome of one backtest run: mean queue wait (admit −
    submit on the shared fake clock) and goodput points — decode tokens
    of requests that FINISHED inside the trace window, as a percentage
    of every decode token the trace offered (sheds and late finishers
    count against it)."""
    waits = [float(r.admit_t) - float(r.submit_t) for r in done.values()
             if r.admit_t is not None and r.submit_t is not None]
    offered = sum(int(e["max_new"]) for e in trace.requests)
    served = sum(len(r.tokens) for r in done.values()
                 if r.finish_t is not None and r.finish_t <= horizon_s)
    return {
        "finished": len(done),
        "queue_wait_mean_s": (sum(waits) / len(waits)) if waits else None,
        "offered_decode_tokens": int(offered),
        "served_by_horizon": int(served),
        "goodput_pts": (100.0 * served / offered) if offered else None,
    }


def scaling_backtest(engine, serving: dict, *, sizes=(1, 2),
                     requests_target: int = 48, prompt_len: int = 6,
                     max_new: int = 8, overload: float = 1.5,
                     burst_factor: float = 3.0, seed: int = 0,
                     tolerance_pts: float = 10.0,
                     programs=None) -> dict:
    """Backtest the loadscope scaling advisor against replayed reality.

    Self-calibrating: a probe run on ONE replica measures the fleet's
    fake-time decode capacity from its span ring, then a diurnal ×
    bursty trace is synthesized whose offered decode-token rate is
    ``overload`` × that capacity — so one replica is genuinely
    saturated and two are comfortably inside the knee, whatever the
    host's clock granularity. For each fleet size ``n`` in ``sizes``
    the trace replays at ``n`` and ``n+1`` replicas on a shared
    :class:`ReplayClock`; the advisor's add-replica what-if from the
    ``n``-replica run (predicted ρ, queue wait, goodput after scaling)
    is scored against the MEASURED ``n+1`` outcome:

    - ``goodput_error_pts`` — |predicted − achieved| goodput, in
      percentage points of offered decode tokens;
    - ``wait_error_pts`` — |predicted − achieved| post-scale queue
      wait, normalized by the larger of the pre-scale measured wait and
      one request's service time (so a near-zero wait on both sides
      scores near-zero, and an overloaded baseline isn't penalized for
      absolute seconds).

    The run passes when every size's both errors are within
    ``tolerance_pts``. Degradation contract: if the probe cannot
    measure capacity (spans off, no decode steps), the report carries
    ``unmeasured`` reasons and ``pass: None`` — never an exception."""
    from collections import OrderedDict as _OD

    from ..serving.fleet import FleetEngine

    progs = programs if programs is not None else _OD()
    base = {**serving, "spans": True}
    base.pop("loadscope", None)

    def _fleet(n: int, scope: dict, clock: ReplayClock) -> FleetEngine:
        return FleetEngine(engine, {**base, "loadscope": scope},
                           replicas=n, clock=clock, programs=progs)

    # ---- probe: measure fake-time capacity on one saturated replica.
    # The span ring alone cannot price the fake timeline: on a ticking
    # clock most reads land OUTSIDE the compute spans (on hardware the
    # compute dominates wall time; here every read costs dt), so the
    # probe floods one replica and measures REALIZED tokens per fake
    # second, then installs that as the loadscope service calibration
    # (``LoadScope.service_override``) for every backtest run. The
    # span-vs-realized ratio also rescales the prefill rate.
    probe_trace = TrafficTrace()
    probe_n = 24
    for rid in range(probe_n):
        probe_trace.add_request(rid, 0.0,
                                gen={"seed": rid, "len": prompt_len,
                                     "vocab": 256},
                                max_new=max_new, seed=rid)
    clock = ReplayClock(dt=1e-4)
    fl = _fleet(1, {"window_s": 1e9}, clock)
    done, _ = _drive_timeline(fl, probe_trace, clock)
    replica = next(iter(fl.replicas.values()))
    snap = replica.scaling_snapshot()
    svc = (snap or {}).get("service") or {}
    span_per_slot = svc.get("decode_tokens_per_slot_s")
    span_prefill = svc.get("prefill_tokens_per_s")
    slots = int(svc.get("slots") or 0)
    wall = clock.t
    fl.close()
    if span_per_slot is None or slots < 1 or wall <= 0 or not done:
        return {"schema": SCALING_BACKTEST_SCHEMA, "pass": None,
                "unmeasured": ["probe run measured no decode service rate "
                               "(spans ring empty?) — backtest degraded"],
                "sizes": []}
    serviceable = probe_n * max_new / wall         # tokens/fake-s, 1 replica
    per_slot = serviceable / slots
    alpha = per_slot / float(span_per_slot)        # loop time per span time
    calibration = {
        "slots": slots,
        "decode_tokens_per_slot_s": per_slot,
        "decode_tokens_per_s": serviceable,
        "prefill_tokens_per_s": (float(span_prefill) * alpha
                                 if span_prefill is not None else None),
    }
    mean_service_s = max_new / per_slot            # one request in a slot

    # ---- the offered stream: mean decode-token rate = `overload` × the
    # one-replica capacity. The diurnal shape (base 0.6×, peak 1.1× of
    # the reference rate → mean 0.85×) and the burst square wave (mean
    # multiplier 1 + duty·(factor−1)) both inflate the mean, so the
    # reference rate divides them back out.
    duty = 0.3
    shape_mean = 0.5 * (0.6 + 1.1) * (1.0 + duty * (burst_factor - 1.0))
    rate_req = overload * serviceable / max_new / shape_mean
    duration_s = requests_target / (rate_req * shape_mean)
    trace = make_diurnal_trace(
        duration_s=duration_s, base_rate=0.6 * rate_req,
        peak_rate=1.1 * rate_req, burst_factor=burst_factor,
        burst_duty=duty, prompt_len=prompt_len, max_new=max_new,
        seed=seed)
    problems = trace.validate()
    if problems:
        raise ValueError(f"synthesized trace failed validation: {problems}")

    # ---- replay at every needed fleet size (each size once, reused).
    # One shared clock serializes the replicas' steps, so a round over n
    # replicas costs n× the reads of one — but real replicas run in
    # PARALLEL. dt/n makes a full fleet round cost the same fake time as
    # one replica's step, so fleet capacity scales n× like hardware's.
    need = sorted({int(n) for n in sizes} | {int(n) + 1 for n in sizes})
    runs: dict = {}
    for n in need:
        clock = ReplayClock(dt=1e-4 / n)
        fl = _fleet(n, {"window_s": 1e9}, clock)
        for rep_eng in fl.replicas.values():
            rep_eng.loadscope.service_override = calibration
        done, shed = _drive_timeline(fl, trace, clock)
        rep = fl.scaling_report() or {}
        runs[n] = {
            "replicas": n,
            "rho": (rep.get("fleet") or {}).get("rho"),
            "what_ifs": rep.get("what_ifs") or [],
            "shed": shed,
            **_achieved(done, trace, duration_s),
        }
        fl.close()

    # ---- score the advisor: prediction at n vs measurement at n+1
    out_sizes = []
    all_pass: Optional[bool] = True
    for s in sorted({int(n) for n in sizes}):
        now, after = runs[s], runs[s + 1]
        wi = next((w for w in now["what_ifs"]
                   if w.get("action") == "add_replica"), None)
        entry: dict = {"replicas": s, "measured_now": {
            "rho": now["rho"], "queue_wait_mean_s": now["queue_wait_mean_s"],
            "goodput_pts": now["goodput_pts"], "shed": now["shed"]}}
        if wi is None or wi.get("rho_after") is None:
            entry["unmeasured"] = ["no add_replica what-if at this size "
                                   "(utilization unmeasured)"]
            entry["pass"] = None
            all_pass = None
            out_sizes.append(entry)
            continue
        pred_good = wi.get("goodput_after")
        pred_good_pts = 100.0 * pred_good if pred_good is not None else None
        pred_wait = wi.get("predicted_queue_wait_s_after")
        meas_good_pts = after["goodput_pts"]
        meas_wait = after["queue_wait_mean_s"]
        entry["predicted_after"] = {
            "rho": wi.get("rho_after"), "queue_wait_s": pred_wait,
            "goodput_pts": pred_good_pts}
        entry["measured_after"] = {
            "rho": after["rho"], "queue_wait_s": meas_wait,
            "goodput_pts": meas_good_pts, "shed": after["shed"]}
        t_ref = max(now["queue_wait_mean_s"] or 0.0, mean_service_s)
        entry["goodput_error_pts"] = (
            abs(pred_good_pts - meas_good_pts)
            if pred_good_pts is not None and meas_good_pts is not None
            else None)
        entry["wait_error_pts"] = (
            100.0 * abs(pred_wait - meas_wait) / t_ref
            if pred_wait is not None and meas_wait is not None else None)
        errs = [entry["goodput_error_pts"], entry["wait_error_pts"]]
        if any(e is None for e in errs):
            entry["pass"] = None
            all_pass = None
        else:
            ok = all(e <= tolerance_pts for e in errs)
            entry["pass"] = ok
            if all_pass is True and not ok:
                all_pass = False
        out_sizes.append(entry)

    return {
        "schema": SCALING_BACKTEST_SCHEMA,
        "serviceable_tokens_per_s": serviceable,
        "mean_service_s": mean_service_s,
        "trace": {"requests": len(trace.requests),
                  "duration_s": duration_s,
                  "offered_req_per_s_peak": 1.1 * rate_req,
                  "overload": overload, "seed": seed},
        "runs": {str(n): r for n, r in runs.items()},
        "tolerance_pts": float(tolerance_pts),
        "sizes": out_sizes,
        "pass": all_pass,
    }
