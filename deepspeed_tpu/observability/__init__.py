"""Serving & training observability: metrics core, request tracing,
lifecycle spans, flight recorder, Perfetto export, SLO/anomaly
detection, workload/capacity attribution (traffic analytics, HBM
ledger, per-program cost census, capacity advisor), machine-readable
sinks, and XLA profiler integration.

See ``docs/OBSERVABILITY.md`` for the metric namespace and runbook, and
``python -m deepspeed_tpu.observability.doctor`` for file-based triage.
"""

from .capacity import (ProgramCensus, capacity_report, hbm_ledger,
                       kv_cache_bytes, validate_capacity_report,
                       write_capacity_report)
from .export import (RequestLogSink, request_record, to_chrome_trace,
                     validate_chrome_trace, write_chrome_trace)
from .flight import (FlightRecorder, newest_flight_record,
                     read_flight_record)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, Reservoir,
                      get_registry)
from .sinks import (JsonlSink, PrometheusTextfileSink,
                    format_prometheus_value, parse_prometheus_textfile,
                    prometheus_name)
from .slo import (CompileStormDetector, MedianMADDetector, SLOConfig,
                  SLOScorer)
from .spans import SpanEvent, SpanRecorder
from .tracing import RequestRecord, RequestTracer, ServingStats
from .workload import WorkloadAnalyzer, WorkloadConfig
from .xla import TraceWindow, sample_memory

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "Reservoir",
    "get_registry",
    "JsonlSink", "PrometheusTextfileSink", "parse_prometheus_textfile",
    "prometheus_name", "format_prometheus_value",
    "RequestRecord", "RequestTracer", "ServingStats",
    "SpanEvent", "SpanRecorder",
    "FlightRecorder", "newest_flight_record", "read_flight_record",
    "RequestLogSink", "request_record", "to_chrome_trace",
    "validate_chrome_trace", "write_chrome_trace",
    "SLOConfig", "SLOScorer", "MedianMADDetector", "CompileStormDetector",
    "WorkloadAnalyzer", "WorkloadConfig",
    "ProgramCensus", "hbm_ledger", "kv_cache_bytes", "capacity_report",
    "validate_capacity_report", "write_capacity_report",
    "TraceWindow", "sample_memory",
]
