"""Serving & training observability: metrics core, request tracing,
lifecycle spans, flight recorder, Perfetto export, SLO/anomaly
detection, workload/capacity attribution (traffic analytics, HBM
ledger, per-program cost census, capacity advisor), machine-readable
sinks, XLA profiler integration, the communication observatory
(exposed-collective step anatomy, achieved bus-bandwidth ledger,
straggler detection — ``commscope.py``), and the live telemetry plane
(per-engine HTTP ops surface, goodput/badput wall-time ledger, fleet
scrape aggregator).

See ``docs/OBSERVABILITY.md`` for the metric namespace and runbook, and
``python -m deepspeed_tpu.observability.doctor`` for triage — file-based
(``--dir``) or against a live engine (``--url``).
"""

from .capacity import (ProgramCensus, capacity_report, hbm_ledger,
                       kv_cache_bytes, validate_capacity_report,
                       write_capacity_report)
from .commscope import (CommScope, CommScopeConfig, StragglerDetector,
                        bandwidth_ledger, classify_op, decompose,
                        step_anatomy)
from .expfmt import (exposition_from_events, labeled_name, parse_labels,
                     prometheus_series, render_exposition, split_series)
from .export import (HOP_NAMES, RequestLogSink, hop_trace,
                     merge_fleet_trace, request_record, to_chrome_trace,
                     validate_chrome_trace, write_chrome_trace)
from .fleet_scrape import FleetScraper
from .flight import (FlightRecorder, newest_flight_record,
                     read_flight_record)
from .goodput import BADPUT_BUCKETS, GoodputLedger
from .kvscope import KVScope, KVScopeConfig, measure_copy_bandwidth
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, Reservoir,
                      get_registry)
# perf_ledger is intentionally NOT imported here: like doctor.py it is a
# `python -m` CLI, and importing it from the package __init__ makes the
# -m runner warn about the double module object. Import it as
# deepspeed_tpu.observability.perf_ledger.
from .replay import (TRACE_SCHEMA, ReplayClock, ReplayDriver, ReplayReport,
                     TrafficCapture, TrafficTrace, advisor_backtest,
                     trace_from_request_log, write_backtest_report)
from .sinks import (JsonlSink, PrometheusTextfileSink,
                    format_prometheus_value, parse_prometheus_textfile,
                    prometheus_name)
from .server import (TelemetryConfig, TelemetryHooks, TelemetryServer,
                     flight_summary)
from .slo import (CompileStormDetector, MedianMADDetector, SLOConfig,
                  SLOScorer)
from .spans import SpanEvent, SpanRecorder
from .tenantscope import TenantScope, TenantScopeConfig
from .tracing import RequestRecord, RequestTracer, ServingStats
from .workload import WorkloadAnalyzer, WorkloadConfig
from .xla import TraceWindow, sample_memory

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "Reservoir",
    "get_registry",
    "JsonlSink", "PrometheusTextfileSink", "parse_prometheus_textfile",
    "prometheus_name", "prometheus_series", "format_prometheus_value",
    "labeled_name", "split_series", "parse_labels",
    "render_exposition", "exposition_from_events",
    "GoodputLedger", "BADPUT_BUCKETS",
    "TelemetryConfig", "TelemetryHooks", "TelemetryServer",
    "flight_summary", "FleetScraper",
    "RequestRecord", "RequestTracer", "ServingStats",
    "SpanEvent", "SpanRecorder",
    "FlightRecorder", "newest_flight_record", "read_flight_record",
    "RequestLogSink", "request_record", "to_chrome_trace",
    "validate_chrome_trace", "write_chrome_trace",
    "merge_fleet_trace", "hop_trace", "HOP_NAMES",
    "SLOConfig", "SLOScorer", "MedianMADDetector", "CompileStormDetector",
    "WorkloadAnalyzer", "WorkloadConfig",
    "KVScope", "KVScopeConfig", "measure_copy_bandwidth",
    "ProgramCensus", "hbm_ledger", "kv_cache_bytes", "capacity_report",
    "validate_capacity_report", "write_capacity_report",
    "CommScope", "CommScopeConfig", "StragglerDetector",
    "bandwidth_ledger", "classify_op", "decompose", "step_anatomy",
    "TraceWindow", "sample_memory",
    "TrafficCapture", "TrafficTrace", "ReplayClock", "ReplayDriver",
    "ReplayReport", "advisor_backtest", "trace_from_request_log",
    "write_backtest_report", "TRACE_SCHEMA",
    "TenantScope", "TenantScopeConfig",
]
