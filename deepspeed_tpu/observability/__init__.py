"""Serving & training observability: metrics core, request tracing,
machine-readable sinks, and XLA profiler integration.

See ``docs/OBSERVABILITY.md`` for the metric namespace and runbook.
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, Reservoir,
                      get_registry)
from .sinks import (JsonlSink, PrometheusTextfileSink,
                    parse_prometheus_textfile, prometheus_name)
from .tracing import RequestRecord, RequestTracer, ServingStats
from .xla import TraceWindow, sample_memory

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "Reservoir",
    "get_registry",
    "JsonlSink", "PrometheusTextfileSink", "parse_prometheus_textfile",
    "prometheus_name",
    "RequestRecord", "RequestTracer", "ServingStats",
    "TraceWindow", "sample_memory",
]
