"""Communication observatory: multichip step anatomy from profiler traces.

The census layer (``observability/capacity.py`` + ``comm/hlo_analysis``)
counts *static* collective bytes — what a step's program promises to move.
This module measures what those collectives actually *cost*:

- **Step anatomy** — parse the windowed ``jax.profiler`` capture (the
  PR-2 :class:`~.xla.TraceWindow` target) into per-device op timelines,
  classify collective vs compute ops, and tile each step's wall into
  ``compute + exposed_collective + other`` (T3's headline decomposition:
  exposed-collective time is the collective interval union MINUS its
  overlap with concurrent compute — the only part worth optimizing away).
  The tiling is exact by construction:
  ``|compute| + |collective \\ compute| + (wall - |compute ∪ collective|)
  == wall``.
- **Achieved bus-bandwidth ledger** — join measured per-kind collective
  wall time against the static per-step bytes from
  :func:`~..comm.hlo_analysis.collective_totals` into per-kind algorithm
  bandwidth (bytes moved / time), bus bandwidth (the ring-scaled figure
  NCCL-style benchmarks report — The Big Send-off's comparison axis), and
  a roofline ratio against the chip's ICI peak — the collective analog of
  the decode MBU.
- **Straggler detection** — per-device step stamps feed a rolling
  median+MAD skew detector (the ``slo.py`` discipline: relative skew
  within a step, so a UNIFORM slowdown — bigger batch, thermal throttle
  on every chip — never flags). Episodes are edge-triggered: one flight
  why-marker per episode, gauges while it burns, recovery after
  ``straggler_clear`` clean steps.

Degradation contract (same as ``capacity.py``, pinned by tier-1 tests):
a backend whose profiler emits no device op timeline (CPU) degrades
every anatomy/ledger field to ``None`` with ONE warning — never a raise.
Disabled (the default) builds nothing: the engine holds ``commscope =
None`` and the hot path pays one ``is not None`` per step; zero new
programs, zero added syncs (the compile-freeze gates stay green).

Clock discipline: all timestamps flow through the injectable ``clock``
seam (fake-clock tests), except the profiler's own trace timestamps,
which live on the profiler clock and are re-based onto the host clock
only for the merged Perfetto export (affine shift from the recorded
host-side step windows).
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import math
import os
import time
from pathlib import Path
from typing import Any, Callable, Iterable, Optional

from ..utils.logging import warning_once
from . import spans as S

# Collective kinds the anatomy/ledger report, in a stable row order (the
# HLO census kinds plus the decode-path psum spelling, which XLA lowers
# to all-reduce — classify_op folds it in).
COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                    "all-to-all", "ragged-all-to-all",
                    "collective-permute", "collective-broadcast")

# op-name substring → kind; ordered, first match wins ("reduce-scatter"
# before "all-reduce": a fused name can mention both, and the scatter is
# the op doing the moving; "ragged-all-to-all" before "all-to-all" so
# the ragged MoE op keeps its own kind — the ledger joins trace kinds
# against the HLO census kinds BY KEY, and the census counts ragged
# separately).
_KIND_PATTERNS = (
    ("reduce-scatter", "reduce-scatter"), ("reduce_scatter", "reduce-scatter"),
    ("all-reduce", "all-reduce"), ("all_reduce", "all-reduce"),
    ("allreduce", "all-reduce"), ("psum", "all-reduce"),
    ("all-gather", "all-gather"), ("all_gather", "all-gather"),
    ("allgather", "all-gather"),
    ("ragged-all-to-all", "ragged-all-to-all"),
    ("ragged_all_to_all", "ragged-all-to-all"),
    ("all-to-all", "all-to-all"), ("all_to_all", "all-to-all"),
    ("alltoall", "all-to-all"),
    ("collective-permute", "collective-permute"),
    ("collective_permute", "collective-permute"),
    ("ppermute", "collective-permute"),
    ("collective-broadcast", "collective-broadcast"),
)

# Bus-bandwidth scaling per kind: busbw = algbw * factor(n). The NCCL
# convention (The Big Send-off reports on this axis): an n-way all-reduce
# moves 2(n-1)/n of the payload per link, gather/scatter/a2a (n-1)/n, a
# permute is a point-to-point send (factor 1).
def busbw_factor(kind: str, n: int) -> float:
    if n <= 1:
        return 1.0
    if kind == "all-reduce":
        return 2.0 * (n - 1) / n
    if kind in ("reduce-scatter", "all-gather", "all-to-all",
                "ragged-all-to-all"):
        return (n - 1) / n
    return 1.0


def classify_op(name: str) -> Optional[str]:
    """Collective kind of a trace/HLO op name, or None for compute.

    Trace op names carry HLO instruction names (``all-reduce.3``,
    ``fusion.12``) and sometimes jax primitive spellings (``psum``,
    ``ppermute``); both vocabularies are mapped. ``-done`` halves of an
    async pair classify like their ``-start`` (the interval between them
    IS the collective in flight — the pair renders as two ops but the
    parser keeps both so overlapped windows stay visible)."""
    low = name.lower()
    for pat, kind in _KIND_PATTERNS:
        if pat in low:
            return kind
    return None


# ------------------------------------------------------------ interval math
def merge_intervals(iv: Iterable[tuple]) -> list:
    """Sorted union of (t0, t1) intervals (degenerate/inverted dropped)."""
    ivs = sorted((float(a), float(b)) for a, b in iv if b > a)
    out: list = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def total_length(iv: Iterable[tuple]) -> float:
    return sum(b - a for a, b in iv)


def subtract_intervals(a: Iterable[tuple], b: Iterable[tuple]) -> list:
    """``a - b`` for MERGED interval lists (the exposed-time primitive:
    collective intervals minus their overlap with concurrent compute)."""
    a = merge_intervals(a)
    b = merge_intervals(b)
    out: list = []
    j = 0
    for a0, a1 in a:
        cur = a0
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < a1:
            b0, b1 = b[k]
            if b0 > cur:
                out.append((cur, b0))
            cur = max(cur, b1)
            if cur >= a1:
                break
            k += 1
        if cur < a1:
            out.append((cur, a1))
    return out


def clip_intervals(iv: Iterable[tuple], t0: float, t1: float) -> list:
    return [(max(a, t0), min(b, t1)) for a, b in iv
            if min(b, t1) > max(a, t0)]


# ------------------------------------------------------------- trace parsing
@dataclasses.dataclass
class OpSpan:
    """One device op occurrence from the profiler timeline (seconds on
    the profiler clock). ``kind`` is a collective kind or None
    (compute)."""

    name: str
    t0: float
    t1: float
    device: str
    kind: Optional[str] = None


def _is_device_pid(process_name: str) -> bool:
    # jax's trace names accelerator processes "/device:TPU:0" (host
    # python threads land under "/host:CPU") — only device timelines
    # carry the XLA op spans the anatomy needs
    return "/device:" in process_name


def parse_trace_events(trace: dict) -> dict[str, list[OpSpan]]:
    """Chrome-trace JSON (the profiler's ``*.trace.json.gz`` payload, or
    a hand-built fake) → per-device op timelines in SECONDS.

    Only complete (``X``) events under device-named pids count; host
    python/runtime tracks are not step work. Returns ``{}`` when the
    capture holds no device timeline (CPU backend) — the caller's
    degradation path."""
    evs = trace.get("traceEvents") or []
    names: dict = {}
    for e in evs:
        if isinstance(e, dict) and e.get("ph") == "M" \
                and e.get("name") == "process_name":
            names[e.get("pid")] = str((e.get("args") or {}).get("name", ""))
    out: dict[str, list[OpSpan]] = {}
    for e in evs:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        pname = names.get(e.get("pid"), "")
        if not _is_device_pid(pname):
            continue
        try:
            ts = float(e["ts"]) * 1e-6
            dur = float(e.get("dur", 0.0)) * 1e-6
        except (KeyError, TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        name = str(e.get("name", ""))
        out.setdefault(pname, []).append(
            OpSpan(name=name, t0=ts, t1=ts + dur, device=pname,
                   kind=classify_op(name)))
    for ops in out.values():
        ops.sort(key=lambda o: o.t0)
    return out


def find_trace_file(trace_dir) -> Optional[Path]:
    """Newest ``*.trace.json.gz`` under a ``jax.profiler`` log dir (the
    TraceWindow target), or None."""
    pats = (os.path.join(str(trace_dir), "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(str(trace_dir), "**", "*.trace.json.gz"))
    cands: list[str] = []
    for pat in pats:
        cands = glob.glob(pat, recursive="**" in pat)
        if cands:
            break
    if not cands:
        return None
    return Path(max(cands, key=lambda p: (os.path.getmtime(p), p)))


def load_trace(source) -> Optional[dict]:
    """A Chrome-trace dict from a dict / .json / .json.gz / profiler log
    dir; None when nothing parseable is there."""
    if isinstance(source, dict):
        return source
    p = Path(source)
    if p.is_dir():
        f = find_trace_file(p)
        if f is None:
            return None
        p = f
    try:
        raw = p.read_bytes()
        if p.name.endswith(".gz"):
            raw = gzip.decompress(raw)
        obj = json.loads(raw.decode("utf-8", errors="replace"))
    except (OSError, json.JSONDecodeError, gzip.BadGzipFile):
        return None
    return obj if isinstance(obj, dict) else None


# ------------------------------------------------------------- step anatomy
# the per-window row fields, always present (None = unmeasured)
_ANATOMY_FIELDS = ("wall_s", "compute_s", "collective_s",
                   "exposed_collective_s", "overlapped_collective_s",
                   "other_s", "exposed_comm_frac", "overlap_frac")


def step_anatomy(ops: Iterable[OpSpan], t0: float, t1: float) -> dict:
    """Tile ONE device's window ``[t0, t1]`` into compute + exposed
    collective + other (seconds), plus per-kind rows.

    The invariant callers (and the smoke gate) pin:
    ``compute_s + exposed_collective_s + other_s == wall_s`` exactly —
    compute is the compute-interval union, exposed collective is the
    collective union minus compute, and other is the wall not covered by
    either union."""
    wall = t1 - t0
    comp_iv = merge_intervals(clip_intervals(
        [(o.t0, o.t1) for o in ops if o.kind is None], t0, t1))
    by_kind_iv = {k: [] for k in COLLECTIVE_KINDS}
    for o in ops:
        if o.kind is not None:
            by_kind_iv.setdefault(o.kind, []).append((o.t0, o.t1))
    coll_all = merge_intervals(clip_intervals(
        [iv for k in by_kind_iv for iv in by_kind_iv[k]], t0, t1))
    compute_s = total_length(comp_iv)
    collective_s = total_length(coll_all)
    exposed_iv = subtract_intervals(coll_all, comp_iv)
    exposed_s = total_length(exposed_iv)
    busy = total_length(merge_intervals(comp_iv + coll_all))
    other_s = max(0.0, wall - busy)
    kinds = {}
    for k in COLLECTIVE_KINDS:
        iv = merge_intervals(clip_intervals(by_kind_iv.get(k, []), t0, t1))
        if not iv:
            continue
        kinds[k] = {
            "time_s": total_length(iv),
            "exposed_s": total_length(subtract_intervals(iv, comp_iv)),
            "count": sum(1 for o in ops if o.kind == k
                         and min(o.t1, t1) > max(o.t0, t0)),
        }
    return {
        "wall_s": wall, "compute_s": compute_s,
        "collective_s": collective_s,
        "exposed_collective_s": exposed_s,
        "overlapped_collective_s": collective_s - exposed_s,
        "other_s": other_s,
        "exposed_comm_frac": (exposed_s / wall) if wall > 0 else None,
        "overlap_frac": (1.0 - exposed_s / collective_s)
        if collective_s > 0 else None,
        "by_kind": kinds,
        "exposed_intervals": exposed_iv,
    }


def decompose(timelines: dict[str, list[OpSpan]],
              windows: Optional[list] = None) -> dict:
    """Anatomy over every device, averaged into one aggregate row.

    ``windows`` is the step-window list (profiler-clock seconds); None =
    the whole captured extent as one window. Each device's per-window
    anatomies are summed (a 5-step window reports 5 steps' worth of
    seconds), then fracs are re-derived from the sums; the aggregate is
    the device mean — the fleet-of-chips view, with ``per_device``
    retained for the skew table."""
    out = {k: None for k in _ANATOMY_FIELDS}
    out.update({"n_devices": 0, "n_windows": 0, "by_kind": {},
                "per_device": {}})
    if not timelines:
        return out
    per_dev: dict[str, dict] = {}
    for dev, ops in timelines.items():
        if windows is None:
            w = [(min(o.t0 for o in ops), max(o.t1 for o in ops))] \
                if ops else []
        else:
            w = [(float(a), float(b)) for a, b in windows]
        rows = [step_anatomy(ops, a, b) for a, b in w]
        if not rows:
            continue
        agg = {f: sum(r[f] for r in rows) for f in _ANATOMY_FIELDS
               if f not in ("exposed_comm_frac", "overlap_frac")}
        agg["exposed_comm_frac"] = (agg["exposed_collective_s"]
                                    / agg["wall_s"]) if agg["wall_s"] else None
        agg["overlap_frac"] = (1.0 - agg["exposed_collective_s"]
                               / agg["collective_s"]) \
            if agg["collective_s"] else None
        kinds: dict = {}
        for r in rows:
            for k, v in r["by_kind"].items():
                d = kinds.setdefault(k, {"time_s": 0.0, "exposed_s": 0.0,
                                         "count": 0})
                for f in d:
                    d[f] += v[f]
        agg["by_kind"] = kinds
        agg["n_windows"] = len(rows)
        per_dev[dev] = agg
    if not per_dev:
        return out
    n = len(per_dev)
    for f in _ANATOMY_FIELDS:
        vals = [d[f] for d in per_dev.values() if d.get(f) is not None]
        out[f] = (sum(vals) / len(vals)) if vals else None
    kinds = {}
    for d in per_dev.values():
        for k, v in d["by_kind"].items():
            row = kinds.setdefault(k, {"time_s": 0.0, "exposed_s": 0.0,
                                       "count": 0})
            for f in row:
                row[f] += v[f]
    # device-mean per kind (each device saw its own copy of the step)
    for v in kinds.values():
        v["time_s"] /= n
        v["exposed_s"] /= n
        v["count"] = int(round(v["count"] / n))
    out["by_kind"] = kinds
    out["n_devices"] = n
    out["n_windows"] = max(d["n_windows"] for d in per_dev.values())
    out["per_device"] = per_dev
    return out


# -------------------------------------------------------- bandwidth ledger
_LEDGER_FIELDS = ("mbytes_per_step", "count_per_step", "time_s_per_step",
                  "exposed_s_per_step", "algbw_gbps", "busbw_gbps",
                  "roofline_ratio")


def bandwidth_ledger(by_kind_bytes: Optional[dict],
                     anatomy: Optional[dict], *, n_steps: int = 1,
                     n_devices: int = 1,
                     peak_ici_gbps: Optional[float] = None) -> dict:
    """Per-collective-kind achieved-bandwidth rows.

    ``by_kind_bytes`` is ``collective_totals(...)["by_kind"]`` — the
    static per-STEP payload ({kind: {count, mbytes}}); ``anatomy`` is a
    :func:`decompose` aggregate whose ``by_kind`` times cover
    ``n_steps`` steps. Rows keep the census bytes EXACTLY (the smoke
    gate pins ledger bytes == ``collective_totals``) and derive:

    - ``algbw_gbps`` — payload bytes / measured wall (algorithm bw);
    - ``busbw_gbps`` — algbw × the NCCL-convention ring factor for
      ``n_devices`` participants (the cross-topology comparable);
    - ``roofline_ratio`` — busbw / the chip's ICI peak (the collective
      MBU analog), None when the peak is unknown.

    Every field is PRESENT; anything unmeasured is None."""
    rows: dict[str, dict] = {}
    n_steps = max(1, int(n_steps))
    meas = (anatomy or {}).get("by_kind") or {}
    kinds = sorted(set(by_kind_bytes or {}) | set(meas))
    for k in kinds:
        row: dict[str, Any] = {f: None for f in _LEDGER_FIELDS}
        st = (by_kind_bytes or {}).get(k)
        if st is not None:
            row["mbytes_per_step"] = float(st.get("mbytes", 0.0))
            row["count_per_step"] = int(st.get("count", 0))
        m = meas.get(k)
        if m is not None:
            row["time_s_per_step"] = m["time_s"] / n_steps
            row["exposed_s_per_step"] = m["exposed_s"] / n_steps
        if row["mbytes_per_step"] and row["time_s_per_step"]:
            algbw = row["mbytes_per_step"] * 1e6 / row["time_s_per_step"]
            row["algbw_gbps"] = algbw / 1e9
            row["busbw_gbps"] = row["algbw_gbps"] * busbw_factor(
                k, n_devices)
            if peak_ici_gbps:
                row["roofline_ratio"] = row["busbw_gbps"] / peak_ici_gbps
        rows[k] = row
    return {"by_kind": rows, "n_devices": n_devices, "n_steps": n_steps,
            "peak_ici_gbps": peak_ici_gbps}


def peak_ici_gbps_for(device=None) -> Optional[float]:
    """Per-chip aggregate ICI bandwidth (GB/s) for the collective
    roofline, None when unknown — ledger rows then keep a null ratio
    (same degradation stance as :func:`~.capacity.roofline_peaks`)."""
    from ..utils.timer import peak_ici_bw_for

    if device is None:
        import jax

        device = jax.devices()[0]
    try:
        return peak_ici_bw_for(device) / 1e9
    except ValueError:
        return None


# --------------------------------------------------------------- straggler
@dataclasses.dataclass
class CommScopeConfig:
    """Observatory knobs (``observability.commscope`` config dict).

    All decoding/analysis is host-side; ``enabled`` only controls whether
    the engine builds the observatory at all (one ``is not None`` per
    step when off)."""

    enabled: bool = False
    # straggler detector: a device whose within-step skew exceeds
    # k * MAD of the cross-device skews (floored at min_skew_s) for
    # `confirm` consecutive steps opens an episode; `clear` consecutive
    # clean steps closes it. k = 0 disables detection.
    straggler_mad_k: float = 4.0
    straggler_confirm: int = 3
    straggler_clear: int = 3
    min_skew_s: float = 1e-3
    # rolling per-step history kept for the doctor's skew table
    skew_window: int = 64

    def __post_init__(self):
        if self.straggler_mad_k < 0:
            raise ValueError(f"straggler_mad_k must be >= 0, "
                             f"got {self.straggler_mad_k}")
        for knob in ("straggler_confirm", "straggler_clear", "skew_window"):
            if getattr(self, knob) < 1:
                raise ValueError(f"{knob} must be >= 1, "
                                 f"got {getattr(self, knob)}")
        if self.min_skew_s < 0:
            raise ValueError(f"min_skew_s must be >= 0, "
                             f"got {self.min_skew_s}")

    @classmethod
    def from_any(cls, cfg) -> "Optional[CommScopeConfig]":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown commscope config keys: {sorted(unknown)}")
        return cls(**cfg)


def _median(vals: list) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return math.nan
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class StragglerDetector:
    """Cross-device step-skew detector (median + MAD, episode-scoped).

    ``observe(step, stamps)`` takes one step's per-device completion
    stamps ``{device_id: t}`` (any clock — only differences within the
    step matter, which is exactly why a UNIFORM slowdown can never
    flag). Skew is each device's stamp minus the step median; a device
    whose skew exceeds ``k * max(MAD, min_skew_s)`` for ``confirm``
    consecutive steps opens an episode (returned as ``("open", dev)``),
    which closes after ``clear`` consecutive clean steps
    (``("close", dev)``). One episode = one flight marker, however many
    steps it burns."""

    def __init__(self, k: float = 4.0, confirm: int = 3, clear: int = 3,
                 min_skew_s: float = 1e-3, window: int = 64):
        self.k = float(k)
        self.confirm = int(confirm)
        self.clear = int(clear)
        self.min_skew_s = float(min_skew_s)
        self._suspect: dict[Any, int] = {}    # device -> consecutive hits
        self._clean: dict[Any, int] = {}      # burning device -> clean run
        self.burning: set = set()
        self.episodes = 0
        self.last_skew: dict[Any, float] = {}
        self.window = int(window)
        self._hist: list[dict] = []

    @property
    def enabled(self) -> bool:
        return self.k > 0

    def observe(self, step: int, stamps: dict) -> list:
        """One step's stamps → list of ``("open"|"close", device_id,
        skew_s)`` episode edges (usually empty)."""
        if not self.enabled or len(stamps) < 3:
            # skew needs a quorum: with <3 stamps the median IS one of
            # the samples and MAD is degenerate — single-host training
            # feeds 1 stamp and detection stays honestly inert
            return []
        med = _median(list(stamps.values()))
        skews = {d: float(t) - med for d, t in stamps.items()}
        self.last_skew = dict(skews)
        self._hist.append({"step": int(step), "skew": dict(skews)})
        if len(self._hist) > self.window:
            self._hist = self._hist[-self.window:]
        mad = _median([abs(v) for v in skews.values()])
        thresh = self.k * max(mad, self.min_skew_s)
        edges: list = []
        for dev, skew in skews.items():
            hit = skew > thresh
            if hit:
                self._suspect[dev] = self._suspect.get(dev, 0) + 1
                self._clean.pop(dev, None)
                if dev not in self.burning \
                        and self._suspect[dev] >= self.confirm:
                    self.burning.add(dev)
                    self.episodes += 1
                    edges.append(("open", dev, skew))
            else:
                self._suspect.pop(dev, None)
                if dev in self.burning:
                    self._clean[dev] = self._clean.get(dev, 0) + 1
                    if self._clean[dev] >= self.clear:
                        self.burning.discard(dev)
                        self._clean.pop(dev, None)
                        edges.append(("close", dev, skew))
        return edges

    def skew_table(self) -> dict:
        """Per-device skew summary for the doctor: last skew plus the
        rolling mean/max over the window."""
        devs: dict[Any, dict] = {}
        for row in self._hist:
            for d, v in row["skew"].items():
                e = devs.setdefault(d, {"n": 0, "sum": 0.0, "max": -1e30})
                e["n"] += 1
                e["sum"] += v
                e["max"] = max(e["max"], v)
        return {str(d): {"last_skew_s": self.last_skew.get(d),
                         "mean_skew_s": (e["sum"] / e["n"]) if e["n"] else None,
                         "max_skew_s": e["max"] if e["n"] else None,
                         "burning": d in self.burning}
                for d, e in sorted(devs.items(), key=lambda kv: str(kv[0]))}


# -------------------------------------------------------------- observatory
class CommScope:
    """The per-engine communication observatory.

    Wires the three measurements above to the engine's registry / span
    ring / flight recorder. All methods are host-side float work; the
    engine calls:

    - :meth:`on_step` once per train step (host window + this process's
      stamp; one clock read when the engine didn't already take one);
    - :meth:`observe_stamps` with cross-host/device stamps when a
      launcher gathers them (single-process training feeds one stamp and
      the detector stays inert — the seam is what ships);
    - :meth:`analyze` after the TraceWindow closes, to parse the capture
      and produce the anatomy + ledger report.
    """

    def __init__(self, cfg: Optional[CommScopeConfig] = None, *,
                 registry=None, spans: Optional[S.SpanRecorder] = None,
                 flight=None, n_devices: int = 1,
                 clock: Callable[[], float] = time.perf_counter):
        self.cfg = cfg if cfg is not None else CommScopeConfig(enabled=True)
        self.registry = registry
        self.spans = spans
        self.flight = flight
        self.n_devices = int(n_devices)
        self.clock = clock
        self.detector = StragglerDetector(
            self.cfg.straggler_mad_k, self.cfg.straggler_confirm,
            self.cfg.straggler_clear, self.cfg.min_skew_s,
            self.cfg.skew_window)
        # host-clock step windows, kept bounded: the affine rebase for
        # the merged Perfetto export + per-step normalization (traced =
        # the subset that ran inside the profiler TraceWindow)
        self._step_windows: list[tuple[int, float, float]] = []
        self._traced_windows: list[tuple[int, float, float]] = []
        self._by_kind_bytes: Optional[dict] = None
        self._last_report: Optional[dict] = None

    # ------------------------------------------------------------- recording
    def on_step(self, step: int, t0: float, t1: float,
                traced: bool = False) -> None:
        """One train step's host-clock window. ``traced=True`` marks a
        step that ran INSIDE the profiler TraceWindow — the Perfetto
        rebase anchors the capture's first op to the first TRACED
        window's start (anchoring to the first recorded window of any
        kind would shift comm spans earlier by however many pre-window
        steps were stamped)."""
        self._step_windows.append((int(step), float(t0), float(t1)))
        if len(self._step_windows) > 4096:
            self._step_windows = self._step_windows[-4096:]
        if traced:
            self._traced_windows.append((int(step), float(t0), float(t1)))
            if len(self._traced_windows) > 4096:
                self._traced_windows = self._traced_windows[-4096:]

    def observe_stamps(self, step: int, stamps: dict) -> list:
        """Cross-host/device per-step stamps → straggler detection.
        Returns the episode edges; emits gauges, counters, and ONE
        flight why-marker per opened episode."""
        edges = self.detector.observe(step, stamps)
        r = self.registry
        if r is not None and self.detector.last_skew:
            worst = max(self.detector.last_skew.values())
            r.set_gauges({
                "Train/straggler_active":
                    1.0 if self.detector.burning else 0.0,
                "Train/straggler_skew_s": worst,
            })
            for d, v in self.detector.last_skew.items():
                r.gauge(f"Train/straggler_skew_s_d{d}").set(v)
        for kind, dev, skew in edges:
            if kind == "open":
                if r is not None:
                    r.counter("Train/straggler_episodes").inc()
                    r.gauge("Train/straggler_device").set(
                        float(dev) if isinstance(dev, (int, float))
                        else -1.0)
                if self.flight is not None:
                    # once per EPISODE by construction: edges only fire
                    # on the open transition
                    self.flight.note("straggler", device=str(dev),
                                     skew_s=round(float(skew), 6),
                                     step=int(step))
            elif kind == "close" and r is not None:
                r.gauge("Train/straggler_device").set(-1.0)
        return edges

    def set_collective_bytes(self, by_kind: Optional[dict]) -> None:
        """Static per-step collective payload
        (``collective_totals(...)["by_kind"]``) for the ledger join."""
        self._by_kind_bytes = dict(by_kind) if by_kind else None

    # -------------------------------------------------------------- analysis
    def analyze(self, trace_source, *, n_steps: Optional[int] = None,
                windows: Optional[list] = None,
                peak_ici_gbps: Optional[float] = None,
                emit_spans: bool = True) -> dict:
        """Parse a profiler capture and produce the observatory report:
        ``{anatomy, ledger, straggler, trace}``.

        ``trace_source`` is a trace dict / file / profiler log dir;
        ``windows`` optionally lists per-step (t0, t1) windows on the
        PROFILER clock (None = the captured extent as one window;
        ``n_steps`` then normalizes the ledger's per-step figures). A
        missing or device-less capture (CPU backend) degrades every
        anatomy/ledger value to None with one warning — never a
        raise."""
        trace = load_trace(trace_source)
        timelines = parse_trace_events(trace) if trace is not None else {}
        if not timelines:
            warning_once(
                "commscope: no device op timeline in the profiler capture "
                "(CPU backend, or no trace taken) — anatomy and "
                "achieved-bandwidth rows degrade to null values")
        anatomy = decompose(timelines, windows=windows)
        steps = n_steps if n_steps is not None else \
            (len(windows) if windows else
             (anatomy.get("n_windows") or 1))
        if peak_ici_gbps is None:
            peak_ici_gbps = self._peak_ici()
        ledger = bandwidth_ledger(
            self._by_kind_bytes, anatomy if timelines else None,
            n_steps=steps, n_devices=max(self.n_devices,
                                         anatomy.get("n_devices") or 1),
            peak_ici_gbps=peak_ici_gbps)
        report = {
            "anatomy": {k: anatomy.get(k) for k in
                        _ANATOMY_FIELDS + ("n_devices", "n_windows",
                                           "by_kind")},
            "ledger": ledger,
            "straggler": {
                "episodes": self.detector.episodes,
                "burning": sorted(str(d) for d in self.detector.burning),
                "skew_table": self.detector.skew_table(),
            },
            "trace": {"devices": sorted(timelines),
                      "ops": sum(len(v) for v in timelines.values())},
        }
        self._last_report = report
        self._emit_gauges(report)
        if emit_spans and timelines:
            self._emit_comm_spans(timelines, anatomy)
        return report

    def _peak_ici(self) -> Optional[float]:
        try:
            return peak_ici_gbps_for()
        except Exception:  # no jax/device in pure-host tests
            return None

    def _emit_gauges(self, report: dict) -> None:
        r = self.registry
        if r is None:
            return
        an = report["anatomy"]
        gauges: dict[str, float] = {}
        for key, name in (("exposed_comm_frac", "Comm/exposed_frac"),
                          ("overlap_frac", "Comm/overlap_frac"),
                          ("exposed_collective_s", "Comm/exposed_s"),
                          ("collective_s", "Comm/collective_s")):
            v = an.get(key)
            if v is not None:
                gauges[name] = float(v)
        for k, row in report["ledger"]["by_kind"].items():
            for f, suffix in (("algbw_gbps", "algbw_gbps"),
                              ("busbw_gbps", "busbw_gbps"),
                              ("roofline_ratio", "roofline")):
                if row.get(f) is not None:
                    gauges[f"Comm/{k}/{suffix}"] = float(row[f])
        if gauges:
            r.set_gauges(gauges)

    # ------------------------------------------------------- perfetto export
    def _rebase(self) -> Optional[tuple]:
        """Affine profiler→host clock map: the capture's first op lands
        at the first TRACED step window's start (falling back to the
        first recorded window when no step was marked traced — ad-hoc
        captures outside a TraceWindow). None when no windows were
        recorded (offline parse — spans then keep the profiler
        clock)."""
        windows = self._traced_windows or self._step_windows
        if not windows:
            return None
        h0 = min(t0 for _, t0, _ in windows)
        return (1.0, h0)

    def _emit_comm_spans(self, timelines: dict,
                         anatomy: dict) -> None:
        """Collective ops + exposed gaps → ``comm_op``/``comm_exposed``
        spans in the engine ring, re-based onto the host clock so the
        merged Perfetto trace shows them beside the train_step track."""
        if self.spans is None:
            return
        rebase = self._rebase()
        dev0 = sorted(timelines)[0]
        ops = timelines[dev0]
        if not ops:
            return
        p0 = min(o.t0 for o in ops)

        def to_host(t: float) -> float:
            if rebase is None:
                return t
            scale, h0 = rebase
            return h0 + scale * (t - p0)

        for o in ops:
            if o.kind is None:
                continue
            # meta key is "collective", not "kind" — emit()'s first
            # positional is the span kind and **meta must not collide
            self.spans.emit(S.COMM_OP, to_host(o.t0), to_host(o.t1),
                            collective=o.kind, op=o.name,
                            device=o.device)
        per_dev = anatomy.get("per_device") or {}
        # exposed gaps for the rendered device (re-derive on its merged
        # timeline: decompose() keeps sums, not intervals, per device)
        if dev0 in per_dev:
            w0 = min(o.t0 for o in ops)
            w1 = max(o.t1 for o in ops)
            row = step_anatomy(ops, w0, w1)
            for a, b in row["exposed_intervals"]:
                self.spans.emit(S.COMM_EXPOSED, to_host(a), to_host(b),
                                device=dev0)

    # --------------------------------------------------------------- readout
    def report(self) -> Optional[dict]:
        """The last :meth:`analyze` result (None before the first)."""
        return self._last_report

    def snapshot(self) -> dict:
        """Flight-recorder snapshot provider: the straggler state plus
        the last analysis (if any)."""
        return {
            "straggler": {
                "episodes": self.detector.episodes,
                "burning": sorted(str(d) for d in self.detector.burning),
                "skew_table": self.detector.skew_table(),
            },
            "last_report": self._last_report,
            "step_windows": len(self._step_windows),
        }
