"""tenantscope: per-tenant cost attribution, fairness & noisy-neighbor
observatory.

Every prior observatory metered a fleet-wide resource (kvscope → KV
eviction regret, commscope → collective anatomy, loadscope → arrival
process); this one splits the SAME totals along the `Request.tenant_id`
dimension so the multi-tenant build (S-LoRA adapter serving, ROADMAP)
lands against its own meter. Design rules:

- **Conservation, not estimation.** Per-tenant cells are incremented at
  the exact call sites (and with the exact arithmetic) that move the
  fleet totals: completed tokens at the retirement funnel with
  ``len(req.tokens)`` (the same expression ``ServingStats.on_retire``
  counts into ``Serve/completed_tokens``), KV pages through the
  ``PagePool.on_pages(rid, ±pages)`` hook whose deltas net to zero per
  request, resident tier bytes through ``TierStore.owner_bytes`` which
  moves with ``bytes_used`` at every path. So Σ per-tenant == fleet
  total *exactly* (integer token counts; page-second integrals agree
  interval-by-interval on the same injectable clock).
- **Inert by default.** The engine builds this only when
  ``serving.tenantscope`` is set; enabled, it is host-side arithmetic
  on the submit/admission/retirement paths — zero new compiled
  programs, zero syncs (the bench compile-freeze gates stay the
  oracle). Requests that never set a tenant bill to ``"default"``.
- **Bounded cardinality.** At most ``max_tenants`` label values; later
  tenants fold into ``"(overflow)"`` so a tenant-id-per-request abuse
  cannot mint unbounded Prometheus series. Reservoirs and the
  block-owner map are bounded deques/LRU.

Exports label-aware series (``Serve/tenant_*{tenant="..."}`` — see
``expfmt.labeled_name``), Jain's fairness index + dominant-resource
shares, and an edge-triggered noisy-neighbor detector: one tenant's
arrival burst correlated with fleet SLO burn marks the flight ring
(``noisy_neighbor`` why-marker) and dumps a per-tenant breakdown
artifact (``tenant_breakdown.json``) into the incident dir.
"""

from __future__ import annotations

import dataclasses
import json
from collections import OrderedDict, deque
from typing import Callable, Optional

from .expfmt import labeled_name
from .workload import prefix_hashes, token_hash

OVERFLOW_TENANT = "(overflow)"
UNOWNED = "(unowned)"


@dataclasses.dataclass
class TenantScopeConfig:
    """Knobs for the per-tenant observatory (``serving.tenantscope``)."""

    enabled: bool = True
    # label-cardinality bound: distinct tenants beyond this fold into
    # OVERFLOW_TENANT (their costs still conserve — just unsplit)
    max_tenants: int = 64
    # per-tenant latency reservoir depth (queue-wait / TTFT / TPOT)
    reservoir: int = 256
    # block-prefix → first-writer tenant map bound (tier-byte owners)
    block_owner_cap: int = 16384
    # noisy-neighbor detector: arrival window, minimum burst evidence,
    # the arrival share that makes one tenant "dominant", the SLO burn
    # that makes the fleet "hurting", the re-trigger cooldown, and the
    # detector's own tick rate-limit (all on the injectable clock)
    window_s: float = 30.0
    min_burst_arrivals: int = 8
    burst_share: float = 0.5
    burn_threshold: float = 1.0
    cooldown_s: float = 30.0
    check_interval_s: float = 1.0

    def __post_init__(self):
        if self.max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1, "
                             f"got {self.max_tenants}")
        if self.reservoir < 1:
            raise ValueError(f"reservoir must be >= 1, "
                             f"got {self.reservoir}")
        for knob in ("window_s", "cooldown_s", "check_interval_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0, "
                                 f"got {getattr(self, knob)}")
        if not (0.0 < self.burst_share <= 1.0):
            raise ValueError(f"burst_share must be in (0, 1], "
                             f"got {self.burst_share}")

    @classmethod
    def from_any(cls, cfg) -> "TenantScopeConfig":
        if cfg is None:
            return cls()
        if isinstance(cfg, cls):
            return cfg
        if cfg is True:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown tenantscope config keys: {sorted(unknown)}")
        return cls(**cfg)


class _Cell:
    """One tenant's ledger row. Plain attributes — every field is either
    an exact conserved integer or a bounded reservoir."""

    __slots__ = ("submitted", "admitted", "completed_tokens",
                 "prompt_tokens", "shared_prefix_tokens", "sheds",
                 "timeouts", "cancelled", "nonfinite", "requeues",
                 "retired_ok", "pages_held", "page_seconds",
                 "last_page_t", "queue_wait", "ttft", "tpot", "arrivals")

    def __init__(self, reservoir: int):
        self.submitted = 0
        self.admitted = 0
        self.completed_tokens = 0
        self.prompt_tokens = 0
        self.shared_prefix_tokens = 0
        self.sheds = 0
        self.timeouts = 0
        self.cancelled = 0
        self.nonfinite = 0
        self.requeues = 0
        self.retired_ok = 0
        self.pages_held = 0
        self.page_seconds = 0.0
        self.last_page_t: Optional[float] = None
        self.queue_wait: deque = deque(maxlen=reservoir)
        self.ttft: deque = deque(maxlen=reservoir)
        self.tpot: deque = deque(maxlen=reservoir)
        self.arrivals: deque = deque(maxlen=4096)


def _pct(values, q: float) -> Optional[float]:
    if not values:
        return None
    s = sorted(values)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[i]


def jain_index(xs) -> Optional[float]:
    """Jain's fairness index over per-tenant allocations: 1.0 when all
    equal, → 1/n when one tenant holds everything. None when nothing
    was allocated yet."""
    xs = [float(x) for x in xs if x > 0]
    if not xs:
        return None
    sq = sum(x * x for x in xs)
    if sq <= 0:
        return None
    return (sum(xs) ** 2) / (len(xs) * sq)


class TenantScope:
    """The per-tenant cost/fairness ledger (see module docstring).

    Wiring (all done by ``ServingEngine.__init__`` when
    ``serving.tenantscope`` is set):

    - ``on_submit(req)`` / ``on_shed(tid)`` on the intake path;
    - ``on_admit(req, workload=...)`` at admission (the PR-6 workload
      estimator's per-request dict partitions prefix overlap by tenant);
    - ``on_retire(req)`` at the terminal funnel (``_store_result``);
    - ``on_requeue(req)`` / ``on_adopt(req)`` on the fleet seams, so a
      moved request keeps billing its tenant on the new replica;
    - ``PagePool.on_pages = ts.on_pages`` for the page-second integral;
    - ``on_blocks(req)`` beside ``pool.on_inserted`` so demoted blocks
      can be billed to the tenant that first wrote them
      (``block_owner(tokens)`` at the demote-drain ``put``).
    """

    def __init__(self, cfg: TenantScopeConfig, registry,
                 clock: Callable[[], float], flight=None,
                 page_size: int = 0):
        self.cfg = cfg
        self.registry = registry
        self.clock = clock
        self.flight = flight
        self.page_size = int(page_size)
        self.tenants: "OrderedDict[str, _Cell]" = OrderedDict()
        self._rid_tenant: dict = {}
        self._rid_pages: dict = {}
        # (prefix_len, rolling_hash) → tenant, first-writer-wins: the
        # same identity TierStore keys entries by, so a demoted block
        # resolves its owner without carrying a rid through the tree
        self._block_owner: OrderedDict = OrderedDict()
        # page-second integral of the whole pool, updated at the same
        # events (same clock reads) as the per-tenant integrals — the
        # independent side of the conservation test
        self.pool_pages_held = 0
        self.pool_page_seconds = 0.0
        self._pool_last_t: Optional[float] = None
        # noisy-neighbor episode state (edge-triggered)
        self.episodes = 0
        self.active_episode: Optional[dict] = None
        self.last_episode: Optional[dict] = None
        self._last_check = -float("inf")
        self._last_end = -float("inf")

    # ------------------------------------------------------------ plumbing
    def _cell(self, tenant_id: str) -> _Cell:
        tid = str(tenant_id)
        cell = self.tenants.get(tid)
        if cell is None:
            if len(self.tenants) >= self.cfg.max_tenants:
                tid = OVERFLOW_TENANT
                cell = self.tenants.get(tid)
                if cell is None:
                    cell = self.tenants[tid] = _Cell(self.cfg.reservoir)
            else:
                cell = self.tenants[tid] = _Cell(self.cfg.reservoir)
        return cell

    def _resolve(self, tenant_id: str) -> str:
        tid = str(tenant_id)
        if tid in self.tenants:
            return tid
        if len(self.tenants) >= self.cfg.max_tenants:
            return OVERFLOW_TENANT
        return tid

    def _count(self, name: str, tenant: str, n: int = 1) -> None:
        self.registry.counter(
            labeled_name(name, tenant=tenant)).inc(n)

    # ----------------------------------------------------------- intake
    def on_submit(self, req) -> None:
        tid = self._resolve(getattr(req, "tenant_id", "default"))
        cell = self._cell(tid)
        now = self.clock()
        cell.submitted += 1
        cell.arrivals.append(now)
        self._rid_tenant[req.rid] = tid
        self._count("Serve/tenant_submitted", tid)
        if now - self._last_check >= self.cfg.check_interval_s:
            self._last_check = now
            self._detect(now)

    def on_shed(self, tenant_id) -> None:
        tid = self._resolve("default" if tenant_id is None else tenant_id)
        self._cell(tid).sheds += 1
        self._count("Serve/tenant_sheds", tid)

    def on_admit(self, req, workload: Optional[dict] = None) -> None:
        tid = self._rid_tenant.get(req.rid)
        if tid is None:
            tid = self._resolve(getattr(req, "tenant_id", "default"))
            self._rid_tenant[req.rid] = tid
        cell = self._cell(tid)
        cell.admitted += 1
        cell.prompt_tokens += int(req.prompt_len)
        self._count("Serve/tenant_admitted", tid)
        self._count("Serve/tenant_prompt_tokens", tid,
                    int(req.prompt_len))
        if workload is not None:
            shared = int(workload.get("shared_prefix_tokens") or 0)
            cell.shared_prefix_tokens += shared
            self._count("Serve/tenant_shared_prefix_tokens", tid, shared)

    def on_requeue(self, req) -> None:
        tid = self._rid_tenant.get(req.rid)
        if tid is None:
            tid = self._resolve(getattr(req, "tenant_id", "default"))
            self._rid_tenant[req.rid] = tid
        self._cell(tid).requeues += 1
        self._count("Serve/tenant_requeues", tid)

    def on_adopt(self, req) -> None:
        """A request imported from another replica (disaggregated
        handoff / failover): learn its rid → tenant binding BEFORE the
        pool admission fires the pages hook."""
        self._rid_tenant[req.rid] = self._resolve(
            getattr(req, "tenant_id", "default"))

    # -------------------------------------------------------- retirement
    def on_retire(self, req) -> None:
        """Terminal attribution at the engine's ``_store_result``
        funnel. OK retirements credit ``len(req.tokens)`` — the same
        expression ``ServingStats.on_retire`` adds to
        ``Serve/completed_tokens`` — so Σ per-tenant completed tokens
        equals that counter exactly."""
        tid = self._rid_tenant.pop(req.rid, None)
        if tid is None:
            tid = self._resolve(getattr(req, "tenant_id", "default"))
        cell = self._cell(tid)
        status = getattr(req.status, "value", str(req.status))
        if status == "ok":
            n = len(req.tokens)
            cell.retired_ok += 1
            cell.completed_tokens += n
            self._count("Serve/tenant_completed_tokens", tid, n)
            self._count("Serve/tenant_retired", tid)
        elif status == "timeout":
            cell.timeouts += 1
            self._count("Serve/tenant_timeouts", tid)
        elif status == "cancelled":
            cell.cancelled += 1
            self._count("Serve/tenant_cancelled", tid)
        elif status == "shed":
            cell.sheds += 1
            self._count("Serve/tenant_sheds", tid)
        else:
            cell.nonfinite += 1
            self._count("Serve/tenant_nonfinite", tid)
        at = getattr(req, "admit_t", None)
        if at is not None:
            cell.queue_wait.append(at - req.submit_t)
        ft = getattr(req, "first_token_t", None)
        if ft is not None:
            cell.ttft.append(ft - req.submit_t)
            n = len(req.tokens)
            if req.finish_t is not None and n > 1:
                cell.tpot.append((req.finish_t - ft) / (n - 1))
        self._publish_shares()

    # ------------------------------------------------------ KV attribution
    def on_pages(self, rid: int, delta: int) -> None:
        """``PagePool`` hook: integrate page-seconds per tenant AND for
        the whole pool at the same clock read, so the two integrals
        agree interval-by-interval (the conservation test's two sides).
        Deltas net to zero per rid (admit +n, truncate −k, release
        −(n−k)), so a drained pool always integrates at its true
        occupancy."""
        now = self.clock()
        tid = self._rid_tenant.get(rid, "default")
        cell = self._cell(tid)
        if cell.last_page_t is not None and cell.pages_held > 0:
            cell.page_seconds += cell.pages_held * (now - cell.last_page_t)
        cell.pages_held = max(0, cell.pages_held + int(delta))
        cell.last_page_t = now
        if self._pool_last_t is not None and self.pool_pages_held > 0:
            self.pool_page_seconds += (
                self.pool_pages_held * (now - self._pool_last_t))
        self.pool_pages_held = max(0, self.pool_pages_held + int(delta))
        self._pool_last_t = now
        held = self._rid_pages.get(rid, 0) + int(delta)
        if held <= 0:
            self._rid_pages.pop(rid, None)
        else:
            self._rid_pages[rid] = held

    def on_blocks(self, req) -> None:
        """Register ``req``'s full prompt blocks as owned by its tenant
        (first writer wins — the prefix tree's own sharing rule), keyed
        exactly like ``TierStore`` entries, so a later demotion of any
        of these blocks bills its resident bytes to this tenant."""
        if self.page_size <= 0:
            return
        tid = self._rid_tenant.get(req.rid)
        if tid is None:
            tid = self._resolve(getattr(req, "tenant_id", "default"))
        for key in prefix_hashes(req.prompt, self.page_size):
            if key not in self._block_owner:
                self._block_owner[key] = tid
                if len(self._block_owner) > self.cfg.block_owner_cap:
                    self._block_owner.popitem(last=False)

    def block_owner(self, tokens) -> Optional[str]:
        """Owner tenant of one demoted block's full token prefix (the
        demote-drain's ``TierStore.put(..., owner=...)`` argument)."""
        toks = tuple(int(t) for t in tokens)
        return self._block_owner.get((len(toks), token_hash(toks)))

    # ------------------------------------------------------------ fairness
    def _flush_integrals(self, now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        for cell in self.tenants.values():
            if cell.last_page_t is not None and cell.pages_held > 0:
                cell.page_seconds += (
                    cell.pages_held * (now - cell.last_page_t))
            cell.last_page_t = now
        if self._pool_last_t is not None and self.pool_pages_held > 0:
            self.pool_page_seconds += (
                self.pool_pages_held * (now - self._pool_last_t))
        self._pool_last_t = now

    def _publish_shares(self) -> None:
        total = sum(c.completed_tokens for c in self.tenants.values())
        g = self.registry.gauge
        j = jain_index(
            c.completed_tokens for c in self.tenants.values())
        if j is not None:
            g("Serve/tenant_fairness_jain").set(j)
        g("Serve/tenant_count").set(float(len(self.tenants)))
        if total > 0:
            for tid, cell in self.tenants.items():
                g(labeled_name("Serve/tenant_goodput_share",
                               tenant=tid)).set(
                    cell.completed_tokens / total)

    def fairness(self, tiers: Optional[dict] = None) -> dict:
        """Jain's index over completed tokens plus each tenant's
        dominant-resource share: max of its token share, current
        HBM-page share, and resident tier-byte share."""
        self._flush_integrals()
        tok_total = sum(c.completed_tokens for c in self.tenants.values())
        page_total = sum(c.pages_held for c in self.tenants.values())
        tier_by_tenant: dict = {}
        tier_total = 0
        for store in (tiers or {}).values():
            ob = getattr(store, "owner_bytes", None) or {}
            for tid, b in ob.items():
                tier_by_tenant[tid] = tier_by_tenant.get(tid, 0) + b
            tier_total += getattr(store, "bytes_used", 0)
        dom = {}
        for tid, cell in self.tenants.items():
            shares = []
            if tok_total > 0:
                shares.append(cell.completed_tokens / tok_total)
            if page_total > 0:
                shares.append(cell.pages_held / page_total)
            if tier_total > 0:
                shares.append(tier_by_tenant.get(tid, 0) / tier_total)
            dom[tid] = max(shares) if shares else 0.0
        return {
            "jain": jain_index(
                c.completed_tokens for c in self.tenants.values()),
            "dominant_shares": dom,
            "n_tenants": len(self.tenants),
        }

    # -------------------------------------------------- noisy neighbor
    def _burn_max(self) -> float:
        worst = 0.0
        for which in ("ttft", "tpot", "error"):
            gauge = self.registry.gauge(f"Serve/slo_{which}_burn")
            if gauge.updated and gauge.value == gauge.value:
                worst = max(worst, gauge.value)
        return worst

    def _detect(self, now: float) -> None:
        """Edge-triggered: a single tenant dominating the arrival window
        while the fleet burns SLO budget opens one episode (flight
        why-marker + incident dump); the episode closes when either
        signal clears. Needs >= 2 tenants — a noisy *neighbor* needs a
        neighbor."""
        cut = now - self.cfg.window_s
        counts = {}
        for tid, cell in self.tenants.items():
            while cell.arrivals and cell.arrivals[0] < cut:
                cell.arrivals.popleft()
            if cell.arrivals:
                counts[tid] = len(cell.arrivals)
        total = sum(counts.values())
        burst_tid, share = None, 0.0
        if total > 0 and len(self.tenants) >= 2:
            burst_tid = max(counts, key=counts.get)
            share = counts[burst_tid] / total
            if (counts[burst_tid] < self.cfg.min_burst_arrivals
                    or share < self.cfg.burst_share):
                burst_tid = None
        burn = self._burn_max()
        firing = (burst_tid is not None
                  and burn >= self.cfg.burn_threshold)
        g = self.registry.gauge
        if firing and self.active_episode is None:
            if now - self._last_end < self.cfg.cooldown_s:
                return
            self.episodes += 1
            self.active_episode = {
                "tenant": burst_tid, "t0": now, "share": share,
                "burn": burn, "arrivals": counts.get(burst_tid, 0),
            }
            self.registry.counter("Serve/tenant_noisy_episodes").inc()
            g("Serve/tenant_noisy_active").set(1.0)
            if self.flight is not None:
                self.flight.note("noisy_neighbor", t=now,
                                 tenant=burst_tid,
                                 share=round(share, 4),
                                 burn=round(burn, 4))
                self.flight.dump("noisy_neighbor")
        elif not firing and self.active_episode is not None:
            ep = dict(self.active_episode)
            ep["t1"] = now
            ep["duration_s"] = now - ep["t0"]
            self.last_episode = ep
            self.active_episode = None
            self._last_end = now
            g("Serve/tenant_noisy_active").set(0.0)

    # ------------------------------------------------------------- readout
    def report(self, tiers: Optional[dict] = None) -> dict:
        """The full per-tenant breakdown: one row per tenant, totals
        that are sums of the rows (conservation by construction — the
        tests pin them against the fleet's own counters), the fairness
        block, and the noisy-neighbor state. ``tiers`` maps tier kind →
        TierStore so resident bytes split by owner."""
        self._flush_integrals()
        tier_rows: dict = {}
        for kind, store in (tiers or {}).items():
            ob = dict(getattr(store, "owner_bytes", None) or {})
            used = getattr(store, "bytes_used", 0)
            unowned = used - sum(ob.values())
            if unowned > 0:
                ob[UNOWNED] = unowned
            tier_rows[kind] = ob
        rows = {}
        for tid, c in self.tenants.items():
            rows[tid] = {
                "submitted": c.submitted, "admitted": c.admitted,
                "retired_ok": c.retired_ok,
                "completed_tokens": c.completed_tokens,
                "prompt_tokens": c.prompt_tokens,
                "shared_prefix_tokens": c.shared_prefix_tokens,
                "prefix_overlap": (
                    c.shared_prefix_tokens / c.prompt_tokens
                    if c.prompt_tokens else None),
                "sheds": c.sheds, "timeouts": c.timeouts,
                "cancelled": c.cancelled, "nonfinite": c.nonfinite,
                "requeues": c.requeues,
                "pages_held": c.pages_held,
                "page_seconds": c.page_seconds,
                "tier_bytes": {k: v.get(tid, 0)
                               for k, v in tier_rows.items()},
                "queue_wait_p50_s": _pct(c.queue_wait, 0.50),
                "queue_wait_p95_s": _pct(c.queue_wait, 0.95),
                "ttft_p50_s": _pct(c.ttft, 0.50),
                "ttft_p95_s": _pct(c.ttft, 0.95),
                "tpot_p50_s": _pct(c.tpot, 0.50),
                "tpot_p95_s": _pct(c.tpot, 0.95),
            }
        tok_total = sum(r["completed_tokens"] for r in rows.values())
        for tid, r in rows.items():
            r["goodput_share"] = (
                r["completed_tokens"] / tok_total if tok_total else None)
        totals = {
            "submitted": sum(r["submitted"] for r in rows.values()),
            "admitted": sum(r["admitted"] for r in rows.values()),
            "completed_tokens": tok_total,
            "prompt_tokens": sum(r["prompt_tokens"]
                                 for r in rows.values()),
            "sheds": sum(r["sheds"] for r in rows.values()),
            "requeues": sum(r["requeues"] for r in rows.values()),
            "page_seconds": sum(r["page_seconds"]
                                for r in rows.values()),
            "pool_page_seconds": self.pool_page_seconds,
        }
        fair = self.fairness(tiers=tiers)
        g = self.registry.gauge
        if fair["jain"] is not None:
            g("Serve/tenant_fairness_jain").set(fair["jain"])
        for tid, share in fair["dominant_shares"].items():
            g(labeled_name("Serve/tenant_dominant_share",
                           tenant=tid)).set(share)
        for tid, r in rows.items():
            g(labeled_name("Serve/tenant_page_seconds",
                           tenant=tid)).set(r["page_seconds"])
            for kind, b in r["tier_bytes"].items():
                g(labeled_name(f"Serve/tenant_{kind}_bytes",
                               tenant=tid)).set(float(b))
        self._publish_shares()
        return {
            "schema": "dstpu.tenantscope.v1",
            "tenants": rows,
            "totals": totals,
            "fairness": fair,
            "noisy": {
                "episodes": self.episodes,
                "active": self.active_episode,
                "last": self.last_episode,
            },
        }

    def snapshot(self) -> dict:
        return self.report()

    def breakdown_text(self) -> str:
        """Flight artifact provider (``tenant_breakdown.json``): every
        flight/incident dump carries the current per-tenant breakdown."""
        return json.dumps(self.report(), indent=1, default=str)
