"""Fleet scrape aggregator: N live engines → one labeled exposition.

The future SLO-aware router (ROADMAP "multi-replica serving fleet")
needs exactly one signal surface: per-engine readiness, goodput, and
SLO burn, merged and labeled so a dead replica is a *data point*
(``dstpu_scrape_up{engine="..."} 0``), never an exception. This module
is that surface, built on the per-engine telemetry servers
(``server.py``):

- :class:`FleetScraper` polls each target's ``/metrics`` (and
  ``/healthz`` for the ready bit), relabels every sample with an
  ``engine`` label, and rolls up fleet aggregates:

  - ``dstpu_scrape_up{engine=...}``     1/0 per target;
  - ``dstpu_scrape_latency_s{engine=}`` scrape round-trip;
  - ``dstpu_fleet_engines`` / ``dstpu_fleet_up`` / ``dstpu_fleet_ready``;
  - ``dstpu_fleet_goodput_frac`` — wall-weighted mean of per-engine
    goodput fractions (an engine that has lived 10× longer carries 10×
    the weight — a freshly restarted replica must not mask fleet-wide
    badput);
  - ``dstpu_fleet_slo_burn_max`` — the worst burning SLO anywhere (the
    router's shed signal).

- ``python -m deepspeed_tpu.observability.fleet_scrape --targets ...``
  renders the merged exposition to stdout or ``--out <file>.prom``
  (atomic rename — a concurrent textfile-collector scrape never reads a
  torn file).

Degradation contract: a dead/slow/garbled target contributes
``scrape_up 0`` and drops out of the rollups; the aggregator itself
never raises on target failure. ``fetch`` is injectable (tests fake the
fleet without sockets), as is ``clock``.
"""

from __future__ import annotations

import os
import re
import time
from pathlib import Path
from typing import Callable, Optional
from urllib.request import urlopen

from .expfmt import (format_prometheus_value, labeled_name,
                     parse_prometheus_textfile)

_SLO_BURN = re.compile(r"_slo_.*_burn$")
_LABEL_SAFE = re.compile(r"[^a-zA-Z0-9_.-]")


def _default_fetch(url: str, timeout: float) -> str:
    with urlopen(url, timeout=timeout) as r:   # nosec: operator-supplied
        return r.read().decode("utf-8", errors="replace")


def engine_label(target: str) -> str:
    """Default ``engine`` label for a target URL: ``host:port`` with
    exposition-hostile characters squashed."""
    t = target.rstrip("/")
    for prefix in ("http://", "https://"):
        if t.startswith(prefix):
            t = t[len(prefix):]
    return _LABEL_SAFE.sub("_", t) or "engine"


class FleetScraper:
    """Poll N engine telemetry endpoints; merge + relabel + roll up.

    ``targets`` are base URLs (``http://host:port``); ``labels`` (same
    length, optional) overrides the derived ``engine`` label per
    target. One :meth:`scrape` is one fleet pass — the result dict
    feeds :meth:`render` (exposition text) and the router-to-be."""

    def __init__(self, targets: list[str],
                 labels: Optional[list[str]] = None,
                 fetch: Optional[Callable[[str, float], str]] = None,
                 timeout: float = 2.0,
                 clock: Callable[[], float] = time.perf_counter):
        if not targets:
            raise ValueError("FleetScraper needs at least one target")
        if labels is not None and len(labels) != len(targets):
            raise ValueError(f"{len(labels)} labels for "
                             f"{len(targets)} targets")
        self.targets = [t.rstrip("/") for t in targets]
        # explicit labels go through the same sanitizer as derived ones:
        # a quote or backslash inside {engine="..."} would invalidate
        # the whole merged exposition (one bad label must not blackhole
        # the fleet's metrics); empty entries fall back like empty URLs
        self.labels = ([_LABEL_SAFE.sub("_", str(lb)) or "engine"
                        for lb in labels] if labels is not None
                       else [engine_label(t) for t in self.targets])
        if len(set(self.labels)) != len(self.labels):
            raise ValueError(f"duplicate engine labels: {self.labels} — "
                             "pass explicit distinct labels")
        self.fetch = fetch if fetch is not None else _default_fetch
        self.timeout = float(timeout)
        self.clock = clock
        self.scrapes = 0

    # ------------------------------------------------------------ one pass
    def scrape_target(self, target: str, label: str) -> dict:
        """One target: ``/metrics`` + the ``/healthz`` ready bit. Any
        failure — refused connection, timeout, garbage body — degrades
        to ``up: False``; the exception never propagates."""
        t0 = self.clock()
        out: dict = {"target": target, "engine": label, "up": False,
                     "latency_s": 0.0, "metrics": {}, "ready": None,
                     "error": None}
        try:
            text = self.fetch(target + "/metrics", self.timeout)
            out["metrics"] = parse_prometheus_textfile(text)
            out["up"] = True
        except Exception as e:   # degrade-per-target is the contract:
            out["error"] = repr(e)   # a dead engine is a data point
        out["latency_s"] = self.clock() - t0
        if out["up"]:
            try:
                import json as _json

                health = _json.loads(
                    self.fetch(target + "/healthz", self.timeout))
                out["ready"] = bool(health.get("ready", False))
            except Exception:
                # metrics answered but healthz didn't: fall back to the
                # mirrored gauge (health() exports Serve/ready)
                ready = out["metrics"].get("dstpu_serve_ready")
                out["ready"] = bool(ready) if ready is not None else None
        return out

    def scrape(self) -> dict:
        """One fleet pass over every target + the rollups. Targets are
        polled CONCURRENTLY (one thread each, results in target order):
        k dead pods timing out must cost one timeout, not k — a
        sequential pass goes stale exactly when replicas are dying,
        which is when the router needs the signal most."""
        if len(self.targets) == 1:
            engines = [self.scrape_target(self.targets[0], self.labels[0])]
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=min(16, len(self.targets)),
                    thread_name_prefix="dstpu-fleet") as pool:
                engines = list(pool.map(self.scrape_target, self.targets,
                                        self.labels))
        self.scrapes += 1
        up = [e for e in engines if e["up"]]
        # wall-weighted goodput: weight each engine's fraction by its
        # ledger wall time (any *_goodput_wall_s / *_goodput_frac pair,
        # serving or training) — the SAME weighting the in-process
        # FleetEngine rollup uses (goodput.weighted_goodput_frac), so
        # the scraped and in-process fleet numbers cannot drift
        from .goodput import weighted_goodput_frac

        pairs = []
        burn_max = None
        # loadscope rollups (arrival & scaling observatory): offered
        # load SUMS across replicas, utilization takes the bottleneck
        # MAX, and time-to-violation the nearest MIN — each None until
        # some engine exports the gauge (observatory off → absent lines)
        offered_load = util_max = ttv_min = None
        for e in up:
            frac = wall = None
            for k, v in e["metrics"].items():
                if k.endswith("_goodput_frac"):
                    frac = v
                elif k.endswith("_goodput_wall_s"):
                    wall = v
                if _SLO_BURN.search(k):
                    burn_max = v if burn_max is None else max(burn_max, v)
                if k.endswith("_serve_offered_tokens_per_s"):
                    offered_load = v if offered_load is None \
                        else offered_load + v
                elif k.endswith("_serve_utilization"):
                    util_max = v if util_max is None else max(util_max, v)
                elif k.endswith("_serve_slo_ttv_s"):
                    ttv_min = v if ttv_min is None else min(ttv_min, v)
            pairs.append((frac, wall))
        return {
            "engines": engines,
            "fleet": {
                "engines": len(engines),
                "up": len(up),
                "ready": sum(1 for e in up if e["ready"]),
                "goodput_frac": weighted_goodput_frac(pairs),
                "slo_burn_max": burn_max,
                "offered_load": offered_load,
                "utilization_max": util_max,
                "slo_ttv_min_s": ttv_min,
            },
        }

    # -------------------------------------------------------------- render
    def render(self, snap: Optional[dict] = None) -> str:
        """Merged exposition: per-engine samples relabeled with
        ``engine``, then the fleet rollups — the file/endpoint a single
        Prometheus job scrapes instead of N."""
        snap = snap if snap is not None else self.scrape()
        lines = ["# deepspeed_tpu fleet scrape "
                 f"({snap['fleet']['up']}/{snap['fleet']['engines']} up)"]
        for e in snap["engines"]:
            lab = f'{{engine="{e["engine"]}"}}'
            lines.append(f"dstpu_scrape_up{lab} {1 if e['up'] else 0}")
            lines.append(f"dstpu_scrape_latency_s{lab} "
                         f"{format_prometheus_value(e['latency_s'])}")
            for name, value in sorted(e["metrics"].items()):
                if "{" in name:
                    # already-labeled sample (tenant-labeled series, or
                    # an engine proxying a fleet file): COMPOSE — merge
                    # the engine label into the existing set instead of
                    # nesting/clobbering. An engine="..." label already
                    # present wins (proxied fleet files keep their own
                    # attribution).
                    merged = labeled_name(name, engine=e["engine"]) \
                        if 'engine="' not in name else name
                    lines.append(f"{merged} "
                                 f"{format_prometheus_value(value)}")
                    continue
                lines.append(f"{name}{lab} "
                             f"{format_prometheus_value(value)}")
        fl = snap["fleet"]
        lines.append(f"dstpu_fleet_engines {fl['engines']}")
        lines.append(f"dstpu_fleet_up {fl['up']}")
        lines.append(f"dstpu_fleet_ready {fl['ready']}")
        if fl["goodput_frac"] is not None:
            lines.append("dstpu_fleet_goodput_frac "
                         f"{format_prometheus_value(fl['goodput_frac'])}")
        if fl["slo_burn_max"] is not None:
            lines.append("dstpu_fleet_slo_burn_max "
                         f"{format_prometheus_value(fl['slo_burn_max'])}")
        if fl.get("offered_load") is not None:
            lines.append("dstpu_fleet_offered_load "
                         f"{format_prometheus_value(fl['offered_load'])}")
        if fl.get("utilization_max") is not None:
            lines.append("dstpu_fleet_utilization_max "
                         f"{format_prometheus_value(fl['utilization_max'])}")
        if fl.get("slo_ttv_min_s") is not None:
            lines.append("dstpu_fleet_slo_ttv_min_s "
                         f"{format_prometheus_value(fl['slo_ttv_min_s'])}")
        return "\n".join(lines) + "\n"

    def write(self, path, snap: Optional[dict] = None) -> Path:
        """Render to ``path`` atomically (tmp + rename, the textfile
        sink's torn-scrape discipline)."""
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_name(p.name + ".tmp")
        tmp.write_text(self.render(snap), encoding="utf-8")
        os.replace(tmp, p)
        return p


def main(argv=None) -> int:
    """CLI: one scrape pass (or a loop) over ``--targets``. Stdout is
    this module's interface when ``--out`` is absent (exempt from the
    bare-print lint like the doctor)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.observability.fleet_scrape",
        description="Scrape N engine telemetry endpoints, merge their "
                    "expositions with an engine label, roll up fleet "
                    "goodput/readiness/SLO burn.")
    ap.add_argument("--targets", required=True,
                    help="comma-separated base URLs "
                         "(http://host:port,...)")
    ap.add_argument("--labels", default=None,
                    help="comma-separated engine labels (default: "
                         "derived host_port)")
    ap.add_argument("--out", default=None,
                    help="write the merged exposition to this .prom "
                         "file (atomic) instead of stdout")
    ap.add_argument("--interval", type=float, default=0.0,
                    help="loop every N seconds (default: one pass)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-target fetch timeout (default 2s)")
    args = ap.parse_args(argv)
    scraper = FleetScraper(
        [t for t in args.targets.split(",") if t],
        labels=([x for x in args.labels.split(",")]
                if args.labels else None),
        timeout=args.timeout)
    while True:
        snap = scraper.scrape()
        if args.out:
            scraper.write(args.out, snap)
        else:
            print(scraper.render(snap), end="")
        if args.interval <= 0:
            return 0 if snap["fleet"]["up"] == snap["fleet"]["engines"] \
                else 1
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
