"""Inference request tracing: per-``generate()`` structured records.

Every traced request produces a :class:`RequestRecord` — prefill wall time
(TTFT), steady per-token decode latency (TPOT), tokens/s, and the roofline
attribution numbers (achieved weight-GB/s and MBU against the chip's peak
HBM bandwidth, reusing the per-step HBM-bytes model the PR-1 WOQ work
introduced in ``inference/quantization.py:decode_weight_bytes``). Records
land in a bounded ring buffer and feed ``Serve/*`` histograms in a
:class:`~.metrics.MetricsRegistry`, so ``InferenceEngine.metrics_snapshot()``
can answer "what is my p99 TTFT right now" without any bench script.

Timing honesty: the engine only gets split prefill/decode timings when
tracing is ON (it compiles the generation in two programs and pays exactly
one extra host sync per request, between prefill and decode — never one per
token). Cold calls (first compile of a shape) are recorded and flagged but
kept OUT of the latency reservoirs, so one retrace can't blow up p99.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Callable, Optional

from .metrics import MetricsRegistry


@dataclasses.dataclass
class RequestRecord:
    """One generate() call, fully attributed."""

    request_id: int
    batch: int
    prompt_len: int
    new_tokens: int
    prefill_s: float                    # TTFT: prompt in → first token out
    decode_s: float                     # remaining new_tokens - 1 steps
    cold: bool                          # this shape compiled during the call
    tpot_s: Optional[float] = None      # per-token decode latency
    tokens_per_sec: Optional[float] = None
    achieved_gbps: Optional[float] = None
    weight_bytes_per_step: Optional[int] = None
    mbu: Optional[float] = None         # achieved / peak HBM bandwidth

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class RequestTracer:
    """Ring buffer + rolling latency accounting for served requests.

    ``bytes_per_step`` is the decode weight-read model (quantized leaves
    count their int8/int4 bytes); ``peak_bw`` the per-chip HBM roofline.
    Either may be None (unknown hardware): the trace still records
    latencies, only the MBU attribution is omitted.

    ``clock`` is injectable for tests (fake-clock TTFT/TPOT accounting).
    """

    def __init__(self, ring_size: int = 256,
                 registry: Optional[MetricsRegistry] = None,
                 bytes_per_step: Optional[int] = None,
                 peak_bw: Optional[float] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.bytes_per_step = bytes_per_step
        self.peak_bw = peak_bw
        self.clock = clock
        self._ring: deque[RequestRecord] = deque(maxlen=int(ring_size))
        self._lock = threading.Lock()
        self._next_id = 0

    # ------------------------------------------------------------- recording
    def observe(self, *, batch: int, prompt_len: int, new_tokens: int,
                prefill_s: float, decode_s: float,
                cold: bool = False) -> RequestRecord:
        """Account one request from its measured phase times."""
        decode_steps = max(0, new_tokens - 1)
        tpot = (decode_s / decode_steps) if decode_steps else None
        total = prefill_s + decode_s
        tps = (batch * new_tokens / total) if total > 0 else None
        gbps = mbu = None
        if tpot and self.bytes_per_step:
            # decode streams the weights once per step regardless of batch
            gbps = self.bytes_per_step / tpot / 1e9
            if self.peak_bw:
                mbu = self.bytes_per_step / tpot / self.peak_bw
        with self._lock:
            rec = RequestRecord(
                request_id=self._next_id, batch=batch, prompt_len=prompt_len,
                new_tokens=new_tokens, prefill_s=prefill_s, decode_s=decode_s,
                cold=cold, tpot_s=tpot, tokens_per_sec=tps,
                achieved_gbps=gbps, weight_bytes_per_step=self.bytes_per_step,
                mbu=mbu)
            self._next_id += 1
            self._ring.append(rec)
        r = self.registry
        r.counter("Serve/requests").inc()
        r.counter("Serve/tokens_generated").inc(batch * new_tokens)
        if cold:
            # compile time must not pollute the latency percentiles, but a
            # retrace storm is itself worth seeing
            r.counter("Serve/cold_starts").inc()
            return rec
        r.histogram("Serve/ttft_s").observe(prefill_s)
        if tpot is not None:
            r.histogram("Serve/tpot_s").observe(tpot)
        if tps is not None:
            r.gauge("Serve/tokens_per_sec").set(tps)
        if gbps is not None:
            r.gauge("Serve/achieved_gbps").set(gbps)
        if mbu is not None:
            r.gauge("Serve/decode_mbu").set(mbu)
        return rec

    # --------------------------------------------------------------- readout
    def records(self) -> list[RequestRecord]:
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> dict:
        """Aggregate view: warm-request latency percentiles + roofline."""
        snap = self.registry.snapshot()
        hist = snap["histograms"]
        gauges = snap["gauges"]
        counters = snap["counters"]
        recent = [r.as_dict() for r in self.records()[-8:]]
        out = {
            "requests": int(counters.get("Serve/requests", 0)),
            "cold_starts": int(counters.get("Serve/cold_starts", 0)),
            "tokens_generated": int(counters.get("Serve/tokens_generated", 0)),
            "ttft_s": hist.get("Serve/ttft_s", {}),
            "tpot_s": hist.get("Serve/tpot_s", {}),
            "tokens_per_sec": gauges.get("Serve/tokens_per_sec", math.nan),
            "achieved_gbps": gauges.get("Serve/achieved_gbps"),
            "decode_mbu": gauges.get("Serve/decode_mbu"),
            "weight_bytes_per_step": self.bytes_per_step,
            "peak_hbm_bw": self.peak_bw,
            "recent": recent,
        }
        return out


class ServingStats:
    """Scheduler-side serving accounting: the load picture the per-request
    :class:`RequestTracer` can't see.

    Where the tracer attributes ONE request's latency (TTFT/TPOT of a lone
    ``generate()``), this records the continuous-batching picture: queue
    depth, slot occupancy, admission/retirement counters, per-request TTFT
    and TPOT *under load* (a request's first token waits behind whatever
    the scheduler interleaved before it), and aggregate goodput — completed
    tokens per second across all requests, the number static batching
    leaves on the table. Everything lands in ``Serve/*`` names of a
    :class:`~.metrics.MetricsRegistry`, so the same MonitorMaster sinks
    (JSONL / Prometheus / CSV / TensorBoard) that carry ``Train/*`` carry
    these.

    ``clock`` is injectable (fake-clock scheduler tests drive admission /
    retirement order without a device).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.clock = clock
        self._t0: Optional[float] = None     # first admission: goodput window
        self.completed_tokens = 0
        # time-weighted occupancy: (last sample time, fraction held since)
        # — Serve/slot_occupancy is point-in-time; the AVG is what
        # capacity math needs (a slot 90% full between samples and 10%
        # full at them must not read as 10%)
        self._occ_prev: Optional[tuple] = None
        self._occ_time = 0.0
        self._occ_weighted = 0.0
        self._last_submit_t: Optional[float] = None

    def reset(self) -> None:
        """Clear every Serve/* series and restart the goodput window —
        benches call this between the warmup pass (compile-laden TTFT/TPOT
        samples) and the measured pass."""
        self.registry.reset()
        self._t0 = None
        self.completed_tokens = 0
        self._occ_prev = None
        self._occ_time = 0.0
        self._occ_weighted = 0.0
        self._last_submit_t = None

    # ---------------------------------------------------- request lifecycle
    def on_submit(self, queue_depth: int) -> float:
        t = self.clock()
        r = self.registry
        r.counter("Serve/submitted").inc()
        # sampled at SUBMIT time (not only on admission): a flooded queue
        # between admissions must not read a stale depth on scrape
        r.gauge("Serve/queue_depth").set(queue_depth)
        if self._last_submit_t is not None:
            # the arrival-process histogram loadscope's CV estimator
            # summarizes — kept here so the raw distribution survives
            # in every sink even with the observatory off
            r.histogram("Serve/interarrival_s").observe(
                t - self._last_submit_t)
        self._last_submit_t = t
        return t

    def on_admit(self, queue_depth: int,
                 submit_t: Optional[float] = None) -> float:
        t = self.clock()
        if self._t0 is None:
            self._t0 = t
        r = self.registry
        r.counter("Serve/admitted").inc()
        r.gauge("Serve/queue_depth").set(queue_depth)
        if submit_t is not None:
            # admission wait: how long the request sat in the queue before
            # the scheduler picked it (previously only recoverable by
            # hand-subtracting TTFT components)
            r.histogram("Serve/queue_wait_s").observe(t - submit_t)
        return t

    def on_first_token(self, submit_t: float) -> float:
        t = self.clock()
        self.registry.histogram("Serve/ttft_s").observe(t - submit_t)
        return t

    def on_retire(self, n_tokens: int, first_token_t: float) -> float:
        """A request finished with ``n_tokens`` generated."""
        t = self.clock()
        r = self.registry
        r.counter("Serve/retired").inc()
        r.counter("Serve/completed_tokens").inc(n_tokens)
        self.completed_tokens += n_tokens
        if n_tokens > 1:
            r.histogram("Serve/tpot_s").observe(
                (t - first_token_t) / (n_tokens - 1))
        if self._t0 is not None and t > self._t0:
            r.gauge("Serve/goodput_tps").set(
                self.completed_tokens / (t - self._t0))
        return t

    # ------------------------------------------------------ guard outcomes
    def on_shed(self, queue_depth: int) -> None:
        """A submit was rejected (queue full / draining) — the SHED path."""
        self.registry.counter("Serve/shed").inc()
        self.registry.gauge("Serve/queue_depth").set(queue_depth)

    def on_abort(self, status) -> float:
        """A request terminated with a non-OK :class:`RequestStatus`
        (TIMEOUT / CANCELLED / NONFINITE): per-status counter, no goodput
        credit (aborted tokens are not completed work)."""
        t = self.clock()
        name = getattr(status, "value", str(status))
        self.registry.counter(f"Serve/{name}").inc()
        self.registry.counter("Serve/aborted").inc()
        return t

    def on_requeue(self, queue_depth: int) -> None:
        """A fleet failover re-queued a request onto this replica after
        its original replica was lost (status ``REQUEUED``, attempts
        bumped) — counted here so the SURVIVOR's load picture shows the
        inherited work."""
        r = self.registry
        r.counter("Serve/requeued").inc()
        r.gauge("Serve/queue_depth").set(queue_depth)

    def on_requeue_delay(self, delay_s: float) -> None:
        """A REQUEUED request was re-admitted: ``delay_s`` is kill →
        re-admission on the injectable clock. Its own histogram keeps
        failover cost separable from TTFT in the request log (a requeued
        request's TTFT legitimately includes this delay — without the
        split, a failover burst reads as a latency regression)."""
        self.registry.histogram("Serve/requeue_delay_s").observe(delay_s)

    def on_watchdog_stall(self, step_s: float, threshold_s: float) -> None:
        """One decode step exceeded the watchdog budget."""
        r = self.registry
        r.counter("Serve/watchdog_stalls").inc()
        r.gauge("Serve/last_stall_s").set(step_s)
        r.gauge("Serve/watchdog_s").set(threshold_s)

    def on_results_evicted(self) -> None:
        """The bounded results store dropped its oldest finished request
        (nobody collected it)."""
        self.registry.counter("Serve/results_evicted").inc()

    # ------------------------------------------------------- per-iteration
    def on_iteration(self, queue_depth: int, occupied: int, slots: int,
                     prefill_chunk: bool, decode_ran: bool = False) -> None:
        r = self.registry
        r.counter("Serve/iterations").inc()
        if prefill_chunk:
            r.counter("Serve/prefill_chunks").inc()
        if decode_ran:
            # decode_steps x slots is the slot-step work the batch paid —
            # against sum(max_new) it gives the occupancy-efficiency the
            # bench compares to static batching's dead tail
            r.counter("Serve/decode_steps").inc()
        r.gauge("Serve/queue_depth").set(queue_depth)
        frac = occupied / max(1, slots)
        r.gauge("Serve/slot_occupancy").set(frac)
        # time-weighted average on the injectable clock: the PREVIOUS
        # sample's fraction held over the interval that just elapsed
        # (left-continuous integral); published via publish_metrics with
        # everything else
        t = self.clock()
        if self._occ_prev is not None:
            t0, f0 = self._occ_prev
            dt = t - t0
            if dt > 0:
                self._occ_time += dt
                self._occ_weighted += f0 * dt
                r.gauge("Serve/slot_occupancy_avg").set(
                    self._occ_weighted / self._occ_time)
        self._occ_prev = (t, frac)

    def snapshot(self) -> dict:
        snap = self.registry.snapshot()
        c, g, h = snap["counters"], snap["gauges"], snap["histograms"]
        return {
            "submitted": int(c.get("Serve/submitted", 0)),
            "admitted": int(c.get("Serve/admitted", 0)),
            "retired": int(c.get("Serve/retired", 0)),
            "completed_tokens": int(c.get("Serve/completed_tokens", 0)),
            "iterations": int(c.get("Serve/iterations", 0)),
            "prefill_chunks": int(c.get("Serve/prefill_chunks", 0)),
            "decode_steps": int(c.get("Serve/decode_steps", 0)),
            # guard outcomes (resilience layer): sheds, per-status aborts,
            # watchdog stalls, results-store evictions
            "shed": int(c.get("Serve/shed", 0)),
            "aborted": int(c.get("Serve/aborted", 0)),
            "timeout": int(c.get("Serve/timeout", 0)),
            "cancelled": int(c.get("Serve/cancelled", 0)),
            "nonfinite": int(c.get("Serve/nonfinite", 0)),
            "watchdog_stalls": int(c.get("Serve/watchdog_stalls", 0)),
            "results_evicted": int(c.get("Serve/results_evicted", 0)),
            "requeued": int(c.get("Serve/requeued", 0)),
            "queue_depth": g.get("Serve/queue_depth"),
            "slot_occupancy": g.get("Serve/slot_occupancy"),
            "slot_occupancy_avg": g.get("Serve/slot_occupancy_avg"),
            "goodput_tps": g.get("Serve/goodput_tps"),
            "ttft_s": h.get("Serve/ttft_s", {}),
            "tpot_s": h.get("Serve/tpot_s", {}),
            "queue_wait_s": h.get("Serve/queue_wait_s", {}),
            "interarrival_s": h.get("Serve/interarrival_s", {}),
            "requeue_delay_s": h.get("Serve/requeue_delay_s", {}),
        }
