"""Request-lifecycle span events: the time-attribution layer.

The PR-2 metrics answer *aggregate* questions ("what is p99 TTFT"); a
span ring answers *attribution* questions ("which request blew its TTFT
SLO and where did the time go — queue, chunked prefill, or decode
co-tenancy"), the same transparent-tracking need T3 motivates for
compute/collective overlap. Every lifecycle edge the serving scheduler
and training engine already stamp (``submit_t`` / ``first_token_t`` /
retirement, the wall-clock-breakdown timers) becomes a typed
:class:`SpanEvent` in a bounded, thread-safe ring buffer.

Cost discipline: recording is host-side floats into a deque under a
lock — no device buffers, no host↔device syncs, no new compiled
programs. Engines hold ``spans = None`` when disabled, so the hot path
pays one ``is not None`` and the ``bench_serving.py --smoke``
compile-freeze gate stays green. Timestamps come from the owner's
injectable clock (the same one ``ServingStats`` fakes in tests).

The ring is the substrate for two consumers: the Chrome-trace/Perfetto
export (``export.py``) and the crash/stall flight recorder
(``flight.py``), which snapshots the last-N events into a post-mortem
artifact.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Optional

# ------------------------------------------------------------- event kinds
# Serving request lifecycle (rid-carrying):
QUEUED = "queued"                  # span: submit → admission (queue wait)
PREFILL_CHUNK = "prefill_chunk"    # span: one bucket-shaped chunk dispatch
PLACED = "placed"                  # instant: request occupied a slot
DECODE_RESIDENCY = "decode"        # span: first token → retirement, in slot
RETIRED = "retired"                # instant: terminal status lands
# Serving engine cadence (no rid):
DECODE_STEP = "decode_step"        # span: one slot decode step (all slots)
OCCUPANCY = "occupancy"            # counter: slots occupied / queue depth
# Training engine cadence:
TRAIN_STEP = "train_step"          # span: one train_batch() call
TRAIN_PHASE = "train_phase"        # span: a wall-clock-breakdown timer
                                   # interval (batch_prep/step_dispatch/
                                   # step_sync, fwd/bwd/host_step offload)
# Fleet request hops (serving/fleet.py — recorded in the FLEET-level
# ring, rid-carrying; the cross-replica half of a distributed trace):
ROUTE = "route"                    # instant: router picked an admission
                                   # target (meta: replica)
REQUEUE = "requeue"                # instant: failover moved the request
                                   # onto a survivor (meta: replica,
                                   # attempt)
HANDOFF_EXPORT = "handoff_export"  # span: prefill pages gathered to host
HANDOFF_PENDING = "handoff_pending"  # span: payload host-held, waiting
                                   # for a decode slot/pool
HANDOFF_IMPORT = "handoff_import"  # span: scatter into the decode replica
# KV residency observatory (observability/kvscope.py — rendered as
# per-session residency tracks in the Perfetto export; meta carries
# ``session``):
SESSION_ACTIVE = "session_active"  # span: first admit/resume → last retire
SESSION_IDLE = "session_idle"      # span: idle gap closed by a resume
                                   # (meta: regret_tokens the resume
                                   # re-paid — 0 when the prefix survived)
# Communication observatory (observability/commscope.py — rendered as a
# `comm` track beside the train pid in the Perfetto export):
COMM_OP = "comm_op"                # span: one collective op in flight
                                   # (meta: kind, op, device)
COMM_EXPOSED = "comm_exposed"      # span: an exposed gap — collective
                                   # time NOT hidden behind compute
# Cross-cutting:
MARKER = "marker"                  # instant: SLO burn, anomaly, watchdog,
                                   # compile storm — the "why" of a dump

_COUNTER_KINDS = frozenset({OCCUPANCY})
_INSTANT_KINDS = frozenset({PLACED, RETIRED, MARKER, ROUTE, REQUEUE})


@dataclasses.dataclass
class SpanEvent:
    """One typed lifecycle event. ``t1 is None`` marks an instant event;
    counters carry their samples in ``meta``."""

    kind: str
    t0: float
    t1: Optional[float] = None
    rid: Optional[int] = None
    slot: Optional[int] = None
    step: Optional[int] = None
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> float:
        return 0.0 if self.t1 is None else self.t1 - self.t0

    @property
    def instant(self) -> bool:
        return self.t1 is None

    def as_dict(self) -> dict:
        out = {"kind": self.kind, "t0": self.t0}
        if self.t1 is not None:
            out["t1"] = self.t1
        for k in ("rid", "slot", "step"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.meta:
            out["meta"] = self.meta
        return out


class SpanRecorder:
    """Bounded thread-safe ring of :class:`SpanEvent`.

    ``capacity`` bounds host memory for the life of the process (a busy
    replica emits a handful of events per iteration; 4096 covers minutes
    of context around a fault, which is what a post-mortem needs — the
    JSONL sinks carry the unbounded history). ``clock`` is only used by
    the convenience emitters that stamp "now" themselves; callers that
    already hold timestamps (the scheduler's ``submit_t``, the decode
    window's ``t0``) pass them explicitly so spans and metrics agree to
    the exact float."""

    def __init__(self, capacity: int = 4096,
                 clock: Callable[[], float] = time.perf_counter):
        if capacity <= 0:
            raise ValueError(f"span ring capacity must be > 0, "
                             f"got {capacity}")
        self.capacity = int(capacity)
        self.clock = clock
        self._ring: deque[SpanEvent] = deque(maxlen=self.capacity)
        # RLock, not Lock: the PreemptionGuard SIGTERM handler notes a
        # marker from the MAIN thread — which may be interrupted inside
        # emit() holding this very lock; a non-reentrant lock would
        # deadlock the handler through the whole grace window
        self._lock = threading.RLock()
        self._emitted = 0

    # ------------------------------------------------------------ recording
    def emit(self, kind: str, t0: float, t1: Optional[float] = None, *,
             rid: Optional[int] = None, slot: Optional[int] = None,
             step: Optional[int] = None, **meta) -> SpanEvent:
        ev = SpanEvent(kind=kind, t0=float(t0),
                       t1=None if t1 is None else float(t1),
                       rid=rid, slot=slot, step=step, meta=meta)
        with self._lock:
            self._ring.append(ev)
            self._emitted += 1
        return ev

    def marker(self, name: str, t: Optional[float] = None,
               **meta) -> SpanEvent:
        """Instant MARKER event ("why" annotations: SLO burn, anomaly,
        watchdog stall, compile storm)."""
        return self.emit(MARKER, self.clock() if t is None else t,
                         name=name, **meta)

    def counter(self, t: Optional[float] = None, **samples) -> SpanEvent:
        """OCCUPANCY counter sample (queue depth, slots occupied, ...)."""
        return self.emit(OCCUPANCY, self.clock() if t is None else t,
                         **samples)

    # -------------------------------------------------------------- readout
    def events(self) -> list[SpanEvent]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def emitted(self) -> int:
        """Total events ever emitted (ring evictions included)."""
        with self._lock:
            return self._emitted

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
