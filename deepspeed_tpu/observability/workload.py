"""Serving traffic analytics: what the *workload* would pay for.

The roadmap's next perf levers — paged KV with prefix sharing, n-gram
self-speculative decoding, quantized KV — are each justified only on
traffic with particular structure (shared prompt prefixes, repetitive
text, long contexts). This module measures that structure on the live
admission stream, so every what-if in the capacity advisor
(``capacity.py``) is computed on *observed* traffic rather than assumed:

- **prefix-overlap estimator** — a rolling-hash sketch over admitted
  prompt tokens: prefixes are hashed at ``block``-token boundaries into a
  bounded LRU of recently seen prefixes; an admitted prompt's longest
  matching boundary estimates the tokens a radix-style prefix cache would
  NOT have to prefill again. Reported as the shared-prefix token fraction
  (``Serve/workload_prefix_overlap``) and the cumulative dedupable-token
  count — the prefill work prefix sharing saves at the current overlap.
  The estimate is additionally SPLIT by attribution: same-session resume
  overlap (``Serve/workload_resume_overlap`` — the share a host KV tier
  could restore from demoted session pages; the input the ``tiered_kv``
  capacity lever sizes on) vs cross-request overlap
  (``Serve/workload_cross_overlap`` — shared system prompts that stay
  HBM-hot regardless).
- **self-speculation estimator** — an n-gram / prompt-lookup scan over
  each prompt: the fraction of positions where the preceding ``ngram``
  tokens have occurred before *and* correctly predict the next token is
  the acceptance rate a draft-free prompt-lookup speculator would get on
  this text (``Serve/workload_selfspec_accept``).
- **shape histograms** — prompt and decode length distributions
  (``Serve/workload_prompt_len`` / ``Serve/workload_decode_len``), the
  inputs every KV-budget what-if needs.

Cost discipline: everything here is host-side Python/numpy over prompt
arrays the scheduler already holds — O(tokens) per request, zero device
syncs, zero new compiled programs (the ``bench_serving.py --smoke``
compile-freeze gate stays the acceptance test). Disabled (the default)
the serving engine holds ``workload = None`` and pays one ``is not
None`` per admission. The analyzer's own overhead is measured into
``Serve/workload_analysis_s`` so the capacity report carries the cost of
its measurement.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from .metrics import MetricsRegistry

# Polynomial rolling hash over token ids, mod a Mersenne prime: cheap,
# incremental per block, and collision-safe enough for an estimator with
# a ±5-point acceptance band (a collision can only OVERSTATE overlap,
# and at 2^61 space it is vanishingly rare at any realistic table size).
_HASH_P = 1_000_003
_HASH_M = (1 << 61) - 1


@dataclasses.dataclass
class WorkloadConfig:
    """Traffic-analytics knobs (``ServingConfig.workload``). Constructing
    one (or passing a dict) opts in; ``None`` on the serving config means
    no analyzer is built at all."""

    enabled: bool = True
    # Prefix hashes are taken at multiples of this many tokens: the
    # granularity of the overlap estimate AND the page size a paged-KV
    # prefix cache would share at (align them to make the estimate the
    # cache's actual hit rate).
    block: int = 16
    # Bounded LRU of distinct prefix hashes kept (each entry is one dict
    # slot — a few MB at the default). Evicting old prefixes makes the
    # estimate "overlap against *recent* traffic", which is what a
    # finite-size prefix cache would experience.
    max_prefixes: int = 65536
    # Context length for the prompt-lookup / self-speculation scan.
    ngram: int = 3
    # Bounded LRU of per-session prefix sets: the resume-vs-cross
    # overlap split (sessions beyond the cap fall back to cross-only).
    max_sessions: int = 4096

    def __post_init__(self):
        if self.block < 1:
            raise ValueError(f"workload block must be >= 1, got {self.block}")
        if self.max_prefixes < 1:
            raise ValueError(f"workload max_prefixes must be >= 1, "
                             f"got {self.max_prefixes}")
        if self.ngram < 1:
            raise ValueError(f"workload ngram must be >= 1, got {self.ngram}")
        if self.max_sessions < 1:
            raise ValueError(f"workload max_sessions must be >= 1, "
                             f"got {self.max_sessions}")

    @classmethod
    def from_any(cls, cfg: "WorkloadConfig | dict | None") \
            -> "WorkloadConfig | None":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown workload config keys: "
                             f"{sorted(unknown)}")
        return cls(**cfg)


def prefix_hashes(tokens: np.ndarray, block: int) -> list:
    """Rolling hash of every ``block``-aligned prefix of ``tokens``:
    ``[(length, hash), ...]`` for lengths ``block, 2*block, ...`` — one
    O(tokens) pass, each entry extending the previous hash."""
    toks = np.asarray(tokens).reshape(-1)
    out = []
    h = 0
    for i, t in enumerate(toks.tolist()):
        h = (h * _HASH_P + (int(t) + 1)) % _HASH_M
        if (i + 1) % block == 0:
            out.append((i + 1, h))
    return out


def token_hash(tokens) -> int:
    """The same polynomial rolling hash over a WHOLE token sequence —
    one shared spelling so the prefix sketch here and the ghost-tree
    ledger (``kvscope.py``) key identical prefixes identically."""
    h = 0
    for t in np.asarray(tokens).reshape(-1).tolist():
        h = (h * _HASH_P + (int(t) + 1)) % _HASH_M
    return h


def selfspec_acceptance(tokens: np.ndarray, ngram: int) -> Optional[float]:
    """Prompt-lookup acceptance potential of one token sequence: the
    fraction of scored positions whose next token is correctly predicted
    by the most recent earlier occurrence of the preceding ``ngram``
    tokens — exactly what an n-gram self-speculator drafts. None when the
    sequence is too short to score a single position.

    Runs on the SAME :class:`~..inference.speculation.NGramTable` the
    live drafter uses, so the estimate and the serving engine's achieved
    acceptance cannot drift: both are one implementation scored two ways
    (here unconditionally — a position with no table entry counts as a
    miss — because the estimator prices the whole stream)."""
    from ..inference.speculation import acceptance_stats

    stats = acceptance_stats(tokens, ngram)
    return None if stats is None else stats["rate"]


class WorkloadAnalyzer:
    """Admission-path traffic analytics into ``Serve/workload_*``.

    ``on_admit(prompt)`` runs when the scheduler picks a request for
    prefill (the admission hook in ``ServingEngine.step``);
    ``on_retire(request)`` when it terminates. All state is host-side and
    bounded; ``clock`` is injectable like every observability clock and
    is used ONLY to measure the analyzer's own overhead."""

    def __init__(self, cfg: "WorkloadConfig | dict | None" = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.cfg = WorkloadConfig.from_any(cfg) or WorkloadConfig()
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.clock = clock
        # LRU of recently seen prefix hashes: hash -> prefix length. The
        # dict is keyed by hash alone (not (len, hash)) so a longer
        # prefix with the same boundary hash refreshes recency.
        self._prefixes: OrderedDict = OrderedDict()
        # per-session boundary sets (hash -> length of that session's own
        # most recent prompt): the RESUME overlap — the share of a
        # prompt a session replays from its OWN earlier turns, which is
        # what a host KV tier can serve from demoted pages. The
        # remainder of the total overlap is CROSS-request (shared system
        # prompts), which stays hot in HBM regardless.
        self._sessions: OrderedDict = OrderedDict()
        self.prompt_tokens = 0          # all admitted prompt tokens
        self.shared_tokens = 0          # tokens covered by a seen prefix
        self.resume_tokens = 0          # covered by the SAME session
        self.requests = 0
        # live self-speculation tallies (``on_spec``): what the drafter
        # ACHIEVED, exported next to the offline estimate above so
        # predicted-vs-achieved is one snapshot read.
        self.spec_steps = 0             # verify steps scored
        self.spec_proposed = 0          # draft tokens proposed
        self.spec_accepted = 0          # draft tokens accepted
        self.spec_emitted = 0           # tokens emitted by verify steps
        self.spec_first_scored = 0      # slots with a non-empty draft
        self.spec_first_hits = 0        # ... whose FIRST draft token hit

    # ------------------------------------------------------------ admission
    def _match_and_insert(self, bounds: list) -> int:
        """Longest block-aligned prefix already in the sketch (tokens),
        then record this prompt's own boundaries."""
        shared = 0
        for length, h in bounds:
            if self._prefixes.get(h) == length:
                # each boundary hash covers the WHOLE prefix from 0, so a
                # hit at any length stands alone — no contiguity needed.
                # (The LRU evicts a prompt's shorter boundaries first;
                # breaking at the first miss would score a fully resident
                # longer prefix as 0 near capacity.) Lengths ascend, so
                # the last hit is the longest resident match.
                shared = length
                self._prefixes.move_to_end(h)
        for length, h in bounds:
            self._prefixes[h] = length
            self._prefixes.move_to_end(h)
        while len(self._prefixes) > self.cfg.max_prefixes:
            self._prefixes.popitem(last=False)
        return shared

    def _session_match(self, session_id, bounds: list) -> int:
        """Longest boundary this SESSION itself registered before, then
        replace its set with this prompt's boundaries (conversations
        replay a growing prefix — the latest prompt's set covers every
        earlier one)."""
        if session_id is None:
            return 0
        prev = self._sessions.get(session_id)
        shared = 0
        if prev is not None:
            for length, h in bounds:
                if prev.get(h) == length:
                    shared = length
        self._sessions[session_id] = {h: length for length, h in bounds}
        self._sessions.move_to_end(session_id)
        while len(self._sessions) > self.cfg.max_sessions:
            self._sessions.popitem(last=False)
        return shared

    def on_admit(self, prompt: np.ndarray, session_id=None) -> dict:
        """Score one admitted prompt; returns the per-request estimates
        (the scheduler ignores them — callers like benches may not)."""
        t0 = self.clock() if self.clock is not None else None
        prompt = np.asarray(prompt).reshape(-1)
        P = len(prompt)
        bounds = prefix_hashes(prompt, self.cfg.block)
        shared = self._match_and_insert(bounds)
        resume = min(self._session_match(session_id, bounds), shared)
        accept = selfspec_acceptance(prompt, self.cfg.ngram)
        self.requests += 1
        self.prompt_tokens += P
        self.shared_tokens += shared
        self.resume_tokens += resume
        r = self.registry
        r.counter("Serve/workload_prompt_tokens").inc(P)
        r.counter("Serve/workload_shared_prefix_tokens").inc(shared)
        r.counter("Serve/workload_resume_tokens").inc(resume)
        r.histogram("Serve/workload_prompt_len").observe(P)
        r.histogram("Serve/workload_prefix_share").observe(
            shared / P if P else 0.0)
        if self.prompt_tokens:
            r.gauge("Serve/workload_prefix_overlap").set(
                self.shared_tokens / self.prompt_tokens)
            # the split the host-tier advisor sizes on: resume overlap
            # (same-session replay — host-restorable) vs cross-request
            # overlap (shared system prompts — stays HBM-hot anyway)
            r.gauge("Serve/workload_resume_overlap").set(
                self.resume_tokens / self.prompt_tokens)
            r.gauge("Serve/workload_cross_overlap").set(
                (self.shared_tokens - self.resume_tokens)
                / self.prompt_tokens)
        if accept is not None:
            r.histogram("Serve/workload_selfspec_accept").observe(accept)
        if t0 is not None:
            r.histogram("Serve/workload_analysis_s").observe(
                self.clock() - t0)
        return {"prompt_len": P, "shared_prefix_tokens": shared,
                "resume_prefix_tokens": resume,
                "selfspec_accept": accept}

    # ---------------------------------------------------------- speculation
    def on_spec(self, proposed: int, accepted: int, emitted: int,
                first_scored: int = 0, first_hits: int = 0) -> None:
        """Record one verify step's live outcome (the serving engine's
        decode lane calls this once per speculative step, summed over
        slots). ``first_scored`` / ``first_hits`` isolate the FIRST draft
        token per slot — the live counterpart of the offline estimator's
        per-position hit rate, which is what the replay backtest compares
        against the prediction."""
        self.spec_steps += 1
        self.spec_proposed += int(proposed)
        self.spec_accepted += int(accepted)
        self.spec_emitted += int(emitted)
        self.spec_first_scored += int(first_scored)
        self.spec_first_hits += int(first_hits)
        r = self.registry
        r.counter("Serve/workload_spec_proposed_tokens").inc(int(proposed))
        r.counter("Serve/workload_spec_accepted_tokens").inc(int(accepted))
        r.counter("Serve/workload_spec_emitted_tokens").inc(int(emitted))
        if self.spec_proposed:
            r.gauge("Serve/workload_spec_accept_rate").set(
                self.spec_accepted / self.spec_proposed)
        if self.spec_first_scored:
            r.gauge("Serve/workload_spec_first_accept_rate").set(
                self.spec_first_hits / self.spec_first_scored)

    @property
    def spec_accept_rate(self) -> "float | None":
        """Achieved draft-token acceptance fraction (live), None before
        any draft was verified."""
        return (self.spec_accepted / self.spec_proposed
                if self.spec_proposed else None)

    @property
    def spec_first_accept_rate(self) -> "float | None":
        """Achieved FIRST-draft-token acceptance (live) — the comparable
        of the offline estimator's conditional ``hit_rate``."""
        return (self.spec_first_hits / self.spec_first_scored
                if self.spec_first_scored else None)

    # ----------------------------------------------------------- retirement
    def on_retire(self, request) -> None:
        """Record the decode-side shape of a terminated request (accepts
        anything with ``.tokens``; the scheduler's ``Request``)."""
        self.registry.histogram("Serve/workload_decode_len").observe(
            len(getattr(request, "tokens", ())))

    # -------------------------------------------------------------- readout
    @property
    def prefix_overlap(self) -> float:
        """Shared-prefix token fraction over all admitted prompt tokens —
        the fraction of prefill work a prefix cache would have skipped."""
        return (self.shared_tokens / self.prompt_tokens
                if self.prompt_tokens else 0.0)

    @property
    def resume_overlap(self) -> float:
        """Same-session replayed-prefix fraction — the share of prefill
        work a HOST KV tier could serve from demoted session pages."""
        return (self.resume_tokens / self.prompt_tokens
                if self.prompt_tokens else 0.0)

    def snapshot(self) -> dict:
        snap = self.registry.snapshot()
        h = snap["histograms"]
        accept = h.get("Serve/workload_selfspec_accept", {})
        return {
            "requests": self.requests,
            "prompt_tokens": self.prompt_tokens,
            "shared_prefix_tokens": self.shared_tokens,
            "prefix_overlap": self.prefix_overlap,
            "resume_prefix_tokens": self.resume_tokens,
            "resume_overlap": self.resume_overlap,
            "cross_overlap": self.prefix_overlap - self.resume_overlap,
            "dedupable_prefill_tokens": self.shared_tokens,
            "distinct_prefixes": len(self._prefixes),
            "tracked_sessions": len(self._sessions),
            "block": self.cfg.block,
            "ngram": self.cfg.ngram,
            "selfspec_accept": accept,
            "spec_live": {
                "steps": self.spec_steps,
                "proposed_tokens": self.spec_proposed,
                "accepted_tokens": self.spec_accepted,
                "emitted_tokens": self.spec_emitted,
                "accept_rate": self.spec_accept_rate,
                "first_accept_rate": self.spec_first_accept_rate,
            },
            "prompt_len": h.get("Serve/workload_prompt_len", {}),
            "decode_len": h.get("Serve/workload_decode_len", {}),
            "analysis_s": h.get("Serve/workload_analysis_s", {}),
        }
