"""Shared Prometheus exposition formatting: ONE renderer, two transports.

The textfile sink (``sinks.PrometheusTextfileSink``) and the live
``GET /metrics`` endpoint (``server.TelemetryServer``) must emit the
*same bytes* for the same registry state — an operator who graduates
from the node-exporter textfile handoff to a real scrape must not see
metric names shift, HELP lines change, or non-finite spellings drift.
Both paths therefore call :func:`render_exposition` on an identical
``{sanitized_name: value}`` map; neither carries its own formatter, so
they *cannot* drift (the round-trip is regression-pinned in
``tests/unit/test_telemetry.py``).

Contents:

- :func:`prometheus_name` — metric name → legal Prometheus identifier;
- :func:`format_prometheus_value` — exposition scalar spelling
  (``+Inf`` / ``-Inf`` / ``NaN`` for non-finite values);
- :func:`render_exposition` — the full textfile/scrape body (step gauge
  first, then sorted metrics, each with ``# HELP`` / ``# TYPE`` lines);
- :func:`exposition_from_events` — ``(name, value, step)`` event tuples
  (``MetricsRegistry.to_events``) → exposition text, the one-call path
  the HTTP endpoint uses;
- :func:`parse_prometheus_textfile` — the tiny reader (tests + the
  doctor CLI), label-tolerant so it also reads the fleet aggregator's
  relabeled output.
"""

from __future__ import annotations

import math
import re
from typing import Optional, Sequence

_PROM_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str, prefix: str = "dstpu") -> str:
    """Metric name → legal Prometheus identifier (``Serve/ttft_s/p99`` →
    ``dstpu_serve_ttft_s_p99``)."""
    n = _PROM_BAD_CHARS.sub("_", name.strip()).strip("_").lower()
    full = f"{prefix}_{n}" if prefix else n
    if not _PROM_NAME_OK.match(full):
        full = "_" + full
    return full


def format_prometheus_value(v: float) -> str:
    """Exposition-format scalar: non-finite values spell ``+Inf`` /
    ``-Inf`` / ``NaN`` (a bare ``nan``/``inf`` from ``%g`` is rejected by
    strict scrapers)."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return f"{v:.10g}"


def render_exposition(values: dict[str, float],
                      source: Optional[dict[str, str]] = None,
                      step: int = 0, prefix: str = "dstpu") -> str:
    """The canonical exposition body: a ``<prefix>_step`` gauge first
    (the step is its own gauge, NOT a label — a step label would mint a
    new Prometheus series per metric per step and blow up TSDB head
    cardinality), then every metric in sorted order with ``# HELP`` /
    ``# TYPE`` lines. ``values`` keys are already-sanitized names;
    ``source`` maps them back to the registry's original names for the
    HELP text."""
    source = source or {}
    step_name = prometheus_name("step", prefix)
    lines = [f"# HELP {step_name} deepspeed_tpu metric 'step'",
             f"# TYPE {step_name} gauge",
             f"{step_name} {int(step)}"]
    for name in sorted(values):
        lines.append(f"# HELP {name} deepspeed_tpu metric "
                     f"{source.get(name, name)!r}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {format_prometheus_value(values[name])}")
    return "\n".join(lines) + "\n"


def exposition_from_events(events: Sequence[tuple],
                           prefix: str = "dstpu") -> str:
    """``(name, value, step)`` tuples → exposition text, via the exact
    accumulation rule the textfile sink applies (last write per sanitized
    name wins; the step gauge is the max step seen) — so a ``/metrics``
    body rendered from ``registry.to_events(step)`` is byte-identical to
    the textfile the sink would write from the same events."""
    values: dict[str, float] = {}
    source: dict[str, str] = {}
    step = 0
    for name, value, s in events:
        pn = prometheus_name(name, prefix)
        values[pn] = float(value)
        source[pn] = name
        step = max(step, int(s))
    return render_exposition(values, source, step, prefix)


_SAMPLE_LINE = re.compile(
    r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)")


def parse_prometheus_textfile(text: str) -> dict[str, float]:
    """Tiny exposition-format reader (tests + doctors): name -> value.
    Labeled samples (the fleet aggregator's output) key as
    ``name{labels}`` so per-engine series stay distinct."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_LINE.match(line)
        if m:
            key = m.group(1) + (m.group(2) or "")
            out[key] = float(m.group(3))
    return out
