"""Shared Prometheus exposition formatting: ONE renderer, two transports.

The textfile sink (``sinks.PrometheusTextfileSink``) and the live
``GET /metrics`` endpoint (``server.TelemetryServer``) must emit the
*same bytes* for the same registry state — an operator who graduates
from the node-exporter textfile handoff to a real scrape must not see
metric names shift, HELP lines change, or non-finite spellings drift.
Both paths therefore call :func:`render_exposition` on an identical
``{sanitized_name: value}`` map; neither carries its own formatter, so
they *cannot* drift (the round-trip is regression-pinned in
``tests/unit/test_telemetry.py``).

Contents:

- :func:`prometheus_name` — metric name → legal Prometheus identifier;
- :func:`labeled_name` / :func:`split_series` / :func:`parse_labels` —
  multi-label series support: registry names may carry a canonical
  ``{k="v",...}`` label block (sorted keys, escaped values), and
  relabelers COMPOSE into it (``{engine=...}`` merges with
  ``{tenant=...}``) instead of clobbering it;
- :func:`prometheus_series` — full series name (base sanitized, label
  block canonicalized) — what every renderer keys samples by;
- :func:`format_prometheus_value` — exposition scalar spelling
  (``+Inf`` / ``-Inf`` / ``NaN`` for non-finite values);
- :func:`render_exposition` — the full textfile/scrape body (step gauge
  first, then sorted metrics; ``# HELP`` / ``# TYPE`` lines emitted once
  per BASE name, since a labeled series shares its base's type);
- :func:`exposition_from_events` — ``(name, value, step)`` event tuples
  (``MetricsRegistry.to_events``) → exposition text, the one-call path
  the HTTP endpoint uses;
- :func:`parse_prometheus_textfile` — the tiny reader (tests + the
  doctor CLI), label-tolerant so it also reads the fleet aggregator's
  relabeled output (samples key as ``name{labels}``).
"""

from __future__ import annotations

import math
import re
from typing import Optional, Sequence

_PROM_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str, prefix: str = "dstpu") -> str:
    """Metric name → legal Prometheus identifier (``Serve/ttft_s/p99`` →
    ``dstpu_serve_ttft_s_p99``)."""
    n = _PROM_BAD_CHARS.sub("_", name.strip()).strip("_").lower()
    full = f"{prefix}_{n}" if prefix else n
    if not _PROM_NAME_OK.match(full):
        full = "_" + full
    return full


# ------------------------------------------------------- labeled series
# A registry name may end in a label block: `Serve/tenant_tokens
# {tenant="acme"}` (no space — shown split here for line width). The
# block must survive sanitization verbatim (prometheus_name would squash
# `{="}` to underscores), so every series-aware path splits the name
# first, sanitizes only the base, and re-attaches the CANONICAL block
# (sorted label keys) — which is what makes render→parse round-trip
# stable and lets relabelers compose rather than clobber.
_SERIES_RE = re.compile(r"^(.*?)(\{.*\})$", re.S)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def split_series(name: str) -> tuple[str, str]:
    """``base{labels}`` → ``(base, "{labels}")``; plain names →
    ``(name, "")``."""
    m = _SERIES_RE.match(name)
    return (m.group(1), m.group(2)) if m else (name, "")


def parse_labels(block: str) -> dict[str, str]:
    """``'{a="x",b="y"}'`` → ``{"a": "x", "b": "y"}`` (values kept in
    their escaped spelling, so re-emission is byte-stable)."""
    return {k: v for k, v in _LABEL_RE.findall(block or "")}


def _escape_label_value(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def labeled_name(name: str, **labels) -> str:
    """Attach (or merge) labels onto a metric name, canonically: label
    keys sorted, values escaped. Existing labels on ``name`` are kept;
    a key passed here overrides the same key already present — the
    COMPOSE rule the fleet relabeler relies on (``engine=`` merges with
    a tenant label instead of clobbering the block)."""
    base, block = split_series(name)
    merged = parse_labels(block)
    for k, v in labels.items():
        merged[k] = _escape_label_value(v)
    if not merged:
        return base
    body = ",".join(f'{k}="{merged[k]}"' for k in sorted(merged))
    return f"{base}{{{body}}}"


def prometheus_series(name: str, prefix: str = "dstpu") -> str:
    """Full series name → legal exposition key: the base goes through
    :func:`prometheus_name`, the label block (if any) is re-emitted in
    canonical sorted-key order. The identity every renderer and the
    parser agree on."""
    base, block = split_series(name)
    if not block:
        return prometheus_name(base, prefix)
    labels = parse_labels(block)
    body = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return prometheus_name(base, prefix) + "{" + body + "}"


def format_prometheus_value(v: float) -> str:
    """Exposition-format scalar: non-finite values spell ``+Inf`` /
    ``-Inf`` / ``NaN`` (a bare ``nan``/``inf`` from ``%g`` is rejected by
    strict scrapers)."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return f"{v:.10g}"


def render_exposition(values: dict[str, float],
                      source: Optional[dict[str, str]] = None,
                      step: int = 0, prefix: str = "dstpu") -> str:
    """The canonical exposition body: a ``<prefix>_step`` gauge first
    (the step is its own gauge, NOT a label — a step label would mint a
    new Prometheus series per metric per step and blow up TSDB head
    cardinality), then every metric in sorted order with ``# HELP`` /
    ``# TYPE`` lines. ``values`` keys are already-sanitized names;
    ``source`` maps them back to the registry's original names for the
    HELP text."""
    source = source or {}
    step_name = prometheus_name("step", prefix)
    lines = [f"# HELP {step_name} deepspeed_tpu metric 'step'",
             f"# TYPE {step_name} gauge",
             f"{step_name} {int(step)}"]
    seen_bases: set = set()
    for name in sorted(values):
        base, block = split_series(name)
        if base not in seen_bases:
            # HELP/TYPE describe the BASE metric once — a `# TYPE
            # name{labels}` line is illegal exposition format, and for
            # unlabeled names this emits the exact bytes it always did
            seen_bases.add(base)
            src = source.get(name, name)
            if block:
                src = split_series(src)[0]
            lines.append(f"# HELP {base} deepspeed_tpu metric {src!r}")
            lines.append(f"# TYPE {base} gauge")
        lines.append(f"{name} {format_prometheus_value(values[name])}")
    return "\n".join(lines) + "\n"


def exposition_from_events(events: Sequence[tuple],
                           prefix: str = "dstpu") -> str:
    """``(name, value, step)`` tuples → exposition text, via the exact
    accumulation rule the textfile sink applies (last write per sanitized
    name wins; the step gauge is the max step seen) — so a ``/metrics``
    body rendered from ``registry.to_events(step)`` is byte-identical to
    the textfile the sink would write from the same events."""
    values: dict[str, float] = {}
    source: dict[str, str] = {}
    step = 0
    for name, value, s in events:
        pn = prometheus_series(name, prefix)
        values[pn] = float(value)
        source[pn] = name
        step = max(step, int(s))
    return render_exposition(values, source, step, prefix)


_SAMPLE_LINE = re.compile(
    r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)")


def parse_prometheus_textfile(text: str) -> dict[str, float]:
    """Tiny exposition-format reader (tests + doctors): name -> value.
    Labeled samples (the fleet aggregator's output) key as
    ``name{labels}`` so per-engine series stay distinct."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_LINE.match(line)
        if m:
            key = m.group(1) + (m.group(2) or "")
            out[key] = float(m.group(3))
    return out
