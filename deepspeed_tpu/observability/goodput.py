"""Goodput/badput attribution: where did the wall time actually go?

Every perf number shipped so far (tokens/s, MBU, MFU, step time) rates
the work that *ran*; none of them says what fraction of the process's
wall time was productive at all. That decomposition — DeepSpeed's
monitor + flops-profiler split, T3's insistence that time be
*attributed* before overlap work can be trusted — is what the fleet
router needs to tell "slow engine" from "starved engine".

:class:`GoodputLedger` is an interval accountant on the owner's
injectable clock. Engines feed it the windows they already measure
(the serving iteration, the decode window the watchdog times, the train
step dispatch) and it attributes **every second between the first and
the latest observation** to exactly one bucket:

- ``productive`` — decode steps with >= 1 live slot, prefill chunk
  dispatch, train step dispatch;
- ``compile`` — iterations that built a new XLA program (detected via
  the engine's compile counter, never a guess);
- ``queue_empty`` — idle: no request anywhere (serving), inter-step
  host/data time (training);
- ``stall`` — the portion of a decode step beyond the watchdog budget;
- ``checkpoint`` — checkpoint commit windows;
- ``drain`` — idle time while intake is closed for a drain;
- ``preempt`` — the SIGTERM grace window (PreemptionGuard handler);
- ``other`` — host scheduling overhead inside a working iteration.

The invariant — pinned by the fake-clock tests and the
``bench_telemetry.py --smoke`` gate — is ``productive + sum(badput) ==
wall`` to within float tolerance: attribution that doesn't sum to wall
time is attribution that silently dropped a failure mode.

Cost discipline matches the rest of the stack: disabled engines hold
``goodput = None`` (one ``is not None`` per iteration, zero clock
reads, zero programs, zero syncs); enabled, the serving ledger adds two
host clock reads per iteration and pure-Python float math.
"""

from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

# Badput buckets, in the order reports print them. "productive" is not
# in this tuple: it is the goodput side of the ledger.
BADPUT_BUCKETS = ("compile", "queue_empty", "stall", "checkpoint",
                  "drain", "preempt", "other")
PRODUCTIVE = "productive"


class GoodputLedger:
    """Wall-time accountant: every interval lands in exactly one bucket.

    ``account(bucket, t0, t1)`` is the primitive: it first charges any
    gap since the previous attributed instant to the ledger's current
    *idle bucket* (``queue_empty`` by default; ``drain`` while the owner
    reports draining), then charges ``[t0, t1]`` to ``bucket``. Engines
    call the typed helpers (:meth:`on_serving_iteration`,
    :meth:`on_train_step`, :meth:`window`) which encode the attribution
    policy; the primitive keeps the sum-to-wall invariant true by
    construction — there is no instant between ``start_t`` and
    ``last_t`` that belongs to no bucket.

    Thread-safe (the telemetry server snapshots from its own thread);
    ``clock`` is the owner's injectable clock so fake-clock tests drive
    attribution deterministically.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 registry=None, prefix: str = "Serve"):
        self.clock = clock
        self.registry = registry
        self.prefix = prefix
        self._lock = threading.RLock()
        self._buckets: dict[str, float] = {PRODUCTIVE: 0.0}
        for b in BADPUT_BUCKETS:
            self._buckets[b] = 0.0
        self._start: Optional[float] = None   # first attributed instant
        self._last: Optional[float] = None    # latest attributed instant
        self._idle_bucket = "queue_empty"

    # ------------------------------------------------------------ primitive
    def account(self, bucket: str, t0: float, t1: float) -> None:
        """Charge ``[t0, t1]`` to ``bucket``; the gap since the previous
        attributed instant goes to the current idle bucket. Out-of-order
        or zero-length windows degrade to no-ops rather than corrupting
        the wall sum."""
        if bucket not in self._buckets:
            raise ValueError(f"unknown goodput bucket {bucket!r} "
                             f"(have {sorted(self._buckets)})")
        t0, t1 = float(t0), float(t1)
        if t1 < t0:
            return
        with self._lock:
            if self._start is None:
                self._start = t0
                self._last = t0
            if t0 > self._last:
                self._buckets[self._idle_bucket] += t0 - self._last
                self._last = t0
            lo = max(t0, self._last)
            if t1 > lo:
                self._buckets[bucket] += t1 - lo
                self._last = t1

    def set_idle_reason(self, draining: bool) -> None:
        """What the NEXT inter-observation gap means: ``drain`` while
        intake is closed, ``queue_empty`` otherwise."""
        with self._lock:
            self._idle_bucket = "drain" if draining else "queue_empty"

    # --------------------------------------------------------- typed feeds
    def on_serving_iteration(self, t0: float, t1: float, *,
                             decode_s: float = 0.0, ran_decode: bool = False,
                             ran_chunk: bool = False, compiled: bool = False,
                             stall_excess_s: float = 0.0,
                             draining: bool = False,
                             idle: bool = False) -> None:
        """Attribute one ``ServingEngine.step()`` window ``[t0, t1]``.

        Policy: an iteration that built a new XLA program is a COMPILE
        window end to end — the build may have happened inside the
        decode dispatch itself (the cold engine's first decode step),
        so splitting it would book compile time as productive or, with
        a watchdog set, as a phantom stall. Otherwise the decode window
        splits into productive time (up to the watchdog budget) and
        ``stall`` excess; the rest of the iteration is host-overhead
        ``other`` when work ran, and idle (``drain`` / ``queue_empty``)
        when the engine had nothing to do."""
        span = max(0.0, float(t1) - float(t0))
        decode_s = min(max(0.0, float(decode_s)), span)
        stall = min(max(0.0, float(stall_excess_s)), decode_s)
        parts: list[tuple[str, float]] = []
        if compiled:
            # the whole window is compile badput: decode_s/stall split
            # below would misattribute the program build that ran
            # INSIDE the decode dispatch (the watchdog fires on it too)
            parts.append(("compile", span))
            decode_s = stall = 0.0
        rest = span - decode_s if not compiled else 0.0
        if ran_decode and decode_s > 0:
            parts.append((PRODUCTIVE, decode_s - stall))
            if stall > 0:
                parts.append(("stall", stall))
        if rest > 0:
            if ran_chunk or ran_decode:
                # host scheduling overhead around real work: close to
                # zero on a healthy engine, and worth seeing when not
                parts.append(("other", rest))
            elif draining:
                parts.append(("drain", rest))
            elif idle:
                parts.append(("queue_empty", rest))
            else:
                parts.append(("other", rest))
        cur = float(t0)
        for bucket, dur in parts:
            if dur > 0:
                self.account(bucket, cur, cur + dur)
                cur += dur
        if cur < t1:   # float dust / empty parts: close the window
            self.account("other" if not (draining or idle) else
                         ("drain" if draining else "queue_empty"), cur, t1)
        self.set_idle_reason(draining)

    def on_train_step(self, t0: float, t1: float,
                      compiled: bool = False) -> None:
        """Attribute one ``train_batch`` window: ``compile`` when this
        call built the step program (its wall time is dominated by the
        XLA compile), else ``productive``. The inter-step gap — data
        loading, host optimizer work outside the window — lands in
        ``queue_empty`` via the gap rule."""
        self.account("compile" if compiled else PRODUCTIVE, t0, t1)

    @contextmanager
    def window(self, bucket: str):
        """Bracket a code region into one bucket (checkpoint commits,
        the preemption grace window): ``with ledger.window("checkpoint"):
        ...``."""
        t0 = self.clock()
        try:
            yield
        finally:
            self.account(bucket, t0, self.clock())

    # -------------------------------------------------------------- readout
    def snapshot(self) -> dict:
        """Machine-readable decomposition; ``unattributed_s`` is the float
        dust between ``wall_s`` and the bucket sum (0 by construction, a
        bug if ever material)."""
        with self._lock:
            wall = 0.0 if self._start is None else self._last - self._start
            buckets = dict(self._buckets)
        badput = {b: buckets[b] for b in BADPUT_BUCKETS}
        total = buckets[PRODUCTIVE] + sum(badput.values())
        return {
            "wall_s": wall,
            "productive_s": buckets[PRODUCTIVE],
            "badput_s": badput,
            "badput_total_s": sum(badput.values()),
            "goodput_frac": (buckets[PRODUCTIVE] / wall) if wall > 0
            else math.nan,
            "unattributed_s": wall - total,
        }

    def export(self, registry=None, prefix: Optional[str] = None) -> dict:
        """Write the decomposition as ``<prefix>/goodput_*`` gauges
        (``Serve/goodput_frac``, ``Serve/goodput_badput_stall_s``, ...)
        into ``registry`` (default: the ledger's own); returns the
        snapshot. Called from ``publish_metrics`` and before every
        ``/metrics`` render so scrapes always see current numbers."""
        reg = registry if registry is not None else self.registry
        snap = self.snapshot()
        if reg is None:
            return snap
        p = prefix if prefix is not None else self.prefix
        gauges = {
            f"{p}/goodput_wall_s": snap["wall_s"],
            f"{p}/goodput_productive_s": snap["productive_s"],
            f"{p}/goodput_badput_total_s": snap["badput_total_s"],
        }
        if not math.isnan(snap["goodput_frac"]):
            gauges[f"{p}/goodput_frac"] = snap["goodput_frac"]
        for b, v in snap["badput_s"].items():
            gauges[f"{p}/goodput_badput_{b}_s"] = v
        reg.set_gauges(gauges)
        return snap


def weighted_goodput_frac(pairs) -> "float | None":
    """Wall-weighted mean over ``(goodput_frac, wall_s)`` pairs — THE
    fleet goodput definition, shared by the in-process rollup below and
    the scrape aggregator (``fleet_scrape.py``) so the two surfaces
    cannot drift. A replica that has lived 10x longer carries 10x the
    weight (a freshly joined replica must not mask fleet-wide badput);
    None/NaN fractions drop out, and a zero/unknown wall falls back to
    weight 1.0 (the replica still counts, it just cannot dominate).
    None when no replica has a usable fraction."""
    wsum = fsum = 0.0
    for frac, wall in pairs:
        if frac is None or (isinstance(frac, float) and math.isnan(frac)):
            continue
        w = wall if wall and wall > 0 else 1.0
        wsum += w
        fsum += frac * w
    return (fsum / wsum) if wsum > 0 else None


def rollup_goodput(snaps: list) -> dict:
    """Fleet rollup over per-replica ledger snapshots — the SAME math
    the ``fleet_scrape`` aggregator applies to scraped gauges (both go
    through :func:`weighted_goodput_frac`), applied to in-process
    :meth:`GoodputLedger.snapshot` dicts: per-bucket seconds sum plus
    the wall-weighted fleet fraction."""
    out = {"replicas": len(snaps), "wall_s": 0.0, "productive_s": 0.0,
           "badput_s": {b: 0.0 for b in BADPUT_BUCKETS},
           "badput_total_s": 0.0, "goodput_frac": None}
    pairs = []
    for s in snaps:
        if not s:
            continue
        out["wall_s"] += s.get("wall_s", 0.0)
        out["productive_s"] += s.get("productive_s", 0.0)
        for b, v in (s.get("badput_s") or {}).items():
            out["badput_s"][b] = out["badput_s"].get(b, 0.0) + v
        out["badput_total_s"] += s.get("badput_total_s", 0.0)
        pairs.append((s.get("goodput_frac"), s.get("wall_s", 0.0)))
    out["goodput_frac"] = weighted_goodput_frac(pairs)
    return out
