"""XLA profiler integration: windowed trace capture + HBM watermark.

Two pieces the training loop wires in:

- :class:`TraceWindow` — config-driven ``trace_steps=(start, stop)``: the
  engine calls ``on_step(global_step)`` once per ``train_batch`` and the
  window starts ``jax.profiler.start_trace`` entering step ``start`` and
  stops it after step ``stop`` completes. Capturing a *bounded* window in
  prod is the point: an unbounded trace on a busy serving host fills disk
  in minutes, while a 5-step window around a suspect region is megabytes.
  View with ``tensorboard --logdir <dir>`` or xprof/perfetto.

- :func:`sample_memory` — the HBM watermark: reads the accelerator's
  ``memory_stats()`` (bytes in use / peak / limit) into ``Memory/*``
  gauges. Sampled at step boundaries only (one cheap host call; never
  inside a compiled program).

The ``jax.named_scope`` annotations on the model blocks (attn / mlp / moe
/ decode_step — see ``models/transformer.py``) are what make the captured
trace readable: XLA ops inherit the scope names, so the trace viewer's
timeline groups by transformer block instead of a flat fusion soup.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..utils.logging import log_dist


class TraceWindow:
    """Windowed ``jax.profiler`` capture around a step interval.

    ``trace_steps=(start, stop)``: the trace runs for global steps in
    ``[start, stop]`` inclusive. ``sync_fn`` (optional) is called before
    stopping so the trace includes the full device activity of the last
    step (async dispatch would otherwise close the file mid-step).
    """

    def __init__(self, trace_steps: Sequence[int], logdir: str,
                 sync_fn=None):
        if len(tuple(trace_steps)) != 2:
            raise ValueError(
                f"trace_steps must be (start, stop), got {trace_steps!r}")
        self.start_step, self.stop_step = (int(s) for s in trace_steps)
        if self.stop_step < self.start_step:
            raise ValueError(
                f"trace_steps stop ({self.stop_step}) precedes start "
                f"({self.start_step})")
        self.logdir = logdir
        self.sync_fn = sync_fn
        self.active = False
        self.done = False

    def on_step(self, step: int) -> None:
        """Call once per train step with the CURRENT global step (the step
        about to run). Idempotent after the window closes."""
        if self.done:
            return
        if not self.active and self.start_step <= step <= self.stop_step:
            import jax

            jax.profiler.start_trace(self.logdir)
            self.active = True
            log_dist(f"observability: XLA trace window open at step {step} "
                     f"→ {self.logdir}", ranks=[0])
        elif self.active and step > self.stop_step:
            self._stop(step)

    def close(self) -> None:
        """Stop the trace if still open (end of training, error paths)."""
        if self.active:
            self._stop(None)

    def _stop(self, step: Optional[int]) -> None:
        import jax

        if self.sync_fn is not None:
            try:
                self.sync_fn()
            except Exception:   # sync is best-effort; the trace still closes
                pass
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        at = f" at step {step}" if step is not None else ""
        log_dist(f"observability: XLA trace window closed{at} "
                 f"(view: tensorboard --logdir {self.logdir})", ranks=[0])


def sample_memory(registry, accelerator=None, prefix: str = "Memory") -> dict:
    """HBM watermark → ``Memory/*`` gauges; returns the sampled dict.

    Uses ``platform/accelerator.py`` ``memory_stats()`` (zeros on backends
    that don't report, e.g. CPU) — callers need no platform guard."""
    if accelerator is None:
        from ..platform.accelerator import get_accelerator

        accelerator = get_accelerator()
    stats = accelerator.memory_stats().as_dict()
    registry.set_gauges({f"{prefix}/{k}": float(v)
                         for k, v in stats.items()})
    return stats
