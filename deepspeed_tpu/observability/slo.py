"""Declarative SLOs + anomaly detection over the metric registry.

Three detectors, all host-side arithmetic over already-recorded floats
(no device work, no syncs):

- :class:`SLOScorer` — declarative targets (TTFT/TPOT p99, error-rate
  budget) scored into ``Serve/slo_*`` burn-rate gauges. Burn rate is
  ``observed / target``: 1.0 means exactly on budget, 2.0 means the p99
  is twice the target — the multi-window burn-rate alerting shape SRE
  books recommend, reduced to the rolling window the reservoirs keep.
- :class:`MedianMADDetector` — rolling median + MAD outlier test for
  step-time regressions (``Train/step_time_s``, the serving decode
  step). Median/MAD instead of mean/stddev because one genuine stall
  must not drag the baseline up and mask the next one.
- :class:`CompileStormDetector` — watches a monotonically increasing
  compile counter; a burst of recompiles after warmup (shape drift, a
  config bug evicting the program cache) is a latency cliff operators
  need attributed.

Every firing lands as a counter bump, a gauge, and a flight-recorder
marker (when one is attached) — the dump then *explains* why it was
taken instead of showing a bare timeline.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Optional

from .metrics import MetricsRegistry


@dataclasses.dataclass
class SLOConfig:
    """Declarative serving/training SLO + anomaly knobs (all off by
    default; 0 disables the corresponding detector)."""

    # p99 latency targets over the rolling histogram window, seconds.
    ttft_p99_s: float = 0.0
    tpot_p99_s: float = 0.0
    # Error budget: max acceptable fraction of non-OK terminal requests
    # (timeouts, non-finite retirements, sheds) among all terminated.
    error_rate: float = 0.0
    # Step-time regression: flag a step slower than median + k * MAD over
    # the rolling window (k = this knob; 0 disables).
    step_time_mad_k: float = 0.0
    step_time_window: int = 64
    step_time_min_samples: int = 16
    # Compile storm: more than this many new compiles inside one trailing
    # window of iterations/steps, after the warmup grace (0 disables).
    compile_storm_threshold: int = 0
    compile_storm_window: int = 32
    compile_storm_grace: int = 64

    def __post_init__(self):
        for knob in ("ttft_p99_s", "tpot_p99_s", "error_rate",
                     "step_time_mad_k"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0, "
                                 f"got {getattr(self, knob)}")
        if self.error_rate > 1:
            raise ValueError(f"error_rate is a fraction in [0, 1], "
                             f"got {self.error_rate}")
        for knob in ("step_time_window", "step_time_min_samples",
                     "compile_storm_window"):
            if getattr(self, knob) < 1:
                raise ValueError(f"{knob} must be >= 1, "
                                 f"got {getattr(self, knob)}")

    @property
    def any_enabled(self) -> bool:
        return bool(self.ttft_p99_s or self.tpot_p99_s or self.error_rate
                    or self.step_time_mad_k or self.compile_storm_threshold)

    @classmethod
    def from_any(cls, cfg: "SLOConfig | dict | None") -> "SLOConfig | None":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown slo config keys: {sorted(unknown)}")
        return cls(**cfg)


# Non-OK terminal outcomes charged against the error budget. SHED counts:
# a shed request is a request the service failed to serve.
_ERROR_COUNTERS = ("Serve/timeout", "Serve/nonfinite", "Serve/shed")


class SLOScorer:
    """Scores one registry against one :class:`SLOConfig`.

    ``score()`` reads the rolling ``Serve/ttft_s`` / ``Serve/tpot_s``
    reservoirs and the terminal-status counters, writes
    ``Serve/slo_{ttft,tpot,error}_burn`` gauges plus a cumulative
    ``Serve/slo_violations`` counter, and notes each NEW violation into
    the flight recorder. Violations edge-trigger: a burn that stays > 1
    across many score() calls marks once until it recovers below 1."""

    # error-rate burn is computed over the outcomes of the last this-many
    # score() passes, mirroring the rolling-reservoir semantics of the
    # latency burns — lifetime counters would let a million healthy
    # requests mask the first ten thousand of a total outage
    ERROR_WINDOW_SCORES = 32

    def __init__(self, cfg: SLOConfig, registry: MetricsRegistry,
                 flight=None):
        self.cfg = cfg
        self.registry = registry
        self.flight = flight
        self._breached: set[str] = set()
        self._err_hist: deque[tuple[float, float]] = deque(
            maxlen=self.ERROR_WINDOW_SCORES)
        self._prev_errors = 0.0
        self._prev_total = 0.0

    def _mark(self, which: str, burn: float, observed: float,
              target: float) -> None:
        r = self.registry
        r.gauge(f"Serve/slo_{which}_burn").set(burn)
        if burn <= 1.0:
            self._breached.discard(which)
            return
        if which in self._breached:      # still breached: already marked
            return
        self._breached.add(which)
        r.counter("Serve/slo_violations").inc()
        if self.flight is not None:
            self.flight.note(f"slo_{which}_breach", burn=round(burn, 4),
                             observed=observed, target=target)

    def score(self) -> dict:
        """One scoring pass; returns ``{which: burn}`` for the enabled
        targets (NaN burn while the window is still empty)."""
        snap = self.registry.snapshot()
        hist, counters = snap["histograms"], snap["counters"]
        out: dict[str, float] = {}
        for which, target, series in (
                ("ttft", self.cfg.ttft_p99_s, "Serve/ttft_s"),
                ("tpot", self.cfg.tpot_p99_s, "Serve/tpot_s")):
            if not target:
                continue
            p99 = hist.get(series, {}).get("p99", math.nan)
            burn = p99 / target
            out[which] = burn
            if not math.isnan(burn):
                self._mark(which, burn, p99, target)
        if self.cfg.error_rate:
            errors = sum(counters.get(n, 0) for n in _ERROR_COUNTERS)
            total = errors + counters.get("Serve/retired", 0)
            # rolling window over score() passes: push this pass's delta,
            # rate the window — recent traffic, not process history
            self._err_hist.append((errors - self._prev_errors,
                                   total - self._prev_total))
            self._prev_errors, self._prev_total = errors, total
            win_err = sum(e for e, _ in self._err_hist)
            win_total = sum(t for _, t in self._err_hist)
            if win_total > 0:
                rate = win_err / win_total
                burn = rate / self.cfg.error_rate
                out["error"] = burn
                self._mark("error", burn, rate, self.cfg.error_rate)
            else:
                out["error"] = math.nan
        return out


class MedianMADDetector:
    """Rolling median + MAD step-time regression detector.

    ``observe(v)`` returns True when ``v > median + k * MAD`` over the
    trailing window (MAD floored at 5% of the median so a perfectly
    steady window — MAD 0 — doesn't flag micro-jitter). The offending
    sample is NOT added to the window, so a stall can't poison its own
    baseline; recovery samples re-enter normally. A shift that PERSISTS
    (``REGIME_SHIFT_FIRES`` consecutive outliers — e.g. occupancy
    legitimately grew and every step is now slower) is adopted as the
    new baseline instead of firing forever and flooding the flight ring
    with one marker per step."""

    # consecutive outliers after which the detector stops flagging and
    # starts admitting samples — a regime shift, not a regression
    REGIME_SHIFT_FIRES = 16

    def __init__(self, k: float = 0.0, window: int = 64,
                 min_samples: int = 16):
        if window < 1 or min_samples < 1:
            raise ValueError("window and min_samples must be >= 1")
        self.k = float(k)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self._buf: deque[float] = deque(maxlen=self.window)
        self._consecutive = 0
        self.fired = 0

    @property
    def enabled(self) -> bool:
        return self.k > 0

    def stats(self) -> tuple[float, float]:
        s = sorted(self._buf)
        n = len(s)
        if not n:
            return math.nan, math.nan
        med = s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
        dev = sorted(abs(v - med) for v in s)
        mad = dev[n // 2] if n % 2 else 0.5 * (dev[n // 2 - 1] + dev[n // 2])
        return med, mad

    def observe(self, v: float) -> bool:
        v = float(v)
        if not self.enabled:
            return False
        if len(self._buf) >= self.min_samples:
            med, mad = self.stats()
            floor = 0.05 * med
            if v > med + self.k * max(mad, floor):
                self._consecutive += 1
                if self._consecutive <= self.REGIME_SHIFT_FIRES:
                    self.fired += 1
                    return True
                # persistent: adopt the new regime — admit the sample so
                # the median converges to it, and stop flagging
                self._buf.append(v)
                return False
        self._consecutive = 0
        self._buf.append(v)
        return False


class CompileStormDetector:
    """Burst detector over a monotonically increasing compile counter.

    ``update(iteration, compiles)`` returns the number of new compiles in
    the trailing ``window`` when it exceeds ``threshold`` (else 0). The
    first ``grace`` iterations are warmup — bucket-shaped programs are
    *supposed* to compile there. Edge-triggered per storm: fires once on
    the RISING edge (window count crosses the threshold) and stays
    silent until the window drains back below it — an ongoing storm is
    one storm, not one firing per iteration."""

    def __init__(self, threshold: int = 0, window: int = 32,
                 grace: int = 64):
        self.threshold = int(threshold)
        self.window = int(window)
        self.grace = int(grace)
        self._hist: deque[tuple[int, int]] = deque()   # (iteration, total)
        self._in_storm = False
        self.fired = 0

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def update(self, iteration: int, compiles: int) -> int:
        if not self.enabled:
            return 0
        self._hist.append((int(iteration), int(compiles)))
        # drop pre-grace entries too: warmup compiles are *supposed* to
        # happen, and leaving them in the deque would count them in the
        # first post-grace trailing window — a false storm at the boundary
        while self._hist and (self._hist[0][0] < iteration - self.window
                              or self._hist[0][0] < self.grace):
            self._hist.popleft()
        if iteration < self.grace:
            return 0
        new = compiles - self._hist[0][1]
        if new <= self.threshold:
            self._in_storm = False
            return 0
        if self._in_storm:
            return 0
        self._in_storm = True
        self.fired += 1
        return new
