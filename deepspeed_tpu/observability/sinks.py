"""Machine-readable monitor sinks: JSONL event log + Prometheus textfile.

Both plug into :class:`~..monitor.monitor.MonitorMaster` next to the
CSV/TensorBoard/WandB writers (same ``write_events([(name, value, step)])``
contract) and exist because the reference trio's outputs are either
binary (TB event files) or external services (WandB): perf attribution
tooling wants something it can ``json.loads`` or scrape.

- :class:`JsonlSink` appends one JSON object per event — the replayable
  ground-truth log (``{"name", "value", "step", "time"}``).
- :class:`PrometheusTextfileSink` maintains the *latest* value per metric
  and atomically rewrites a textfile in Prometheus exposition format, the
  standard node-exporter textfile-collector handoff: point
  ``--collector.textfile.directory`` at its directory and the job's gauges
  show up in every scrape without running an HTTP server inside the
  training process.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Callable, Sequence

# Exposition formatting lives in expfmt.py (shared verbatim with the
# live /metrics endpoint in server.py — the two outputs are
# byte-compatible by construction); re-exported here for compatibility.
from .expfmt import (format_prometheus_value, parse_prometheus_textfile,
                     prometheus_name, prometheus_series, render_exposition)

__all__ = ["JsonlSink", "PrometheusTextfileSink", "prometheus_name",
           "format_prometheus_value", "parse_prometheus_textfile"]


class JsonlSink:
    """Append-only JSONL event log with a persistent file handle.

    ``rotate_mb`` (config, default 0 = off) bounds the file for
    long-running serving jobs: at flush boundaries only (the persistent
    handle is never churned per event), a file past the limit rolls to
    ``<name>.jsonl.1`` (one generation kept — the rolling window plus
    whatever external log shipping already collected) and a fresh file
    takes over. ``clock`` stamps event wall time and is injectable; the
    default is ``time.time`` because a log record's timestamp is
    calendar time, not a measured interval."""

    # subclass seams (RequestLogSink): filename suffix + flush cadence
    SUFFIX = ".jsonl"
    FLUSH_EVERY = 64

    def __init__(self, cfg: dict, clock: Callable[[], float] = time.time):
        path = Path(cfg.get("output_path", "./monitor")) / (
            cfg.get("job_name", "DeepSpeedTpuJob") + self.SUFFIX)
        path.parent.mkdir(parents=True, exist_ok=True)
        self.path = path
        self.clock = clock
        self._f = open(path, "a", encoding="utf-8")
        # 0 = rely on close(); N = fsync-less flush every N events
        self._flush_every = int(cfg.get("flush_every", self.FLUSH_EVERY))
        self._pending = 0
        self._rotate_bytes = int(float(cfg.get("rotate_mb", 0))
                                 * 1024 * 1024)
        self.rotations = 0

    def _write_line(self, line: str) -> None:
        self._f.write(line + "\n")
        self._pending += 1
        # the size check keeps rotate_mb honest even with flush_every=0
        # ("rely on close()"): a standalone sink must not grow unbounded
        # just because nothing else calls flush()
        if (self._flush_every and self._pending >= self._flush_every) or \
                (self._rotate_bytes and not self._f.closed
                 and self._f.tell() >= self._rotate_bytes):
            self.flush()

    def write_events(self, events: Sequence[tuple]) -> None:
        now = self.clock()
        for name, value, step in events:
            self._write_line(json.dumps(
                {"name": name, "value": float(value), "step": int(step),
                 "time": now}, separators=(",", ":")))

    def _maybe_rotate(self) -> None:
        # flush-boundary-only: the handle persists between rotations, and
        # a half-written line can never straddle a roll (we just flushed)
        if not self._rotate_bytes or self._f.closed:
            return
        if self._f.tell() < self._rotate_bytes:
            return
        self._f.close()
        os.replace(self.path, self.path.with_name(self.path.name + ".1"))
        self._f = open(self.path, "a", encoding="utf-8")
        self.rotations += 1

    def flush(self) -> None:
        self._pending = 0
        if not self._f.closed:
            self._f.flush()
        self._maybe_rotate()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()
        self._pending = 0


class PrometheusTextfileSink:
    """Latest-value gauge exporter in Prometheus exposition format.

    The textfile is rewritten atomically (tmp + rename) on every flush so a
    concurrent scrape never reads a torn file."""

    def __init__(self, cfg: dict):
        d = Path(cfg.get("output_path", "./monitor"))
        d.mkdir(parents=True, exist_ok=True)
        self.path = d / (cfg.get("job_name", "DeepSpeedTpuJob") + ".prom")
        self.prefix = cfg.get("prefix", "dstpu")
        self._values: dict[str, float] = {}
        self._source: dict[str, str] = {}    # sanitized -> original name
        self._step = 0
        self._dirty = False

    def write_events(self, events: Sequence[tuple]) -> None:
        # buffered: the textfile is rewritten at flush() (report boundaries
        # / close), not per event batch
        for name, value, step in events:
            # series-aware: a labeled registry name (Serve/tenant_*
            # {tenant="..."}) keeps its label block; plain names render
            # exactly as before
            pn = prometheus_series(name, self.prefix)
            self._values[pn] = float(value)
            self._source[pn] = name
            self._step = max(self._step, int(step))
            self._dirty = True

    def flush(self) -> None:
        if not self._dirty:
            return
        # one shared renderer (expfmt.render_exposition) with the live
        # /metrics endpoint: same step-gauge-first layout, same HELP
        # lines, same non-finite spellings — byte-compatible by
        # construction, pinned by the telemetry round-trip test
        body = render_exposition(self._values, self._source, self._step,
                                 self.prefix)
        tmp = self.path.with_suffix(".prom.tmp")
        tmp.write_text(body, encoding="utf-8")
        os.replace(tmp, self.path)
        self._dirty = False

    def close(self) -> None:
        self.flush()
