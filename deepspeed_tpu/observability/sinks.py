"""Machine-readable monitor sinks: JSONL event log + Prometheus textfile.

Both plug into :class:`~..monitor.monitor.MonitorMaster` next to the
CSV/TensorBoard/WandB writers (same ``write_events([(name, value, step)])``
contract) and exist because the reference trio's outputs are either
binary (TB event files) or external services (WandB): perf attribution
tooling wants something it can ``json.loads`` or scrape.

- :class:`JsonlSink` appends one JSON object per event — the replayable
  ground-truth log (``{"name", "value", "step", "time"}``).
- :class:`PrometheusTextfileSink` maintains the *latest* value per metric
  and atomically rewrites a textfile in Prometheus exposition format, the
  standard node-exporter textfile-collector handoff: point
  ``--collector.textfile.directory`` at its directory and the job's gauges
  show up in every scrape without running an HTTP server inside the
  training process.
"""

from __future__ import annotations

import json
import os
import re
import time
from pathlib import Path
from typing import Sequence


class JsonlSink:
    """Append-only JSONL event log with a persistent file handle."""

    def __init__(self, cfg: dict):
        path = Path(cfg.get("output_path", "./monitor")) / (
            cfg.get("job_name", "DeepSpeedTpuJob") + ".jsonl")
        path.parent.mkdir(parents=True, exist_ok=True)
        self.path = path
        self._f = open(path, "a", encoding="utf-8")
        # 0 = rely on close(); N = fsync-less flush every N events
        self._flush_every = int(cfg.get("flush_every", 64))
        self._pending = 0

    def write_events(self, events: Sequence[tuple]) -> None:
        now = time.time()
        for name, value, step in events:
            self._f.write(json.dumps(
                {"name": name, "value": float(value), "step": int(step),
                 "time": now}, separators=(",", ":")) + "\n")
            self._pending += 1
        if self._flush_every and self._pending >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        self._pending = 0
        if not self._f.closed:
            self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


_PROM_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str, prefix: str = "dstpu") -> str:
    """Metric name → legal Prometheus identifier (``Serve/ttft_s/p99`` →
    ``dstpu_serve_ttft_s_p99``)."""
    n = _PROM_BAD_CHARS.sub("_", name.strip()).strip("_").lower()
    full = f"{prefix}_{n}" if prefix else n
    if not _PROM_NAME_OK.match(full):
        full = "_" + full
    return full


class PrometheusTextfileSink:
    """Latest-value gauge exporter in Prometheus exposition format.

    The textfile is rewritten atomically (tmp + rename) on every flush so a
    concurrent scrape never reads a torn file."""

    def __init__(self, cfg: dict):
        d = Path(cfg.get("output_path", "./monitor"))
        d.mkdir(parents=True, exist_ok=True)
        self.path = d / (cfg.get("job_name", "DeepSpeedTpuJob") + ".prom")
        self.prefix = cfg.get("prefix", "dstpu")
        self._values: dict[str, float] = {}
        self._step = 0
        self._dirty = False

    def write_events(self, events: Sequence[tuple]) -> None:
        # buffered: the textfile is rewritten at flush() (report boundaries
        # / close), not per event batch
        for name, value, step in events:
            self._values[prometheus_name(name, self.prefix)] = float(value)
            self._step = max(self._step, int(step))
            self._dirty = True

    def flush(self) -> None:
        if not self._dirty:
            return
        # The step is its own gauge, NOT a label: a step label would mint a
        # brand-new Prometheus series per metric per step (label sets key
        # series), fragmenting graphs and blowing up TSDB head cardinality.
        lines = [f"# TYPE {prometheus_name('step', self.prefix)} gauge",
                 f"{prometheus_name('step', self.prefix)} {self._step}"]
        for name in sorted(self._values):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {self._values[name]:.10g}")
        tmp = self.path.with_suffix(".prom.tmp")
        tmp.write_text("\n".join(lines) + "\n", encoding="utf-8")
        os.replace(tmp, self.path)
        self._dirty = False

    def close(self) -> None:
        self.flush()


def parse_prometheus_textfile(text: str) -> dict[str, float]:
    """Tiny exposition-format reader (tests + doctors): name -> value."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})?\s+(\S+)", line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out
