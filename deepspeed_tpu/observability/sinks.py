"""Machine-readable monitor sinks: JSONL event log + Prometheus textfile.

Both plug into :class:`~..monitor.monitor.MonitorMaster` next to the
CSV/TensorBoard/WandB writers (same ``write_events([(name, value, step)])``
contract) and exist because the reference trio's outputs are either
binary (TB event files) or external services (WandB): perf attribution
tooling wants something it can ``json.loads`` or scrape.

- :class:`JsonlSink` appends one JSON object per event — the replayable
  ground-truth log (``{"name", "value", "step", "time"}``).
- :class:`PrometheusTextfileSink` maintains the *latest* value per metric
  and atomically rewrites a textfile in Prometheus exposition format, the
  standard node-exporter textfile-collector handoff: point
  ``--collector.textfile.directory`` at its directory and the job's gauges
  show up in every scrape without running an HTTP server inside the
  training process.
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from pathlib import Path
from typing import Callable, Sequence


class JsonlSink:
    """Append-only JSONL event log with a persistent file handle.

    ``rotate_mb`` (config, default 0 = off) bounds the file for
    long-running serving jobs: at flush boundaries only (the persistent
    handle is never churned per event), a file past the limit rolls to
    ``<name>.jsonl.1`` (one generation kept — the rolling window plus
    whatever external log shipping already collected) and a fresh file
    takes over. ``clock`` stamps event wall time and is injectable; the
    default is ``time.time`` because a log record's timestamp is
    calendar time, not a measured interval."""

    # subclass seams (RequestLogSink): filename suffix + flush cadence
    SUFFIX = ".jsonl"
    FLUSH_EVERY = 64

    def __init__(self, cfg: dict, clock: Callable[[], float] = time.time):
        path = Path(cfg.get("output_path", "./monitor")) / (
            cfg.get("job_name", "DeepSpeedTpuJob") + self.SUFFIX)
        path.parent.mkdir(parents=True, exist_ok=True)
        self.path = path
        self.clock = clock
        self._f = open(path, "a", encoding="utf-8")
        # 0 = rely on close(); N = fsync-less flush every N events
        self._flush_every = int(cfg.get("flush_every", self.FLUSH_EVERY))
        self._pending = 0
        self._rotate_bytes = int(float(cfg.get("rotate_mb", 0))
                                 * 1024 * 1024)
        self.rotations = 0

    def _write_line(self, line: str) -> None:
        self._f.write(line + "\n")
        self._pending += 1
        # the size check keeps rotate_mb honest even with flush_every=0
        # ("rely on close()"): a standalone sink must not grow unbounded
        # just because nothing else calls flush()
        if (self._flush_every and self._pending >= self._flush_every) or \
                (self._rotate_bytes and not self._f.closed
                 and self._f.tell() >= self._rotate_bytes):
            self.flush()

    def write_events(self, events: Sequence[tuple]) -> None:
        now = self.clock()
        for name, value, step in events:
            self._write_line(json.dumps(
                {"name": name, "value": float(value), "step": int(step),
                 "time": now}, separators=(",", ":")))

    def _maybe_rotate(self) -> None:
        # flush-boundary-only: the handle persists between rotations, and
        # a half-written line can never straddle a roll (we just flushed)
        if not self._rotate_bytes or self._f.closed:
            return
        if self._f.tell() < self._rotate_bytes:
            return
        self._f.close()
        os.replace(self.path, self.path.with_name(self.path.name + ".1"))
        self._f = open(self.path, "a", encoding="utf-8")
        self.rotations += 1

    def flush(self) -> None:
        self._pending = 0
        if not self._f.closed:
            self._f.flush()
        self._maybe_rotate()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()
        self._pending = 0


_PROM_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str, prefix: str = "dstpu") -> str:
    """Metric name → legal Prometheus identifier (``Serve/ttft_s/p99`` →
    ``dstpu_serve_ttft_s_p99``)."""
    n = _PROM_BAD_CHARS.sub("_", name.strip()).strip("_").lower()
    full = f"{prefix}_{n}" if prefix else n
    if not _PROM_NAME_OK.match(full):
        full = "_" + full
    return full


class PrometheusTextfileSink:
    """Latest-value gauge exporter in Prometheus exposition format.

    The textfile is rewritten atomically (tmp + rename) on every flush so a
    concurrent scrape never reads a torn file."""

    def __init__(self, cfg: dict):
        d = Path(cfg.get("output_path", "./monitor"))
        d.mkdir(parents=True, exist_ok=True)
        self.path = d / (cfg.get("job_name", "DeepSpeedTpuJob") + ".prom")
        self.prefix = cfg.get("prefix", "dstpu")
        self._values: dict[str, float] = {}
        self._source: dict[str, str] = {}    # sanitized -> original name
        self._step = 0
        self._dirty = False

    def write_events(self, events: Sequence[tuple]) -> None:
        # buffered: the textfile is rewritten at flush() (report boundaries
        # / close), not per event batch
        for name, value, step in events:
            pn = prometheus_name(name, self.prefix)
            self._values[pn] = float(value)
            self._source[pn] = name
            self._step = max(self._step, int(step))
            self._dirty = True

    def flush(self) -> None:
        if not self._dirty:
            return
        # The step is its own gauge, NOT a label: a step label would mint a
        # brand-new Prometheus series per metric per step (label sets key
        # series), fragmenting graphs and blowing up TSDB head cardinality.
        step_name = prometheus_name("step", self.prefix)
        lines = [f"# HELP {step_name} deepspeed_tpu metric 'step'",
                 f"# TYPE {step_name} gauge",
                 f"{step_name} {self._step}"]
        for name in sorted(self._values):
            lines.append(f"# HELP {name} deepspeed_tpu metric "
                         f"{self._source.get(name, name)!r}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {format_prometheus_value(self._values[name])}")
        tmp = self.path.with_suffix(".prom.tmp")
        tmp.write_text("\n".join(lines) + "\n", encoding="utf-8")
        os.replace(tmp, self.path)
        self._dirty = False

    def close(self) -> None:
        self.flush()


def format_prometheus_value(v: float) -> str:
    """Exposition-format scalar: non-finite values spell ``+Inf`` /
    ``-Inf`` / ``NaN`` (a bare ``nan``/``inf`` from ``%g`` is rejected by
    strict scrapers)."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return f"{v:.10g}"


def parse_prometheus_textfile(text: str) -> dict[str, float]:
    """Tiny exposition-format reader (tests + doctors): name -> value."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})?\s+(\S+)", line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out
