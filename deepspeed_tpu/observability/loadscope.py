"""Arrival & scaling observatory: what the *load* would pay for.

The roadmap's next wall — the elastic autoscaling control loop — needs a
measured signal surface before any scale decision is more than a guess:
what rate is traffic arriving at (and is it trending up), how much of it
can each replica actually serve, and how long until the live SLO burns?
This module answers those three questions from data the serving engine
already holds, repeating the repo's measure→price→build loop (kvscope →
host KV, commscope → quantized collectives, workload → speculation):

- **arrival-process analytics** — a bounded event ring over the submit
  hook (injectable clock, zero device syncs): rolling arrival rate over
  a time window, interarrival coefficient of variation (burstiness —
  ~0 uniform, ~1 Poisson, >1 bursty), prompt/decode token demand rates,
  and a rate-trend estimator (first-vs-second half-window slope).
  Exported as ``Serve/arrival_*`` gauges.
- **service-rate & utilization estimation** — decode slot-throughput
  (tokens per slot-second from the span ring's ``decode_step`` spans)
  and prefill token rate (the ``_prefill_rate`` spelling the tiered_kv
  lever already trusts) give a serviceable token rate; utilization is
  the queueing-model ρ = offered token rate / serviceable token rate,
  with a predicted steady-state queue wait from an M/G/k-style
  (Allen–Cunneen) approximation. Unmeasured inputs degrade to ``None``
  with a stated reason — never an exception (the PR-6/13 contract).
- **SLO-burn forecasting** — arrival trend + ρ + the live
  :class:`~.slo.SLOConfig` join into a time-to-violation horizon
  (``Serve/slo_ttv_s``; null when not trending toward violation), and
  :func:`score_what_ifs` prices add_replica / remove_replica /
  prefill↔decode-rebalance moves by predicted goodput and queue-wait
  delta — the ``scaling`` lever in the capacity advisor and the input
  ``FleetEngine.scaling_report()`` aggregates.

Cost discipline: everything is host-side arithmetic over a bounded
deque plus one pass over the span ring at *readout* time (scrape /
report cadence, never per token). Disabled (the default) the serving
engine holds ``loadscope = None`` and pays one ``is not None`` per
submit — zero new compiled programs (the ``bench_serving.py --smoke``
compile-freeze gate stays the acceptance test). Validation is replay-
backtested: :func:`~.replay.scaling_backtest` replays a synthetic
diurnal+bursty trace on the fake clock at two fleet sizes and scores
predicted queue-wait/goodput deltas against achieved (±10 pt band).
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Callable, Optional

from .metrics import MetricsRegistry

SCALING_SCHEMA = "dstpu.loadscope.v1"


@dataclasses.dataclass
class LoadScopeConfig:
    """Arrival/scaling-observatory knobs (``ServingConfig.loadscope``).
    Constructing one (or passing a dict) opts in; ``None`` on the
    serving config means no observatory is built at all."""

    enabled: bool = True
    # Rolling window for the arrival estimators, seconds on the
    # injectable clock. Rates, CV, and trend are computed over events
    # younger than this; size it to a few times the scrape interval.
    window_s: float = 60.0
    # Bounded arrival ring (one small tuple per submit) — the window
    # above trims by age, this caps worst-case memory under floods.
    max_events: int = 8192
    # Utilization above which the scaling advisor starts scoring
    # add_replica urgency (score ramps 0→100 between here and ρ=1).
    rho_high: float = 0.85
    # TTV values beyond this horizon report as null ("not trending
    # toward violation on any actionable timescale").
    ttv_horizon_s: float = 3600.0

    def __post_init__(self):
        if self.window_s <= 0:
            raise ValueError(f"loadscope window_s must be > 0, "
                             f"got {self.window_s}")
        if self.max_events < 2:
            raise ValueError(f"loadscope max_events must be >= 2, "
                             f"got {self.max_events}")
        if not 0.0 < self.rho_high < 1.0:
            raise ValueError(f"loadscope rho_high must be in (0, 1), "
                             f"got {self.rho_high}")
        if self.ttv_horizon_s <= 0:
            raise ValueError(f"loadscope ttv_horizon_s must be > 0, "
                             f"got {self.ttv_horizon_s}")

    @classmethod
    def from_any(cls, cfg: "LoadScopeConfig | dict | None") \
            -> "LoadScopeConfig | None":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown loadscope config keys: "
                             f"{sorted(unknown)}")
        return cls(**cfg)


def _clamp01(x: float) -> float:
    return 0.0 if x < 0.0 else (1.0 if x > 1.0 else x)


def goodput_frac(rho: "float | None") -> Optional[float]:
    """Steady-state serviceable fraction of offered work at utilization
    ``rho``: 1 under capacity, capacity/offered past saturation. The
    model side of the backtest's window-throughput measurement."""
    if rho is None:
        return None
    if rho <= 1.0:
        return 1.0
    return 1.0 / rho


def predicted_queue_wait_s(rho: "float | None", k: "int | None",
                           mean_service_s: "float | None",
                           arrival_cv: "float | None" = None) \
        -> Optional[float]:
    """Predicted steady-state queue wait for an M/G/k-style station:
    the Allen–Cunneen approximation ``(Ca²+Cs²)/2 · Wq(M/M/k)`` with
    ``Wq(M/M/k) ≈ ρ^√(2(k+1)) / (k(1-ρ)) · E[S]`` (Sakasegawa's form).
    Service-time variability is unmeasured, so Cs² is taken as 1
    (exponential); ``arrival_cv`` defaults to Poisson when unmeasured.
    None when any input is unmeasured or the station is saturated
    (ρ ≥ 1: the steady-state wait is unbounded — callers report the
    saturation flag instead of a fabricated number)."""
    if rho is None or mean_service_s is None or not k or k < 1:
        return None
    if rho <= 0.0:
        return 0.0
    if rho >= 1.0:
        return None
    ca2 = arrival_cv * arrival_cv if arrival_cv is not None else 1.0
    mmk = (rho ** math.sqrt(2.0 * (k + 1))) / (k * (1.0 - rho))
    return max(0.0, 0.5 * (ca2 + 1.0) * mmk * float(mean_service_s))


def time_to_violation_s(*, rate_per_s: "float | None",
                        trend_per_s2: "float | None",
                        rho: "float | None", slo=None,
                        horizon_s: float = 3600.0) -> Optional[float]:
    """Seconds until the arrival trend pushes utilization to saturation
    (ρ → 1), the point past which every latency SLO burns: 0 when
    already saturated, null when any input is unmeasured, no latency
    SLO is armed, the trend is flat/falling, or the crossing lies
    beyond ``horizon_s`` (not trending toward violation on any
    actionable timescale)."""
    if slo is None or not (getattr(slo, "ttft_p99_s", 0.0)
                           or getattr(slo, "tpot_p99_s", 0.0)):
        return None
    if rate_per_s is None or rho is None or rate_per_s <= 0:
        return None
    if rho >= 1.0:
        return 0.0
    if trend_per_s2 is None or trend_per_s2 <= 0:
        return None
    # ρ scales linearly with the arrival rate: the violating rate is
    # rate/ρ, and the trend says how fast we approach it
    ttv = (rate_per_s / rho - rate_per_s) / trend_per_s2
    return ttv if ttv <= horizon_s else None


def score_what_ifs(*, rho: "float | None", replicas: int = 1,
                   slots: "int | None" = None,
                   mean_service_s: "float | None" = None,
                   arrival_cv: "float | None" = None,
                   rho_high: float = 0.85,
                   rho_prefill: "float | None" = None,
                   rho_decode: "float | None" = None,
                   prefill_replicas: int = 0) -> list:
    """Score the scaling moves the autoscaler could make, from measured
    utilization. Each entry carries the predicted ρ / queue-wait /
    goodput before and after plus a 0–100 urgency score:

    - ``add_replica`` — scores the overload headroom: 0 at/below
      ``rho_high``, ramping to 100 at saturation (monotone in ρ).
    - ``remove_replica`` — scores idle capacity: high only when the
      fleet is far under ``rho_high`` AND removing one keeps it there.
    - ``rebalance_prefill_decode`` — only on a disaggregated fleet with
      both per-phase utilizations measured: scores their imbalance.

    ρ unmeasured → empty list (the capacity lever self-demotes with the
    reason; this function never guesses)."""
    if rho is None:
        return []
    out = []
    n = max(1, int(replicas))
    k_each = max(1, int(slots or 1))

    def _wait(r, k):
        return predicted_queue_wait_s(r, k, mean_service_s, arrival_cv)

    def _entry(action, rho_after, k_after, score):
        w_now = _wait(rho, k_each * n)
        w_after = _wait(rho_after, k_after)
        g_now, g_after = goodput_frac(rho), goodput_frac(rho_after)
        return {
            "action": action,
            "rho_now": rho, "rho_after": rho_after,
            "saturated_now": rho >= 1.0,
            "predicted_queue_wait_s_now": w_now,
            "predicted_queue_wait_s_after": w_after,
            "queue_wait_delta_s": (w_now - w_after
                                   if w_now is not None
                                   and w_after is not None else None),
            "goodput_now": g_now, "goodput_after": g_after,
            "goodput_delta": (g_after - g_now
                              if g_now is not None and g_after is not None
                              else None),
            "score": round(float(score), 2),
        }

    # add_replica: homogeneous replicas — n→n+1 scales serviceable rate
    # by (n+1)/n, so ρ falls by n/(n+1)
    rho_add = rho * n / (n + 1)
    score_add = 100.0 * _clamp01((rho - rho_high)
                                 / max(1e-9, 1.0 - rho_high))
    out.append(_entry("add_replica", rho_add, k_each * (n + 1), score_add))

    if n >= 2:
        rho_rm = rho * n / (n - 1)
        rho_low = 0.5 * rho_high
        score_rm = (100.0 * _clamp01((rho_low - rho) / max(1e-9, rho_low))
                    if rho_rm < rho_high else 0.0)
        out.append(_entry("remove_replica", rho_rm, k_each * (n - 1),
                          score_rm))

    if (prefill_replicas >= 1 and n - prefill_replicas >= 1
            and rho_prefill is not None and rho_decode is not None):
        # moving one replica across the prefill/decode split helps only
        # when the phases are imbalanced AND the hot side is actually hot
        imbalance = abs(rho_prefill - rho_decode)
        hot = max(rho_prefill, rho_decode)
        score_rb = 100.0 * _clamp01(imbalance) * _clamp01(
            (hot - rho_high) / max(1e-9, 1.0 - rho_high))
        donor_ok = ((n - prefill_replicas >= 2)
                    if rho_prefill > rho_decode
                    else (prefill_replicas >= 2))
        out.append({
            "action": "rebalance_prefill_decode",
            "direction": ("decode_to_prefill"
                          if rho_prefill > rho_decode
                          else "prefill_to_decode"),
            "rho_prefill": rho_prefill, "rho_decode": rho_decode,
            "imbalance": imbalance,
            "score": round(float(score_rb if donor_ok else 0.0), 2),
        })
    return out


class LoadScope:
    """Submit-path arrival analytics into ``Serve/arrival_*`` plus the
    utilization / forecast readout (:meth:`report`).

    ``on_submit`` runs on the serving intake (the submit hook in
    ``ServingEngine.submit``); :meth:`report` is the scrape-cadence
    readout — the engine feeds it the span-measured service rates and
    the live SLO config, and it degrades field-by-field to ``None``
    when any input is unmeasured. All state is host-side and bounded;
    ``clock`` is injectable like every observability clock."""

    def __init__(self, cfg: "LoadScopeConfig | dict | None" = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.cfg = LoadScopeConfig.from_any(cfg) or LoadScopeConfig()
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.clock = clock if clock is not None else (lambda: 0.0)
        # (t, prompt_tokens, decode_budget_tokens) per submit, trimmed
        # by window age at readout and capped by max_events always
        self._events: deque = deque(maxlen=self.cfg.max_events)
        self.requests = 0
        self.prompt_tokens = 0
        self.decode_tokens = 0          # budgeted (max_new), not emitted
        # backtest attachment: when a scaling_backtest has validated the
        # advisor on this build, its predicted-vs-achieved block rides
        # every report (and the capacity lever marks itself backtested)
        self.achieved: Optional[dict] = None
        # calibration seam: replaces the engine's span-measured service
        # rates in report(). Needed when span time and loop time diverge
        # — on a ticking fake clock most reads land OUTSIDE the compute
        # spans, so the replay harness measures capacity with a
        # saturation probe instead. None (the default) trusts the spans.
        self.service_override: Optional[dict] = None

    # --------------------------------------------------------------- intake
    def on_submit(self, prompt_len: int, max_new: int,
                  queue_depth: int = 0) -> None:
        """Record one accepted submit (the engine calls this after the
        scheduler admitted the request to its queue)."""
        t = self.clock()
        self._events.append((t, int(prompt_len), int(max_new)))
        self.requests += 1
        self.prompt_tokens += int(prompt_len)
        self.decode_tokens += int(max_new)
        arr = self.arrival(now=t)
        r = self.registry
        r.counter("Serve/arrival_requests").inc()
        for key, name in ((arr["rate_per_s"], "Serve/arrival_rate_per_s"),
                          (arr["interarrival_cv"], "Serve/arrival_cv"),
                          (arr["trend_per_s2"], "Serve/arrival_trend_per_s2"),
                          (arr["prompt_tokens_per_s"],
                           "Serve/arrival_prompt_tokens_per_s"),
                          (arr["decode_tokens_per_s"],
                           "Serve/arrival_decode_tokens_per_s"),
                          (arr["offered_tokens_per_s"],
                           "Serve/offered_tokens_per_s")):
            if key is not None:
                r.gauge(name).set(key)

    # -------------------------------------------------------------- readout
    def _window(self, now: "float | None" = None) -> list:
        t = self.clock() if now is None else now
        lo = t - self.cfg.window_s
        return [e for e in self._events if e[0] >= lo]

    def arrival(self, now: "float | None" = None) -> dict:
        """The arrival-process estimate over the rolling window. Every
        field is ``None`` until enough events support it: rates need 2,
        CV needs 3, the trend needs 4 — unmeasured, not guessed."""
        win = self._window(now)
        out = {"window_s": self.cfg.window_s,
               "requests_in_window": len(win),
               "rate_per_s": None, "interarrival_cv": None,
               "trend_per_s2": None, "prompt_tokens_per_s": None,
               "decode_tokens_per_s": None, "offered_tokens_per_s": None}
        if len(win) < 2:
            return out
        span = win[-1][0] - win[0][0]
        if span <= 0:
            return out
        # rate over the observed span: (n-1) interarrivals across it
        out["rate_per_s"] = (len(win) - 1) / span
        out["prompt_tokens_per_s"] = sum(e[1] for e in win[:-1]) / span
        out["decode_tokens_per_s"] = sum(e[2] for e in win[:-1]) / span
        out["offered_tokens_per_s"] = (out["prompt_tokens_per_s"]
                                       + out["decode_tokens_per_s"])
        gaps = [b[0] - a[0] for a, b in zip(win, win[1:])]
        if len(gaps) >= 2:
            mean = sum(gaps) / len(gaps)
            if mean > 0:
                var = sum((g - mean) ** 2 for g in gaps) / len(gaps)
                out["interarrival_cv"] = math.sqrt(var) / mean
        if len(win) >= 4:
            # rate slope: second half-window rate minus first, over the
            # half-window gap — a two-point regression that is robust to
            # the bursty on/off structure a full LSQ fit would chase
            mid_t = win[0][0] + 0.5 * span
            first = [e for e in win if e[0] < mid_t]
            second = [e for e in win if e[0] >= mid_t]
            if len(first) >= 2 and len(second) >= 2:
                s1 = first[-1][0] - first[0][0]
                s2 = second[-1][0] - second[0][0]
                if s1 > 0 and s2 > 0:
                    r1 = (len(first) - 1) / s1
                    r2 = (len(second) - 1) / s2
                    out["trend_per_s2"] = (r2 - r1) / (0.5 * span)
        return out

    def mean_decode_budget(self, now: "float | None" = None) \
            -> Optional[float]:
        """Mean decode-token budget (max_new) per windowed request — the
        per-request service demand the queue-wait model prices."""
        win = self._window(now)
        if not win:
            return None
        return sum(e[2] for e in win) / len(win)

    # --------------------------------------------------------------- report
    def report(self, *, service: "dict | None" = None, slo=None,
               queue_depth: "int | None" = None,
               replicas: int = 1) -> dict:
        """Join the arrival estimate with engine-measured service rates
        into the scaling snapshot (``GET /scaling``'s body, the
        ``loadscope`` section of the capacity report, and the per-
        replica row of ``FleetEngine.scaling_report()``).

        ``service`` is the engine's measured side: ``slots`` plus
        (possibly ``None``) ``decode_tokens_per_slot_s`` and
        ``prefill_tokens_per_s``. Missing measurements degrade the
        dependent fields to ``None`` with a reason — never raise."""
        arr = self.arrival()
        if self.service_override is not None:
            service = self.service_override
        svc = dict(service or {})
        slots = int(svc.get("slots") or 0)
        per_slot = svc.get("decode_tokens_per_slot_s")
        prefill_rate = svc.get("prefill_tokens_per_s")
        serviceable = (slots * per_slot
                       if per_slot is not None and slots > 0 else None)
        svc.setdefault("serviceable_decode_tokens_per_s", serviceable)

        reasons = []
        if arr["rate_per_s"] is None:
            reasons.append("arrival rate unmeasured "
                           "(fewer than 2 submits in the window)")
        rho_decode = rho_prefill = None
        if serviceable is None:
            reasons.append("decode service rate unmeasured "
                           "(spans off or no decode steps in the ring)")
        elif arr["decode_tokens_per_s"] is not None and serviceable > 0:
            rho_decode = arr["decode_tokens_per_s"] / serviceable
        if prefill_rate is None:
            reasons.append("prefill rate unmeasured "
                           "(spans off or no prefill chunks in the ring)")
        elif arr["prompt_tokens_per_s"] is not None and prefill_rate > 0:
            rho_prefill = arr["prompt_tokens_per_s"] / prefill_rate
        rho = (max(v for v in (rho_decode, rho_prefill) if v is not None)
               if rho_decode is not None or rho_prefill is not None
               else None)

        mean_budget = self.mean_decode_budget()
        mean_service_s = (mean_budget / per_slot
                          if mean_budget is not None and per_slot
                          else None)
        wait = predicted_queue_wait_s(rho, slots * max(1, int(replicas)),
                                      mean_service_s,
                                      arr["interarrival_cv"])
        ttv = time_to_violation_s(
            rate_per_s=arr["rate_per_s"],
            trend_per_s2=arr["trend_per_s2"], rho=rho, slo=slo,
            horizon_s=self.cfg.ttv_horizon_s)
        slo_armed = bool(slo is not None
                         and (getattr(slo, "ttft_p99_s", 0.0)
                              or getattr(slo, "tpot_p99_s", 0.0)))
        if not slo_armed:
            reasons.append("no latency SLO armed "
                           "(serving.slo ttft/tpot targets unset) — "
                           "time-to-violation undefined")

        what_ifs = score_what_ifs(
            rho=rho, replicas=replicas, slots=slots,
            mean_service_s=mean_service_s,
            arrival_cv=arr["interarrival_cv"],
            rho_high=self.cfg.rho_high)

        r = self.registry
        for v, name in ((rho, "Serve/utilization"),
                        (wait, "Serve/predicted_queue_wait_s"),
                        (ttv, "Serve/slo_ttv_s")):
            if v is not None:
                r.gauge(name).set(v)

        out = {
            "schema": SCALING_SCHEMA,
            "requests": self.requests,
            "queue_depth": queue_depth,
            "replicas": int(replicas),
            "arrival": arr,
            "service": svc,
            "utilization": {
                "rho": rho, "rho_decode": rho_decode,
                "rho_prefill": rho_prefill,
                "saturated": (rho >= 1.0) if rho is not None else None,
                "mean_service_s": mean_service_s,
                "predicted_queue_wait_s": wait,
                "rho_high": self.cfg.rho_high,
            },
            "forecast": {
                "slo_armed": slo_armed,
                "slo_ttv_s": ttv,
                "trend_per_s2": arr["trend_per_s2"],
            },
            "what_ifs": what_ifs,
            "unmeasured": reasons,
        }
        if self.achieved is not None:
            out["achieved"] = dict(self.achieved)
        return out
