"""KV residency observatory: eviction regret, session heat, host-tier math.

The ROADMAP's tiered-KV wall ("host-offloaded pages for million-session
residency") starts from a cost the paged cache pays silently today: when
``PagePool._evict`` reclaims tree-held pages under pressure, the NEXT
admission of the same prefix re-pays its prefill. ZeRO-Infinity's
memory-wall playbook (PAPERS.md) would demote those idle pages to pinned
host memory instead — but whether that trade wins depends on numbers
nothing measured yet. This module measures all three sides of it:

- **ghost-tree eviction-regret ledger** — evicted tree entries leave a
  bounded ARC-style *ghost list* of block keys (rolling-hash of the full
  token prefix, one entry per evicted block/tail) stamped with their
  eviction event and time. The admission-path probe (beside the
  ``workload.py`` hook) matches an incoming prompt's block boundaries
  against the ghosts: every prefill token re-paid *because of* a past
  eviction is counted (``Serve/eviction_regret_tokens``, capped at the
  tokens the admission actually recomputes) and attributed to the
  eviction event that caused it, with time-to-regret / reuse-interval
  histograms. Uniform traffic that never evicts reports exactly zero.
- **session-lifecycle heat tracking** — a per-``session_id`` state
  machine (active → idle → resumed / dead) on the injectable clock:
  idle-interval and resume-count histograms, plus the *HBM
  byte-seconds-held-while-idle* integral — the two costs a host tier
  trades (idle HBM residency vs regretted recompute). Transitions emit
  ``session_active``/``session_idle`` spans, rendered as per-session
  residency tracks in the Perfetto export.
- **measured host-tier inputs** — :func:`measure_copy_bandwidth` times a
  real host↔device transfer (the AIO/offload discipline: measured, or
  degraded to None with one warning — never a guess), and the engine
  joins it with the span ring's measured prefill throughput into the
  ``tiered_kv`` capacity-advisor lever (``capacity.py``): projected
  resume-TTFT via host-restore (page bytes ÷ measured copy bandwidth)
  vs measured prefill-recompute cost, scored by observed regret traffic.

Cost discipline, like every layer before it: everything here is
host-side Python over arrays the scheduler already holds — zero device
syncs, zero new compiled programs (the ``bench_serving.py --smoke`` /
``bench_kv_residency.py --smoke`` compile-freeze gates are the
acceptance tests). Disabled (the default) the serving engine holds
``kvscope = None`` and the page pool ``on_evict = None``: one ``is not
None`` per admission/retirement/eviction, nothing else. The
copy-bandwidth probe runs only when a capacity report asks for it.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from ..utils.logging import warning_once
from .metrics import MetricsRegistry
from .workload import prefix_hashes, token_hash

__all__ = ["KVScope", "KVScopeConfig", "measure_copy_bandwidth"]

# session states (readout strings; the machine itself is live-set + stamps)
ACTIVE = "active"
IDLE = "idle"
DEAD = "dead"


@dataclasses.dataclass
class KVScopeConfig:
    """KV residency observatory knobs (``ServingConfig.kvscope``).
    Constructing one (or passing a dict) opts in; ``None`` on the serving
    config means none of the machinery is built."""

    enabled: bool = True
    # Bounded ghost list of recently evicted block keys (ARC-style: the
    # ghosts remember what the cache forgot). Each entry is one dict slot.
    ghost_entries: int = 4096
    # Idle sessions older than this are scored DEAD: their held pages are
    # pure waste a host tier would NOT need to keep either (they never
    # resume) — the advisor's idle distribution splits on it.
    dead_after_s: float = 300.0
    # LRU bound on tracked sessions; evicting one finalizes its stats.
    max_sessions: int = 4096
    # Bounded per-eviction-event attribution ring (regret per event).
    max_events: int = 512
    # Host↔device copy-bandwidth probe transfer size (bytes).
    probe_bytes: int = 1 << 23

    def __post_init__(self):
        for knob in ("ghost_entries", "max_sessions", "max_events",
                     "probe_bytes"):
            if getattr(self, knob) < 1:
                raise ValueError(f"kvscope {knob} must be >= 1, "
                                 f"got {getattr(self, knob)}")
        if self.dead_after_s <= 0:
            raise ValueError(f"kvscope dead_after_s must be > 0, "
                             f"got {self.dead_after_s}")

    @classmethod
    def from_any(cls, cfg: "KVScopeConfig | dict | None") \
            -> "KVScopeConfig | None":
        if cfg is None or isinstance(cfg, cls):
            return cfg
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown kvscope config keys: "
                             f"{sorted(unknown)}")
        return cls(**cfg)


def measure_copy_bandwidth(nbytes: int = 1 << 23, repeats: int = 3,
                           device=None,
                           clock: Callable[[], float] = time.perf_counter) \
        -> dict:
    """Measured host↔device copy bandwidth: time ``repeats`` blocking
    ``device_put`` (H2D — the host-tier RESTORE path) and ``device_get``
    (D2H — the demotion path) transfers of ``nbytes`` and report the
    best of each. Every field is PRESENT; a backend where the probe
    fails (or a clock that doesn't advance) degrades fields to None
    with one warning — never a raise, never an invented number."""
    out = {"bytes": int(nbytes), "repeats": int(repeats),
           "h2d_gbps": None, "d2h_gbps": None, "h2d_s": None, "d2h_s": None}
    try:
        import jax

        if device is None:
            device = jax.devices()[0]
        host = np.zeros(max(1, nbytes // 4), np.float32)
        buf = jax.device_put(host, device)         # warmup (alloc paths)
        jax.block_until_ready(buf)
        h2d, d2h = [], []
        for _ in range(repeats):
            t0 = clock()
            buf = jax.device_put(host, device)
            jax.block_until_ready(buf)
            h2d.append(clock() - t0)
            t0 = clock()
            np.asarray(jax.device_get(buf))
            d2h.append(clock() - t0)
        real = nbytes if nbytes >= 4 else 4
        if min(h2d) > 0:
            out["h2d_s"] = min(h2d)
            out["h2d_gbps"] = real / min(h2d) / 1e9
        if min(d2h) > 0:
            out["d2h_s"] = min(d2h)
            out["d2h_gbps"] = real / min(d2h) / 1e9
    except Exception as e:
        warning_once(f"kvscope copy-bandwidth probe failed on this "
                     f"backend ({e!r}) — host-tier lever degrades to "
                     "score 0 (unmeasured, not guessed)")
    return out


class _Session:
    """One tracked session's residency state."""

    __slots__ = ("live", "state", "start_t", "active_since", "idle_since",
                 "last_t", "resumes", "regret_tokens", "regret_resumes",
                 "held_tokens", "idle_token_s")

    def __init__(self, t: float):
        self.live: set = set()          # rids currently admitted/decoding
        self.state = ACTIVE
        self.start_t = t
        self.active_since = t
        self.idle_since: Optional[float] = None
        self.last_t = t
        self.resumes = 0
        self.regret_tokens = 0          # regretted re-prefill this session paid
        self.regret_resumes = 0
        self.held_tokens = 0            # longest registered prompt (tree-held)
        self.idle_token_s = 0.0         # closed idle integral, token-seconds


class KVScope:
    """The residency observatory an engine holds when
    ``serving.kvscope`` is set. Three hooks drive it:

    - ``on_evictions(entries)`` — the page pool's ``on_evict`` seam: one
      call per eviction EVENT, entries carrying the evicted block's full
      token prefix + its block token count;
    - ``on_admit(req)`` — beside the workload hook, once per admission:
      ghost probe + session resume accounting;
    - ``on_retire(req)`` — once per terminal request: session idle edge.

    ``clock`` is the engine's injectable clock (fake-clock tests drive
    the whole lifecycle); ``probe_clock`` times the REAL copy-bandwidth
    probe and stays wall time unless a test injects one."""

    def __init__(self, cfg: "KVScopeConfig | dict | None" = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Optional[Callable[[], float]] = None,
                 spans=None, page_size: int = 0,
                 per_token_bytes: Optional[int] = None,
                 tree_held_tokens: Optional[Callable[[], int]] = None,
                 probe_clock: Callable[[], float] = time.perf_counter):
        self.cfg = KVScopeConfig.from_any(cfg) or KVScopeConfig()
        # the pool-truth cap for "reclaimable now": per-session
        # held_tokens don't see which session a later eviction hit, so
        # their sum can exceed what the tree still holds — the engine
        # wires the pool's live tree-held token count here
        self.tree_held_tokens = tree_held_tokens
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.clock = clock if clock is not None else time.perf_counter
        self.spans = spans
        self.page_size = int(page_size)
        self.per_token_bytes = per_token_bytes
        self.probe_clock = probe_clock
        # ghost list: (prefix_len, prefix_hash) -> {block, t, event}
        self.ghosts: OrderedDict = OrderedDict()
        self.ghost_added = 0
        self.ghost_overflow = 0
        self.ghost_hits = 0
        self.stale_ghost_hits = 0       # ghost for a block the tree re-holds
        self.restored_ghost_hits = 0    # ghost popped by a host-tier restore
        self.host_restored_resumes = 0  # resumes served from the host tier
        # per-eviction-event attribution, bounded
        self._events: OrderedDict = OrderedDict()
        self._event_seq = 0
        # regret accounting
        self.regret_tokens = 0
        self.regret_admissions = 0
        self.prefill_tokens_paid = 0    # sum of (P - skip) over admissions
        # sessions
        self.sessions: "OrderedDict[object, _Session]" = OrderedDict()
        self.sessions_started = 0
        self.sessions_resumed = 0
        self.sessions_finalized = 0
        self.regret_resumes = 0
        self._idle_token_s_closed = 0.0  # finalized sessions' integrals
        # fleet seam (serving/fleet.py): called as (session_id,
        # regret_tokens) when a RESUME re-pays ghost-covered prefill —
        # the router checks whether the sticky replica is the one that
        # evicted the prefix (Fleet/affinity_regret). None outside a fleet.
        self.on_regret_resume = None
        self._copy_bw: Optional[dict] = None

    # ------------------------------------------------------------ evictions
    def on_evictions(self, entries: list) -> None:
        """One eviction EVENT (one ``PagePool._evict`` pass that freed
        tree entries): stamp every evicted block key into the ghost
        list. ``entries`` carry ``tokens`` (full token prefix from the
        tree root through the entry) and ``block`` (the entry's own
        token count — ``page_size`` for a full block, the tail length
        for a partial tail)."""
        if not entries:
            return
        t = self.clock()
        self._event_seq += 1
        eid = self._event_seq
        self._events[eid] = {"event": eid, "t": t, "ghosts": len(entries),
                             "regret_tokens": 0, "hits": 0}
        while len(self._events) > self.cfg.max_events:
            self._events.popitem(last=False)
        for e in entries:
            toks = e["tokens"]
            key = (len(toks), token_hash(toks))
            self.ghosts[key] = {"block": int(e["block"]), "t": t,
                                "event": eid}
            self.ghosts.move_to_end(key)
            self.ghost_added += 1
        while len(self.ghosts) > self.cfg.ghost_entries:
            self.ghosts.popitem(last=False)
            self.ghost_overflow += 1
        r = self.registry
        r.counter("Serve/kv_ghosts_added").inc(len(entries))
        r.gauge("Serve/kv_ghost_entries").set(float(len(self.ghosts)))

    # ------------------------------------------------------------ admission
    def _probe_ghosts(self, prompt: np.ndarray, shared: int, skip: int,
                      now: float, restored: int = 0) -> int:
        """Match the prompt's block boundaries against the ghost list
        and return the regret: re-paid prefill tokens this admission
        owes to past evictions. A hit at block ``b < shared`` means the
        tree holds that block again (a later registration) — the ghost
        is stale, dropped without regret. A hit at ``shared <= b <
        shared + restored`` is a block the host tier restored
        (serving/hostkv.py): the resume paid copy bytes, not prefill —
        the ghost pops WITHOUT booking regret tokens. The total is
        capped at the tokens the admission actually recomputes
        (``P - 1 - skip``: even a fully live tree re-runs the final
        token's forward)."""
        P = len(prompt)
        cap = max(0, P - 1 - skip)
        if not self.ghosts or not self.page_size \
                or (cap == 0 and not restored):
            return 0
        hits = []
        for b, (length, h) in enumerate(
                prefix_hashes(prompt, self.page_size)):
            g = self.ghosts.pop((length, h), None)
            if g is None:
                continue
            if b < shared:
                self.stale_ghost_hits += 1
                continue
            if b < shared + restored:
                self.restored_ghost_hits += 1
                self.registry.counter(
                    "Serve/kv_restored_ghost_hits").inc()
                continue
            hits.append(g)
        if cap == 0:
            self.registry.gauge("Serve/kv_ghost_entries").set(
                float(len(self.ghosts)))
            return 0
        if P % self.page_size:
            g = self.ghosts.pop((P, token_hash(prompt)), None)
            if g is not None:
                hits.append(g)
        if not hits:
            return 0
        r = self.registry
        regret = 0
        for g in hits:
            take = min(int(g["block"]), cap - regret)
            if take <= 0:
                break
            regret += take
            self.ghost_hits += 1
            ev = self._events.get(g["event"])
            if ev is not None:
                ev["regret_tokens"] += take
                ev["hits"] += 1
            r.histogram("Serve/kv_time_to_regret_s").observe(now - g["t"])
        r.gauge("Serve/kv_ghost_entries").set(float(len(self.ghosts)))
        return regret

    def on_admit(self, req) -> dict:
        """Score one admission: ghost-probe the prompt (regret) and
        advance the session machine (resume edge). Returns the
        per-admission readout (callers like benches may use it; the
        engine ignores it)."""
        t = self.clock()
        prompt = np.asarray(req.prompt).reshape(-1)
        P = len(prompt)
        alloc = getattr(req, "page_alloc", None)
        shared = alloc.shared if alloc is not None else 0
        skip = alloc.skip if alloc is not None else 0
        restored = getattr(alloc, "restored", 0) if alloc is not None else 0
        self.prefill_tokens_paid += P - skip
        regret = self._probe_ghosts(prompt, shared, skip, t,
                                    restored=restored)
        r = self.registry
        if regret:
            self.regret_tokens += regret
            self.regret_admissions += 1
            r.counter("Serve/eviction_regret_tokens").inc(regret)
            r.histogram("Serve/kv_regret_admission_tokens").observe(regret)
        if self.prefill_tokens_paid:
            r.gauge("Serve/eviction_regret_frac").set(
                self.regret_tokens / self.prefill_tokens_paid)
        resumed = self._session_admit(req, P, t, regret,
                                      restored=restored)
        return {"regret_tokens": regret, "resumed": resumed,
                "restored_blocks": restored, "prompt_len": P, "skip": skip}

    def _session_admit(self, req, P: int, t: float, regret: int,
                       restored: int = 0) -> bool:
        sid = getattr(req, "session_id", None)
        if sid is None:
            return False
        s = self.sessions.get(sid)
        r = self.registry
        resumed = False
        if s is None:
            s = self.sessions[sid] = _Session(t)
            self.sessions_started += 1
            r.counter("Serve/sessions_started").inc()
        elif not s.live:
            # resume edge: idle (or scored-dead) → active. The idle
            # interval is the reuse interval a host tier must bridge.
            idle = t - s.idle_since if s.idle_since is not None else 0.0
            s.idle_token_s += s.held_tokens * idle
            r.histogram("Serve/session_idle_s").observe(idle)
            r.histogram("Serve/kv_reuse_interval_s").observe(idle)
            s.resumes += 1
            self.sessions_resumed += 1
            r.counter("Serve/session_resumed").inc()
            if regret:
                s.regret_resumes += 1
                s.regret_tokens += regret
                self.regret_resumes += 1
                r.counter("Serve/session_regret_resumes").inc()
                if self.on_regret_resume is not None:
                    self.on_regret_resume(sid, regret)
            if restored:
                # the resume the host tier SAVED: its evicted prefix
                # came back at copy bandwidth — a hit, not a regret
                # (the fleet's affinity-regret ledger must not count it)
                self.host_restored_resumes += 1
                r.counter("Serve/session_host_restored_resumes").inc()
            if self.spans is not None and s.idle_since is not None:
                from . import spans as S

                self.spans.emit(S.SESSION_IDLE, s.idle_since, t,
                                session=str(sid), regret_tokens=regret)
            s.state = ACTIVE
            s.active_since = t
            s.idle_since = None
            resumed = True
        s.live.add(req.rid)
        if self.page_size:
            # the tree retains the longest registered prompt's blocks —
            # the HBM a host tier could demote while the session idles
            s.held_tokens = max(s.held_tokens, P)
        s.last_t = t
        self.sessions.move_to_end(sid)
        while len(self.sessions) > self.cfg.max_sessions:
            _osid, old = self.sessions.popitem(last=False)
            self._finalize_session(old, t)
        return resumed

    def on_import(self, req) -> None:
        """Disaggregated decode-side intake (``import_request``): take
        over the session residency WITHOUT regret probing or prefill
        accounting — a decode replica seating already-computed KV pays
        no prefill, but its tree now holds the session's blocks and its
        retirement must find the rid in the live set."""
        self._session_admit(req, len(np.asarray(req.prompt).reshape(-1)),
                            self.clock(), 0)

    def on_retire(self, req) -> None:
        """A request terminated: if it was its session's last live one,
        the session goes idle — the byte-seconds meter starts. The
        disaggregated prefill replica's ``release_request`` (the
        request moves on, the slot frees, the prompt blocks stay
        tree-held HERE) funnels through this too: for residency
        purposes a handoff ends the session's activity on the source
        replica exactly like a retirement would."""
        sid = getattr(req, "session_id", None)
        if sid is None:
            return
        s = self.sessions.get(sid)
        if s is None or req.rid not in s.live:
            return
        s.live.discard(req.rid)
        if not s.live:
            t = self.clock()
            if self.spans is not None:
                from . import spans as S

                self.spans.emit(S.SESSION_ACTIVE, s.active_since, t,
                                session=str(sid), resumes=s.resumes)
            s.state = IDLE
            s.idle_since = t
            s.last_t = t

    def _finalize_session(self, s: _Session, now: float) -> None:
        """Close one session's books (LRU eviction from the tracker):
        its resume count lands in the histogram, its idle integral in
        the closed total."""
        if s.idle_since is not None:
            s.idle_token_s += s.held_tokens * (now - s.idle_since)
        self._idle_token_s_closed += s.idle_token_s
        self.sessions_finalized += 1
        self.registry.histogram("Serve/session_resume_count").observe(
            s.resumes)

    # -------------------------------------------------------------- readout
    def _cap_held(self, tokens: int) -> int:
        """Cap a session-summed held-token figure at what the tree
        ACTUALLY holds right now: per-session ``held_tokens`` can't see
        which session a later eviction hit, so their sum overstates
        residency under churn — the pool's live count is the truth."""
        if self.tree_held_tokens is not None:
            return min(tokens, int(self.tree_held_tokens()))
        return tokens

    def idle_kv_tokens(self) -> int:
        """Tree-held prompt tokens of currently idle (incl. dead)
        sessions — what a host tier could demote right now, capped at
        the pool's live tree residency."""
        return self._cap_held(sum(s.held_tokens
                                  for s in self.sessions.values()
                                  if not s.live))

    def idle_kv_bytes(self) -> Optional[int]:
        """The host-tier ledger row: bytes reclaimable by demoting idle
        sessions' tree-held pages (None when the byte cost of a cached
        token is unknown — contiguous engines hold nothing per-session)."""
        if not self.per_token_bytes:
            return None
        return int(self.idle_kv_tokens() * self.per_token_bytes)

    def copy_bandwidth(self, device=None) -> dict:
        """The measured host↔device copy-bandwidth probe, run ONCE and
        cached (capacity reports re-read it for free)."""
        if self._copy_bw is None:
            self._copy_bw = measure_copy_bandwidth(
                self.cfg.probe_bytes, device=device, clock=self.probe_clock)
        return self._copy_bw

    def snapshot(self) -> dict:
        """The observatory's full readout: regret ledger, ghost state,
        per-event attribution, session heat — the ``kvscope`` section of
        the capacity report and the flight recorder's provider. Also
        refreshes the ``Serve/sessions_*`` gauges (the states are
        time-derived: an idle session crosses into DEAD by the clock,
        not by an event)."""
        now = self.clock()
        active = idle = dead = 0
        idle_token_s = self._idle_token_s_closed
        idle_tokens_now = 0
        hottest = []
        for sid, s in self.sessions.items():
            if s.live:
                active += 1
            else:
                gap = now - s.idle_since if s.idle_since is not None else 0.0
                if gap > self.cfg.dead_after_s:
                    s.state = DEAD
                    dead += 1
                else:
                    idle += 1
                idle_tokens_now += s.held_tokens
            idle_token_s += s.idle_token_s
            if s.idle_since is not None and not s.live:
                idle_token_s += s.held_tokens * (now - s.idle_since)
            if s.regret_tokens:
                hottest.append({"session": str(sid),
                                "regret_tokens": s.regret_tokens,
                                "regret_resumes": s.regret_resumes,
                                "resumes": s.resumes,
                                "held_tokens": s.held_tokens,
                                "state": s.state})
        hottest.sort(key=lambda d: d["regret_tokens"], reverse=True)
        # "now" is HBM truth (capped at live tree residency: eviction
        # may have already reclaimed a session's pages); the INTEGRAL
        # deliberately is not capped — it measures what a host tier
        # WOULD have held through the idle gaps (evicted-then-regretted
        # pages included), i.e. the tier's demand, not HBM's supply
        idle_tokens_now = self._cap_held(idle_tokens_now)
        ptb = self.per_token_bytes
        byte_s = idle_token_s * ptb if ptb else None
        self.registry.set_gauges({
            "Serve/sessions_active": float(active),
            "Serve/sessions_idle": float(idle),
            "Serve/sessions_dead": float(dead),
            "Serve/session_idle_kv_tokens": float(idle_tokens_now),
        })
        if byte_s is not None:
            self.registry.gauge("Serve/session_idle_kv_byte_s").set(byte_s)
        mean_regret = (self.regret_tokens / self.regret_admissions
                       if self.regret_admissions else None)
        events = sorted(self._events.values(),
                        key=lambda e: e["regret_tokens"], reverse=True)
        return {
            "enabled": True,
            "page_size": self.page_size,
            "per_token_bytes": ptb,
            "regret": {
                "regret_tokens": self.regret_tokens,
                "regret_admissions": self.regret_admissions,
                "prefill_tokens_paid": self.prefill_tokens_paid,
                "regret_frac": (self.regret_tokens
                                / self.prefill_tokens_paid
                                if self.prefill_tokens_paid else 0.0),
                "mean_regret_tokens": mean_regret,
                "ghost_hits": self.ghost_hits,
                "stale_ghost_hits": self.stale_ghost_hits,
                "restored_ghost_hits": self.restored_ghost_hits,
            },
            "ghosts": {
                "entries": len(self.ghosts),
                "capacity": self.cfg.ghost_entries,
                "added": self.ghost_added,
                "overflow": self.ghost_overflow,
            },
            "events": {
                "count": self._event_seq,
                "tracked": len(self._events),
                "top": events[:8],
            },
            "sessions": {
                "tracked": len(self.sessions),
                "active": active,
                "idle": idle,
                "dead": dead,
                "started": self.sessions_started,
                "resumed": self.sessions_resumed,
                "regret_resumes": self.regret_resumes,
                "host_restored_resumes": self.host_restored_resumes,
                "finalized": self.sessions_finalized,
                "idle_kv_tokens_now": idle_tokens_now,
                "idle_kv_bytes_now": (idle_tokens_now * ptb
                                      if ptb else None),
                "idle_kv_token_s": idle_token_s,
                "idle_kv_byte_s": byte_s,
                "hottest": hottest[:8],
            },
            "copy_bandwidth": self._copy_bw,
        }
