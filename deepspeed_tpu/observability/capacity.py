"""Capacity attribution: HBM ledger, per-program cost census, advisor.

ZeRO-Infinity's memory-wall analysis starts from an explicit
per-component byte ledger, and EQuARX motivates quantized collectives
from measured per-program collective-byte attribution; this module is
that measurement substrate for the serving/training stack, composed from
three pieces:

- :func:`hbm_ledger` — the live HBM budget decomposed into weights
  (WOQ/dtype-aware), KV cache (from the cache layout the slot engine
  actually allocates), and per-program temp/peak (from the compiler's own
  ``memory_analysis``), with projected headroom (max slots / max context
  at the current config) as ``Memory/ledger_*`` gauges.
- :class:`ProgramCensus` — a registry over the engines' bounded compiled
  program set: static FLOPs / HBM bytes (``compiled_cost_analysis``) and
  collective bytes (``comm.hlo_analysis``) per program, joined against
  achieved per-program wall time from the PR-5 span ring to produce
  achieved-vs-roofline MBU/MFU attribution per program.
- :func:`capacity_report` — the advisor: composes workload analytics
  (``workload.py``), the ledger, and the census into what-if estimates on
  the *observed* traffic (prefill tokens prefix sharing would have saved,
  the decode-step speedup bound from int8 KV bytes, the collective-byte
  share of the step) and ranks the roadmap levers by measured payoff.
  Emitted as ``CAPACITY_REPORT.json`` and a ``doctor`` section.

Degradation contract (pinned by tier-1 tests): every compiler analysis
(``cost_analysis`` / ``memory_analysis``) is best-effort per backend —
on a backend that doesn't implement one, the census and ledger keep
every field PRESENT with ``None`` values and warn once; they never
raise. A capacity report from a CPU smoke run is partial, not absent.
"""

from __future__ import annotations

import json
import math
import os
from pathlib import Path
from typing import Any, Optional

from ..utils.logging import warning_once
from .metrics import MetricsRegistry, Reservoir

CAPACITY_SCHEMA = "dstpu-capacity-report/v1"

# Advisor lever names, in the order the smoke bench asserts on.
LEVER_PREFIX = "prefix_sharing"
LEVER_KV_QUANT = "kv_quantization"
LEVER_COLLECTIVES = "quantized_collectives"
LEVER_SPECULATION = "speculative_decoding"
LEVER_TIERED_KV = "tiered_kv"
LEVER_SCALING = "scaling"
LEVER_TENANT = "tenant_affinity"


def roofline_peaks(device=None) -> tuple:
    """``(peak_flops, peak_hbm_bw)`` for ``device`` (default: device 0),
    ``None`` where the chip is unknown to the peak tables — census rows
    then degrade their MFU/MBU fields to null. The one shared probe both
    engines' census entry points use."""
    import jax

    from ..utils.timer import peak_flops_for, peak_hbm_bw_for

    if device is None:
        device = jax.devices()[0]
    out = []
    for fn in (peak_flops_for, peak_hbm_bw_for):
        try:
            out.append(fn(device))
        except ValueError:
            out.append(None)
    return tuple(out)


# ------------------------------------------------------------------ ledger
def kv_cache_bytes(model_cfg, slots: int, max_len: int, dtype, *,
                   page_size: int = 0, pool_pages: int = 0,
                   kv_quant_bits: int = 0) -> dict:
    """KV-cache byte breakdown for the slot engine's ONE persistent cache,
    from the same :func:`~..inference.decode.cache_layout` the allocator
    uses (k + v buffers).

    ``page_size > 0`` accounts the pooled page layout instead: the
    resident total is the pool (+ the fp32 scale planes when the pool is
    int8), ``per_token_bytes`` is what one cached token actually costs —
    the figure the int8-KV lever halves — and ``page_bytes`` is the unit
    the operator sizes the pool in (docs/OPERATIONS.md)."""
    import jax.numpy as jnp

    from ..inference.decode import cache_layout

    if page_size > 0:
        shape, dt = cache_layout(model_cfg, slots, max_len, dtype,
                                 page_size=page_size, pages=pool_pages)
        if kv_quant_bits == 8:
            itemsize = 1
            scale_bytes = 2 * int(math.prod(shape[:-1])) * 4   # f32 scales
        else:
            itemsize = jnp.dtype(dt).itemsize
            scale_bytes = 0
        pool_bytes = 2 * int(math.prod(shape)) * itemsize
        total = pool_bytes + scale_bytes
        page_bytes = total // max(1, pool_pages)
        per_slot = page_bytes * (max_len // page_size)
        return {"total_bytes": total, "per_slot_bytes": per_slot,
                "per_token_bytes": page_bytes // page_size,
                "itemsize": itemsize, "slots": slots, "max_len": max_len,
                "shape": list(shape),
                "dtype": "int8" if kv_quant_bits == 8 else
                str(jnp.dtype(dt)),
                "page_size": page_size, "pool_pages": pool_pages,
                "page_bytes": page_bytes, "scale_bytes": scale_bytes,
                "kv_quant_bits": kv_quant_bits}
    shape, dt = cache_layout(model_cfg, slots, max_len, dtype)
    itemsize = jnp.dtype(dt).itemsize
    total = 2 * int(math.prod(shape)) * itemsize
    per_slot = total // slots
    return {"total_bytes": total, "per_slot_bytes": per_slot,
            "per_token_bytes": per_slot // max_len,
            "itemsize": itemsize, "slots": slots, "max_len": max_len,
            "shape": list(shape), "dtype": str(jnp.dtype(dt)),
            "page_size": 0, "pool_pages": 0, "page_bytes": 0,
            "scale_bytes": 0, "kv_quant_bits": 0}


def hbm_ledger(*, params: Any, model_cfg, slots: int, max_len: int,
               cache_dtype, temp_bytes: Optional[int] = None,
               limit_bytes: Optional[int] = None,
               registry: Optional[MetricsRegistry] = None,
               page_size: int = 0, pool_pages: int = 0,
               kv_quant_bits: int = 0,
               pages_used: Optional[int] = None,
               pages_free: Optional[int] = None,
               idle_kv_bytes: Optional[int] = None,
               host_tier_bytes: Optional[int] = None) -> dict:
    """Decompose the HBM budget of a serving config into its components.

    ``params`` is the engine's (possibly WOQ-quantized) tree — weights
    count their *resident* bytes (int8/int4 + scales for quantized
    leaves) plus the per-decode-step streamed-bytes model the MBU gauges
    already use. ``temp_bytes`` is the largest per-program temp
    allocation the census measured (None = unknown on this backend).
    ``limit_bytes`` defaults to the accelerator's reported HBM limit
    (None when the platform doesn't report one, e.g. CPU). Every field is
    always present; unknown values are None."""
    from ..inference.quantization import decode_weight_bytes, quantized_bytes

    weights = int(quantized_bytes(params))
    stream = int(decode_weight_bytes(params))
    kv = kv_cache_bytes(model_cfg, slots, max_len, cache_dtype,
                        page_size=page_size, pool_pages=pool_pages,
                        kv_quant_bits=kv_quant_bits)
    if limit_bytes is None:
        from ..platform.accelerator import get_accelerator

        limit_bytes = int(get_accelerator().memory_stats().bytes_limit) \
            or None
    known = weights + kv["total_bytes"] + (temp_bytes or 0)
    out = {
        "weights_bytes": weights,
        "weights_stream_bytes_per_step": stream,
        "kv_bytes": kv["total_bytes"],
        "kv_per_slot_bytes": kv["per_slot_bytes"],
        "kv_per_token_bytes": kv["per_token_bytes"],
        "cache_itemsize": kv["itemsize"],
        "cache_dtype": kv["dtype"],
        "slots": slots,
        "max_len": max_len,
        "temp_bytes": temp_bytes,
        "total_bytes": known,
        "limit_bytes": limit_bytes,
        "headroom_bytes": None,
        "projected_max_slots": None,
        "projected_max_context": None,
        # paged decomposition: pool pages used/free at their byte cost —
        # the live occupancy truth replacing the contiguous estimate
        # (all zero/None on the contiguous path)
        "kv_page_size": kv["page_size"],
        "kv_pool_pages": kv["pool_pages"],
        "kv_page_bytes": kv["page_bytes"],
        "kv_scale_bytes": kv["scale_bytes"],
        "kv_quant_bits": kv["kv_quant_bits"],
        "kv_pool_used_pages": pages_used,
        "kv_pool_free_pages": pages_free,
        "kv_pool_used_bytes": (pages_used * kv["page_bytes"]
                               if pages_used is not None else None),
        "kv_pool_free_bytes": (pages_free * kv["page_bytes"]
                               if pages_free is not None else None),
        # the host-tier row (kvscope): HBM currently held by IDLE
        # sessions' tree-retained pages — what demoting them to pinned
        # host memory would reclaim at the measured idle distribution.
        # None when the residency observatory isn't running (older
        # reports simply lack the figure; null is the contract).
        "kv_idle_resident_bytes": idle_kv_bytes,
        # ACHIEVED host tier (serving/hostkv.py): bytes of demoted KV
        # the pinned-host store holds right now — the projected
        # kv_idle_resident_bytes reclaim, realized. None when no tier
        # is attached (serving.host_pool_bytes=0).
        "kv_host_tier_bytes": host_tier_bytes,
    }
    if limit_bytes:
        free_for_kv = limit_bytes - weights - (temp_bytes or 0)
        out["headroom_bytes"] = limit_bytes - known
        if page_size > 0 and kv["page_bytes"] > 0:
            per_slot_pages = max_len // page_size
            out["projected_max_slots"] = max(
                0, free_for_kv // (kv["page_bytes"] * per_slot_pages))
        elif kv["per_slot_bytes"] > 0:
            out["projected_max_slots"] = max(
                0, free_for_kv // kv["per_slot_bytes"])
        if kv["per_token_bytes"] > 0 and slots > 0:
            out["projected_max_context"] = max(
                0, free_for_kv // (kv["per_token_bytes"] * slots))
    if registry is not None:
        for k, v in out.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                registry.gauge(f"Memory/ledger_{k}").set(float(v))
    return out


# ------------------------------------------------------------------ census
_CENSUS_STATIC_FIELDS = ("flops", "bytes_accessed", "collective_mbytes",
                         "collective_count", "collectives", "temp_bytes",
                         "peak_bytes")


class ProgramCensus:
    """Static cost + achieved wall time per compiled program.

    ``measure(name, jitted, *args)`` AOT-lowers/compiles the program
    (ShapeDtypeStruct args make this device-memory-free) and records the
    compiler's FLOPs / bytes-accessed, the HLO collective census, and the
    buffer-assignment temp/peak. ``observe_wall`` / ``attach_spans`` feed
    achieved per-call wall times (the serving span ring's ``decode_step``
    and ``prefill_chunk`` spans, the training ``train_step`` spans), and
    ``report()`` joins the two into per-program achieved-vs-roofline
    MBU/MFU. Analyses that a backend doesn't support leave their fields
    None (one warning, never a raise)."""

    def __init__(self, peak_flops: Optional[float] = None,
                 peak_bw: Optional[float] = None):
        self.peak_flops = peak_flops
        self.peak_bw = peak_bw
        self._static: dict[str, dict] = {}
        self._wall: dict[str, Reservoir] = {}
        self._calls: dict[str, int] = {}

    # ----------------------------------------------------------- static side
    def measure(self, name: str, jitted, *args, mesh=None, **kwargs) -> dict:
        """Record the static costs of one program; returns the row."""
        from ..comm.hlo_analysis import collective_totals
        from ..profiling.flops_profiler import (compiled_cost_analysis,
                                                compiled_memory_analysis)

        row: dict[str, Any] = {k: None for k in _CENSUS_STATIC_FIELDS}
        compiled = None
        try:
            lowered = jitted
            if hasattr(lowered, "lower"):
                if mesh is not None:
                    with mesh:
                        lowered = lowered.lower(*args, **kwargs)
                else:
                    lowered = lowered.lower(*args, **kwargs)
            compiled = lowered.compile() if hasattr(lowered, "compile") \
                else lowered
        except Exception as e:
            warning_once(f"capacity census: lowering {name!r} for analysis "
                         f"failed on this backend ({e!r}) — census row "
                         "kept with null values")
        if compiled is not None:
            try:
                cost = compiled_cost_analysis(compiled)
                row["flops"] = _maybe_num(cost.get("flops"))
                row["bytes_accessed"] = _maybe_num(cost.get("bytes accessed"))
            except Exception as e:
                warning_once("capacity census: cost_analysis unavailable on "
                             f"this backend ({e!r}) — FLOPs/bytes fields "
                             "stay null")
            try:
                mem = compiled_memory_analysis(compiled)
                row["temp_bytes"] = mem.get("temp_size_in_bytes")
                row["peak_bytes"] = mem.get(
                    "peak_memory_in_bytes",
                    _sum_or_none(mem, ("argument_size_in_bytes",
                                       "output_size_in_bytes",
                                       "temp_size_in_bytes")))
            except Exception as e:
                warning_once("capacity census: memory_analysis unavailable "
                             f"on this backend ({e!r}) — temp/peak fields "
                             "stay null")
            try:
                coll = collective_totals(compiled)
                row["collective_mbytes"] = coll["mbytes"]
                row["collective_count"] = int(coll["count"])
                row["collectives"] = coll["by_kind"]
            except Exception as e:
                warning_once("capacity census: HLO text unavailable on this "
                             f"backend ({e!r}) — collective fields stay "
                             "null")
        self._static[name] = row
        return row

    # --------------------------------------------------------- achieved side
    def observe_wall(self, name: str, seconds: float) -> None:
        r = self._wall.get(name)
        if r is None:
            r = self._wall[name] = Reservoir(1024)
        r.add(float(seconds))
        self._calls[name] = self._calls.get(name, 0) + 1

    def attach_spans(self, events) -> int:
        """Fold a span ring into per-program wall samples: ``decode_step``
        spans belong to the slot decode program, ``prefill_chunk`` spans
        to their ``chunk_<size>``/``final_<size>`` bucket program,
        ``train_step`` spans to the train step. Returns samples taken."""
        from . import spans as S

        n = 0
        for ev in events:
            if ev.t1 is None:
                continue
            if ev.kind == S.DECODE_STEP:
                name = "step"
            elif ev.kind == S.PREFILL_CHUNK:
                stem = "final" if ev.meta.get("final") else "chunk"
                name = f"{stem}_{ev.meta.get('size')}"
            elif ev.kind == S.TRAIN_STEP:
                name = "train_step"
            else:
                continue
            self.observe_wall(name, ev.duration)
            n += 1
        return n

    # --------------------------------------------------------------- readout
    def report(self) -> dict:
        """Per-program rows, static + achieved joined. Programs with no
        wall samples report static columns only (and vice versa)."""
        rows: dict[str, dict] = {}
        for name in sorted(set(self._static) | set(self._wall)):
            row = dict(self._static.get(
                name, {k: None for k in _CENSUS_STATIC_FIELDS}))
            res = self._wall.get(name)
            calls = self._calls.get(name, 0)
            wall = res.percentile(50) if res is not None and len(res) \
                else None
            row.update({"calls": calls, "wall_s_p50": wall,
                        "achieved_tflops": None, "mfu": None,
                        "achieved_gbps": None, "mbu": None})
            if wall:
                if row["flops"]:
                    ach = row["flops"] / wall
                    row["achieved_tflops"] = ach / 1e12
                    if self.peak_flops:
                        row["mfu"] = ach / self.peak_flops
                if row["bytes_accessed"]:
                    gbs = row["bytes_accessed"] / wall
                    row["achieved_gbps"] = gbs / 1e9
                    if self.peak_bw:
                        row["mbu"] = gbs / self.peak_bw
            rows[name] = row
        return {"programs": rows, "peak_flops": self.peak_flops,
                "peak_hbm_bw": self.peak_bw}


def _maybe_num(v):
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def _sum_or_none(d: dict, keys) -> Optional[int]:
    vals = [d.get(k) for k in keys]
    if any(v is None for v in vals):
        return None
    return int(sum(vals))


# ----------------------------------------------------------------- advisor
def capacity_report(*, ledger: dict, census: Optional[dict] = None,
                    workload: Optional[dict] = None,
                    occupancy_avg: Optional[float] = None,
                    meta: Optional[dict] = None,
                    pages: Optional[dict] = None,
                    commscope: Optional[dict] = None,
                    kvscope: Optional[dict] = None,
                    loadscope: Optional[dict] = None,
                    tenantscope: Optional[dict] = None) -> dict:
    """Compose ledger + census + workload into the ranked what-if advisor.

    Every lever's score is the estimated fraction of its bounding
    resource it would save ON THE OBSERVED TRAFFIC — comparable across
    levers, honest about what was actually measured (unmeasured inputs
    degrade the lever to score 0 with a stated reason, they never
    invent a payoff). ``pages`` (the paged engine's
    ``PagePool.snapshot()``) closes the loop: levers the paged cache has
    ALREADY pulled report achieved savings next to the projection, so
    the report distinguishes "would save" from "is saving"."""
    levers = []

    # Prefix sharing: the measured shared-prefix fraction IS the fraction
    # of prefill compute (and prefill KV writes) a radix prefix cache
    # would have skipped on this traffic.
    overlap = (workload or {}).get("prefix_overlap")
    dedup = (workload or {}).get("dedupable_prefill_tokens")
    prefix_est = {"prefill_tokens_saved": dedup,
                  "shared_prefix_fraction": overlap}
    why_prefix = ("measured shared-prefix token fraction of admitted "
                  "prompts — the prefill work a prefix cache skips"
                  if overlap is not None else
                  "no workload analytics measured (serving.workload off)")
    if pages is not None and pages.get("prefix_sharing"):
        prefix_est["achieved"] = {
            "prefill_tokens_saved": pages.get("prefill_tokens_saved"),
            "tokens_saved_fraction": pages.get("tokens_saved_fraction"),
            "shared_page_acquires": pages.get("shared_page_acquires"),
            "prefix_hit_rate": pages.get("prefix_hit_rate"),
            "cow_copies": pages.get("cow_copies"),
        }
        why_prefix += ("; paged cache ACTIVE — achieved savings reported "
                       "alongside the estimator's projection")
    levers.append({
        "name": LEVER_PREFIX,
        "score": float(overlap) if overlap is not None else 0.0,
        "estimate": prefix_est,
        "why": why_prefix,
    })

    # int8 KV: decode is bandwidth-bound; the step's byte budget is the
    # streamed weights + the live KV it reads. Quantizing KV to int8
    # shrinks only the KV term — the bound is the byte ratio.
    kv_score = 0.0
    kv_est: dict[str, Any] = {"decode_step_speedup_bound": None,
                              "kv_read_bytes_per_step": None}
    itemsize = ledger.get("cache_itemsize")
    stream = ledger.get("weights_stream_bytes_per_step")
    per_tok = ledger.get("kv_per_token_bytes")
    slots = ledger.get("slots") or 0
    why_kv = "cache itemsize/weight-stream bytes unavailable"
    if itemsize and stream and per_tok:
        mean_ctx = _mean_context(workload, ledger)
        occ = occupancy_avg if occupancy_avg is not None else 1.0
        kv_read = per_tok * mean_ctx * occ * slots
        # int8 keeps 1 byte/elem + per-head scales (small); bound by the
        # pure byte ratio of the step's HBM traffic
        quant_kv = kv_read / itemsize
        bound = (stream + kv_read) / max(1.0, stream + quant_kv)
        kv_score = 1.0 - 1.0 / bound
        kv_est = {"decode_step_speedup_bound": bound,
                  "kv_read_bytes_per_step": int(kv_read),
                  "mean_context_tokens": mean_ctx,
                  "occupancy_avg": occ}
        why_kv = ("byte-ratio bound on the decode step: streamed weights "
                  "+ live KV read at measured occupancy/context, KV "
                  f"shrunk {itemsize}x to int8")
    if ledger.get("kv_quant_bits") == 8:
        # int8 KV is ON: the per-token bytes in the ledger ARE the
        # achieved figure; report them next to the fp equivalent so the
        # report shows the realized shrink, and zero the projection (the
        # lever is already pulled)
        kv_est["achieved"] = {
            "kv_bytes_per_token": per_tok,
            "kv_scale_bytes": ledger.get("kv_scale_bytes"),
            "kv_quant_bits": 8,
        }
        kv_score = 0.0
        why_kv = ("int8 KV ACTIVE — ledger per-token KV bytes are the "
                  "achieved (quantized) cost; nothing further to project")
    levers.append({"name": LEVER_KV_QUANT, "score": float(kv_score),
                   "estimate": kv_est, "why": why_kv})

    # Quantized/overlapped collectives: projected from the step's wire
    # bytes as a share of its HBM bytes (EQuARX-style int8 wires) — and
    # UPGRADED to the measured exposed-collective fraction when the
    # commscope observatory ran (observability/commscope.py): exposed
    # time is exactly the wall a T3-style overlap or a quantized wire
    # can reclaim, so the lever ranks on measured cost, not a proxy.
    coll_score = 0.0
    coll_est: dict[str, Any] = {"collective_byte_share": None}
    step_row = ((census or {}).get("programs") or {}).get("step") or {}
    cb, ba = step_row.get("collective_mbytes"), step_row.get("bytes_accessed")
    why_coll = "no census row for the decode step on this backend"
    if cb is not None and ba:
        share = (cb * 1e6) / ba
        coll_score = 0.5 * share          # int8 wires halve 16-bit bytes
        coll_est = {"collective_byte_share": share,
                    "collective_mbytes_per_step": cb}
        why_coll = ("measured collective bytes as a share of the decode "
                    "step's HBM bytes, halved by int8 wire quantization")
    cs_an = (commscope or {}).get("anatomy") or {}
    if cs_an.get("exposed_comm_frac") is not None:
        coll_score = float(cs_an["exposed_comm_frac"])
        cs_led = ((commscope or {}).get("ledger") or {}).get("by_kind") \
            or {}
        coll_est["measured"] = {
            "exposed_comm_frac": cs_an.get("exposed_comm_frac"),
            "overlap_frac": cs_an.get("overlap_frac"),
            "exposed_collective_s": cs_an.get("exposed_collective_s"),
            "achieved_busbw_gbps": {k: r.get("busbw_gbps")
                                    for k, r in cs_led.items()},
            "roofline_ratio": {k: r.get("roofline_ratio")
                               for k, r in cs_led.items()},
        }
        why_coll = ("MEASURED exposed-collective fraction of the step "
                    "wall (commscope trace anatomy) — the time "
                    "overlapping/quantizing collectives can reclaim; "
                    "achieved bus bandwidth per kind attached")
    # the lever is PULLED (quantized grad collectives / bucketed overlap
    # / int8 TP decode wire active): report what the spelling achieves —
    # exact static wire bytes vs the fp32 equivalent
    # (Engine.grad_comm_summary), the serving tp_quant bits — beside the
    # projection, and score only what REMAINS: the measured exposed
    # fraction still on the wall (self-demoting toward zero as the
    # overlap absorbs it — the PR-14 tiered_kv pattern), or 0 with the
    # reason stated when this backend can't measure what remains.
    gq = (commscope or {}).get("quantized") or {}
    if gq.get("active"):
        coll_est["achieved"] = {k: gq.get(k) for k in (
            "mode", "overlap", "error_feedback", "buckets",
            "tp_quant_bits", "wire_mbytes_per_step",
            "fp32_equivalent_mbytes", "wire_ratio", "data_world")}
        if cs_an.get("exposed_comm_frac") is not None:
            coll_score = float(cs_an["exposed_comm_frac"])
            why_coll += ("; quantized/overlapped collectives ACTIVE — "
                         "achieved wire ratio reported, score is the "
                         "REMAINING measured exposed fraction "
                         "(self-demotes as overlap absorbs it)")
        else:
            coll_score = 0.0
            why_coll = ("quantized/overlapped collectives ACTIVE — "
                        "achieved wire ratio reported; exposed fraction "
                        "unmeasured on this backend, so nothing further "
                        "to project (run the commscope observatory on "
                        "TPU for the remaining-exposed score)")
    levers.append({"name": LEVER_COLLECTIVES, "score": float(coll_score),
                   "estimate": coll_est, "why": why_coll})

    # Tiered (host-offloaded) KV: scored ENTIRELY from measurements —
    # observed eviction-regret traffic (the prefill the tree silently
    # re-pays today, kvscope's ghost ledger), the measured host↔device
    # copy bandwidth (the restore path's cost), and the span ring's
    # measured prefill throughput (the recompute path's cost). The score
    # is the regretted share of prefill work times the fraction of it a
    # host restore would win back (1 - restore/recompute, clipped at 0).
    # ANY unmeasured input degrades the lever to score 0 with the reason
    # stated — the advisor never invents a host-tier payoff.
    ks = kvscope or {}
    reg = ks.get("regret") or {}
    sess = ks.get("sessions") or {}
    tk_score = 0.0
    tk_est: dict[str, Any] = {
        "regret_tokens": reg.get("regret_tokens"),
        "regret_frac": reg.get("regret_frac"),
        "mean_regret_tokens_per_admission": reg.get("mean_regret_tokens"),
        "projected_restore_s_per_resume": None,
        "measured_recompute_s_per_resume": None,
        "copy_h2d_gbps": ((ks.get("copy_bandwidth") or {})
                          .get("h2d_gbps")),
        "prefill_tokens_per_s": ((ks.get("prefill") or {})
                                 .get("tokens_per_s")),
        "hbm_reclaimable_bytes": sess.get("idle_kv_bytes_now"),
        "idle_kv_byte_s": sess.get("idle_kv_byte_s"),
        "resume_overlap": (workload or {}).get("resume_overlap"),
    }
    regret_tokens = reg.get("regret_tokens") or 0
    regret_frac = reg.get("regret_frac")
    mean_tok = reg.get("mean_regret_tokens")
    cbw = tk_est["copy_h2d_gbps"]
    pr = tk_est["prefill_tokens_per_s"]
    ptb = ks.get("per_token_bytes") or ledger.get("kv_per_token_bytes")
    if not ks or not reg:
        why_tk = ("no KV residency observatory measured "
                  "(serving.kvscope off)")
    elif not regret_tokens:
        why_tk = ("no eviction regret observed on this traffic — the "
                  "tree covers the working set; a host tier would only "
                  "add restore latency")
    elif cbw is None:
        why_tk = ("host-to-device copy bandwidth unmeasured on this "
                  "backend — restore cost unknown, lever degraded")
    elif pr is None:
        why_tk = ("no measured prefill timings (serving.spans off) — "
                  "recompute cost unknown, lever degraded")
    elif not ptb:
        why_tk = ("per-token KV byte cost unknown (no paged cache "
                  "layout) — restore bytes unknown, lever degraded")
    else:
        restore_s = mean_tok * ptb / (cbw * 1e9)
        recompute_s = mean_tok / pr
        tk_est["projected_restore_s_per_resume"] = restore_s
        tk_est["measured_recompute_s_per_resume"] = recompute_s
        advantage = max(0.0, 1.0 - restore_s / recompute_s) \
            if recompute_s > 0 else 0.0
        tk_score = float(regret_frac or 0.0) * advantage
        why_tk = ("measured eviction-regret share of prefill work, "
                  "scaled by the measured restore-vs-recompute "
                  f"advantage (host restore {restore_s:.3g}s vs prefill "
                  f"recompute {recompute_s:.3g}s per mean regretted "
                  "resume)")
    ht = ks.get("host_tier") or {}
    if ht.get("restores"):
        # the tier is LIVE: report what it actually restored next to
        # the projection. Remaining regret (the score's input) already
        # excludes restored resumes — the lever demotes itself as the
        # tier absorbs the traffic it was priced on.
        tk_est["achieved"] = {
            "host_tier_bytes": ht.get("bytes"),
            "host_tier_pages": ht.get("pages"),
            "restores": ht.get("restores"),
            "restored_tokens": ht.get("restored_tokens"),
            "restore_bytes": ht.get("restore_bytes"),
            "restore_wait_s": ht.get("restore_wait_s"),
            "restore_tokens_per_s": ht.get("restore_tokens_per_s"),
            "hits": ht.get("hits"),
            "misses": ht.get("misses"),
            "prunes": ht.get("prunes"),
            "fallbacks": ht.get("fallbacks"),
        }
        why_tk += ("; host tier ACTIVE — achieved restores reported "
                   "alongside the projection (remaining regret scores "
                   "what the tier still misses)")
    # The disk rung's sub-estimate: same regret × advantage shape, but
    # the restore cost is the NVMe tier's MEASURED read bandwidth (its
    # verified promotions), falling back to AIO_BENCH numbers would be a
    # projection — unmeasured means score 0 with the reason stated.
    nv = ks.get("nvme_tier")
    if nv is not None:
        nv_score = 0.0
        nv_est: dict[str, Any] = {
            "pages": nv.get("pages"),
            "bytes": nv.get("bytes"),
            "capacity_bytes": nv.get("capacity_bytes"),
            "promotions": nv.get("promotions"),
            "spilled_in": ht.get("spills"),
            "fallbacks": nv.get("fallbacks"),
            "aio_errors": nv.get("aio_errors"),
            "read_mb_s": nv.get("read_mb_s"),
            "projected_nvme_restore_s_per_resume": None,
        }
        rbw = nv.get("read_mb_s")
        if not regret_tokens:
            why_nv = ("no eviction regret on this traffic — the upper "
                      "rungs cover the working set")
        elif rbw is None:
            why_nv = ("NVMe read bandwidth unmeasured (no verified "
                      "promotions yet) — disk restore cost unknown, "
                      "sub-estimate degraded; see AIO_BENCH.json for "
                      "the standalone sweep")
        elif pr is None or not ptb or mean_tok is None:
            why_nv = ("prefill/recompute cost unmeasured — cannot "
                      "price disk restore against recompute")
        else:
            nvme_restore_s = mean_tok * ptb / (rbw * 1e6)
            recompute_s = mean_tok / pr
            nv_est["projected_nvme_restore_s_per_resume"] = nvme_restore_s
            adv = max(0.0, 1.0 - nvme_restore_s / recompute_s) \
                if recompute_s > 0 else 0.0
            nv_score = float(regret_frac or 0.0) * adv
            why_nv = ("measured regret share scaled by the measured "
                      f"NVMe-read-vs-recompute advantage (disk restore "
                      f"{nvme_restore_s:.3g}s vs recompute "
                      f"{recompute_s:.3g}s per mean regretted resume, "
                      f"at the tier's achieved {rbw:.1f} MB/s)")
        tk_est["nvme"] = nv_est
        tk_est["nvme_score"] = nv_score
        tk_est["nvme_why"] = why_nv
    levers.append({"name": LEVER_TIERED_KV, "score": float(tk_score),
                   "estimate": tk_est, "why": why_tk})

    # Self-speculation: the prompt-lookup acceptance estimate bounds the
    # extra tokens per verify pass draft-free speculation gets for free.
    accept = ((workload or {}).get("selfspec_accept") or {}).get("mean")
    accept = None if (isinstance(accept, float) and math.isnan(accept)) \
        else accept
    levers.append({
        "name": LEVER_SPECULATION,
        "score": float(accept) if accept is not None else 0.0,
        "estimate": {"selfspec_acceptance": accept},
        "why": ("measured n-gram prompt-lookup acceptance potential on "
                "admitted prompts" if accept is not None else
                "no workload analytics measured (serving.workload off)"),
    })

    # Scaling: the arrival & scaling observatory's measured utilization
    # (loadscope.py) prices capacity moves — add/remove replica and the
    # prefill↔decode rebalance — by predicted goodput and queue-wait
    # delta. Only present when the observatory ran (inert-by-default);
    # any unmeasured input self-demotes the lever with its reason.
    if loadscope is not None:
        util = loadscope.get("utilization") or {}
        rho = util.get("rho")
        wis = loadscope.get("what_ifs") or []
        sc_est: dict[str, Any] = {
            "rho": rho,
            "rho_decode": util.get("rho_decode"),
            "rho_prefill": util.get("rho_prefill"),
            "predicted_queue_wait_s": util.get("predicted_queue_wait_s"),
            "slo_ttv_s": (loadscope.get("forecast") or {}).get("slo_ttv_s"),
            "arrival_rate_per_s": (loadscope.get("arrival")
                                   or {}).get("rate_per_s"),
            "what_ifs": wis,
        }
        reasons = [str(r) for r in (loadscope.get("unmeasured") or [])]
        if rho is None or not wis:
            sc_score = 0.0
            why_sc = ("scaling inputs unmeasured — " + "; ".join(reasons)
                      if reasons else
                      "no utilization estimate on this traffic")
        else:
            best = max(wis, key=lambda w: w.get("score") or 0.0)
            # what-if scores are 0–100 urgency; lever scores are 0–1
            # fractions comparable across the advisor
            sc_score = float(best.get("score") or 0.0) / 100.0
            sc_est["recommendation"] = best.get("action")
            why_sc = (f"measured utilization rho={rho:.3g} prices "
                      f"{best.get('action')} by predicted goodput and "
                      "queue-wait delta (loadscope what-ifs)")
            if reasons:
                why_sc += "; partial inputs: " + "; ".join(reasons)
        ach = loadscope.get("achieved")
        if ach:
            sc_est["achieved"] = ach
            why_sc += ("; scaling backtest ACTIVE — achieved queue-wait/"
                       "goodput deltas reported alongside the prediction")
        levers.append({"name": LEVER_SCALING, "score": sc_score,
                       "estimate": sc_est, "why": why_sc})

    # Tenant affinity / adapter locality: the per-tenant observatory
    # (tenantscope.py) prices tenant-affine routing — keeping each
    # tenant's requests (and, once the S-LoRA build lands, its adapters)
    # on few replicas preserves exactly the prefix sharing the tenant's
    # OWN traffic exhibits, and matters in proportion to how unevenly
    # tenants consume the fleet (cross-tenant interference). Only
    # present when the observatory ran; single-tenant traffic
    # self-demotes with its reason.
    if tenantscope is not None:
        rows = tenantscope.get("tenants") or {}
        fair = tenantscope.get("fairness") or {}
        noisy = tenantscope.get("noisy") or {}
        jain = fair.get("jain")
        ptoks = sum(r.get("prompt_tokens") or 0 for r in rows.values())
        # token-weighted mean of each tenant's OWN prefix overlap — the
        # sharing a tenant-affine replica keeps hot
        t_overlap = (sum((r.get("prefix_overlap") or 0.0)
                         * (r.get("prompt_tokens") or 0)
                         for r in rows.values()) / ptoks
                     if ptoks else None)
        dom = fair.get("dominant_shares") or {}
        top = max(dom, key=dom.get) if dom else None
        tn_est: dict[str, Any] = {
            "per_tenant_overlap": t_overlap,
            "fairness_jain": jain,
            "n_tenants": len(rows),
            "noisy_episodes": noisy.get("episodes"),
            "top_tenant": top,
            "top_dominant_share": dom.get(top) if top else None,
        }
        if len(rows) < 2 or jain is None or t_overlap is None:
            tn_score = 0.0
            why_tn = ("single-tenant traffic (or nothing retired yet) — "
                      "tenant-affine routing has nothing to separate")
        else:
            # interference: 1 - Jain is 0 when tenants consume evenly
            # and → 1 as one tenant dominates; the affinity win is the
            # tenant-local overlap that routing can preserve, scaled by
            # how much there is to isolate
            tn_score = max(0.0, min(1.0, t_overlap * (1.0 - jain)))
            why_tn = (f"measured per-tenant overlap {t_overlap:.3g} × "
                      f"interference (1 - jain {jain:.3g}) prices "
                      "tenant-affine routing / adapter locality on this "
                      "traffic")
            if noisy.get("episodes"):
                why_tn += (f"; {noisy['episodes']} noisy-neighbor "
                           "episode(s) observed — isolation also buys "
                           "SLO protection")
        levers.append({"name": LEVER_TENANT, "score": tn_score,
                       "estimate": tn_est, "why": why_tn})

    levers.sort(key=lambda d: d["score"], reverse=True)
    return {
        "schema": CAPACITY_SCHEMA,
        "meta": dict(meta or {}),
        "workload": workload,
        "ledger": ledger,
        "census": census,
        "pages": pages,
        # the communication observatory's measured rows (None when it
        # didn't run — older reports simply lack the key, which the
        # validator accepts: nulls are the degradation contract, absence
        # is a pre-commscope artifact)
        "commscope": commscope,
        # the KV residency observatory's measured rows (same contract)
        "kvscope": kvscope,
        # the arrival & scaling observatory's measured rows (same
        # contract: None when it didn't run, absent on older artifacts)
        "loadscope": loadscope,
        # the per-tenant observatory's measured rows (same contract)
        "tenantscope": tenantscope,
        "advisor": {"levers": levers,
                    "ranked": [d["name"] for d in levers]},
    }


def _mean_context(workload: Optional[dict], ledger: dict) -> float:
    """Time-averaged live context (prompt + generated-so-far) per
    occupied slot, from the workload histograms when measured, else half
    the slot capacity. The decode-side mean is halved: ``decode_len``
    records the FINAL generated count at retirement, but context grows
    linearly over a slot's residency, so its time average is ~half."""
    if workload:
        p = (workload.get("prompt_len") or {}).get("mean")
        d = (workload.get("decode_len") or {}).get("mean")
        ok = [isinstance(v, (int, float)) and not math.isnan(v)
              for v in (p, d)]
        if any(ok):
            return float((p if ok[0] else 0.0) + (d / 2.0 if ok[1] else 0.0))
    return float(ledger.get("max_len") or 0) / 2.0


_REQUIRED_LEDGER_KEYS = (
    "weights_bytes", "weights_stream_bytes_per_step", "kv_bytes",
    "kv_per_slot_bytes", "kv_per_token_bytes", "cache_itemsize",
    "temp_bytes", "total_bytes", "limit_bytes", "headroom_bytes",
    "projected_max_slots", "projected_max_context",
    # paged decomposition (zero/None on the contiguous path)
    "kv_page_size", "kv_pool_pages", "kv_page_bytes", "kv_quant_bits",
    "kv_pool_used_pages", "kv_pool_free_pages")


def validate_capacity_report(report: dict) -> list:
    """Schema gate for ``CAPACITY_REPORT.json`` (same contract as
    ``validate_chrome_trace``): returns a list of problems, empty when
    the report is well-formed. Null values are legal everywhere — the
    degradation contract — but every field must be PRESENT."""
    errs = []
    if not isinstance(report, dict):
        return [f"report is {type(report).__name__}, not dict"]
    if report.get("schema") != CAPACITY_SCHEMA:
        errs.append(f"schema is {report.get('schema')!r}, "
                    f"want {CAPACITY_SCHEMA!r}")
    ledger = report.get("ledger")
    if not isinstance(ledger, dict):
        errs.append("missing ledger section")
    else:
        for k in _REQUIRED_LEDGER_KEYS:
            if k not in ledger:
                errs.append(f"ledger missing key {k!r}")
    adv = report.get("advisor")
    if not isinstance(adv, dict) or not isinstance(adv.get("levers"), list):
        errs.append("missing advisor.levers list")
    else:
        for i, lv in enumerate(adv["levers"]):
            if not isinstance(lv, dict):
                errs.append(f"advisor.levers[{i}] is "
                            f"{type(lv).__name__}, not dict")
                continue
            for k in ("name", "score", "estimate", "why"):
                if k not in lv:
                    errs.append(f"advisor.levers[{i}] missing {k!r}")
        ranked = adv.get("ranked")
        if ranked != [lv.get("name") for lv in adv["levers"]
                      if isinstance(lv, dict)]:
            errs.append("advisor.ranked does not match lever order")
    census = report.get("census")
    if census is not None and not isinstance(census, dict):
        errs.append(f"census is {type(census).__name__}, not dict")
    elif census is not None and not isinstance(
            census.get("programs", {}), dict):
        errs.append("census.programs is not a dict")
    for k in ("workload", "census", "pages"):
        if k not in report:
            errs.append(f"missing {k!r} section (null is fine)")
    return errs


def write_capacity_report(report: dict, path) -> Path:
    """Atomically write the report (tmp + rename, like the Prometheus
    sink: a concurrent reader never sees a torn file)."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + ".tmp")
    tmp.write_text(json.dumps(report, indent=2, default=_json_default),
                   encoding="utf-8")
    os.replace(tmp, p)
    return p


def _json_default(o):
    f = getattr(o, "item", None)
    if callable(f) and getattr(o, "size", 1) == 1:
        return f()
    f = getattr(o, "tolist", None)
    if callable(f):
        return f()
    return str(o)
