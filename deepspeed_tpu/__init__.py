"""deepspeed_tpu: a TPU-native large-scale training & inference framework.

Brand-new JAX/XLA/Pallas implementation of the full capability set of the
reference (DeepSpeed v0.11.2 — see SURVEY.md): JSON-config-driven training
engine, ZeRO-style optimizer/gradient/parameter sharding with tiered offload,
data/tensor/pipeline/expert/sequence parallelism on one named device mesh,
Pallas kernels for the hot ops, sharded universal checkpoints, inference/
decode engine, and the observability stack.
"""

from . import compat  # noqa: F401  (must run before any jax-0.9 API use)
from .config import Config
from .inference import (InferenceConfig, InferenceEngine, ServingConfig,
                        init_inference)
from .serving import ServingEngine
from .platform import (get_accelerator, init_distributed, build_mesh, MeshSpec)
from .resilience import (ChaosConfig, NonFiniteLossError, PreemptionGuard,
                         QueueFullError, RequestStatus)
from .runtime.engine import Engine, initialize
from .runtime.hybrid_engine import HybridEngine
from .version import __version__

from . import comm  # noqa: F401  (deepspeed.comm analog)
from . import observability  # noqa: F401  (metrics/tracing/sinks layer)
from . import resilience  # noqa: F401  (chaos + guards + checkpoint integrity)

__all__ = ["initialize", "Engine", "HybridEngine", "Config",
           "init_inference", "InferenceEngine", "InferenceConfig",
           "ServingConfig", "ServingEngine",
           "RequestStatus", "QueueFullError", "NonFiniteLossError",
           "ChaosConfig", "PreemptionGuard",
           "get_accelerator", "init_distributed", "build_mesh", "MeshSpec",
           "__version__"]
