"""Memory-mapped indexed token dataset (.bin + .idx).

Analog of the reference's Megatron-derived ``data_pipeline/indexed_dataset.py``
(617 LoC): token sequences packed back-to-back in a flat binary ``.bin`` file
with an ``.idx`` sidecar of offsets/lengths, read zero-copy via ``np.memmap``.
The reference keeps the Megatron wire format for checkpoint compatibility;
this implementation keeps the same *shape* (flat token file + offset index,
mmap reads, O(1) __getitem__) with a simpler self-describing header.

Why it matters on TPU: per-host dataloading for a pod must stream from a
shared filesystem without deserialization cost — mmap + fixed dtype is the
same answer as on GPU clusters.
"""

from __future__ import annotations

import struct
from typing import Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.uint16, 8: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def _data_path(prefix: str) -> str:
    return prefix + ".bin"


def _index_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item(tokens)`` per sequence, ``finalize()``.

    Mirrors ``MMapIndexedDatasetBuilder`` (reference ``indexed_dataset.py``);
    ``merge_`` of shard files is a straight concat of .bin plus index fixup.
    """

    def __init__(self, prefix: str, dtype=np.int32):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported token dtype {dtype}")
        self._data = open(_data_path(prefix), "wb")
        self._lengths: list[int] = []

    def add_item(self, tokens: Sequence[int] | np.ndarray) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        assert arr.ndim == 1, "one flat token sequence per item"
        self._data.write(arr.tobytes(order="C"))
        self._lengths.append(len(arr))

    def merge_file_(self, other_prefix: str) -> None:
        """Append another builder's finalized shard (multi-worker writes)."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self.dtype:
            raise ValueError(
                f"cannot merge {other_prefix!r} (dtype {other.dtype}) into a "
                f"{self.dtype} builder: offsets are element-indexed and the "
                "merged index would decode garbage")
        with open(_data_path(other_prefix), "rb") as f:
            while chunk := f.read(1 << 24):
                self._data.write(chunk)
        self._lengths.extend(other.lengths.tolist())

    def finalize(self) -> None:
        self._data.close()
        lengths = np.asarray(self._lengths, np.int64)
        offsets = np.zeros(len(lengths) + 1, np.int64)
        np.cumsum(lengths, out=offsets[1:])
        with open(_index_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<HHq", _VERSION, _DTYPE_CODES[self.dtype],
                                len(lengths)))
            f.write(offsets.tobytes())


class MMapIndexedDataset:
    """Zero-copy reader. ``ds[i]`` → 1-D token array (a view into the mmap)."""

    def __init__(self, prefix: str):
        with open(_index_path(prefix), "rb") as f:
            magic = f.read(8)
            if magic != _MAGIC:
                raise ValueError(f"{_index_path(prefix)}: bad magic {magic!r}")
            version, dcode, n = struct.unpack("<HHq", f.read(12))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self.dtype = np.dtype(_DTYPES[dcode])
            self._offsets = np.frombuffer(f.read(8 * (n + 1)), np.int64)
        self._n = n
        self._data = np.memmap(_data_path(prefix), dtype=self.dtype, mode="r")

    def __len__(self) -> int:
        return self._n

    @property
    def lengths(self) -> np.ndarray:
        return np.diff(self._offsets)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        return self._data[self._offsets[i]:self._offsets[i + 1]]

    def get(self, i: int, offset: int = 0, length: int | None = None):
        """Partial read (the reference API used by packed-sample builders).
        Bounds-checked: an over-long read raises instead of silently leaking
        the next sequence's tokens into this one."""
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        seq_len = int(self._offsets[i + 1] - self._offsets[i])
        if not 0 <= offset <= seq_len:
            raise IndexError(f"offset {offset} outside sequence {i} "
                             f"(length {seq_len})")
        if length is not None and offset + length > seq_len:
            raise IndexError(f"read [{offset}, {offset + length}) exceeds "
                             f"sequence {i} (length {seq_len})")
        start = self._offsets[i] + offset
        stop = self._offsets[i + 1] if length is None else start + length
        return self._data[start:stop]
