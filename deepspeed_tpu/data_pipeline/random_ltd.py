"""Random layerwise token dropping (random-LTD).

Analog of the reference's ``data_pipeline/data_routing/basic_layer.py:113``
(``RandomLayerTokenDrop``) + scheduler: middle transformer layers process a
random *subset* of tokens (gather → layer → scatter-back), cutting attention
and FFN cost per dropped token while the first/last layers see the full
sequence.  The kept-token count follows a schedule over training steps.

TPU-native shape discipline: the kept count is a **static** value per
compiled step (dynamic shapes don't exist under jit).  The schedule has few
distinct values (it moves in ``difficulty_step`` quanta), so each change
costs one retrace — the engine passes the current value as a static argument
so the jit cache keys on it.

Subset causality: kept indices are sorted ascending, so the subset's
triangular mask equals true causality restricted to the subset (token i
attends kept token j iff pos_j ≤ pos_i) — the same approximation the
reference makes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


class RandomLTDMixin:
    """Overrides ``_scan_layers``: full first/last layer, token-dropped
    middle layers. Activated when ``self.ltd_tokens`` ∈ (0, S)."""

    ltd_tokens: int = 0      # kept tokens per middle layer; 0 = off
    ltd_seed: int = 17

    def set_ltd_tokens(self, r: int) -> None:
        self.ltd_tokens = int(r)

    def _scan_layers(self, x, layers, positions, attn_mask, remat_policy):
        B, S, d = x.shape
        r = int(self.ltd_tokens)
        L = jax.tree.leaves(layers)[0].shape[0]
        if r <= 0 or r >= S or L < 3:
            return super()._scan_layers(x, layers, positions, attn_mask,
                                        remat_policy)
        first = jax.tree.map(lambda a: a[:1], layers)
        middle = jax.tree.map(lambda a: a[1:-1], layers)
        last = jax.tree.map(lambda a: a[-1:], layers)

        x, aux0 = super()._scan_layers(x, first, positions, attn_mask,
                                       remat_policy)

        # Per-step entropy: loss() has no step argument, so fold the raw BITS
        # of the first activation row into the key — activations depend on
        # the (updated-every-step) params, so the pattern varies per step.
        # (A plain float→int cast would truncate ~0.02-magnitude values to 0.)
        bits = lax.bitcast_convert_type(x[0, 0].astype(jnp.float32), jnp.int32)
        key = jax.random.fold_in(jax.random.PRNGKey(self.ltd_seed),
                                 jnp.sum(bits, dtype=jnp.int32) & 0x7fffffff)

        def mid_layer(carry, layer_params):
            x, key = carry
            key, sub = jax.random.split(key)
            # sorted random subset per batch row: (B, r)
            scores = jax.random.uniform(sub, (B, S))
            idx = jnp.sort(jnp.argsort(scores, axis=-1)[:, :r], axis=-1)
            brow = jnp.arange(B)[:, None]
            x_sub = x[brow, idx]                            # (B, r, d)
            pos_sub = positions[brow, idx]
            mask_sub = attn_mask[brow, idx] if attn_mask is not None else None
            body = self._layer
            if remat_policy is not None:
                body = jax.checkpoint(self._layer, policy=remat_policy,
                                      prevent_cse=False)
            y_sub, aux = body(x_sub, layer_params, pos_sub, mask_sub)
            x = x.at[brow, idx].set(y_sub)
            return (x, key), aux

        (x, _), auxs = lax.scan(mid_layer, (x, key), middle)
        x, aux1 = super()._scan_layers(x, last, positions, attn_mask,
                                       remat_policy)
        return x, aux0 + jnp.sum(auxs) + aux1


def convert_to_random_ltd(model, *, seed: int = 17):
    """Wrap a built model (TransformerLM or MoE trunk) with random-LTD
    (reference ``convert_to_random_ltd``). Same params/specs/pytree; only
    ``_scan_layers`` changes."""
    cls = type(model)
    new_cls = type(f"RandomLTD{cls.__name__}", (RandomLTDMixin, cls), {})
    new = object.__new__(new_cls)
    new.__dict__.update(model.__dict__)
    new.ltd_tokens = 0
    new.ltd_seed = seed
    return new
