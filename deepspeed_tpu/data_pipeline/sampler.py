"""Curriculum-aware data sampler.

Analog of the reference's ``data_pipeline/data_sampler.py:36``
(``DeepSpeedDataSampler``): given a per-sample difficulty metric (e.g. token
length, loss-based score), restrict sampling at step t to samples whose
metric ≤ the scheduler's current difficulty, with deterministic per-epoch
shuffling and per-host sharding (composes with the engine DataLoader the same
way the reference sampler feeds its dataloader).

The reference clusters samples by metric value into index files; at this
scale a sorted index + binary search over thresholds gives the same access
pattern without the clustering machinery.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import numpy as np

from .curriculum import CurriculumScheduler


class CurriculumSampler:
    """Iterator over dataset indices eligible at the current difficulty.

    ``metric`` maps a sample (or its index) to a difficulty value; ``None``
    uses ``len(sample["input_ids"])`` (seqlen curriculum, the reference's
    default metric).
    """

    def __init__(self, dataset, scheduler: CurriculumScheduler, *,
                 metric: Callable | None = None,
                 metrics: Sequence[float] | np.ndarray | None = None,
                 metric_index=None,
                 seed: int = 0, batch_size: int = 1,
                 shard_by_process: bool = True):
        self.dataset = dataset
        self.scheduler = scheduler
        self.metric_index = metric_index   # precomputed cluster files
        self.seed = seed
        self.batch_size = batch_size
        self.epoch = 0
        self.global_step = 0
        self.rank = jax.process_index() if shard_by_process else 0
        self.world = jax.process_count() if shard_by_process else 1
        if metric_index is not None:
            # precomputed difficulty-metric cluster index (reference
            # data_sampler.py:36 reads the analyzer's index files); the
            # sampler never touches the dataset to score it, and reuses the
            # index's sorted view rather than re-deriving it
            if len(metric_index.values) != len(dataset):
                raise ValueError(
                    f"metric index covers {len(metric_index.values)} samples "
                    f"but dataset has {len(dataset)}")
            self._metrics = metric_index.values
            self._order = metric_index.sorted_indices
            self._sorted_metrics = metric_index._sorted_values
            return
        if metrics is not None:
            # precomputed per-sample metrics (O(1) startup — pass
            # MMapIndexedDataset.lengths for a seqlen curriculum)
            self._metrics = np.asarray(metrics)
            if len(self._metrics) != len(dataset):
                raise ValueError(
                    f"{len(self._metrics)} metrics for {len(dataset)} samples")
        elif metric is None and hasattr(dataset, "lengths"):
            self._metrics = np.asarray(dataset.lengths)   # mmap index only
        else:
            metric = metric or (lambda s: len(s["input_ids"]))
            self._metrics = np.asarray([metric(dataset[i])
                                        for i in range(len(dataset))])
        self._order = np.argsort(self._metrics, kind="stable")
        self._sorted_metrics = self._metrics[self._order]

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def eligible_indices(self, difficulty) -> np.ndarray:
        """All dataset indices with metric ≤ difficulty (sorted by metric)."""
        n = int(np.searchsorted(self._sorted_metrics, difficulty, side="right"))
        return self._order[:max(n, 1)]   # never empty: easiest sample stays

    def __iter__(self):
        """Yields per-host index batches; difficulty advances per batch (one
        batch == one optimizer step, reference semantics). Batches are always
        full — if the eligible pool is smaller than the global batch, samples
        repeat (the pool is never empty by construction)."""
        rng = np.random.default_rng(self.seed + self.epoch)
        while True:
            difficulty = self.scheduler(self.global_step)
            pool = self.eligible_indices(difficulty)
            need = self.batch_size * self.world
            picks = rng.choice(pool, size=need, replace=len(pool) < need)
            local = picks[self.rank * self.batch_size:
                          (self.rank + 1) * self.batch_size]
            self.global_step += 1
            yield local.tolist(), difficulty
