"""Curriculum-learning difficulty scheduler.

Analog of the reference's ``data_pipeline/curriculum_scheduler.py:11``
(CurriculumScheduler): maps the global step to a difficulty value (typically
the training sequence length) under one of the reference's schedule types —
``fixed_linear``, ``fixed_root``, ``fixed_discrete``.  Difficulties are
rounded down to a multiple of ``difficulty_step`` (the reference does this so
seqlen stays tile/TP-friendly; on TPU it also bounds the number of distinct
compiled shapes).
"""

from __future__ import annotations

from typing import Sequence


class CurriculumScheduler:
    def __init__(self, *, min_difficulty: int, max_difficulty: int,
                 total_curriculum_step: int,
                 schedule_type: str = "fixed_linear",
                 difficulty_step: int = 8,
                 root_degree: int = 2,
                 difficulties: Sequence[int] = (),
                 max_steps: Sequence[int] = ()):
        if schedule_type not in ("fixed_linear", "fixed_root", "fixed_discrete"):
            raise ValueError(f"unknown curriculum schedule {schedule_type!r}")
        if schedule_type == "fixed_discrete" and (
                not difficulties or len(max_steps) != len(difficulties) - 1):
            raise ValueError(
                "fixed_discrete needs `difficulties` (N values) and "
                "`max_steps` (N-1 boundaries)")
        self.min = int(min_difficulty)
        self.max = int(max_difficulty)
        self.total = max(1, int(total_curriculum_step))
        self.kind = schedule_type
        self.step_quantum = max(1, int(difficulty_step))
        self.root = root_degree
        self.difficulties = list(difficulties)
        self.boundaries = list(max_steps)

    def __call__(self, step: int) -> int:
        if self.kind == "fixed_discrete":
            for d, bound in zip(self.difficulties, self.boundaries):
                if step < bound:
                    return int(d)
            return int(self.difficulties[-1])
        frac = min(1.0, max(0.0, step / self.total))
        if self.kind == "fixed_root":
            frac = frac ** (1.0 / self.root)
        d = self.min + (self.max - self.min) * frac
        d = int(d) // self.step_quantum * self.step_quantum
        return max(self.min, min(self.max, d))

    @classmethod
    def from_config(cls, cfg) -> "CurriculumScheduler":
        """Build from a CurriculumConfig pydantic node (config/config.py)."""
        return cls(min_difficulty=cfg.min_difficulty,
                   max_difficulty=cfg.max_difficulty,
                   total_curriculum_step=cfg.total_curriculum_step,
                   schedule_type=cfg.schedule_type,
                   difficulty_step=cfg.difficulty_step,
                   root_degree=cfg.root_degree,
                   difficulties=cfg.difficulties,
                   max_steps=cfg.max_steps)
