from .curriculum import CurriculumScheduler
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder
from .random_ltd import convert_to_random_ltd
from .sampler import CurriculumSampler

__all__ = ["CurriculumScheduler", "CurriculumSampler", "MMapIndexedDataset",
           "MMapIndexedDatasetBuilder", "convert_to_random_ltd"]
