from .curriculum import CurriculumScheduler
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder
from .metric_index import MetricIndex, build_metric_index
from .random_ltd import convert_to_random_ltd
from .sampler import CurriculumSampler

__all__ = ["CurriculumScheduler", "CurriculumSampler", "MMapIndexedDataset",
           "MMapIndexedDatasetBuilder", "MetricIndex", "build_metric_index",
           "convert_to_random_ltd"]
