"""Precomputed difficulty-metric cluster index (data-analyzer analog).

Reference: the data-efficiency library's analyzer precomputes per-sample
metric files and clusters samples by metric value into index files; its
``DeepSpeedDataSampler`` (``data_efficiency/.../data_sampler.py:36``) then
draws from the eligible clusters at each step. This module is the same
two-phase design: ``build_metric_index`` is the offline analyzer (map a
metric over the dataset once, bucket, persist as ``.npy`` files), and
:class:`MetricIndex` is the cluster structure the curriculum sampler reads —
startup cost is loading two small arrays, not re-scoring the corpus.

Files per index directory:
    metric_values.npy     (N,)  per-sample metric value
    bucket_bounds.npy     (B,)  right edge of each bucket (sorted)
    sorted_indices.npy    (N,)  sample ids sorted by metric (stable)
    bucket_offsets.npy    (B+1,) bucket b owns sorted_indices[off[b]:off[b+1]]
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

import numpy as np

_FILES = ("metric_values", "bucket_bounds", "sorted_indices", "bucket_offsets")


class MetricIndex:
    """Samples clustered by difficulty-metric value."""

    def __init__(self, values: np.ndarray, bounds: np.ndarray,
                 sorted_indices: np.ndarray, offsets: np.ndarray):
        self.values = values
        self.bounds = bounds
        self.sorted_indices = sorted_indices
        self.offsets = offsets
        self._sorted_values = values[sorted_indices]

    @property
    def n_buckets(self) -> int:
        return len(self.bounds)

    def eligible(self, difficulty) -> np.ndarray:
        """All sample ids whose metric ≤ difficulty, as one contiguous
        (pre-sorted) view — exact threshold, not bucket-granular (buckets
        exist for per-cluster bookkeeping/draws). Never empty: the easiest
        sample always qualifies."""
        end = int(np.searchsorted(self._sorted_values, difficulty,
                                  side="right"))
        return self.sorted_indices[:max(end, 1)]

    def bucket_of(self, sample_id: int) -> int:
        return int(np.searchsorted(self.bounds, self.values[sample_id],
                                   side="left"))

    # -------------------------------------------------------------- persist
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        for name, arr in zip(_FILES, (self.values, self.bounds,
                                      self.sorted_indices, self.offsets)):
            np.save(os.path.join(path, f"{name}.npy"), arr)

    @classmethod
    def load(cls, path: str) -> "MetricIndex":
        return cls(*(np.load(os.path.join(path, f"{n}.npy"))
                     for n in _FILES))


def build_metric_index(dataset=None, *, metric: Optional[Callable] = None,
                       values: "Optional[Sequence[float]]" = None,
                       n_buckets: int = 64,
                       path: Optional[str] = None) -> MetricIndex:
    """The analyzer pass: score every sample once, cluster by value.

    ``values`` short-circuits scoring (e.g. ``MMapIndexedDataset.lengths``).
    Buckets are quantile-based over the distinct values so skewed metric
    distributions still spread across clusters; ``path`` persists the index.
    """
    if values is None:
        if dataset is None:
            raise ValueError("need a dataset or precomputed values")
        metric = metric or (lambda s: len(s["input_ids"]))
        values = [metric(dataset[i]) for i in range(len(dataset))]
    values = np.asarray(values)
    order = np.argsort(values, kind="stable")
    svals = values[order]
    uniq = np.unique(svals)
    if len(uniq) <= n_buckets:
        bounds = uniq
    else:
        qs = np.quantile(uniq, np.linspace(0, 1, n_buckets + 1)[1:])
        bounds = np.unique(qs)
    # bucket b = metrics in (bounds[b-1], bounds[b]]
    offsets = np.concatenate([
        [0], np.searchsorted(svals, bounds, side="right")]).astype(np.int64)
    idx = MetricIndex(values, bounds, order.astype(np.int64), offsets)
    if path:
        idx.save(path)
    return idx
