"""Device-mesh construction and axis bookkeeping.

One global ``jax.sharding.Mesh`` with named axes replaces the reference's
process-group bookkeeping (``deepspeed/utils/groups.py``, 530 LoC) and the
pipeline cartesian grid (``runtime/pipe/topology.py:244``). Every parallelism
strategy is an axis:

    ====================  =============================================
    axis                  reference analog
    ====================  =============================================
    ``pipe``              pipeline-parallel stage groups (pipe/topology.py)
    ``data``              data-parallel / ZeRO partition groups
    ``expert``            expert-parallel groups (utils/groups.py:113)
    ``seq``               Ulysses sequence-parallel groups (groups.py:420)
    ``model``             tensor(model)-parallel groups (Megatron mpu)
    ====================  =============================================

Axis order is chosen for fabric locality: ``model`` (highest-traffic
collectives) innermost so it lands on the tightest ICI ring, ``pipe``/``data``
outermost so they can span DCN on multi-slice deployments — the 2-level
ICI/DCN hierarchy that the reference builds by hand for MiCS hierarchical
allgather (``runtime/zero/mics.py:227``) and ZeRO++ hpZ falls out of this
layout for free.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.logging import logger

# Canonical axis order, outermost (DCN-friendly) to innermost (ICI-friendly).
# ``zero`` is the hpZ/MiCS sub-axis: a fast-ICI subgroup carved out of the
# data-parallel dimension (total DP world = data x zero). It sits inside
# ``data`` so its collectives ride the tighter interconnect — the 2-level
# hierarchy the reference builds by hand for ZeRO++ hpZ secondary shards
# (runtime/zero/config.py:256) and MiCS sub-groups (runtime/zero/mics.py:55).
AXIS_ORDER = ("pipe", "data", "zero", "expert", "seq", "model")

# Axes that partition *examples* (the batch dim): DP, and expert-parallel
# groups, which are carved out of the DP group in the reference
# (utils/groups.py:113). The ``seq`` axis shards the *sequence* dim of the
# same examples (Ulysses): for batch arithmetic it multiplies nothing, but
# gradient reduction spans data x expert x seq — the reference's "ZeRO dp
# group becomes seq x dp" wiring (engine.py:1116-1122) falls out of XLA's
# partial-sum handling automatically.
BATCH_AXES = ("data", "zero", "expert")
SEQ_AXIS = "seq"


@dataclasses.dataclass
class MeshSpec:
    """Logical parallelism degrees. ``data=-1`` absorbs remaining devices."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1
    zero: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {"pipe": self.pipe, "data": self.data, "zero": self.zero,
                 "expert": self.expert, "seq": self.seq, "model": self.model}
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        n_auto = sum(1 for v in sizes.values() if v == -1)
        if n_auto > 1:
            raise ValueError("at most one mesh axis may be -1 (auto)")
        if n_auto == 1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by fixed axes product {fixed}")
            auto = n_devices // fixed
            sizes = {k: (auto if v == -1 else v) for k, v in sizes.items()}
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} requires {total} devices but {n_devices} are available")
        return sizes


def build_mesh(spec: MeshSpec | None = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except Exception:
        # Fallback (e.g. host-platform CPU devices with no topology info).
        dev_array = np.asarray(list(devices)).reshape(shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    logger.info(f"mesh: {dict(zip(AXIS_ORDER, shape))} over {len(devices)} devices")
    return mesh


# --------------------------------------------------------------------- helpers
def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def dp_world_size(mesh: Mesh) -> int:
    """Examples-parallel world size (data × expert), the divisor in the
    reference's train_batch = micro_batch × GAS × dp_world arithmetic."""
    return int(np.prod([mesh.shape[a] for a in BATCH_AXES]))


def batch_pspec() -> PartitionSpec:
    """Batch-dim sharding over all example-parallel axes."""
    return PartitionSpec(BATCH_AXES)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_batch_slice(mesh: Mesh) -> tuple[int, int]:
    """(index, count) of this host's shard of the global batch dimension."""
    # Per-host data loading: each process owns an equal contiguous slice.
    return jax.process_index(), jax.process_count()


def current_mesh():
    """The mesh active in this trace/context, or None. Checks the abstract
    mesh first (``jax.set_mesh`` / inside-jit), then the legacy
    ``with mesh:`` thread resources."""
    try:
        from jax.sharding import get_abstract_mesh
    except ImportError:          # jax 0.4.x: no abstract-mesh API
        get_abstract_mesh = None
    if get_abstract_mesh is not None:
        ctx = get_abstract_mesh()
        if ctx is not None and not ctx.empty:
            return ctx
    try:
        from jax._src.mesh import thread_resources

        ctx = thread_resources.env.physical_mesh
    except Exception:
        return None
    return None if (ctx is None or ctx.empty) else ctx


def manual_axes_of(mesh) -> frozenset:
    """Axis names that are *manual* in the current trace context — i.e.
    the caller already holds a per-device block of them (inside a
    shard_map body). jax 0.9 exposes this as ``AbstractMesh.manual_axes``;
    on 0.4.x the physical mesh carries no such attribute, but the bound
    axis-env names ARE the manual axes."""
    manual = getattr(mesh, "manual_axes", None)
    if manual is not None:
        # present-but-empty is an ANSWER (nothing manual) — falling
        # through to the axis-env probe would misreport vmap/pmap
        # axis_name frames as manual mesh axes
        return frozenset(manual)
    try:
        from jax.core import unsafe_get_axis_names_DO_NOT_USE as _names

        return frozenset(_names())
    except (ImportError, AttributeError):
        return frozenset()


def constrain(x, *spec_or_pspec):
    """``with_sharding_constraint`` that no-ops when no mesh is in context
    (single-chip / un-meshed execution) and ignores axes the context mesh
    doesn't carry — or that are *manual* in the current ``shard_map`` body
    (the caller already holds a per-device block of those). Models use this
    so the same code runs on a bare chip, on any parallel mesh, and inside
    partially-manual shard_maps (e.g. the compressed-gradient data axis)."""
    ctx = current_mesh()
    if ctx is None:
        return x
    spec = spec_or_pspec[0] if len(spec_or_pspec) == 1 and isinstance(
        spec_or_pspec[0], PartitionSpec) else PartitionSpec(*spec_or_pspec)
    filtered = filter_spec(spec)
    # Inside a manual region a fully-filtered (all-None) constraint is a
    # no-op intent-wise; older JAX additionally has no replication rule
    # for the primitive there (check_rep) — skip it outright.
    if manual_axes_of(ctx) and all(e is None for e in filtered):
        return x
    return jax.lax.with_sharding_constraint(x, filtered)


def filter_spec(spec: PartitionSpec) -> PartitionSpec:
    """Drop axes the context mesh doesn't carry or that are manual."""
    ctx = current_mesh()
    if ctx is None:
        return spec
    manual = manual_axes_of(ctx)

    def filter_entry(e):
        if e is None:
            return None
        names = e if isinstance(e, (tuple, list)) else (e,)
        kept = tuple(n for n in names
                     if n in ctx.axis_names and n not in manual)
        return kept if len(kept) > 1 else (kept[0] if kept else None)

    return PartitionSpec(*(filter_entry(e) for e in spec))


def to_device_memory(tree, spec_tree=None):
    """Copy a (host-memory-resident) pytree into device HBM inside jit —
    the per-layer page-in of ZeRO-Infinity param offload. No-op outside a
    mesh context. ``spec_tree`` preserves each leaf's sharding across the
    memory-space move (device_put needs an explicit sharding in-jit)."""
    ctx = current_mesh()
    if ctx is None:
        return tree

    def put(x, spec):
        spec = filter_spec(spec if isinstance(spec, PartitionSpec)
                           else PartitionSpec())
        try:
            return jax.device_put(
                x, NamedSharding(ctx, spec, memory_kind="device"))
        except ValueError:
            # backends without an addressable "device" memory kind (older
            # JAX CPU exposes only unpinned_host): the page-in is a no-op
            # placement-wise but keeps the sharding
            return jax.device_put(x, NamedSharding(ctx, spec))

    if spec_tree is None:
        return jax.tree.map(lambda x: put(x, None), tree)
    return jax.tree.map(put, tree, spec_tree)
