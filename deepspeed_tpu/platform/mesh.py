"""Device-mesh construction and axis bookkeeping.

One global ``jax.sharding.Mesh`` with named axes replaces the reference's
process-group bookkeeping (``deepspeed/utils/groups.py``, 530 LoC) and the
pipeline cartesian grid (``runtime/pipe/topology.py:244``). Every parallelism
strategy is an axis:

    ====================  =============================================
    axis                  reference analog
    ====================  =============================================
    ``pipe``              pipeline-parallel stage groups (pipe/topology.py)
    ``data``              data-parallel / ZeRO partition groups
    ``expert``            expert-parallel groups (utils/groups.py:113)
    ``seq``               Ulysses sequence-parallel groups (groups.py:420)
    ``model``             tensor(model)-parallel groups (Megatron mpu)
    ====================  =============================================

Axis order is chosen for fabric locality: ``model`` (highest-traffic
collectives) innermost so it lands on the tightest ICI ring, ``pipe``/``data``
outermost so they can span DCN on multi-slice deployments — the 2-level
ICI/DCN hierarchy that the reference builds by hand for MiCS hierarchical
allgather (``runtime/zero/mics.py:227``) and ZeRO++ hpZ falls out of this
layout for free.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.logging import logger

# Canonical axis order, outermost (DCN-friendly) to innermost (ICI-friendly).
AXIS_ORDER = ("pipe", "data", "expert", "seq", "model")

# Axes that partition *examples* (the batch dim): DP, and expert-parallel
# groups, which are carved out of the DP group in the reference
# (utils/groups.py:113). The ``seq`` axis shards the *sequence* dim of the
# same examples (Ulysses): for batch arithmetic it multiplies nothing, but
# gradient reduction spans data x expert x seq — the reference's "ZeRO dp
# group becomes seq x dp" wiring (engine.py:1116-1122) falls out of XLA's
# partial-sum handling automatically.
BATCH_AXES = ("data", "expert")
SEQ_AXIS = "seq"


@dataclasses.dataclass
class MeshSpec:
    """Logical parallelism degrees. ``data=-1`` absorbs remaining devices."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {"pipe": self.pipe, "data": self.data, "expert": self.expert,
                 "seq": self.seq, "model": self.model}
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        n_auto = sum(1 for v in sizes.values() if v == -1)
        if n_auto > 1:
            raise ValueError("at most one mesh axis may be -1 (auto)")
        if n_auto == 1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by fixed axes product {fixed}")
            auto = n_devices // fixed
            sizes = {k: (auto if v == -1 else v) for k, v in sizes.items()}
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} requires {total} devices but {n_devices} are available")
        return sizes


def build_mesh(spec: MeshSpec | None = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except Exception:
        # Fallback (e.g. host-platform CPU devices with no topology info).
        dev_array = np.asarray(list(devices)).reshape(shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    logger.info(f"mesh: {dict(zip(AXIS_ORDER, shape))} over {len(devices)} devices")
    return mesh


# --------------------------------------------------------------------- helpers
def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def dp_world_size(mesh: Mesh) -> int:
    """Examples-parallel world size (data × expert), the divisor in the
    reference's train_batch = micro_batch × GAS × dp_world arithmetic."""
    return int(np.prod([mesh.shape[a] for a in BATCH_AXES]))


def batch_pspec() -> PartitionSpec:
    """Batch-dim sharding over all example-parallel axes."""
    return PartitionSpec(BATCH_AXES)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_batch_slice(mesh: Mesh) -> tuple[int, int]:
    """(index, count) of this host's shard of the global batch dimension."""
    # Per-host data loading: each process owns an equal contiguous slice.
    return jax.process_index(), jax.process_count()


def current_mesh():
    """The mesh active in this trace/context, or None. Checks the abstract
    mesh first (``jax.set_mesh`` / inside-jit), then the legacy
    ``with mesh:`` thread resources."""
    from jax.sharding import get_abstract_mesh

    ctx = get_abstract_mesh()
    if ctx is not None and not ctx.empty:
        return ctx
    try:
        from jax._src.mesh import thread_resources

        ctx = thread_resources.env.physical_mesh
    except Exception:
        return None
    return None if (ctx is None or ctx.empty) else ctx


def constrain(x, *spec_or_pspec):
    """``with_sharding_constraint`` that no-ops when no mesh is in context
    (single-chip / un-meshed execution) and ignores axes the context mesh
    doesn't carry. Models use this so the same code runs on a bare chip and
    on any parallel mesh."""
    ctx = current_mesh()
    if ctx is None:
        return x
    spec = spec_or_pspec[0] if len(spec_or_pspec) == 1 and isinstance(
        spec_or_pspec[0], PartitionSpec) else PartitionSpec(*spec_or_pspec)

    def filter_entry(e):
        if e is None:
            return None
        names = e if isinstance(e, (tuple, list)) else (e,)
        kept = tuple(n for n in names if n in ctx.axis_names)
        return kept if len(kept) > 1 else (kept[0] if kept else None)

    spec = PartitionSpec(*(filter_entry(e) for e in spec))
    return jax.lax.with_sharding_constraint(x, spec)
