"""Hardware/platform abstraction.

TPU-native analog of the reference accelerator layer
(``accelerator/abstract_accelerator.py:10`` and ``real_accelerator.py``): a
single seam through which the rest of the framework asks about devices,
memory, dtypes, and the communication fabric — nothing above this module
touches ``jax.devices()`` directly.

The reference abstracts over CUDA streams/events/RNG; under XLA those concepts
are owned by the compiler, so the surface here is the part that still matters
on TPU: device discovery, platform naming, memory kinds & stats, dtype
support, host/device transfer helpers, and multi-host initialization.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np

from ..utils.logging import logger

_ACCELERATOR: Optional["TpuAccelerator"] = None


@dataclass
class MemoryStats:
    bytes_in_use: int = 0
    peak_bytes_in_use: int = 0
    bytes_limit: int = 0

    @property
    def available_bytes(self) -> int:
        return max(0, self.bytes_limit - self.bytes_in_use)

    def as_dict(self) -> dict:
        """Gauge-ready view (observability HBM watermark sampling)."""
        return {"bytes_in_use": self.bytes_in_use,
                "peak_bytes_in_use": self.peak_bytes_in_use,
                "bytes_limit": self.bytes_limit,
                "available_bytes": self.available_bytes}


class TpuAccelerator:
    """Device/platform facade over JAX.

    Named "Tpu" for the primary target, but transparently backed by whatever
    platform JAX selected (tpu / cpu / gpu / experimental tunnels), the same
    way the reference probes for the real accelerator at import time
    (``accelerator/real_accelerator.py``).
    """

    def __init__(self, platform: str | None = None):
        self._platform = platform or os.environ.get("DSTPU_ACCELERATOR") or None
        self._devices = None

    # ------------------------------------------------------------------ info
    @property
    def platform(self) -> str:
        return self.devices()[0].platform

    def device_name(self, index: int | None = None) -> str:
        if index is None:
            return self.platform
        return f"{self.platform}:{index}"

    def devices(self) -> list[jax.Device]:
        if self._devices is None:
            self._devices = jax.devices(self._platform) if self._platform else jax.devices()
        return self._devices

    def device_count(self) -> int:
        return len(self.devices())

    def local_devices(self) -> list[jax.Device]:
        plat = self._platform
        return [d for d in (jax.local_devices()) if plat is None or d.platform == plat]

    def local_device_count(self) -> int:
        return len(self.local_devices())

    def process_index(self) -> int:
        return jax.process_index()

    def process_count(self) -> int:
        return jax.process_count()

    def current_device(self) -> jax.Device:
        return self.devices()[0]

    def on_tpu(self) -> bool:
        return self.platform == "tpu"

    # -------------------------------------------------------------- memories
    def memory_kinds(self) -> tuple[str, ...]:
        """Addressable memory kinds: device HBM plus host-pinned staging.

        The host memory kind is the TPU analog of the reference's pinned-memory
        APIs (``abstract_accelerator.py`` pin_memory) and is what the offload
        tiers target.
        """
        try:
            return tuple(m.kind for m in self.current_device().addressable_memories())
        except Exception:
            return ("device",)

    def supports_host_offload(self) -> bool:
        return "pinned_host" in self.memory_kinds()

    def memory_stats(self, device: jax.Device | None = None) -> MemoryStats:
        device = device or self.current_device()
        try:
            ms = device.memory_stats() or {}
        except Exception:
            ms = {}
        return MemoryStats(
            bytes_in_use=ms.get("bytes_in_use", 0),
            peak_bytes_in_use=ms.get("peak_bytes_in_use", ms.get("bytes_in_use", 0)),
            bytes_limit=ms.get("bytes_limit", ms.get("bytes_reservable_limit", 0)),
        )

    def total_memory(self) -> int:
        return self.memory_stats().bytes_limit

    def available_memory(self) -> int:
        return self.memory_stats().available_bytes

    # ---------------------------------------------------------------- dtypes
    def is_bf16_supported(self) -> bool:
        return True  # native on every TPU generation this framework targets

    def is_fp16_supported(self) -> bool:
        return True  # representable; bf16 is preferred on TPU

    def is_fp8_supported(self) -> bool:
        return self.platform == "tpu"

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16

    def supported_dtypes(self):
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # ------------------------------------------------------------------ comm
    def communication_backend_name(self) -> str:
        """ICI/DCN via XLA collectives (the NCCL analog is the compiler)."""
        return "xla"

    # ------------------------------------------------------------- op lookup
    def create_op_builder(self, name: str):
        from ..ops.registry import get_op_builder

        return get_op_builder(name, platform=self.platform)

    # ----------------------------------------------------------------- misc
    def synchronize(self) -> None:
        """Block until all dispatched device work is complete."""
        try:
            jax.block_until_ready(jax.device_put(np.zeros(())))
        except Exception:  # pragma: no cover - defensive
            pass

    def random_seed(self, seed: int):
        return jax.random.PRNGKey(seed)


def get_accelerator() -> TpuAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = TpuAccelerator()
        logger.info(
            f"deepspeed_tpu accelerator: platform={_ACCELERATOR.platform} "
            f"devices={_ACCELERATOR.device_count()} processes={_ACCELERATOR.process_count()}"
        )
    return _ACCELERATOR


def set_accelerator(acc: TpuAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = acc


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Multi-host initialization (analog of ``deepspeed.init_distributed``).

    Single-host jobs need not call this. Multi-host jobs call it once per host
    before any JAX computation; afterwards ``jax.devices()`` spans the full
    pod/slice and SPMD programs run over DCN+ICI transparently.
    """
    if num_processes is None:
        num_processes = int(os.environ.get("DSTPU_NUM_PROCESSES", "0")) or None
    if process_id is None:
        pid = os.environ.get("DSTPU_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if num_processes is None or num_processes <= 1:
        logger.info("init_distributed: single-process mode (no coordinator)")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        f"init_distributed: process {jax.process_index()}/{jax.process_count()} "
        f"local_devices={len(jax.local_devices())} global_devices={len(jax.devices())}"
    )
