from .accelerator import (TpuAccelerator, get_accelerator, init_distributed,
                          set_accelerator)
from .mesh import (AXIS_ORDER, BATCH_AXES, MeshSpec, batch_pspec, build_mesh,
                   dp_world_size, named_sharding, replicated)

__all__ = ["TpuAccelerator", "get_accelerator", "set_accelerator", "init_distributed",
           "MeshSpec", "build_mesh", "AXIS_ORDER", "BATCH_AXES", "batch_pspec",
           "dp_world_size", "named_sharding", "replicated"]
