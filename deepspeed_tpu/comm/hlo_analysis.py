"""Collective accounting from compiled HLO.

The reference's comms logger wraps every collective call at the Python layer
(``deepspeed/comm/comm.py:101`` ``timed_op``/``CommsLogger``). Under XLA most
collectives are *inserted by GSPMD* from sharding constraints, so no Python
wrapper ever sees them; the honest TPU analog inspects the compiled program.
``collective_summary(compiled)`` walks the optimized HLO and returns per-op
counts and payload bytes — exact, since shapes are static.

Used by the engine's comms_logger wiring and by tests asserting that
ZeRO++/1-bit actually shrink wire bytes.
"""

from __future__ import annotations

import re
from typing import Any

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

_COLLECTIVES = ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
                "collective-permute", "collective-broadcast",
                "ragged-all-to-all")

# e.g. "s8[8,16,2048]{3,2,1,0}" or "f32[]"
_SHAPE_RE = re.compile(r"\b([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(type_str: str, variadic: bool = False) -> int:
    """Payload bytes of a result type.

    Tuple types appear in two distinct spellings and must be counted
    differently:

    - async ``-start`` ops carry ``(operand, result, ...contexts)`` with
      the operand aliased into the result — counting only the LARGEST
      member avoids double-counting the alias (``variadic=False``);
    - variadic sync collectives (tuple-form ``all-to-all`` over n
      per-peer arrays, multi-operand ``all-reduce``) return one tuple of
      n INDEPENDENT payloads — the wire volume is their SUM
      (``variadic=True``; counting the max here silently undercounted an
      n-way tuple all-to-all n-fold)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        sizes.append(n * _DTYPE_BYTES[dtype])
    if not sizes:
        return 0
    if type_str.lstrip().startswith("(") and not variadic:
        return max(sizes)
    return sum(sizes)


def collective_summary(compiled_or_text: Any) -> dict[str, dict[str, float]]:
    """Per-collective-kind {count, mbytes} from a ``jax.stages.Compiled``
    (or raw HLO text). Bytes are the op result payloads (the gathered /
    reduced tensor), a stable proxy for wire volume."""
    if isinstance(compiled_or_text, str):
        txt = compiled_or_text
    else:
        txt = compiled_or_text.as_text()
    out: dict[str, dict[str, float]] = {}
    for line in txt.splitlines():
        line = line.strip()
        # "%name = <type> <op>(" — match the op after the '=' to avoid
        # counting operand mentions.
        m = re.match(r"%?[\w.\-]+\s*=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\]\S*)\s+"
                     r"([a-z\-]+)", line)
        if not m:
            continue
        op = m.group(2)
        if op.endswith("-done"):   # async pair: count the -start only
            continue
        is_start = op.endswith("-start")
        kind = op[:-6] if is_start else op
        if kind not in _COLLECTIVES:
            continue
        d = out.setdefault(kind, {"count": 0, "mbytes": 0.0})
        d["count"] += 1
        # sync tuple results are variadic payloads (sum); -start tuples
        # alias the operand into the result (max) — see _shape_bytes
        d["mbytes"] += _shape_bytes(m.group(1), variadic=not is_start) / 1e6
    return out


def total_collective_mbytes(compiled_or_text: Any) -> float:
    return sum(d["mbytes"] for d in collective_summary(compiled_or_text).values())


def collective_totals(compiled_or_text: Any) -> dict[str, float]:
    """One-row reduction of :func:`collective_summary` — ``{count,
    mbytes}`` over every collective kind. The per-program row shape the
    capacity census (``observability/capacity.py``) registers for each
    compiled program, so per-program wire bytes can be ranked against
    per-program HBM bytes."""
    per_kind = collective_summary(compiled_or_text)
    return {"count": sum(d["count"] for d in per_kind.values()),
            "mbytes": sum(d["mbytes"] for d in per_kind.values()),
            "by_kind": per_kind}
