"""``dstpu_bench``: collective micro-benchmark CLI.

Analog of the reference's ``bin/ds_bench`` (→ ``benchmarks/communication``):
sweep message sizes over the core collectives and report measured
algorithmic bandwidth per op. Runs on whatever devices JAX sees — the
virtual CPU mesh for plumbing checks, a TPU slice for real ICI numbers.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _bench_op(op_name: str, mesh: Mesh, n_elems: int, iters: int,
              dtype=jnp.float32) -> dict:
    """One (op, size) cell: compile, warm up, time, compute busbw."""
    D = mesh.devices.size
    axis = "x"

    # route through the package's own comm wrappers so the CommsLogger
    # ledger sees the traffic and the call conventions live in one place
    from . import comm as dcomm

    def body(x):
        if op_name == "all_reduce":
            return dcomm.all_reduce(x, axis)
        if op_name == "all_gather":
            return dcomm.all_gather(x, axis)
        if op_name == "reduce_scatter":
            return dcomm.reduce_scatter(x, axis)
        if op_name == "all_to_all":
            return dcomm.all_to_all(x.reshape(D, -1), axis, split_axis=0,
                                    concat_axis=0).reshape(-1)
        raise ValueError(op_name)

    per_dev = max(D * 8, n_elems // D)
    if op_name == "reduce_scatter":
        per_dev = max(per_dev, D)
    per_dev = per_dev // D * D          # a2a/scatter need divisibility
    sharding = NamedSharding(mesh, P(axis))
    x = jax.device_put(
        jnp.arange(per_dev * D, dtype=dtype) / (per_dev * D), sharding)
    fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(axis),
                               out_specs=(P() if op_name == "all_reduce"
                                          else P(axis)),
                               check_vma=False))
    def _sync(o):
        # readback of the local shard only: works on multi-host slices
        # (a full np.asarray of a global array spanning non-addressable
        # devices would raise) and is a true barrier over remote tunnels
        leaf = jax.tree.leaves(o)[0]
        float(np.asarray(leaf.addressable_shards[0].data).ravel()[0])

    out = fn(x)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    _sync(out)
    dt = (time.perf_counter() - t0) / iters

    nbytes = per_dev * D * jnp.dtype(dtype).itemsize
    # standard busbw factors (NCCL-tests convention)
    factor = {"all_reduce": 2 * (D - 1) / D, "all_gather": (D - 1) / D,
              "reduce_scatter": (D - 1) / D, "all_to_all": (D - 1) / D}[op_name]
    busbw = nbytes * factor / dt if dt > 0 else float("inf")
    return {"op": op_name, "bytes": nbytes, "ms": dt * 1e3,
            "busbw_gbps": busbw / 1e9}


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="dstpu_bench", description="collective micro-benchmarks")
    p.add_argument("--ops", default="all_reduce,all_gather,reduce_scatter,"
                                    "all_to_all")
    p.add_argument("--min_elems", type=int, default=1 << 14)
    p.add_argument("--max_elems", type=int, default=1 << 24)
    p.add_argument("--iters", type=int, default=5)
    args = p.parse_args(argv)
    if args.iters < 1:
        p.error("--iters must be >= 1")
    if args.min_elems < 1 or args.max_elems < args.min_elems:
        p.error("need 1 <= min_elems <= max_elems")

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("x",))
    print(f"devices: {len(devices)} × {devices.ravel()[0].platform} | "
          f"iters={args.iters}")
    print(f"{'op':<16} {'bytes':>12} {'latency':>10} {'busbw':>12}")
    for op in args.ops.split(","):
        n = args.min_elems
        while n <= args.max_elems:
            r = _bench_op(op.strip(), mesh, n, args.iters)
            print(f"{r['op']:<16} {r['bytes']:>12,} {r['ms']:>8.2f}ms "
                  f"{r['busbw_gbps']:>9.2f} GB/s")
            n *= 16
    print("done")


if __name__ == "__main__":
    main()
