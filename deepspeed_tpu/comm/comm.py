"""Named-axis collective API + comms logging.

TPU-native analog of ``deepspeed/comm/comm.py``: the reference exposes a
torch.distributed-superset API over a global backend object and wraps every
collective in a ``timed_op`` profiler (``comm/comm.py:101-134``). Here the
"backend" is XLA itself — these wrappers are called *inside* ``shard_map``/
``jit`` bodies with mesh axis names, and XLA lowers them onto ICI/DCN.

Because collectives execute inside a compiled program, per-op host timing is
meaningless; instead the ``CommsLogger`` records op/volume metadata at trace
time (exact, since shapes are static) and can report aggregate volumes per
axis — the analog of the reference's msg-size/algbw log
(``utils/comms_logging.py``).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import log_dist

_REDUCE_OPS = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin, "mean": lax.pmean}


@dataclass
class CommEvent:
    op: str
    axis: str
    bytes: int
    shape: tuple
    dtype: str


@dataclass
class CommsLogger:
    """Trace-time collective ledger (reference ``CommsLogger``)."""

    enabled: bool = False
    verbose: bool = False
    events: list[CommEvent] = field(default_factory=list)

    def record(self, op: str, axis: Any, x: Any) -> None:
        if not self.enabled:
            return
        try:
            leaves = jax.tree_util.tree_leaves(x)
            nbytes = sum(l.size * l.dtype.itemsize for l in leaves)
            shape = tuple(leaves[0].shape) if leaves else ()
            dtype = str(leaves[0].dtype) if leaves else "?"
        except Exception:
            nbytes, shape, dtype = 0, (), "?"
        ev = CommEvent(op=op, axis=str(axis), bytes=nbytes, shape=shape, dtype=dtype)
        self.events.append(ev)
        if self.verbose:
            log_dist(f"comm: {op} axis={axis} {shape} {dtype} ({nbytes / 1e6:.2f} MB)",
                     ranks=[0])

    def summary(self) -> dict[str, dict[str, float]]:
        out: dict[str, dict[str, float]] = {}
        for ev in self.events:
            key = f"{ev.op}@{ev.axis}"
            d = out.setdefault(key, {"count": 0, "mbytes": 0.0})
            d["count"] += 1
            d["mbytes"] += ev.bytes / 1e6
        return out

    def log_summary(self) -> dict[str, dict[str, float]]:
        """Log the aggregate per-op/axis volumes AND return them (the
        reference's version was log-line-only; returning the dict makes the
        ledger testable and lets callers export it as monitor events)."""
        out = self.summary()
        for key, d in out.items():
            log_dist(f"comm summary | {key}: n={int(d['count'])} vol={d['mbytes']:.1f} MB",
                     ranks=[0])
        return out

    def as_monitor_events(self, step: int = 0) -> list[tuple]:
        """Ledger → ``(name, value, step)`` tuples under the ``Comm/*``
        namespace, ready for ``MonitorMaster.write_events`` or a
        ``MetricsRegistry``."""
        events: list[tuple] = []
        for key, d in sorted(self.summary().items()):
            events.append((f"Comm/{key}/count", float(d["count"]), step))
            events.append((f"Comm/{key}/mbytes", float(d["mbytes"]), step))
        return events

    def reset(self) -> None:
        self.events.clear()


comms_logger = CommsLogger()


def _logged(fn):
    @functools.wraps(fn)
    def wrapper(x, axis_name, *args, **kwargs):
        comms_logger.record(fn.__name__, axis_name, x)
        return fn(x, axis_name, *args, **kwargs)

    return wrapper


# ------------------------------------------------------------------ collectives
@_logged
def all_reduce(x, axis_name: str | Sequence[str], op: str = "sum"):
    return jax.tree.map(lambda t: _REDUCE_OPS[op](t, axis_name), x)


@_logged
def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.tree.map(lambda t: lax.all_gather(t, axis_name, axis=axis, tiled=tiled), x)


@_logged
def reduce_scatter(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.tree.map(
        lambda t: lax.psum_scatter(t, axis_name, scatter_dimension=axis, tiled=tiled), x)


@_logged
def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, tiled: bool = True):
    return jax.tree.map(
        lambda t: lax.all_to_all(t, axis_name, split_axis=split_axis,
                                 concat_axis=concat_axis, tiled=tiled), x)


@_logged
def ppermute(x, axis_name: str, perm: Sequence[tuple[int, int]]):
    return jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm=perm), x)


@_logged
def broadcast(x, axis_name: str, src: int = 0):
    """Broadcast ``src``'s value to every member of the axis."""

    def _bcast(t):
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == src, t, jnp.zeros_like(t))
        return lax.psum(masked, axis_name)

    return jax.tree.map(_bcast, x)


def barrier(axis_name: str):
    """Synchronize an axis (a psum of a scalar; XLA orders around it)."""
    return lax.psum(jnp.zeros((), jnp.float32), axis_name)


def get_world_size(axis_name: str | Sequence[str]) -> int:
    """Axis size from inside a shard_map body."""
    if isinstance(axis_name, (tuple, list)):
        size = 1
        for a in axis_name:
            size *= lax.axis_size(a)
        return size
    return lax.axis_size(axis_name)


def get_rank(axis_name: str | None = None):
    """This shard's index along ``axis_name`` (trace-time, inside a
    shard_map body) — or the host PROCESS index when no axis is given.

    .. warning:: the no-axis form is NOT the reference's global per-device
       rank: ``deepspeed.comm.get_rank()`` counts devices, this counts
       host processes, and they diverge whenever a host drives more than
       one chip. Ported rank arithmetic (rank→device maps, per-rank file
       names) should use :func:`get_process_rank` explicitly for host
       identity, or an axis-scoped ``get_rank(axis)`` for device identity.
    """
    if axis_name is None:
        return jax.process_index()
    return lax.axis_index(axis_name)


def get_process_rank() -> int:
    """Host process index (explicit spelling of ``get_rank()``'s no-axis
    form — see the warning there)."""
    return jax.process_index()
