"""Compressed data-parallel gradient synchronization.

TPU-native analog of the reference's compressed collectives:

- ``int8`` mode = ZeRO++ qgZ (``runtime/zero/config.py:268``
  ``zero_quantized_gradients``; ``runtime/comm/coalesced_collectives.py:31``
  quantized reduce-scatter): blockwise-int8 all-to-all, local reduction,
  blockwise-int8 all-gather — 4x fewer bytes on the wire than fp32.
- ``onebit`` mode = 1-bit Adam's error-feedback sign compression
  (``runtime/comm/nccl.py:51`` ``compressed_allreduce``): worker-side
  sign+scale with a worker error residual, all-to-all, server-side average
  re-compressed with a server error residual, all-gather. Signs travel
  bit-packed (8 signs/byte) — ~16x fewer bytes than bf16.

These run *inside* a ``shard_map`` body whose ``data`` axis is manual: the
engine computes per-rank local gradients there, calls one of these to
complete the cross-data reduction explicitly, and XLA lowers the collectives
onto ICI/DCN. The hierarchy falls out of the mesh: the fast ``zero``/
``expert`` sub-axes stay GSPMD-managed (full-precision, ICI-local) and only
the slow ``data`` hop is compressed — the reference's 2-hop qgZ design.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.quant import quant_blocks as _quant_blocks

BLOCK = 2048  # elements per quantization scale


# ------------------------------------------------------------------ flatten
def flat_size(tree_or_shapes) -> int:
    leaves = jax.tree.leaves(tree_or_shapes)
    return int(sum(int(np.prod(getattr(l, "shape", l))) for l in leaves))


def flatten_tree(tree):
    """Pytree → (flat fp32 vector, unflatten closure)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])

    def unflatten(v):
        parts = jnp.split(v, np.cumsum(sizes)[:-1]) if len(sizes) > 1 else [v]
        return jax.tree_util.tree_unflatten(
            treedef, [p.reshape(s) for p, s in zip(parts, shapes)])

    return flat, unflatten


def chunk_elems(n: int, world: int, block: int = BLOCK) -> int:
    """Per-rank chunk length: ceil to a whole number of scale blocks."""
    per = -(-n // world)
    return -(-per // block) * block


# ------------------------------------------------------------------- int8


def int8_reduce_scatter_mean(flat: jax.Array, axis: str = "data",
                             block: int = BLOCK, *,
                             worker_err: Optional[jax.Array] = None):
    """Hop 1 of qgZ: blockwise-int8 all-to-all + local mean — the
    *reduce-scatter* half of the quantized all-reduce. Each rank keeps its
    own contiguous ``per``-element chunk of the (padded) flat vector: the
    flat-vector spelling of "reduce-scatter into the ZeRO partition"
    (the engine's stage>=2 master sharding then slices the gathered
    result locally, with zero extra wire bytes — the gather hop below is
    the only cross-rank traffic after this).

    ``worker_err`` (the ``per * world``-element error-feedback residual,
    in true gradient units) makes the quantization unbiased over steps:
    the residual is added before quantizing and the new residual is the
    quantization error left behind — the same discipline the 1-bit path
    has always had; without it int8 silently drops its rounding error
    every step. Returns ``(my_chunk (per,), new_worker_err | None)``.
    """
    world = lax.axis_size(axis)
    n = flat.shape[0]
    per = chunk_elems(n, world, block)
    x = jnp.pad(flat, (0, per * world - n))
    if worker_err is not None:
        x = x + worker_err
    xb = x.reshape(world, per // block, block)
    q, s = _quant_blocks(xb)
    new_err = None
    if worker_err is not None:
        new_err = x - (q.astype(jnp.float32) * s).reshape(-1)
    # a2a: rank r keeps chunk r of every sender → reduce locally.
    q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)
    mine = jnp.mean(q.astype(jnp.float32) * s, axis=0)        # (nb, block)
    return mine, new_err


def int8_allreduce_mean(flat: jax.Array, axis: str = "data",
                        block: int = BLOCK, *,
                        worker_err: Optional[jax.Array] = None,
                        server_err: Optional[jax.Array] = None):
    """Mean-all-reduce of a flat fp32 vector over a *manual* mesh axis with
    int8 payloads (qgZ). Bytes on the wire: ~N int8 for the a2a hop plus
    ~N int8 for the gather hop, vs 2N fp32 for a ring all-reduce.

    Structure: :func:`int8_reduce_scatter_mean` (each rank reduces its
    chunk) + an int8 re-quantize/all-gather second hop — ZeRO++'s 2-hop
    qgZ. With ``worker_err``/``server_err`` both hops carry error-feedback
    residuals (worker: the pre-a2a quantization error of the full padded
    vector; server: the pre-gather re-quantization error of this rank's
    ``per``-element chunk) and the call returns
    ``(reduced, new_worker_err, new_server_err)`` — both residuals must
    persist across steps like the 1-bit pair. Without residual arguments
    the call returns just ``reduced`` (the historical biased spelling,
    kept for primitive-level callers)."""
    if (worker_err is None) != (server_err is None):
        raise ValueError(
            "int8 error-feedback residuals come as a pair: pass both "
            "worker_err and server_err or neither")
    world = lax.axis_size(axis)
    ef = worker_err is not None
    if world == 1:
        return (flat, worker_err, server_err) if ef else flat
    n = flat.shape[0]
    mine, new_worker = int8_reduce_scatter_mean(
        flat, axis, block, worker_err=worker_err)
    comp = mine
    if server_err is not None:
        comp = mine + server_err.reshape(mine.shape)
    # second hop: re-quantize the reduced chunk and gather all chunks.
    q2, s2 = _quant_blocks(comp)
    new_server = None
    if server_err is not None:
        new_server = (comp - q2.astype(jnp.float32) * s2).reshape(-1)
    qg = lax.all_gather(q2, axis, axis=0, tiled=False)         # (W, nb, block)
    sg = lax.all_gather(s2, axis, axis=0, tiled=False)
    red = (qg.astype(jnp.float32) * sg).reshape(-1)[:n]
    return (red, new_worker, new_server) if ef else red


def int8_psum(x: jax.Array, axis: str = "model",
              block: int = BLOCK) -> jax.Array:
    """Sum-all-reduce of a (any-shape) partial over a *manual* mesh axis
    with int8 payloads on both hops — the EQuARX two-sided quantized
    all-reduce, for the TP decode step's ``model``-axis partial-sum
    reduction (attention ``wo`` / MLP ``w_out`` row-sharded matmuls).

    Unlike the gradient path this is a one-shot activation reduction:
    no error feedback (there is no "next step" for the residual of a
    decode activation), SUM semantics (matmul partials), and the result
    is cast back to the input dtype. Blockwise fp32 scales bound the
    relative error to ~1/127 per hop — small enough that greedy decode
    stays exact on short contexts (the parity oracle the serving tests
    pin)."""
    world = lax.axis_size(axis)
    if world == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    per = chunk_elems(n, world, block)
    xb = jnp.pad(flat, (0, per * world - n)).reshape(
        world, per // block, block)
    q, s = _quant_blocks(xb)
    q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)
    mine = jnp.sum(q.astype(jnp.float32) * s, axis=0)         # (nb, block)
    q2, s2 = _quant_blocks(mine)
    qg = lax.all_gather(q2, axis, axis=0, tiled=False)
    sg = lax.all_gather(s2, axis, axis=0, tiled=False)
    out = (qg.astype(jnp.float32) * sg).reshape(-1)[:n]
    return out.reshape(shape).astype(dtype)


# ------------------------------------------------------------------ onebit
def _pack_signs(sign):
    """(..., block) ±1 → (..., block/8) uint8 bitmap."""
    bits = (sign > 0).astype(jnp.int32).reshape(sign.shape[:-1] + (-1, 8))
    weights = jnp.asarray(1 << np.arange(8), jnp.int32)
    return jnp.sum(bits * weights, axis=-1).astype(jnp.uint8)


def _unpack_signs(packed, block: int):
    """(..., block/8) uint8 → (..., block) ±1 fp32."""
    shifts = jnp.asarray(np.arange(8), jnp.uint8)
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    sign = bits.astype(jnp.float32) * 2.0 - 1.0
    return sign.reshape(packed.shape[:-1] + (block // 8 * 8,))


def onebit_allreduce_mean(flat: jax.Array, worker_err: jax.Array,
                          server_err: jax.Array, axis: str = "data",
                          block: int = BLOCK):
    """Error-feedback sign-compressed mean-all-reduce (1-bit Adam's
    ``compressed_allreduce``). Returns (reduced, new_worker_err,
    new_server_err); both residuals must persist across steps in TrainState.
    """
    world = lax.axis_size(axis)
    if world == 1:
        return flat, worker_err, server_err
    n = flat.shape[0]
    per = chunk_elems(n, world, block)
    total = per * world

    comp = jnp.pad(flat, (0, total - n)) + worker_err           # (total,)
    x = comp.reshape(world, per // block, block)
    scale = jnp.mean(jnp.abs(x), axis=-1, keepdims=True)        # (W, nb, 1)
    sign = jnp.where(x >= 0, 1.0, -1.0)
    new_worker_err = (x - sign * scale).reshape(-1)             # residual

    packed = _pack_signs(sign)                                  # (W, nb, b/8)
    packed = lax.all_to_all(packed, axis, split_axis=0, concat_axis=0, tiled=True)
    scale = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0, tiled=True)
    decoded = _unpack_signs(packed, block) * scale              # (W, nb, block)
    mine = jnp.mean(decoded, axis=0)                            # my chunk, averaged

    comp_s = mine + server_err.reshape(mine.shape)
    scale2 = jnp.mean(jnp.abs(comp_s), axis=-1, keepdims=True)
    sign2 = jnp.where(comp_s >= 0, 1.0, -1.0)
    new_server_err = (comp_s - sign2 * scale2).reshape(-1)

    packed2 = _pack_signs(sign2)                                # (nb, b/8)
    pg = lax.all_gather(packed2, axis, axis=0, tiled=False)     # (W, nb, b/8)
    sg = lax.all_gather(scale2, axis, axis=0, tiled=False)
    reduced = (_unpack_signs(pg, block) * sg).reshape(-1)[:n]
    return reduced, new_worker_err, new_server_err


# --------------------------------------------------------------- bucketing
class BucketPlan(NamedTuple):
    """Static layer-aligned bucketing of a gradient tree's flat vector.

    ``seg_sizes`` are the element counts of the layer-aligned segments in
    ``jax.tree.leaves`` order: an unstacked leaf is one segment, a
    layer-stacked ``(L, ...)`` leaf contributes L per-layer segments
    (contiguous in the C-order flattened vector, so bucket boundaries
    land exactly on layer boundaries). ``buckets`` are ``[lo, hi)``
    segment ranges — each bucket becomes ONE independent collective whose
    data dependency is only its own segments' grads, which is what lets
    XLA's latency-hiding scheduler overlap bucket i's wire time with the
    rest of the backward (per-leaf grads of non-scanned params appear
    progressively during the backward) and, for scanned stacks, with the
    quantize/dequantize compute of the neighbouring buckets — the
    T3-style pipelining the fused flat spelling (one concat over ALL
    leaves → one collective serialized after the whole backward)
    structurally forbids."""

    seg_sizes: tuple
    buckets: tuple

    @property
    def total_elems(self) -> int:
        return int(sum(self.seg_sizes))

    def bucket_elems(self) -> list:
        return [int(sum(self.seg_sizes[lo:hi])) for lo, hi in self.buckets]


def segment_sizes(shapes, stacked_flags) -> tuple:
    """Layer-aligned segment element counts for leaves with the given
    shapes (``jax.tree.leaves`` order). ``stacked_flags[i]`` marks leaf i
    as layer-stacked: its leading dim is a ``lax.scan``-over-layers axis
    and each layer's slice becomes its own segment."""
    sizes = []
    for shp, stk in zip(shapes, stacked_flags):
        n = int(np.prod(shp)) if shp else 1
        if stk and len(shp) >= 2 and shp[0] > 1 and n > 0:
            sizes.extend([n // int(shp[0])] * int(shp[0]))
        else:
            sizes.append(n)
    return tuple(sizes)


def plan_buckets(shapes, stacked_flags, bucket_elems: int) -> BucketPlan:
    """Greedy fixed-size packing of layer-aligned segments into buckets.

    ``bucket_elems <= 0`` (or a tree smaller than one bucket) degrades to
    ONE bucket over the whole tree — numerically the fused flat spelling.
    A segment larger than ``bucket_elems`` gets a bucket of its own
    (buckets never split a segment: layer alignment is the invariant);
    the last bucket is whatever remains (uneven by construction)."""
    sizes = segment_sizes(shapes, stacked_flags)
    if not sizes:
        return BucketPlan((), ())
    if bucket_elems <= 0:
        return BucketPlan(sizes, ((0, len(sizes)),))
    buckets = []
    lo, acc = 0, 0
    for i, s in enumerate(sizes):
        if i > lo and acc + s > bucket_elems:
            buckets.append((lo, i))
            lo, acc = i, 0
        acc += s
    buckets.append((lo, len(sizes)))
    return BucketPlan(sizes, tuple(buckets))


def plan_comm_err_shapes(plan: BucketPlan, world: int,
                         block: int = BLOCK) -> dict:
    """Error-feedback residual shapes for a bucketed plan (leading dim =
    data axis, the engine's ``comm_err`` sharding convention): worker =
    the concatenation of every bucket's padded vector, server = the
    concatenation of every bucket's per-rank chunk. One flat vector per
    role; the bucketed reduce slices its own windows (static offsets)."""
    pers = [chunk_elems(n, world, block) for n in plan.bucket_elems()]
    return {"worker": (world, sum(p * world for p in pers)),
            "server": (world, sum(pers))}


def tree_segments(tree, stacked_fn):
    """Pytree → list of layer-aligned 1-D fp32 segments (leaves order,
    matching :func:`segment_sizes` over the same shapes/flags)."""
    segs = []
    for leaf in jax.tree.leaves(tree):
        shp = leaf.shape
        n = int(np.prod(shp)) if shp else 1
        if stacked_fn(shp) and len(shp) >= 2 and shp[0] > 1 and n > 0:
            rows = leaf.reshape(shp[0], -1).astype(jnp.float32)
            segs.extend(rows[i] for i in range(shp[0]))
        else:
            segs.append(leaf.reshape(-1).astype(jnp.float32))
    return segs


def unflatten_like(tree, flat: jax.Array):
    """Reassemble a flat fp32 vector (concatenated in ``tree_segments``
    order == ``jax.tree.leaves`` order) back into ``tree``'s structure."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [int(np.prod(l.shape)) for l in leaves]
    parts = jnp.split(flat, np.cumsum(sizes)[:-1]) if len(sizes) > 1 \
        else [flat]
    return jax.tree_util.tree_unflatten(
        treedef, [p.reshape(l.shape) for p, l in zip(parts, leaves)])


def bucketed_grad_reduce(grads, plan: BucketPlan, *, mode: str, axis: str,
                         stacked_fn, scale=None,
                         worker_err: Optional[jax.Array] = None,
                         server_err: Optional[jax.Array] = None,
                         block: int = BLOCK):
    """Per-bucket compressed (or fp) mean-reduction of a gradient tree
    over a *manual* mesh axis — the engine's bucketed grad-communication
    core (``runtime/engine.py _compressed_grads``).

    Each bucket concatenates only ITS OWN segments (never the whole
    tree), so bucket i's collective depends on nothing but bucket i's
    grads and XLA's scheduler is free to overlap it with the remaining
    backward / the neighbouring buckets' quantize compute. ``scale``
    (the fp16 loss scale) is divided out per bucket BEFORE compressing,
    so the error-feedback residuals live in true gradient units — a
    dynamic loss-scale change can never leave them stale (the same
    unscale-aware discipline as the fused path).

    ``mode``: ``"fp"`` = uncompressed ``lax.pmean`` per bucket (bitwise
    identical to the fused flat spelling: the reduction is elementwise);
    ``"int8"`` = qgZ with worker+server error feedback; ``"onebit"`` =
    sign compression with the 1-bit residual pair. Returns
    ``(reduced_tree, new_worker_err, new_server_err)`` — the residuals
    are ``None`` for fp mode / world == 1 / residuals not supplied."""
    if (worker_err is None) != (server_err is None):
        raise ValueError(
            "error-feedback residuals come as a pair: pass both "
            "worker_err and server_err or neither (got "
            f"worker_err={'set' if worker_err is not None else None}, "
            f"server_err={'set' if server_err is not None else None})")
    world = lax.axis_size(axis)
    segs = tree_segments(grads, stacked_fn)
    assert len(segs) == len(plan.seg_sizes), \
        (len(segs), len(plan.seg_sizes))
    outs, new_w, new_s = [], [], []
    w_off = s_off = 0
    ef = worker_err is not None and world > 1 and mode != "fp"
    for lo, hi in plan.buckets:
        flat = segs[lo] if hi == lo + 1 else jnp.concatenate(segs[lo:hi])
        if scale is not None:
            flat = flat / scale
        if world == 1 or mode == "fp":
            outs.append(lax.pmean(flat, axis) if world > 1 else flat)
            continue
        n = flat.shape[0]
        per = chunk_elems(n, world, block)
        we = se = None
        if ef:
            # static windows into the flat residual vectors (the plan is
            # trace-time constant, so these are plain slices)
            we = worker_err[w_off:w_off + per * world]
            se = server_err[s_off:s_off + per]
            w_off += per * world
            s_off += per
        if mode == "onebit":
            if we is None:      # residuals are the algorithm for 1-bit
                raise ValueError("onebit grad compression requires the "
                                 "worker/server error-feedback residuals")
            red, nw, ns = onebit_allreduce_mean(flat, we, se, axis, block)
        elif mode == "int8":
            if ef:
                red, nw, ns = int8_allreduce_mean(
                    flat, axis, block, worker_err=we, server_err=se)
            else:
                red, nw, ns = int8_allreduce_mean(flat, axis, block), \
                    None, None
        else:
            raise ValueError(f"unknown grad compression mode {mode!r}")
        outs.append(red)
        if nw is not None:
            new_w.append(nw)
            new_s.append(ns)
    full = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    nw_out = jnp.concatenate(new_w) if new_w else None
    ns_out = jnp.concatenate(new_s) if new_s else None
    return unflatten_like(grads, full), nw_out, ns_out


# byte cost per fp32 element of each mode's wire payload (both hops,
# result-payload convention — the same convention the HLO census counts):
# int8 = 1B payload + 4B/BLOCK scale per hop; onebit = 1/8B signs + scale.
def plan_wire_mbytes(plan: BucketPlan, world: int, mode: str,
                     block: int = BLOCK) -> dict:
    """Static per-step wire summary of a bucketed grad-reduction plan —
    the ``achieved`` side of the capacity advisor's
    ``quantized_collectives`` lever (what the spelling actually puts on
    the wire vs the fp32 flat all-reduce it replaces). Exact from the
    plan's padded bucket sizes; no compile needed.

    The denominator is the UNPADDED flat fp32 all-reduce GSPMD would
    emit with compression off — chunk/block padding is an artifact of
    the compressed reduce-scatter spelling, not of what it replaces.
    ``"fp"`` mode reduces each bucket with a plain elementwise
    ``lax.pmean`` (no padding, no scale planes), so its ratio is
    exactly 1.0; the quantized modes pay each bucket's own padding, so
    their ``wire_ratio`` honestly exceeds the dtype ratio when buckets
    sit near the ``world * block`` padding quantum (and can exceed 1.0
    for degenerate tiny-bucket plans: quantized padding costing more
    than the fp32 wire is a real outcome, reported, never hidden — the
    engine clamps ``bucket_elems`` to the quantum for exactly this
    reason)."""
    pers = [chunk_elems(n, world, block) for n in plan.bucket_elems()]
    padded = sum(p * world for p in pers)
    fp32_equiv = 4.0 * plan.total_elems
    if world <= 1:
        payload = 0.0
    elif mode == "fp":
        payload = 4.0 * plan.total_elems
    elif mode == "int8":
        # hop 1: int8 a2a of the padded vector + f32 block scales;
        # hop 2: int8 gather of the reduced chunks + f32 block scales
        per_hop = padded * 1.0 + (padded // block) * 4.0
        payload = 2.0 * per_hop
    elif mode == "onebit":
        per_hop = padded / 8.0 + (padded // block) * 4.0
        payload = 2.0 * per_hop
    else:
        raise ValueError(f"unknown grad compression mode {mode!r}")
    return {
        "mode": mode,
        "buckets": len(plan.buckets),
        "bucket_elems": plan.bucket_elems(),
        "wire_mbytes_per_step": payload / 1e6,
        "fp32_equivalent_mbytes": fp32_equiv / 1e6,
        "wire_ratio": (payload / fp32_equiv) if fp32_equiv else None,
    }
