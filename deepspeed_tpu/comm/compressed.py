"""Compressed data-parallel gradient synchronization.

TPU-native analog of the reference's compressed collectives:

- ``int8`` mode = ZeRO++ qgZ (``runtime/zero/config.py:268``
  ``zero_quantized_gradients``; ``runtime/comm/coalesced_collectives.py:31``
  quantized reduce-scatter): blockwise-int8 all-to-all, local reduction,
  blockwise-int8 all-gather — 4x fewer bytes on the wire than fp32.
- ``onebit`` mode = 1-bit Adam's error-feedback sign compression
  (``runtime/comm/nccl.py:51`` ``compressed_allreduce``): worker-side
  sign+scale with a worker error residual, all-to-all, server-side average
  re-compressed with a server error residual, all-gather. Signs travel
  bit-packed (8 signs/byte) — ~16x fewer bytes than bf16.

These run *inside* a ``shard_map`` body whose ``data`` axis is manual: the
engine computes per-rank local gradients there, calls one of these to
complete the cross-data reduction explicitly, and XLA lowers the collectives
onto ICI/DCN. The hierarchy falls out of the mesh: the fast ``zero``/
``expert`` sub-axes stay GSPMD-managed (full-precision, ICI-local) and only
the slow ``data`` hop is compressed — the reference's 2-hop qgZ design.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.quant import quant_blocks as _quant_blocks

BLOCK = 2048  # elements per quantization scale


# ------------------------------------------------------------------ flatten
def flat_size(tree_or_shapes) -> int:
    leaves = jax.tree.leaves(tree_or_shapes)
    return int(sum(int(np.prod(getattr(l, "shape", l))) for l in leaves))


def flatten_tree(tree):
    """Pytree → (flat fp32 vector, unflatten closure)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])

    def unflatten(v):
        parts = jnp.split(v, np.cumsum(sizes)[:-1]) if len(sizes) > 1 else [v]
        return jax.tree_util.tree_unflatten(
            treedef, [p.reshape(s) for p, s in zip(parts, shapes)])

    return flat, unflatten


def chunk_elems(n: int, world: int, block: int = BLOCK) -> int:
    """Per-rank chunk length: ceil to a whole number of scale blocks."""
    per = -(-n // world)
    return -(-per // block) * block


# ------------------------------------------------------------------- int8


def int8_allreduce_mean(flat: jax.Array, axis: str = "data",
                        block: int = BLOCK) -> jax.Array:
    """Mean-all-reduce of a flat fp32 vector over a *manual* mesh axis with
    int8 payloads (qgZ). Bytes on the wire: ~N int8 for the a2a hop plus
    ~N int8 for the gather hop, vs 2N fp32 for a ring all-reduce."""
    world = lax.axis_size(axis)
    if world == 1:
        return flat
    n = flat.shape[0]
    per = chunk_elems(n, world, block)
    x = jnp.pad(flat, (0, per * world - n)).reshape(world, per // block, block)
    q, s = _quant_blocks(x)
    # a2a: rank r keeps chunk r of every sender → reduce locally.
    q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)
    mine = jnp.mean(q.astype(jnp.float32) * s, axis=0)        # (nb, block)
    # second hop: re-quantize the reduced chunk and gather all chunks.
    q2, s2 = _quant_blocks(mine)
    qg = lax.all_gather(q2, axis, axis=0, tiled=False)         # (W, nb, block)
    sg = lax.all_gather(s2, axis, axis=0, tiled=False)
    return (qg.astype(jnp.float32) * sg).reshape(-1)[:n]


# ------------------------------------------------------------------ onebit
def _pack_signs(sign):
    """(..., block) ±1 → (..., block/8) uint8 bitmap."""
    bits = (sign > 0).astype(jnp.int32).reshape(sign.shape[:-1] + (-1, 8))
    weights = jnp.asarray(1 << np.arange(8), jnp.int32)
    return jnp.sum(bits * weights, axis=-1).astype(jnp.uint8)


def _unpack_signs(packed, block: int):
    """(..., block/8) uint8 → (..., block) ±1 fp32."""
    shifts = jnp.asarray(np.arange(8), jnp.uint8)
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    sign = bits.astype(jnp.float32) * 2.0 - 1.0
    return sign.reshape(packed.shape[:-1] + (block // 8 * 8,))


def onebit_allreduce_mean(flat: jax.Array, worker_err: jax.Array,
                          server_err: jax.Array, axis: str = "data",
                          block: int = BLOCK):
    """Error-feedback sign-compressed mean-all-reduce (1-bit Adam's
    ``compressed_allreduce``). Returns (reduced, new_worker_err,
    new_server_err); both residuals must persist across steps in TrainState.
    """
    world = lax.axis_size(axis)
    if world == 1:
        return flat, worker_err, server_err
    n = flat.shape[0]
    per = chunk_elems(n, world, block)
    total = per * world

    comp = jnp.pad(flat, (0, total - n)) + worker_err           # (total,)
    x = comp.reshape(world, per // block, block)
    scale = jnp.mean(jnp.abs(x), axis=-1, keepdims=True)        # (W, nb, 1)
    sign = jnp.where(x >= 0, 1.0, -1.0)
    new_worker_err = (x - sign * scale).reshape(-1)             # residual

    packed = _pack_signs(sign)                                  # (W, nb, b/8)
    packed = lax.all_to_all(packed, axis, split_axis=0, concat_axis=0, tiled=True)
    scale = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0, tiled=True)
    decoded = _unpack_signs(packed, block) * scale              # (W, nb, block)
    mine = jnp.mean(decoded, axis=0)                            # my chunk, averaged

    comp_s = mine + server_err.reshape(mine.shape)
    scale2 = jnp.mean(jnp.abs(comp_s), axis=-1, keepdims=True)
    sign2 = jnp.where(comp_s >= 0, 1.0, -1.0)
    new_server_err = (comp_s - sign2 * scale2).reshape(-1)

    packed2 = _pack_signs(sign2)                                # (nb, b/8)
    pg = lax.all_gather(packed2, axis, axis=0, tiled=False)     # (W, nb, b/8)
    sg = lax.all_gather(scale2, axis, axis=0, tiled=False)
    reduced = (_unpack_signs(pg, block) * sg).reshape(-1)[:n]
    return reduced, new_worker_err, new_server_err
