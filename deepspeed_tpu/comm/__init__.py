from .comm import (CommsLogger, all_gather, all_reduce, all_to_all, barrier,
                   broadcast, comms_logger, get_rank, get_world_size, ppermute,
                   reduce_scatter)

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "all_to_all", "broadcast",
           "ppermute", "barrier", "get_rank", "get_world_size", "CommsLogger",
           "comms_logger"]
