"""Autotuner: measured search over mesh shape × ZeRO stage × offload ×
micro-batch × remat (GAS follows: global = micro × gas × dp(mesh)).

Analog of the reference autotuner (``autotuning/autotuner.py:404``), which
profiles the model, generates a grid of experiments (ZeRO stage,
micro-batch-per-GPU, selected subsystem knobs), launches each as a short real
run, and applies model-based early stopping before writing the best config.

TPU-native differences:
- experiments run **in-process**: an engine is just a jitted function +
  sharded arrays, so "launch an experiment" is build → time a few steps →
  drop the references (no process pool / scheduler / hostfile bookkeeping —
  the reference needed those because a torch engine can't be cleanly
  destroyed in-process).
- OOM is a catchable XLA ``RESOURCE_EXHAUSTED`` error, so the tuner walks
  micro-batch sizes upward until the first failure instead of guessing from
  an activation-memory model (the reference's ``max_train_micro_batch_size``
  estimate exists because CUDA OOM often poisons the process).
- early stop: within each (stage, remat) sweep, stop growing the micro-batch
  once throughput turns over (the reference's model-based early stopping,
  reduced to the one signal that matters under a compiled step: measured
  samples/s). Stages always run — on TPU a whole-stage sweep is a handful of
  compiles, not a cluster job per cell like the reference's scheduler.
"""

from __future__ import annotations

import copy
import json
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import numpy as np

from ..utils.logging import log_dist


@dataclass
class Experiment:
    zero_stage: int
    micro_batch: int
    remat: bool
    mesh: dict = field(default_factory=dict)
    offload: Optional[str] = None
    samples_per_sec: float = 0.0
    ok: bool = False
    error: str = ""
    est_bytes: int = 0          # feasibility-model estimate (0 = not run)

    def label(self) -> str:
        mesh = "x".join(f"{k}{v}" for k, v in sorted(self.mesh.items())) or "dp"
        return (f"{mesh}_z{self.zero_stage}_mbs{self.micro_batch}"
                f"{'_remat' if self.remat else ''}"
                f"{'_off-' + self.offload if self.offload else ''}")


#: fp32 optimizer-moment tensors per parameter, by optimizer type. Lion and
#: momentum-SGD carry one; plain SGD none; Adam-family two. Used by the
#: feasibility model so a 1B-Lion config is not pruned for Adam-sized state.
OPTIMIZER_MOMENTS = {
    "adam": 2, "adamw": 2, "fusedadam": 2, "lamb": 2, "fusedlamb": 2,
    "onebitadam": 2, "onebitlamb": 2, "zerooneadam": 2, "adagrad": 1,
    "lion": 1, "fusedlion": 1, "momentum": 1, "sgd": 0,
}


def optimizer_moment_count(config: Optional[dict]) -> int:
    """Moments/param implied by a ds_config's optimizer block (default 2)."""
    try:
        name = str(config["optimizer"]["type"]).lower().replace("_", "")
    except (TypeError, KeyError):
        return 2
    return OPTIMIZER_MOMENTS.get(name, 2)


def estimate_experiment_bytes(model_cfg, exp: Experiment, dp: int,
                              compute_bytes: int = 2,
                              seq: Optional[int] = None,
                              opt_moments: int = 2) -> dict:
    """Per-device memory estimate for one experiment — the reference
    autotuner's model-info pass (``autotuning/autotuner.py:404`` params +
    optimizer-state arithmetic, ``:663`` activation estimate), rebuilt for
    the sharding-based stages: compute params shard over model/pipe (and
    dp at stage 3), fp32 master+moments shard over dp from stage 1,
    gradients from stage 2. The activation term is deliberately
    CONSERVATIVE (counts the fp32 logits slice and per-layer attention
    probs for the no-remat case): over-pruning costs one missed candidate,
    under-pruning costs an OOM'd child — and on the wedge-prone TPU
    tunnel, a killed child can cost the whole session."""
    n = model_cfg.param_count()
    mp = int(np.prod([v for k, v in exp.mesh.items()
                      if k in ("model", "pipe")])) or 1
    params = n * compute_bytes // (mp * (dp if exp.zero_stage >= 3 else 1))
    states = (0 if exp.offload else
              (1 + opt_moments) * 4 * n
              // (mp * (dp if exp.zero_stage >= 1 else 1)))
    grads = 4 * n // (mp * (dp if exp.zero_stage >= 2 else 1))
    S = seq or getattr(model_cfg, "max_seq", 1024)
    d = model_cfg.d_model
    L = model_cfg.n_layer
    # T5Config spells the FFN width d_ff and has no ffn_dim property
    f = (getattr(model_cfg, "ffn_dim", None)
         or getattr(model_cfg, "d_ff", None) or 4 * d)
    h = model_cfg.n_head
    tokens = exp.micro_batch * S
    if exp.remat:
        # saved carries + ~one live layer of intermediates
        act = L * tokens * d * compute_bytes * 2
    else:
        per_tok = (12 * d + 2 * f) * compute_bytes  # qkv/o/mlp intermediates
        probs = h * S * compute_bytes               # attention probs row
        act = L * tokens * (per_tok + probs)
    logits = tokens * model_cfg.vocab_size * 4      # fp32 loss slice
    total = params + states + grads + act + logits
    return {"params": params, "opt_states": states, "grads": grads,
            "activations": act, "logits": logits, "total": total}


class Autotuner:
    """Grid-search tuner over short real runs.

    ``model_builder`` is a zero-arg callable returning a fresh model (fresh
    params each experiment — engines donate/mutate state).  ``make_batch``
    maps a global batch size to a host batch dict."""

    def __init__(self, base_config: dict, model_builder: Callable[[], Any],
                 make_batch: Callable[[int], dict], *,
                 stages: Sequence[int] = (3, 2, 1, 0),
                 micro_batches: Optional[Sequence[int]] = None,
                 remat_options: Sequence[bool] = (False,),
                 mesh_options: "Optional[Sequence[dict]] | str" = None,
                 offload_options: Sequence[Optional[str]] = (None,),
                 steps: int = 3, warmup: int = 1,
                 early_stop_margin: float = 0.05,
                 results_path: Optional[str] = None,
                 model_spec: Optional[dict] = None,
                 isolate: Optional[bool] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 child_timeout_s: float = 900.0):
        self.base_config = base_config
        self.model_builder = model_builder
        self.make_batch = make_batch
        self.stages = list(stages)
        self.micro_batches = list(micro_batches) if micro_batches else None
        self.remat_options = list(remat_options)
        # mesh candidates: None = pure DP only; "auto" = factor the device
        # count into model/seq splits (on TPU the mesh shape is THE knob —
        # reference tunes only stage+mbs, autotuner.py:404)
        self.mesh_options = mesh_options
        self.offload_options = list(offload_options)
        self.steps = steps
        self.warmup = warmup
        self.early_stop_margin = early_stop_margin
        self.results_path = results_path
        # model_spec ({"family", "size", "overrides"}) enables BOTH
        # hardening layers the in-process tuner lacked (round-3 review):
        # the feasibility model (prune before touching the device) and
        # child isolation (each surviving experiment in its own
        # interpreter — a native CHECK-crash or OOM kills the child, not
        # the tune). ``model_builder`` remains for in-process use with
        # arbitrary models.
        self.model_spec = model_spec
        self.isolate = isolate if isolate is not None else model_spec is not None
        if self.isolate and model_spec is None:
            raise ValueError("isolate=True needs model_spec: engines and "
                             "closures do not cross process boundaries")
        self.hbm_budget_bytes = hbm_budget_bytes
        self.child_timeout_s = child_timeout_s
        self._model_cfg = None
        self._probe_seq = None
        if model_spec is not None:
            from .worker import build_model_from_spec

            _, self._model_cfg = build_model_from_spec(model_spec)
            # the seq both the estimate AND the worker run at (they must
            # judge the same workload)
            self._probe_seq = min(getattr(self._model_cfg, "max_seq", 128),
                                  512)
        self.experiments: list[Experiment] = []

    # ------------------------------------------------------------------ grid
    @staticmethod
    def _auto_mesh_options(n_dev: int) -> list[dict]:
        """Candidate (model, seq) splits of the device count; ``data``
        absorbs the remainder. Bounded: at most ~6 candidates."""
        out: list[dict] = [{}]
        for m in (2, 4):
            if n_dev % m == 0 and n_dev > m:
                out.append({"model": m})
        if n_dev % 2 == 0 and n_dev > 2:
            out.append({"seq": 2})
        if n_dev % 4 == 0 and n_dev > 4:
            out.append({"model": 2, "seq": 2})
        return out

    def _mesh_candidates(self, n_dev: int) -> list[dict]:
        if self.mesh_options is None:
            return [{}]
        if self.mesh_options == "auto":
            return self._auto_mesh_options(n_dev)
        return [dict(m) for m in self.mesh_options]

    @staticmethod
    def _dp_for_mesh(mesh: dict, n_dev: int) -> int:
        non_dp = int(np.prod([v for k, v in mesh.items()
                              if k not in ("data", "zero", "expert")])) or 1
        return max(1, n_dev // non_dp)

    def _candidate_micro_batches(self, dp: int) -> list[int]:
        if self.micro_batches is not None:
            return self.micro_batches
        global_bs = int(self.base_config.get("train_batch_size", dp))
        per_dev = max(1, global_bs // dp)
        out, m = [], 1
        while m <= per_dev:
            out.append(m)
            m *= 2
        return out

    def _experiment_config(self, exp: Experiment, dp: int) -> dict:
        cfg = copy.deepcopy(self.base_config)
        zo = cfg.setdefault("zero_optimization", {})
        zo["stage"] = exp.zero_stage
        if exp.mesh:
            cfg["mesh"] = dict(exp.mesh)   # data axis auto-absorbs the rest
        if exp.offload:
            zo["offload_optimizer"] = {"device": exp.offload}
        cfg["train_micro_batch_size_per_gpu"] = exp.micro_batch
        global_bs = int(cfg.get("train_batch_size", dp * exp.micro_batch))
        cfg["gradient_accumulation_steps"] = max(
            1, global_bs // (exp.micro_batch * dp))
        # keep global batch consistent: global = micro * gas * dp
        cfg["train_batch_size"] = (exp.micro_batch
                                   * cfg["gradient_accumulation_steps"] * dp)
        if exp.remat:
            cfg["remat"] = {"enabled": True, "policy": "dots_saveable"}
        else:
            # remat=False must really measure remat-off even when the base
            # config enables it, or the grid dimension compares identical runs
            cfg.pop("remat", None)
        cfg.setdefault("steps_per_print", 10 ** 9)
        return cfg

    # ----------------------------------------------------------- feasibility
    def _probe_device(self) -> dict:
        """(n_devices, bytes_limit) WITHOUT initializing jax in this
        process when isolating: a parent that claims the TPU would starve
        every worker child of the very device isolation exists to protect
        (review r4). Cached; probed from a throwaway subprocess."""
        if getattr(self, "_device_info", None) is not None:
            return self._device_info
        if not self.isolate:
            try:
                dev = jax.local_devices()[0]
                stats = dev.memory_stats() or {}
                self._device_info = {"n_dev": jax.device_count(),
                                     "limit": stats.get("bytes_limit")}
            except Exception:
                self._device_info = {"n_dev": 1, "limit": None}
            return self._device_info
        import subprocess
        import sys as _sys

        code = ("import json, jax; d = jax.local_devices()[0]; "
                "print(json.dumps({'n_dev': jax.device_count(), "
                "'limit': (d.memory_stats() or {}).get('bytes_limit')}))")
        try:
            p = subprocess.run([_sys.executable, "-c", code], timeout=300,
                               capture_output=True, text=True)
            line = next(ln for ln in reversed(p.stdout.strip().splitlines())
                        if ln.startswith("{"))
            self._device_info = json.loads(line)
        except Exception:
            self._device_info = {"n_dev": 1, "limit": None}
        return self._device_info

    def _budget_bytes(self) -> Optional[int]:
        if self.hbm_budget_bytes is not None:
            return self.hbm_budget_bytes
        limit = self._probe_device().get("limit")
        return int(limit * 0.92) if limit else None

    def _prune_infeasible(self, exp: Experiment, dp: int) -> bool:
        """True = pruned (recorded as a failed experiment, never run)."""
        if self._model_cfg is None:
            return False
        budget = self._budget_bytes()
        if budget is None:
            return False
        est = estimate_experiment_bytes(
            self._model_cfg, exp, dp, seq=self._probe_seq,
            opt_moments=optimizer_moment_count(self.base_config))
        exp.est_bytes = int(est["total"])
        if est["total"] <= budget:
            return False
        exp.ok = False
        exp.error = (f"pruned: estimated {est['total'] / 2**30:.2f} GiB "
                     f"> budget {budget / 2**30:.2f} GiB "
                     f"(params {est['params'] / 2**30:.2f}, states "
                     f"{est['opt_states'] / 2**30:.2f}, act "
                     f"{est['activations'] / 2**30:.2f})")
        self.experiments.append(exp)
        log_dist(f"autotune: {exp.label()} {exp.error}", ranks=[0])
        return True

    # --------------------------------------------------------------- measure
    def _run_isolated(self, exp: Experiment, dp: int) -> Experiment:
        """One experiment in a fresh child interpreter (reference
        scheduler-job isolation): a crash/OOM/wedge costs the child."""
        import os
        import subprocess
        import sys as _sys

        payload = json.dumps({"config": self._experiment_config(exp, dp),
                              "model_spec": self.model_spec,
                              "seq": self._probe_seq,
                              "steps": self.steps, "warmup": self.warmup})
        try:
            p = subprocess.run(
                [_sys.executable, "-m", "deepspeed_tpu.autotuning.worker",
                 payload],
                capture_output=True, text=True, env=dict(os.environ),
                timeout=self.child_timeout_s)
        except subprocess.TimeoutExpired:
            exp.error = f"child timeout after {self.child_timeout_s:.0f}s"
            return exp
        # guarded parse (bench_common.run_child's pattern): a child killed
        # mid-flush can leave a truncated '{'-line — that is a failed
        # experiment, never a crashed tune
        result = None
        for ln in reversed((p.stdout or "").strip().splitlines()):
            if ln.startswith("{"):
                try:
                    result = json.loads(ln)
                    break
                except json.JSONDecodeError:
                    continue
        if result is None:
            exp.error = (f"child rc={p.returncode}, no result line: "
                         f"{(p.stderr or '')[-200:]!r}")
            return exp
        exp.ok = bool(result.get("ok"))
        exp.samples_per_sec = float(result.get("samples_per_sec", 0.0))
        exp.error = result.get("error", "")
        return exp

    def _run_one(self, exp: Experiment, dp: int) -> Experiment:
        if self.isolate:
            return self._run_isolated(exp, dp)
        import deepspeed_tpu as ds

        cfg = self._experiment_config(exp, dp)
        try:
            engine = ds.initialize(cfg, self.model_builder())
            batch = self.make_batch(engine.train_batch_size)
            for _ in range(self.warmup):
                engine.train_batch(batch)
            jax.block_until_ready(jax.tree.leaves(
                engine.state.master_params if not engine.offload
                else engine.compute_params)[0])
            t0 = time.perf_counter()
            for _ in range(self.steps):
                engine.train_batch(batch)
            jax.block_until_ready(jax.tree.leaves(
                engine.state.master_params if not engine.offload
                else engine.compute_params)[0])
            dt = (time.perf_counter() - t0) / self.steps
            exp.samples_per_sec = engine.train_batch_size / dt
            exp.ok = True
        except Exception as e:  # RESOURCE_EXHAUSTED, config errors, ...
            exp.error = f"{type(e).__name__}: {e}"[:300]
            exp.ok = False
        finally:
            # drop engine references so the next experiment's arrays can
            # reuse the HBM; donation already released most of it
            engine = None
            jax.clear_caches()
        return exp

    # ------------------------------------------------------------------ tune
    def tune(self) -> dict:
        """Run the grid; return the fastest config (base config if nothing
        succeeded). Results land in ``self.experiments`` +
        ``results_path`` JSON."""
        n_dev = max(1, int(self._probe_device().get("n_dev") or 1))
        best: Optional[Experiment] = None
        for mesh in self._mesh_candidates(n_dev):
            dp = self._dp_for_mesh(mesh, n_dev)
            for offload in self.offload_options:
                for stage in self.stages:
                    if offload and stage < 1:
                        continue   # host optimizer needs a sharded master
                    for remat in self.remat_options:
                        # turnover baseline is per sweep: remat=True starts
                        # slower at small mbs and only wins at larger ones, so
                        # it must not be early-stopped against another sweep
                        sweep_best: Optional[Experiment] = None
                        for mbs in self._candidate_micro_batches(dp):
                            exp = Experiment(stage, mbs, remat, mesh=mesh,
                                             offload=offload)
                            if self._prune_infeasible(exp, dp):
                                break  # larger micro-batches estimate bigger
                            log_dist(f"autotune: running {exp.label()}",
                                     ranks=[0])
                            exp = self._run_one(exp, dp)
                            self.experiments.append(exp)
                            log_dist(
                                f"autotune: {exp.label()} → "
                                f"{exp.samples_per_sec:.1f} samples/s"
                                f"{'' if exp.ok else ' (FAILED: ' + exp.error + ')'}",
                                ranks=[0])
                            if not exp.ok:
                                break  # larger micro-batches will also OOM
                            if sweep_best and exp.samples_per_sec < \
                                    sweep_best.samples_per_sec * (1 - self.early_stop_margin):
                                break  # throughput turned over
                            if not sweep_best or exp.samples_per_sec > \
                                    sweep_best.samples_per_sec:
                                sweep_best = exp
                        if sweep_best and (not best or sweep_best.samples_per_sec
                                           > best.samples_per_sec):
                            best = sweep_best
        # isolate mode never touches jax in-process (the children own the
        # device); the parent is then necessarily single-process
        if self.results_path and (self.isolate or jax.process_index() == 0):
            with open(self.results_path, "w") as f:
                json.dump([e.__dict__ for e in self.experiments], f, indent=2)
        if best is None:
            log_dist("autotune: every experiment failed; keeping base config",
                     ranks=[0])
            return copy.deepcopy(self.base_config)
        log_dist(f"autotune: best = {best.label()} "
                 f"({best.samples_per_sec:.1f} samples/s)", ranks=[0])
        return self._experiment_config(
            best, self._dp_for_mesh(best.mesh, n_dev))


def autotune(base_config: dict, model_builder, make_batch, **kw) -> dict:
    """One-call convenience wrapper."""
    return Autotuner(base_config, model_builder, make_batch, **kw).tune()
