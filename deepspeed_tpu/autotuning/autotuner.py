"""Autotuner: measured search over mesh shape × ZeRO stage × offload ×
micro-batch × remat (GAS follows: global = micro × gas × dp(mesh)).

Analog of the reference autotuner (``autotuning/autotuner.py:404``), which
profiles the model, generates a grid of experiments (ZeRO stage,
micro-batch-per-GPU, selected subsystem knobs), launches each as a short real
run, and applies model-based early stopping before writing the best config.

TPU-native differences:
- experiments run **in-process**: an engine is just a jitted function +
  sharded arrays, so "launch an experiment" is build → time a few steps →
  drop the references (no process pool / scheduler / hostfile bookkeeping —
  the reference needed those because a torch engine can't be cleanly
  destroyed in-process).
- OOM is a catchable XLA ``RESOURCE_EXHAUSTED`` error, so the tuner walks
  micro-batch sizes upward until the first failure instead of guessing from
  an activation-memory model (the reference's ``max_train_micro_batch_size``
  estimate exists because CUDA OOM often poisons the process).
- early stop: within each (stage, remat) sweep, stop growing the micro-batch
  once throughput turns over (the reference's model-based early stopping,
  reduced to the one signal that matters under a compiled step: measured
  samples/s). Stages always run — on TPU a whole-stage sweep is a handful of
  compiles, not a cluster job per cell like the reference's scheduler.
"""

from __future__ import annotations

import copy
import json
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import numpy as np

from ..utils.logging import log_dist


@dataclass
class Experiment:
    zero_stage: int
    micro_batch: int
    remat: bool
    mesh: dict = field(default_factory=dict)
    offload: Optional[str] = None
    samples_per_sec: float = 0.0
    ok: bool = False
    error: str = ""

    def label(self) -> str:
        mesh = "x".join(f"{k}{v}" for k, v in sorted(self.mesh.items())) or "dp"
        return (f"{mesh}_z{self.zero_stage}_mbs{self.micro_batch}"
                f"{'_remat' if self.remat else ''}"
                f"{'_off-' + self.offload if self.offload else ''}")


class Autotuner:
    """Grid-search tuner over short real runs.

    ``model_builder`` is a zero-arg callable returning a fresh model (fresh
    params each experiment — engines donate/mutate state).  ``make_batch``
    maps a global batch size to a host batch dict."""

    def __init__(self, base_config: dict, model_builder: Callable[[], Any],
                 make_batch: Callable[[int], dict], *,
                 stages: Sequence[int] = (3, 2, 1, 0),
                 micro_batches: Optional[Sequence[int]] = None,
                 remat_options: Sequence[bool] = (False,),
                 mesh_options: "Optional[Sequence[dict]] | str" = None,
                 offload_options: Sequence[Optional[str]] = (None,),
                 steps: int = 3, warmup: int = 1,
                 early_stop_margin: float = 0.05,
                 results_path: Optional[str] = None):
        self.base_config = base_config
        self.model_builder = model_builder
        self.make_batch = make_batch
        self.stages = list(stages)
        self.micro_batches = list(micro_batches) if micro_batches else None
        self.remat_options = list(remat_options)
        # mesh candidates: None = pure DP only; "auto" = factor the device
        # count into model/seq splits (on TPU the mesh shape is THE knob —
        # reference tunes only stage+mbs, autotuner.py:404)
        self.mesh_options = mesh_options
        self.offload_options = list(offload_options)
        self.steps = steps
        self.warmup = warmup
        self.early_stop_margin = early_stop_margin
        self.results_path = results_path
        self.experiments: list[Experiment] = []

    # ------------------------------------------------------------------ grid
    @staticmethod
    def _auto_mesh_options(n_dev: int) -> list[dict]:
        """Candidate (model, seq) splits of the device count; ``data``
        absorbs the remainder. Bounded: at most ~6 candidates."""
        out: list[dict] = [{}]
        for m in (2, 4):
            if n_dev % m == 0 and n_dev > m:
                out.append({"model": m})
        if n_dev % 2 == 0 and n_dev > 2:
            out.append({"seq": 2})
        if n_dev % 4 == 0 and n_dev > 4:
            out.append({"model": 2, "seq": 2})
        return out

    def _mesh_candidates(self, n_dev: int) -> list[dict]:
        if self.mesh_options is None:
            return [{}]
        if self.mesh_options == "auto":
            return self._auto_mesh_options(n_dev)
        return [dict(m) for m in self.mesh_options]

    @staticmethod
    def _dp_for_mesh(mesh: dict, n_dev: int) -> int:
        non_dp = int(np.prod([v for k, v in mesh.items()
                              if k not in ("data", "zero", "expert")])) or 1
        return max(1, n_dev // non_dp)

    def _candidate_micro_batches(self, dp: int) -> list[int]:
        if self.micro_batches is not None:
            return self.micro_batches
        global_bs = int(self.base_config.get("train_batch_size", dp))
        per_dev = max(1, global_bs // dp)
        out, m = [], 1
        while m <= per_dev:
            out.append(m)
            m *= 2
        return out

    def _experiment_config(self, exp: Experiment, dp: int) -> dict:
        cfg = copy.deepcopy(self.base_config)
        zo = cfg.setdefault("zero_optimization", {})
        zo["stage"] = exp.zero_stage
        if exp.mesh:
            cfg["mesh"] = dict(exp.mesh)   # data axis auto-absorbs the rest
        if exp.offload:
            zo["offload_optimizer"] = {"device": exp.offload}
        cfg["train_micro_batch_size_per_gpu"] = exp.micro_batch
        global_bs = int(cfg.get("train_batch_size", dp * exp.micro_batch))
        cfg["gradient_accumulation_steps"] = max(
            1, global_bs // (exp.micro_batch * dp))
        # keep global batch consistent: global = micro * gas * dp
        cfg["train_batch_size"] = (exp.micro_batch
                                   * cfg["gradient_accumulation_steps"] * dp)
        if exp.remat:
            cfg["remat"] = {"enabled": True, "policy": "dots_saveable"}
        else:
            # remat=False must really measure remat-off even when the base
            # config enables it, or the grid dimension compares identical runs
            cfg.pop("remat", None)
        cfg.setdefault("steps_per_print", 10 ** 9)
        return cfg

    # --------------------------------------------------------------- measure
    def _run_one(self, exp: Experiment, dp: int) -> Experiment:
        import deepspeed_tpu as ds

        cfg = self._experiment_config(exp, dp)
        try:
            engine = ds.initialize(cfg, self.model_builder())
            batch = self.make_batch(engine.train_batch_size)
            for _ in range(self.warmup):
                engine.train_batch(batch)
            jax.block_until_ready(jax.tree.leaves(
                engine.state.master_params if not engine.offload
                else engine.compute_params)[0])
            t0 = time.perf_counter()
            for _ in range(self.steps):
                engine.train_batch(batch)
            jax.block_until_ready(jax.tree.leaves(
                engine.state.master_params if not engine.offload
                else engine.compute_params)[0])
            dt = (time.perf_counter() - t0) / self.steps
            exp.samples_per_sec = engine.train_batch_size / dt
            exp.ok = True
        except Exception as e:  # RESOURCE_EXHAUSTED, config errors, ...
            exp.error = f"{type(e).__name__}: {e}"[:300]
            exp.ok = False
        finally:
            # drop engine references so the next experiment's arrays can
            # reuse the HBM; donation already released most of it
            engine = None
            jax.clear_caches()
        return exp

    # ------------------------------------------------------------------ tune
    def tune(self) -> dict:
        """Run the grid; return the fastest config (base config if nothing
        succeeded). Results land in ``self.experiments`` +
        ``results_path`` JSON."""
        from ..platform.accelerator import get_accelerator

        n_dev = max(1, get_accelerator().device_count())
        best: Optional[Experiment] = None
        for mesh in self._mesh_candidates(n_dev):
            dp = self._dp_for_mesh(mesh, n_dev)
            for offload in self.offload_options:
                for stage in self.stages:
                    if offload and stage < 1:
                        continue   # host optimizer needs a sharded master
                    for remat in self.remat_options:
                        # turnover baseline is per sweep: remat=True starts
                        # slower at small mbs and only wins at larger ones, so
                        # it must not be early-stopped against another sweep
                        sweep_best: Optional[Experiment] = None
                        for mbs in self._candidate_micro_batches(dp):
                            exp = Experiment(stage, mbs, remat, mesh=mesh,
                                             offload=offload)
                            log_dist(f"autotune: running {exp.label()}",
                                     ranks=[0])
                            exp = self._run_one(exp, dp)
                            self.experiments.append(exp)
                            log_dist(
                                f"autotune: {exp.label()} → "
                                f"{exp.samples_per_sec:.1f} samples/s"
                                f"{'' if exp.ok else ' (FAILED: ' + exp.error + ')'}",
                                ranks=[0])
                            if not exp.ok:
                                break  # larger micro-batches will also OOM
                            if sweep_best and exp.samples_per_sec < \
                                    sweep_best.samples_per_sec * (1 - self.early_stop_margin):
                                break  # throughput turned over
                            if not sweep_best or exp.samples_per_sec > \
                                    sweep_best.samples_per_sec:
                                sweep_best = exp
                        if sweep_best and (not best or sweep_best.samples_per_sec
                                           > best.samples_per_sec):
                            best = sweep_best
        if self.results_path and jax.process_index() == 0:
            with open(self.results_path, "w") as f:
                json.dump([e.__dict__ for e in self.experiments], f, indent=2)
        if best is None:
            log_dist("autotune: every experiment failed; keeping base config",
                     ranks=[0])
            return copy.deepcopy(self.base_config)
        log_dist(f"autotune: best = {best.label()} "
                 f"({best.samples_per_sec:.1f} samples/s)", ranks=[0])
        return self._experiment_config(
            best, self._dp_for_mesh(best.mesh, n_dev))


def autotune(base_config: dict, model_builder, make_batch, **kw) -> dict:
    """One-call convenience wrapper."""
    return Autotuner(base_config, model_builder, make_batch, **kw).tune()
