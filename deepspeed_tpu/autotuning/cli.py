"""``dstpu_autotune``: config search from the command line.

The reference's autotuner is CLI-first (``deepspeed --autotuning run``,
``autotuning/autotuner.py:404``): point it at a model + base config, it
prunes/runs a grid and writes the best config. Same shape here, built on
the isolated tuner — the feasibility model prunes OOM points before they
touch the device and every surviving experiment runs in its own child
interpreter (this process never claims the accelerator).

    dstpu_autotune --model gpt2:125m --config ds_config.json \\
        --stages 3,2,1 --mesh auto --out best_config.json
"""

from __future__ import annotations

import argparse
import json
import sys

from .autotuner import Autotuner


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="dstpu_autotune",
        description="measured config search (feasibility-pruned, "
                    "child-isolated)")
    p.add_argument("--model", required=True,
                   help="preset spec: family[:size], e.g. gpt2:125m, "
                        "llama2:7b, bert:large, tiny_test")
    p.add_argument("--config", default=None,
                   help="base ds_config JSON file (default: a minimal "
                        "adamw config)")
    p.add_argument("--stages", default="3,2,1,0",
                   help="comma-separated ZeRO stages to sweep")
    p.add_argument("--micro-batches", default=None,
                   help="comma-separated micro-batch candidates "
                        "(default: powers of two up to the global batch)")
    p.add_argument("--mesh", default=None, choices=[None, "auto"],
                   help="'auto' sweeps model/seq mesh splits too")
    p.add_argument("--remat", action="store_true",
                   help="sweep remat on/off (default: off only)")
    p.add_argument("--offload", action="store_true",
                   help="include offload_optimizer=cpu in the sweep")
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--budget-gb", type=float, default=None,
                   help="per-device memory budget for the feasibility "
                        "pruner (default: probed from the device)")
    p.add_argument("--out", default="autotune_best.json",
                   help="where the winning config is written")
    p.add_argument("--results", default="autotune_results.json",
                   help="full ranked experiment ledger")
    args = p.parse_args(argv)

    family, _, size = args.model.partition(":")
    spec = {"family": family}
    if size:
        spec["size"] = size
    base = {"train_batch_size": 32,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}}}
    if args.config:
        with open(args.config) as f:
            base = json.load(f)

    tuner = Autotuner(
        base, None, None, model_spec=spec,
        stages=tuple(int(s) for s in args.stages.split(",")),
        # ascending: the sweep early-stops on the first pruned/failed/
        # slower candidate, which assumes micro-batches grow
        micro_batches=(sorted(int(m) for m in args.micro_batches.split(","))
                       if args.micro_batches else None),
        remat_options=(False, True) if args.remat else (False,),
        mesh_options=args.mesh,
        offload_options=(None, "cpu") if args.offload else (None,),
        steps=args.steps,
        hbm_budget_bytes=(int(args.budget_gb * 2**30)
                          if args.budget_gb else None),
        results_path=args.results)
    best = tuner.tune()
    with open(args.out, "w") as f:
        json.dump(best, f, indent=2)
    ok = sum(1 for e in tuner.experiments if e.ok)
    pruned = sum(1 for e in tuner.experiments
                 if e.error.startswith("pruned"))
    print(f"dstpu_autotune: {len(tuner.experiments)} experiments "
          f"({ok} ran, {pruned} pruned by the memory model) — best config "
          f"written to {args.out}, ledger to {args.results}", flush=True)
    if ok == 0:
        # nothing measured: the written config is just the base config —
        # a consuming script must be able to tell that from a real tune
        print("dstpu_autotune: NO experiment succeeded; wrote the "
              "unmodified base config", file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
