from .autotuner import Autotuner, autotune

__all__ = ["Autotuner", "autotune"]
