"""Autotuner experiment worker: one experiment per child interpreter.

The reference autotuner launches every experiment as a separate scheduler
job (``autotuning/scheduler.py``) precisely so a dead experiment cannot
take down the tune; round-3 review flagged that this tuner ran candidates
in-process instead — one XLA CHECK-crash (native abort, uncatchable) or a
wedging OOM kills the whole search. This worker restores that isolation:
the parent serializes ``(config, model_spec, steps)`` to JSON, the child
builds the model from the spec (a preset name + overrides — engines and
closures don't cross process boundaries), times the steps, and prints ONE
JSON result line. Any crash is the child's problem; the parent records a
failure and moves on.

Invoked as ``python -m deepspeed_tpu.autotuning.worker '<json>'``.
"""

from __future__ import annotations

import json
import sys
import time


def build_model_from_spec(spec: dict):
    """{"family": "gpt2", "size": "125m", "overrides": {...}} → model."""
    from .. import models

    family = getattr(models, spec["family"])
    args = (spec["size"],) if "size" in spec else ()
    cfg = family(*args, **spec.get("overrides", {}))
    return models.build_model(cfg), cfg


def make_batch_for(cfg, batch_size: int, seq: int | None = None):
    """Synthetic batch matching the model's objective."""
    import numpy as np

    S = int(seq or min(getattr(cfg, "max_seq", 128), 512))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch_size, S)).astype(np.int32)
    batch = {"input_ids": ids}
    if getattr(cfg, "objective", "clm") == "mlm":
        labels = ids.copy()
        mask = rng.random((batch_size, S)) < 0.15
        ids = ids.copy()
        ids[mask] = min(103, cfg.vocab_size - 1)
        batch = {"input_ids": ids, "labels": labels,
                 "loss_mask": mask.astype(np.float32)}
    return batch


def run_experiment(payload: dict) -> dict:
    import jax

    import deepspeed_tpu as ds

    model, cfg = build_model_from_spec(payload["model_spec"])
    engine = ds.initialize(payload["config"], model)
    batch = make_batch_for(cfg, engine.train_batch_size,
                           payload.get("seq"))
    for _ in range(int(payload.get("warmup", 1))):
        engine.train_batch(dict(batch))
    # host readback barrier (block_until_ready returns early over the
    # axon tunnel)
    float(engine.train_batch(dict(batch))["loss"])
    steps = int(payload.get("steps", 3))
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(dict(batch))
    loss = float(m["loss"])
    dt = (time.perf_counter() - t0) / steps
    if not jax.numpy.isfinite(loss):
        return {"ok": False, "error": f"non-finite loss {loss}"}
    return {"ok": True,
            "samples_per_sec": engine.train_batch_size / dt,
            "loss": loss}


def main(argv=None) -> None:
    payload = json.loads((argv or sys.argv[1:])[0])
    try:
        result = run_experiment(payload)
    except Exception as e:        # noqa: BLE001 — the whole point
        result = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
