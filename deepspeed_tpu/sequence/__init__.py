"""Sequence / context parallelism (long-context training).

Reference analog: DeepSpeed-Ulysses ``deepspeed/sequence/layer.py:15-85``
(all-to-all DistributedAttention) — plus ring attention (context
parallelism over ICI neighbors via ``ppermute``), which the reference
version lacks entirely (SURVEY §5 long-context: "Ring/blockwise attention:
absent") and is the TPU-idiomatic long-context strategy.
"""

from .layer import (make_ring_attention, make_ulysses_attention,
                    ring_attention_local, ulysses_attention_local)

__all__ = ["make_ulysses_attention", "make_ring_attention",
           "ulysses_attention_local", "ring_attention_local"]
