"""Ulysses all-to-all attention + ring attention (context parallelism).

Reference: ``deepspeed/sequence/layer.py:15-85`` — ``DistributedAttention``
wraps any local attention with an all-to-all pair: inputs arrive sharded on
the sequence dim ``[s/p, h]``, the first all-to-all re-shards to ``[s, h/p]``
(full sequence, subset of heads), local attention runs, and the inverse
all-to-all restores the sequence shard. Here that is an all-to-all over the
``seq`` mesh axis under ``shard_map``.

Ring attention (NOT in the reference — SURVEY §5: "Ring/blockwise attention:
absent") keeps q resident and rotates k/v blocks around the ``seq`` axis ring
with ``ppermute`` while maintaining an online-softmax accumulator — exactly
flash attention's streaming update, with the k/v stream arriving over ICI
from the ring neighbor. Communication is neighbor-to-neighbor (perfect for a
torus) and memory per chip is O(S/p), so sequence length scales linearly
with the ring size.

All collectives go through :mod:`deepspeed_tpu.comm` so the CommsLogger
ledger (the reference's ``timed_op``/comms-logger analog) sees seq-axis
traffic.

Both wrappers carry ``handles_sharding = True`` so the model skips its own
GSPMD resharding constraints around the attention call.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import comm
from ..platform.mesh import BATCH_AXES, SEQ_AXIS

# Finite stand-in for -inf: (BIG_NEG - BIG_NEG) must be 0, not NaN, so the
# online-softmax rescale is well defined for fully-masked blocks.
BIG_NEG = -2.0 ** 30


def _repeat_kv(k, v, n_heads: int):
    kvh = k.shape[2]
    if kvh != n_heads:
        k = jnp.repeat(k, n_heads // kvh, axis=2)
        v = jnp.repeat(v, n_heads // kvh, axis=2)
    return k, v


def _shard_mapped(mesh: Mesh, axis: str, body: Callable, q, k, v, mask):
    """Run ``body(q, k, v, mask)`` under shard_map with seq-dim sharding.

    The head dim shards over ``model`` (both bodies are per-head, so TP
    composes: each model-axis shard handles H/tp heads, no cross-model
    collectives), provided both q and kv head counts divide tp — the makers
    pre-repeat GQA kv to guarantee this when tp > 1.
    """
    tp = int(mesh.shape.get("model", 1))
    hshard = "model" if (tp > 1 and q.shape[2] % tp == 0
                         and k.shape[2] % tp == 0) else None
    qspec = P(BATCH_AXES, axis, hshard, None)
    if mask is None:
        f = shard_map(lambda q_, k_, v_: body(q_, k_, v_, None),
                      mesh=mesh, in_specs=(qspec, qspec, qspec),
                      out_specs=qspec)
        return f(q, k, v)
    f = shard_map(body, mesh=mesh,
                  in_specs=(qspec, qspec, qspec, P(BATCH_AXES, axis)),
                  out_specs=qspec)
    return f(q, k, v, mask)


# ---------------------------------------------------------------- ring attn
# Rings up to this size build a flat (unrolled) program — best scheduling
# freedom for XLA, program size linear in ring size. Larger rings roll into
# a ``lax.fori_loop`` so a 64-ring (the point of ring attention) compiles in
# bounded time; the loop body issues the next hop's ppermute BEFORE the
# block compute, so the async collective still overlaps the einsums.
RING_UNROLL_MAX = 8


def ring_attention_local(q, k, v, kmask, *, axis_name: str, n_chunks: int,
                         alibi_slopes=None, unroll_max: int = RING_UNROLL_MAX):
    """Per-shard ring attention body (callable under an existing shard_map).

    q: (B, S/p, H, hd); k/v: (B, S/p, KV, hd) local sequence chunks (GQA kv
    stays un-repeated on the wire — the ring moves KV heads, not H). kmask:
    (B, S/p) key padding mask chunk or None. Causal.

    ``alibi_slopes``: (H,) — the ALiBi distance bias is rebuilt per ring
    step from the global (q_pos, k_pos) the ring already tracks, so
    long-context ALiBi costs H floats instead of an (H, S, S) operand.
    """
    idx = lax.axis_index(axis_name)
    B, Sc, H, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    qf = q.astype(jnp.float32) * scale
    q_pos = idx * Sc + jnp.arange(Sc)
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        if slopes.shape[0] != H:
            # heads are sharded over the model axis: take THIS shard's
            # slice of the full (H_global,) slope vector
            h0 = lax.axis_index("model") * H
            slopes = lax.dynamic_slice(slopes, (h0,), (H,))
        alibi_slopes = slopes

    def block(acc, k, v, kmask, s):
        """One online-softmax update against ring-step ``s``'s k/v block
        (``s`` may be a Python int or a traced loop counter)."""
        m, l, o = acc
        src = (idx - s) % n_chunks
        k_pos = src * Sc + jnp.arange(Sc)
        kb, vb = _repeat_kv(k, v, H)               # expand GQA locally, post-wire
        scores = jnp.einsum("bshd,bthd->bhst", qf, kb.astype(jnp.float32))
        if alibi_slopes is not None:
            rel = (k_pos[None, :] - q_pos[:, None]).astype(jnp.float32)
            scores = scores + alibi_slopes[None, :, None, None] * rel[None, None]
        keep = (q_pos[:, None] >= k_pos[None, :])[None, None]
        if kmask is not None:
            keep = keep & kmask[:, None, None, :].astype(bool)
        scores = jnp.where(keep, scores, BIG_NEG)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.where(keep, jnp.exp(scores - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)                  # (B, H, Sc)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + \
            jnp.einsum("bhst,bthd->bshd", p, vb.astype(jnp.float32))
        return (m_new, l, o)

    acc = (jnp.full((B, H, Sc), BIG_NEG, jnp.float32),
           jnp.zeros((B, H, Sc), jnp.float32),
           jnp.zeros((B, Sc, H, hd), jnp.float32))
    perm = [(i, (i + 1) % n_chunks) for i in range(n_chunks)]

    def rotate(k, v, kmask):
        k = comm.ppermute(k, axis_name, perm)
        v = comm.ppermute(v, axis_name, perm)
        if kmask is not None:
            kmask = comm.ppermute(kmask, axis_name, perm)
        return k, v, kmask

    if n_chunks <= unroll_max:
        # Flat ring: XLA overlaps each ppermute with the previous step's
        # block compute — the comm/compute overlap the reference hand-codes
        # with CUDA streams falls out of the schedule.
        for s in range(n_chunks):
            acc = block(acc, k, v, kmask, s)
            if s != n_chunks - 1:
                k, v, kmask = rotate(k, v, kmask)
    else:
        # Rolled ring: each step issues the NEXT hop's ppermute before
        # computing on the current block (the compute does not depend on
        # the permute result, so the async collective rides under the
        # einsums). First and last blocks are peeled: the first so the
        # loop carry enters with the manual axes already varying (a
        # replicated init vs varying loop output is a carry type error),
        # the last so there is no wasted final hop. Program size is O(1)
        # in ring size.
        nxt = rotate(k, v, kmask)
        acc = block(acc, k, v, kmask, 0)

        def body(s, carry):
            acc, k, v, kmask = carry
            nxt = rotate(k, v, kmask)
            acc = block(acc, k, v, kmask, s)
            return (acc, *nxt)
        acc, k, v, kmask = lax.fori_loop(
            1, n_chunks - 1, body, (acc, *nxt))
        acc = block(acc, k, v, kmask, n_chunks - 1)

    m, l, o = acc
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = SEQ_AXIS,
                        unroll_max: int = RING_UNROLL_MAX) -> Callable:
    """Causal ring attention over the ``seq`` mesh axis.

    Drop-in ``attention_fn`` for :class:`~deepspeed_tpu.models.TransformerLM`:
    takes global (B, S, H, hd) arrays inside jit, shards S over the ring.
    Rings larger than ``unroll_max`` compile to a rolled ``fori_loop``
    (constant program size — a 64-ring compiles as fast as an 8-ring).
    """
    n = int(mesh.shape.get(axis, 1))

    def attn(q, k, v, *, mask: Optional[jnp.ndarray] = None,
             alibi_slopes=None):
        if n == 1:
            from ..models.transformer import alibi_bias, causal_attention

            bias = (alibi_bias(alibi_slopes, q.shape[1])
                    if alibi_slopes is not None else None)
            return causal_attention(q, k, v, mask=mask, bias=bias)
        assert q.shape[1] % n == 0, (
            f"seq len {q.shape[1]} not divisible by ring size {n}")
        tp = int(mesh.shape.get("model", 1))
        if tp > 1 and k.shape[2] % tp != 0:
            k, v = _repeat_kv(k, v, q.shape[2])   # make kv shardable over tp
        # slopes close over the shard_map body as a tiny constant
        body = partial(ring_attention_local, axis_name=axis, n_chunks=n,
                       alibi_slopes=alibi_slopes, unroll_max=unroll_max)
        return _shard_mapped(mesh, axis, body, q, k, v, mask)

    attn.handles_sharding = True
    attn.accepts_alibi_slopes = True   # ramp rebuilt from ring positions
    return attn


# ------------------------------------------------------------- ulysses attn
def ulysses_attention_local(q, k, v, kmask, *, axis_name: str,
                            local_attn: Callable):
    """Per-shard Ulysses body: all-to-all [s/p, h] -> [s, h/p], local
    attention over the full sequence, inverse all-to-all. The reference's
    ``_SeqAllToAll`` pair (``sequence/layer.py:20-55``) in two collectives."""
    q = comm.all_to_all(q, axis_name, split_axis=2, concat_axis=1)
    k = comm.all_to_all(k, axis_name, split_axis=2, concat_axis=1)
    v = comm.all_to_all(v, axis_name, split_axis=2, concat_axis=1)
    if kmask is not None:
        kmask = comm.all_gather(kmask, axis_name, axis=1)
    o = local_attn(q, k, v, mask=kmask)
    return comm.all_to_all(o, axis_name, split_axis=1, concat_axis=2)


def make_ulysses_attention(mesh: Mesh, axis: str = SEQ_AXIS,
                           local_attn: Optional[Callable] = None) -> Callable:
    """Explicit-collective DistributedAttention (reference
    ``sequence/layer.py:15``). ``local_attn`` defaults to plain causal
    attention; pass the Pallas flash kernel for long sequences."""
    n = int(mesh.shape.get(axis, 1))

    def attn(q, k, v, *, mask: Optional[jnp.ndarray] = None):
        from ..models.transformer import causal_attention

        inner = local_attn or causal_attention
        if n == 1:
            return inner(q, k, v, mask=mask)
        H = q.shape[2]
        tp = int(mesh.shape.get("model", 1))
        tp = tp if (tp > 1 and H % tp == 0) else 1
        assert (H // tp) % n == 0, \
            f"n_heads {H} / tp {tp} must be divisible by sp size {n} " \
            "(reference requirement, sequence/layer.py)"
        KV = k.shape[2]
        if tp > 1:
            if KV % (tp * n) != 0:
                k, v = _repeat_kv(k, v, H)        # make kv shardable over tp x sp
        elif KV % n != 0:
            # GQA: repeat kv only to the smallest splittable head count; the
            # local attention's own GQA expansion covers the rest.
            k, v = _repeat_kv(k, v, math.lcm(KV, n))
        body = partial(ulysses_attention_local, axis_name=axis, local_attn=inner)
        return _shard_mapped(mesh, axis, body, q, k, v, mask)

    attn.handles_sharding = True
    return attn
