"""Fused weight-only-quant GEMM: int8/int4 weights dequantized in VMEM.

The decode hot loop is HBM-bandwidth-bound on the weight re-read, and the
reference's entire int8 inference stack (``csrc/transformer/inference/``,
``csrc/quantization/``) exists to cut that traffic. The repo's previous WOQ
path stored int8 but dequantized whole matrices in XLA, which hoists the
loop-invariant convert out of the decode scan (``WOQ_PROBE.json`` round 5:
"hoisted/not-fused: no decode bandwidth win" — int8 decode *slower* than
bf16). These kernels make the hoist impossible: the int8 (or nibble-packed
int4) tiles stream HBM→VMEM, are dequantized *inside the matmul loop* on
the VPU, and feed the MXU in the activation dtype with an fp32 accumulator.
HBM weight traffic per token drops ~2x (int8) / ~4x (int4) vs bf16 — the
EQuARX/qwZ principle of dequantizing at the point of consumption.

Quantization layout (see ``inference/quantization.py``): groups of
``group_size`` rows along the weight's second-to-last dim share a scale
row, so ``scale`` is ``(G, N)`` fp32 for a ``(K, N)`` weight with
``G = K / group_size``. Two consumption patterns:

- :func:`woq_matmul` — ``x @ W`` for projection/MLP weights stored
  ``(K, N)``: the k-loop steps one *group* at a time, so the scale is a
  single ``(1, bn)`` row per step and folds into the accumulator AFTER the
  int8 dot (``(x @ q) * s`` == ``x @ (q * s)`` within a group) — the MXU
  never sees a dequantized weight tile at all;
- :func:`woq_matmul_t` — ``x @ W.T`` for the tied-embedding head, W stored
  ``(V, K)`` with groups along V: the output tile is clamped to one group
  (``bv <= group_size``), the ``(1, bc)`` scale row broadcasts over the
  tile's rows in VMEM, then the MXU contracts the lane dim.

int4 packs two signed nibbles per byte along *adjacent rows* of the grouped
dim (row ``2r`` low nibble, ``2r+1`` high): in-kernel unpack is two
arithmetic shifts + a sublane interleave — lane layout untouched, which is
what Mosaic relayouts care about. Everything runs under
``interpret=True`` off-TPU, so parity is tier-1-testable on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# shared tile math (same helpers the fused-xent kernels use — one place
# for pow2 rounding / axis padding so the two kernel modules can't drift)
from .xent import _pad_to as _pad_axis
from .xent import _pow2_ceil, _resolve_interpret


def _unpack_rows(p):
    """(R/2, C) packed bytes → (R, C) signed int4 values in int8: two
    arithmetic shifts + a sublane interleave (lane dim untouched)."""
    lo = (p << 4).astype(jnp.int8) >> 4          # sign-extend low nibble
    hi = p >> 4                                  # arithmetic: high nibble
    return jnp.stack([lo, hi], axis=1).reshape(p.shape[0] * 2, p.shape[1])


# --------------------------------------------------------- x @ W  (K, N)
def _matmul_kernel(x_ref, q_ref, s_ref, o_ref, acc_sc, *, n_k: int,
                   bits: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_sc[...] = jnp.zeros(acc_sc.shape, jnp.float32)

    q = q_ref[...]
    if bits == 4:
        q = _unpack_rows(q)
    x = x_ref[...]
    # int8→activation-dtype convert happens HERE, on the VPU, on the tile
    # already resident in VMEM — HBM only ever saw the int8 bytes. The
    # group scale is constant over this k-step's rows, so it distributes
    # out of the dot and multiplies the fp32 partial instead (the MXU runs
    # a pure integer-valued matmul).
    part = jnp.dot(x, q.astype(x.dtype), preferred_element_type=jnp.float32)
    acc_sc[...] += part * s_ref[...]             # (1, bn) broadcast

    @pl.when(k == n_k - 1)
    def _emit():
        o_ref[...] = acc_sc[...].astype(o_ref.dtype)


def woq_matmul(x, q, scale, *, group_size: int, bits: int = 8,
               block_m: int = 256, block_n: int = 512,
               interpret: Optional[bool] = None, out_dtype=None):
    """``x @ W`` with ``W`` stored quantized ``(K, N)``.

    x: (M, K) bf16/f32; q: (K, N) int8 — int4 packs row pairs to
    (K/2, N); scale: (G, N) fp32, G = K // group_size. Returns (M, N) in
    ``x.dtype`` (or ``out_dtype``) with fp32 accumulation.
    """
    M, K = x.shape
    G, N = scale.shape
    gs = group_size
    assert G * gs == K, (K, group_size, scale.shape)
    assert bits in (4, 8), bits
    assert q.shape == ((K // 2, N) if bits == 4 else (K, N)), q.shape
    interpret = _resolve_interpret(interpret)
    out_dtype = out_dtype or x.dtype

    bm = min(block_m, max(16, _pow2_ceil(M)))
    bn = min(block_n, _pow2_ceil(N))
    xp = _pad_axis(x, bm, 0)
    qp = _pad_axis(q, bn, 1)
    sp = _pad_axis(scale, bn, 1)
    Mp, Np = xp.shape[0], qp.shape[1]
    rows = gs // 2 if bits == 4 else gs          # q rows per k-step

    out = pl.pallas_call(
        functools.partial(_matmul_kernel, n_k=G, bits=bits),
        grid=(Mp // bm, Np // bn, G),
        in_specs=[
            pl.BlockSpec((bm, gs), lambda i, j, k: (i, k)),
            pl.BlockSpec((rows, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), out_dtype),
        scratch_shapes=[_vmem((bm, bn))],
        interpret=interpret,
    )(xp, qp, sp)
    return out[:M, :N]


# ------------------------------------------------------ x @ W.T  (V, K)
def _matmul_t_kernel(x_ref, q_ref, s_ref, o_ref, acc_sc, *, n_k: int,
                     bits: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_sc[...] = jnp.zeros(acc_sc.shape, jnp.float32)

    q = q_ref[...]
    if bits == 4:
        q = _unpack_rows(q)
    x = x_ref[...]
    # the whole (bv, bc) tile sits in ONE row group (bv <= group_size), so
    # its scale is a single (1, bc) row broadcast down the tile — dequant
    # in VMEM, then contract the lane dim on the MXU
    wd = (q.astype(jnp.float32) * s_ref[...]).astype(x.dtype)
    acc_sc[...] += lax.dot_general(x, wd, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _emit():
        o_ref[...] = acc_sc[...].astype(o_ref.dtype)


def woq_matmul_t(x, q, scale, *, group_size: int, bits: int = 8,
                 block_m: int = 256, block_v: int = 128, block_c: int = 512,
                 interpret: Optional[bool] = None, out_dtype=None):
    """``x @ W.T`` with ``W`` stored quantized ``(V, K)`` — the tied
    embedding table consumed as the unembedding, never transposed in HBM.

    x: (M, K); q: (V, K) int8 — int4 packs row pairs to (V/2, K);
    scale: (G, K) fp32, G = V // group_size. Returns (M, V).
    """
    M, K = x.shape
    G, Ks = scale.shape
    gs = group_size
    V = q.shape[0] * (2 if bits == 4 else 1)
    assert Ks == K and G * gs == V, (q.shape, scale.shape, group_size)
    assert bits in (4, 8), bits
    interpret = _resolve_interpret(interpret)
    out_dtype = out_dtype or x.dtype

    bm = min(block_m, max(16, _pow2_ceil(M)))
    bc = min(block_c, _pow2_ceil(K))
    if G == 1:
        # degraded single group (odd vocab): every row shares the scale
        # row, so the output tile is unconstrained by group alignment
        bv = min(block_v, max(2 if bits == 4 else 1, _pow2_ceil(V)))

        def sidx(i, j, k):
            return (0, k)
    else:
        # output tile bounded by (and aligned to) one group so its scale
        # is a single row: bv | gs, largest candidate first
        bv = block_v if gs % block_v == 0 else gs

        def sidx(i, j, k):
            return (j * bv // gs, k)

    xp = _pad_axis(_pad_axis(x, bm, 0), bc, 1)
    qrows = bv // 2 if bits == 4 else bv
    qp = _pad_axis(_pad_axis(q, qrows, 0), bc, 1)
    Vp = qp.shape[0] * (2 if bits == 4 else 1)
    sp = _pad_axis(scale, bc, 1)
    Mp, Kp = xp.shape
    n_c = Kp // bc

    out = pl.pallas_call(
        functools.partial(_matmul_t_kernel, n_k=n_c, bits=bits),
        grid=(Mp // bm, Vp // bv, n_c),
        in_specs=[
            pl.BlockSpec((bm, bc), lambda i, j, k: (i, k)),
            pl.BlockSpec((qrows, bc), lambda i, j, k: (j, k)),
            pl.BlockSpec((1, bc), sidx),
        ],
        out_specs=pl.BlockSpec((bm, bv), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Vp), out_dtype),
        scratch_shapes=[_vmem((bm, bv))],
        interpret=interpret,
    )(xp, qp, sp)
    return out[:M, :V]


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


# --------------------------------------------------------------- helpers
# VMEM element budget for one kernel step (double-buffered operands +
# accumulator), mirroring ops/xent.py's proven ceiling. Leaves whose
# degraded group covers a huge K (e.g. an odd 50k vocab) would blow this —
# the dispatcher in inference/quantization.py routes them to XLA instead.
_TILE_ELEM_BUDGET = (256 + 512) * 4096


def woq_matmul_eligible(K: int, group_size: int, bits: int) -> bool:
    """Can :func:`woq_matmul` stream this weight? The k-step tile is one
    whole group, so a degraded (group == K) wide leaf must stay on XLA.

    On real TPU the x-tile's LANE dim is the group size, so it must be a
    128 multiple (or the full K, which Pallas pads internally) — Mosaic
    rejects other widths at compile time, inside the decode scan, where
    interpret-mode CI can't see it. Off-TPU (interpret) any group works."""
    if bits == 4 and group_size % 2 != 0:
        return False
    if jax.default_backend() == "tpu" \
            and group_size % 128 != 0 and group_size < K:
        return False
    return K % group_size == 0 and group_size * 512 <= _TILE_ELEM_BUDGET


def woq_matmul_t_eligible(V: int, K: int, group_size: int,
                          bits: int) -> bool:
    """Same gate for the transposed (tied-head) consumption: the output
    tile must fit inside (or be) one group; nothing constrains K (it
    streams). A degraded single group (group >= V) is fine — every tile
    shares the one scale row — but a non-dividing multi-group layout or a
    group too wide to be an output tile stays on XLA."""
    if bits == 4 and (group_size % 2 != 0 or V % 2 != 0):
        return False
    if group_size >= V:
        return True           # single group: bv is a free power of two
    if jax.default_backend() == "tpu" and group_size % 128 != 0:
        # multi-group forces bv | gs; a non-128-multiple bv is a
        # lane-misaligned output tile Mosaic rejects (interpret is fine)
        return False
    return V % group_size == 0 and group_size <= 1024
