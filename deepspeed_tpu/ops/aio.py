"""Async file I/O handle (NVMe offload tier, ZeRO-Infinity).

Reference: ``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp`` — an aio handle
with worker threads, queue depth, and block-size knobs, submitting O_DIRECT
reads/writes of tensors. Same surface here over the C++ thread-pool
extension (``csrc/aio.cpp``); a Python thread-pool fallback keeps the tier
functional without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

import numpy as np

from .builder import build_and_load


def _lib():
    lib = build_and_load("aio")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        lib.ds_aio_create.restype = ctypes.c_void_p
        lib.ds_aio_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.ds_aio_open.restype = ctypes.c_int
        lib.ds_aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.ds_aio_close.argtypes = [ctypes.c_int]
        for f in (lib.ds_aio_submit_read, lib.ds_aio_submit_write):
            f.restype = ctypes.c_int64
            f.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                          ctypes.c_int64, ctypes.c_int64]
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ds_aio_errors.restype = ctypes.c_int64
        lib.ds_aio_errors.argtypes = [ctypes.c_void_p]
        lib._sigs_set = True
    return lib


class AsyncIOHandle:
    """Submit/wait file reads+writes of numpy buffers off the main thread."""

    def __init__(self, n_threads: int = 4, block_size: int = 1 << 20,
                 use_direct: bool = True):
        self.block_size = block_size
        self.use_direct = use_direct
        self._lib = _lib()
        if self._lib is not None:
            self._h = ctypes.c_void_p(self._lib.ds_aio_create(n_threads,
                                                              block_size))
            self._pool = None
        else:
            self._h = None
            self._pool = ThreadPoolExecutor(max_workers=n_threads)
        self._fds: dict[str, int] = {}
        self._futures: dict[int, Future] = {}
        self._next = 1

    # ------------------------------------------------------------------ fds
    def _fd(self, path: str, for_write: bool) -> int:
        key = f"{path}|{int(for_write)}"
        if key not in self._fds:
            if self._lib is not None:
                fd = self._lib.ds_aio_open(path.encode(), int(for_write),
                                           int(self.use_direct))
                if fd < 0:
                    raise OSError(f"aio open failed: {path}")
            else:
                flags = (os.O_WRONLY | os.O_CREAT) if for_write else os.O_RDONLY
                fd = os.open(path, flags, 0o644)
            self._fds[key] = fd
        return self._fds[key]

    # ---------------------------------------------------------------- submit
    def submit_write(self, path: str, buf: np.ndarray, offset: int = 0) -> int:
        assert buf.flags["C_CONTIGUOUS"]
        fd = self._fd(path, True)
        if self._lib is not None:
            return self._lib.ds_aio_submit_write(
                self._h, fd, buf.ctypes.data_as(ctypes.c_void_p),
                buf.nbytes, offset)
        t = self._next
        self._next += 1
        self._futures[t] = self._pool.submit(os.pwrite, fd, buf.tobytes(), offset)
        return t

    def submit_read(self, path: str, buf: np.ndarray, offset: int = 0) -> int:
        assert buf.flags["C_CONTIGUOUS"]
        fd = self._fd(path, False)
        if self._lib is not None:
            return self._lib.ds_aio_submit_read(
                self._h, fd, buf.ctypes.data_as(ctypes.c_void_p),
                buf.nbytes, offset)
        t = self._next
        self._next += 1

        def read_into():
            data = os.pread(fd, buf.nbytes, offset)
            buf.view(np.uint8).reshape(-1)[:len(data)] = np.frombuffer(
                data, np.uint8)

        self._futures[t] = self._pool.submit(read_into)
        return t

    # ------------------------------------------------------------------ wait
    def wait(self, ticket: int) -> None:
        if self._lib is not None:
            self._lib.ds_aio_wait(self._h, ticket)
            if self._lib.ds_aio_errors(self._h):
                raise OSError("aio: outstanding I/O errors")
            return
        for t in sorted(list(self._futures)):
            if t <= ticket:
                self._futures.pop(t).result()

    def sync_write(self, path: str, buf: np.ndarray, offset: int = 0) -> None:
        self.wait(self.submit_write(path, buf, offset))

    def sync_read(self, path: str, buf: np.ndarray, offset: int = 0) -> None:
        self.wait(self.submit_read(path, buf, offset))

    def forget(self, path: str) -> None:
        """Drop (and close) any cached fds for ``path``. Must be called
        when a swap file is unlinked or replaced on disk: the fd cache is
        keyed by path string, so a stale descriptor would silently keep
        serving the deleted inode."""
        for w in (0, 1):
            fd = self._fds.pop(f"{path}|{w}", None)
            if fd is None:
                continue
            try:
                (self._lib.ds_aio_close(fd) if self._lib is not None
                 else os.close(fd))
            except OSError:
                pass

    def close(self) -> None:
        for fd in self._fds.values():
            (self._lib.ds_aio_close(fd) if self._lib is not None
             else os.close(fd))
        self._fds.clear()
        if self._lib is not None and self._h:
            self._lib.ds_aio_destroy(self._h)
            self._h = None
        if self._pool is not None:
            self._pool.shutdown()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class AIOFileStore:
    """One directory of swap files behind a shared :class:`AsyncIOHandle`.

    This is the single NVMe seam: the serving KV disk tier
    (``serving/tiering.py``) and the optimizer-state offload swap
    (``runtime/offload.py``) both run their files through this object
    instead of growing private aio/fd/path disciplines. It owns

    - name→path mapping under one directory (callers speak file *names*),
    - fd-cache hygiene (``unlink`` closes cached descriptors before
      removing the inode, so a later re-create never reads a stale fd),
    - an ``errors`` counter every failed submit/wait increments — the
      ``ds_aio_errors`` signal surfaced by doctor/health.

    Integrity (CRC) policy intentionally stays one layer up: the KV tier
    verifies per-entry checksums, the optimizer swap trusts its own
    fixed-layout files. Both get the same transport discipline here.
    """

    def __init__(self, directory: str, n_threads: int = 4,
                 block_size: int = 1 << 20, use_direct: bool = False):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.aio = AsyncIOHandle(n_threads=n_threads, block_size=block_size,
                                 use_direct=use_direct)
        self.errors = 0

    def path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    # ---------------------------------------------------------- submit/wait
    def submit_write(self, name: str, buf: np.ndarray, offset: int = 0) -> int:
        try:
            return self.aio.submit_write(self.path(name), buf, offset)
        except OSError:
            self.errors += 1
            raise

    def submit_read(self, name: str, buf: np.ndarray, offset: int = 0) -> int:
        try:
            return self.aio.submit_read(self.path(name), buf, offset)
        except OSError:
            self.errors += 1
            raise

    def wait(self, ticket: int) -> None:
        try:
            self.aio.wait(ticket)
        except OSError:
            self.errors += 1
            raise

    def sync_write(self, name: str, buf: np.ndarray, offset: int = 0) -> None:
        self.wait(self.submit_write(name, buf, offset))

    def sync_read(self, name: str, buf: np.ndarray, offset: int = 0) -> None:
        self.wait(self.submit_read(name, buf, offset))

    # ------------------------------------------------------------ lifecycle
    def unlink(self, name: str) -> None:
        p = self.path(name)
        self.aio.forget(p)
        try:
            os.unlink(p)
        except FileNotFoundError:
            pass

    def exists(self, name: str) -> bool:
        return os.path.exists(self.path(name))

    def close(self) -> None:
        self.aio.close()


def native_available() -> bool:
    return _lib() is not None
