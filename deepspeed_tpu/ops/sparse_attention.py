"""Block-sparse attention: sparsity configs + Pallas kernels.

Analog of the reference's sparse-attention subsystem
(``ops/sparse_attention/sparsity_config.py:10-546`` layout generators and the
Triton block-sparse matmul/softmax kernels, ~2.3 kLoC): attention cost drops
from O(S²) to O(S·w) by computing only the (q-block, k-block) pairs named in
a block *layout*.

TPU shape of the idea:
- the layout is a host-side numpy boolean (nq_blocks, nk_blocks) computed
  once per (config, seqlen) — a trace-time constant, like the reference's
  per-head layout tensors;
- the layout is compiled into CSR-style index lists (active k-blocks per
  q-block, and the transpose for the dk/dv pass) that ride the kernel as
  scalar-prefetch operands, so each Pallas program loops over exactly its
  active blocks — no dense iteration, no dynamic shapes;
- fwd/bwd are the flash-attention kernels (online softmax, saved logsumexp,
  recomputed probabilities) restricted to active blocks; the diagonal blocks
  still apply the elementwise causal triangle.

Configs mirror the reference family: Fixed, Variable, BigBird, BSLongformer,
Dense (names and knobs from ``sparsity_config.py``).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import BIG_NEG, SUBLANES


def _delta_operand(do, o):
    """Per-row rowsum(dO * O), sublane-replicated for the bwd kernels
    (shared with flash_attention's backward)."""
    B, H, S, _ = do.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    return jnp.broadcast_to(delta[:, :, None, :], (B, H, SUBLANES, S))


# ------------------------------------------------------------------ configs
@dataclasses.dataclass(frozen=True)
class SparsityConfig:
    """Base: dense layout (reference ``DenseSparsityConfig``)."""

    block: int = 64

    def make_layout(self, n_blocks: int) -> np.ndarray:
        return np.ones((n_blocks, n_blocks), bool)


@dataclasses.dataclass(frozen=True)
class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global blocks (reference
    ``FixedSparsityConfig``): each block attends its window of
    ``num_local_blocks``; the last ``num_global_blocks`` of each window are
    global (attended by and attending everyone)."""

    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        w = self.num_local_blocks
        for i in range(n):
            start = (i // w) * w
            lay[i, start:min(start + w, n)] = True
        for wstart in range(0, n, w):
            gstart = min(wstart + w, n) - self.num_global_blocks
            g = slice(max(wstart, gstart), min(wstart + w, n))
            lay[:, g] = True
            lay[g, :] = True
        return lay


@dataclasses.dataclass(frozen=True)
class VariableSparsityConfig(SparsityConfig):
    """Custom local window sizes + explicit global block indices
    (reference ``VariableSparsityConfig``)."""

    local_window_blocks: Sequence[int] = (4,)
    global_block_indices: Sequence[int] = (0,)

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        start = 0
        windows = list(self.local_window_blocks)
        wi = 0
        while start < n:
            w = windows[min(wi, len(windows) - 1)]
            end = min(start + w, n)
            lay[start:end, start:end] = True
            start = end
            wi += 1
        for g in self.global_block_indices:
            if g < n:
                lay[:, g] = True
                lay[g, :] = True
        return lay


@dataclasses.dataclass(frozen=True)
class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global (reference ``BigBirdSparsityConfig``)."""

    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        half = self.num_sliding_window_blocks // 2
        for i in range(n):
            lay[i, max(0, i - half):min(n, i + half + 1)] = True
        g = min(self.num_global_blocks, n)
        lay[:, :g] = True
        lay[:g, :] = True
        rng = np.random.default_rng(self.seed)
        for i in range(n):
            picks = rng.choice(n, size=min(self.num_random_blocks, n),
                               replace=False)
            lay[i, picks] = True
        return lay


@dataclasses.dataclass(frozen=True)
class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + explicit global indices (reference
    ``BSLongformerSparsityConfig``)."""

    num_sliding_window_blocks: int = 3
    global_block_indices: Sequence[int] = (0,)

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        half = self.num_sliding_window_blocks // 2
        for i in range(n):
            lay[i, max(0, i - half):min(n, i + half + 1)] = True
        for g in self.global_block_indices:
            if g < n:
                lay[:, g] = True
                lay[g, :] = True
        return lay


# ----------------------------------------------------------- layout → lists
def _layout_lists(layout: np.ndarray, causal: bool):
    """Boolean layout → CSR-ish index lists for the kernels.

    Returns (k_idx (nq, A), k_n (nq,), q_idx (nk, B), q_n (nk,)) padded
    int32 arrays: active k-blocks per q-block and the transpose."""
    n = layout.shape[0]
    lay = layout.copy()
    if causal:
        lay &= np.tril(np.ones((n, n), bool))
    if not lay.any(axis=1).all():
        bad = np.where(~lay.any(axis=1))[0]
        raise ValueError(f"layout leaves q-blocks {bad.tolist()} with no "
                         "active k-blocks (causal masking removed them all?)")

    def lists(m):
        counts = m.sum(axis=1)
        width = int(counts.max())
        idx = np.zeros((m.shape[0], width), np.int32)
        for i in range(m.shape[0]):
            act = np.nonzero(m[i])[0]
            idx[i, :len(act)] = act
        return idx, counts.astype(np.int32)

    k_idx, k_n = lists(lay)
    q_idx, q_n = lists(lay.T)
    return k_idx, k_n, q_idx, q_n


# ------------------------------------------------------------------ kernels
def _fwd_kernel(kidx_ref, kn_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                block: int, scale: float, causal: bool):
    iq = pl.program_id(2)
    # storage-dtype operands: bf16 runs the MXU at full rate, f32 operands
    # force multi-pass emulation (flash_attention._masked_scores, round-5)
    q = q_ref[...]
    q_pos = iq * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)

    def body(jj, carry):
        m, l, acc = carry
        jk = kidx_ref[iq, jj]
        k = k_ref[pl.ds(jk * block, block), :]
        v = v_ref[pl.ds(jk * block, block), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = jk * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            keep = q_pos >= kpos
            s = jnp.where(keep, s, BIG_NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p.astype(v.dtype), v,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((block, 1), BIG_NEG, jnp.float32)
    l0 = jnp.zeros((block, 1), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, kn_ref[iq], body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to((m[:, 0] + jnp.log(l_safe[:, 0]))[None, :],
                                    (SUBLANES, block))


def _dq_kernel(kidx_ref, kn_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, *, block: int, scale: float, causal: bool):
    iq = pl.program_id(2)
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[0]
    delta = delta_ref[0]
    q_pos = iq * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)

    def body(jj, dq):
        jk = kidx_ref[iq, jj]
        k = k_ref[pl.ds(jk * block, block), :]
        v = v_ref[pl.ds(jk * block, block), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = jk * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(q_pos >= kpos, s, BIG_NEG)
        p = jnp.exp(s - lse[:, None])
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, kn_ref[iq], body,
                           jnp.zeros(q.shape, jnp.float32))
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(qidx_ref, qn_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, *, block: int, scale: float,
                causal: bool):
    jk = pl.program_id(2)
    k = k_ref[...]
    v = v_ref[...]
    k_pos = jk * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)

    def body(ii, carry):
        dk, dv = carry
        iq = qidx_ref[jk, ii]
        q = q_ref[pl.ds(iq * block, block), :]
        do = do_ref[pl.ds(iq * block, block), :]
        lse = lse_ref[0, pl.ds(iq * block, block)]
        delta = delta_ref[0, pl.ds(iq * block, block)]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = iq * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            s = jnp.where(q_pos >= k_pos, s, BIG_NEG)
        p = jnp.exp(s - lse[:, None])
        dv = dv + jnp.dot(p.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros(k.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(0, qn_ref[jk], body, (z, z))
    # dk accumulated against UNSCALED q: chain-rule factor applied once
    dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------- plumbing
def _block_specs(S, hd, block):
    """The four BlockSpec shapes shared by all three kernels."""
    blk = pl.BlockSpec((None, None, block, hd),
                       lambda b, h, i, *_: (b, h, i, 0))
    full = pl.BlockSpec((None, None, S, hd), lambda b, h, i, *_: (b, h, 0, 0))
    row_blk = pl.BlockSpec((None, None, SUBLANES, block),
                           lambda b, h, i, *_: (b, h, 0, i))
    row_full = pl.BlockSpec((None, None, SUBLANES, S),
                            lambda b, h, i, *_: (b, h, 0, 0))
    return blk, full, row_blk, row_full


def _fwd_call(q, k, v, k_idx, k_n, *, block, causal, interpret):
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    blk, full, row_blk, row_full = _block_specs(S, hd, block)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(B, H, S // block),
        in_specs=[blk, full, full], out_specs=[blk, row_blk])
    return pl.pallas_call(
        partial(_fwd_kernel, block=block, scale=scale, causal=causal),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((B, H, SUBLANES, S), jnp.float32)],
        interpret=interpret,
    )(np.asarray(k_idx), np.asarray(k_n), q, k, v)


def _bwd_call(q, k, v, o, lse, do, lists, *, block, causal, interpret):
    from jax.experimental.pallas import tpu as pltpu

    k_idx, k_n, q_idx, q_n = lists
    B, H, S, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    delta = _delta_operand(do, o)
    blk, full, row_blk, row_full = _block_specs(S, hd, block)

    dq = pl.pallas_call(
        partial(_dq_kernel, block=block, scale=scale, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(B, H, S // block),
            in_specs=[blk, full, full, blk, row_blk, row_blk],
            out_specs=[blk]),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        interpret=interpret,
    )(np.asarray(k_idx), np.asarray(k_n), q, k, v, do, lse, delta)[0]

    dk, dv = pl.pallas_call(
        partial(_dkv_kernel, block=block, scale=scale, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(B, H, S // block),
            in_specs=[full, blk, blk, full, row_full, row_full],
            out_specs=[blk, blk]),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=interpret,
    )(np.asarray(q_idx), np.asarray(q_n), q, k, v, do, lse, delta)
    return dq, dk, dv


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _sparse(block, causal, interpret, lists, q, k, v):
    o, _ = _fwd_call(q, k, v, lists[0], lists[1], block=block, causal=causal,
                     interpret=interpret)
    return o


def _sparse_fwd(block, causal, interpret, lists, q, k, v):
    o, lse = _fwd_call(q, k, v, lists[0], lists[1], block=block,
                       causal=causal, interpret=interpret)
    return o, (q, k, v, o, lse)


def _sparse_bwd(block, causal, interpret, lists, res, g):
    q, k, v, o, lse = res
    return _bwd_call(q, k, v, o, lse, g, lists, block=block, causal=causal,
                     interpret=interpret)


_sparse.defvjp(_sparse_fwd, _sparse_bwd)


# ------------------------------------------------------------- public API
def sparse_attention(q, k, v, config: SparsityConfig, *, causal: bool = True,
                     interpret: Optional[bool] = None):
    """Block-sparse attention. q: (B, S, H, hd); k/v: (B, S, KV, hd).

    float16 inputs on TPU take a dense masked fallback (the layout expanded
    to an elementwise score bias) instead of the Pallas kernels — Mosaic
    has no f16. Warned once, mirroring flash_attention's gate."""
    B, S, H, hd = q.shape
    block = config.block
    if S % block != 0:
        raise ValueError(f"seq {S} not divisible by sparsity block {block}")
    if any(jnp.dtype(x.dtype) == jnp.float16 for x in (q, k, v)) \
            and jax.default_backend() == "tpu":
        from ..utils.logging import warning_once

        warning_once(
            "sparse_attention: float16 inputs fall back to dense masked "
            "attention on TPU (Mosaic has no f16) — the layout becomes "
            "an (S, S) additive bias and full scores materialize; "
            "prefer bf16 compute for long sequences.")
        from ..models.transformer import causal_attention

        layout = config.make_layout(S // block)
        allowed = np.kron(layout, np.ones((block, block), bool))
        bias = jnp.where(jnp.asarray(allowed), 0.0, BIG_NEG
                         ).astype(jnp.float32)
        return causal_attention(q, k, v, causal=causal, bias=bias)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    layout = config.make_layout(S // block)
    # hashable static lists for the custom_vjp nondiff argument
    lists = tuple(_HashableArray(a) for a in _layout_lists(layout, causal))
    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
    o = _sparse(block, causal, interpret, lists, qt, kt, vt)
    return o.swapaxes(1, 2)


class _HashableArray:
    """numpy array wrapper usable as a static (nondiff) jit argument."""

    __slots__ = ("arr", "_hash")

    def __init__(self, arr: np.ndarray):
        self.arr = np.ascontiguousarray(arr)
        self._hash = hash((self.arr.shape, self.arr.dtype.str,
                           self.arr.tobytes()))

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return (isinstance(other, _HashableArray)
                and np.array_equal(self.arr, other.arr))

    # numpy protocol: lets the wrapper pass straight into pallas_call
    def __array__(self, dtype=None):
        return self.arr if dtype is None else self.arr.astype(dtype)

    @property
    def shape(self):
        return self.arr.shape

    @property
    def dtype(self):
        return self.arr.dtype


def make_sparse_attention_fn(config: SparsityConfig,
                             interpret: Optional[bool] = None):
    """attention_fn factory for :class:`TransformerLM` (mask unsupported —
    combine padding with the layout instead)."""

    def attn(q, k, v, *, mask=None):
        if mask is not None:
            raise ValueError("sparse_attention does not take a padding mask; "
                             "fold padding into the sparsity layout")
        return sparse_attention(q, k, v, config, interpret=interpret)

    return attn
