"""Pallas decode attention: one query token against the KV cache.

TPU-native answer to the reference's ``softmax_context`` inference kernel
(``csrc/transformer/inference/csrc/softmax_context_cuda.cu`` via
``pt_binding.cpp``): fused attention of the current token over the cached
keys/values, masking cache slots past the live length.  The XLA fallback in
``inference/decode.py`` materializes the full (B, H, 1, max_len) score tensor
in HBM each step; this kernel streams the cache through VMEM with an online
softmax instead — the decode hot loop is bandwidth-bound, so not spilling
scores is the win.

Layout notes:
- grid (B, H); each program handles one (batch, head) pair.
- the cache keeps its storage layout (B, KV, max_len, hd) — heads-major so
  the per-head block is (None, None, max_len, hd), whose last two dims are
  (sublane, lane)-shaped as the TPU lowering requires (a seq-major cache
  would squeeze the second-to-last dim: rejected on hardware). The GQA head
  group mapping happens in the BlockSpec index_map (h // group), so there is
  no repeated-KV materialization at all (the training kernel pays a
  ``jnp.repeat``; decode can't afford it).
- the single query row is broadcast to the 8-sublane tile (q_sub trick) so
  the s = q @ k.T matmul is MXU/VPU shaped.
- the live length is a scalar-prefetch operand (SMEM), letting the kernel
  bound its streaming loop at ceil(length / block) instead of max_len.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BIG_NEG = -2.0 ** 30
SUBLANES = 8


def _decode_kernel(*refs, block: int, scale: float, alibi: bool):
    if alibi:
        len_ref, slopes_ref, q_ref, k_ref, v_ref, o_ref = refs
    else:
        len_ref, q_ref, k_ref, v_ref, o_ref = refs
        slopes_ref = None
    b = pl.program_id(0)
    h = pl.program_id(1)
    L = len_ref[b]
    q = q_ref[...].astype(jnp.float32) * scale          # (SUBLANES, hd)
    S = k_ref.shape[0]

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block, block), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block, block), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (SUB, blk)
        col = j * block + jax.lax.broadcasted_iota(
            jnp.int32, (SUBLANES, block), 1)
        if slopes_ref is not None:
            # ALiBi is a pure function of (slot, live length): slope·(s -
            # t) with the query at global position t = L-1 — no (H, S)
            # bias tensor ever exists (the dense fallback builds one per
            # step; Bloom's positional signal costs one SMEM scalar here)
            s = s + slopes_ref[h] * (col - (L - 1)).astype(jnp.float32)
        keep = col < L
        s = jnp.where(keep, s, BIG_NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    nb = (L + block - 1) // block                        # only live blocks
    m0 = jnp.full((SUBLANES, 1), BIG_NEG, jnp.float32)
    l0 = jnp.zeros((SUBLANES, 1), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, acc0))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def decode_attention(q, ck, cv, length, *, alibi_slopes=None,
                     block: int = 128, interpret: Optional[bool] = None):
    """q: (B, 1, H, hd) current-token queries; ck/cv: (B, KV, max_len, hd)
    cache; ``length`` scalar or (B,) live lengths (slots < length attended).
    ``alibi_slopes``: optional (H,) per-head slopes — the ALiBi distance
    bias is reconstructed in-kernel from the live length (Bloom decode
    stays on the streaming kernel instead of the dense fallback).

    Returns (B, 1, H, hd)."""
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, hd = q.shape
    assert T == 1, "decode kernel is single-token; use flash_attention for prefill"
    KV, S = ck.shape[1], ck.shape[2]
    blk = min(block, S)
    if S % blk != 0:
        raise ValueError(f"cache length {S} not divisible by block {blk}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    group = H // KV
    scale = 1.0 / math.sqrt(hd)
    lengths = jnp.broadcast_to(jnp.asarray(length, jnp.int32).reshape(-1), (B,))
    alibi = alibi_slopes is not None

    # (B, 1, H, hd) → (B, H, SUBLANES, hd): sublane-replicated single query
    qs = jnp.broadcast_to(q.swapaxes(1, 2), (B, H, SUBLANES, hd))

    n_prefetch = 2 if alibi else 1
    pre_args = ((lengths, jnp.asarray(alibi_slopes, jnp.float32))
                if alibi else (lengths,))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(B, H),
        in_specs=[
            pl.BlockSpec((None, None, SUBLANES, hd),
                         lambda b, h, *pre: (b, h, 0, 0)),
            pl.BlockSpec((None, None, S, hd),
                         lambda b, h, *pre: (b, h // group, 0, 0)),
            pl.BlockSpec((None, None, S, hd),
                         lambda b, h, *pre: (b, h // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, SUBLANES, hd),
                               lambda b, h, *pre: (b, h, 0, 0)),
    )
    out = pl.pallas_call(
        partial(_decode_kernel, block=blk, scale=scale, alibi=alibi),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, SUBLANES, hd), q.dtype),
        interpret=interpret,
    )(*pre_args, qs, ck, cv)
    return out[:, :, :1, :].swapaxes(1, 2)               # (B, 1, H, hd)
