"""JIT build system for the C++ host extensions.

Analog of the reference ``op_builder/builder.py`` (JIT-vs-AOT compile,
``DS_BUILD_*`` env flags, compatibility probing). On TPU only host-side
native code needs compiling (CPU optimizer, async I/O — SURVEY §2.3), so
the builder is small: hash the source, ``g++ -O3 -march=native -fopenmp
-shared -fPIC`` into a per-source cache dir, ``ctypes.CDLL`` the result.
``DSTPU_BUILD_NATIVE=0`` disables native builds (pure-Python fallbacks take
over, mirroring the reference's op-compatibility fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig
from pathlib import Path
from typing import Optional

from ..utils.logging import logger

CSRC = Path(__file__).resolve().parent.parent / "csrc"
_CACHE: dict[str, Optional[ctypes.CDLL]] = {}


def native_enabled() -> bool:
    return os.environ.get("DSTPU_BUILD_NATIVE", "1") != "0"


def _build_dir() -> Path:
    d = Path(os.environ.get("DSTPU_BUILD_DIR",
                            Path.home() / ".cache" / "deepspeed_tpu" / "build"))
    d.mkdir(parents=True, exist_ok=True)
    return d


def build_and_load(name: str, extra_flags: tuple[str, ...] = ()) -> Optional[ctypes.CDLL]:
    """Compile ``csrc/<name>.cpp`` (cached by content hash) and dlopen it.

    Returns None when native builds are disabled or the toolchain fails —
    callers must fall back to their Python implementation.
    """
    if name in _CACHE:
        return _CACHE[name]
    lib = None
    if native_enabled():
        src = CSRC / f"{name}.cpp"
        try:
            code = src.read_bytes()
            tag = hashlib.sha256(code + b"|" + b" ".join(
                f.encode() for f in extra_flags)).hexdigest()[:16]
            out = _build_dir() / f"{name}-{tag}.so"
            if not out.exists():
                cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared",
                       "-fPIC", "-std=c++17", str(src), "-o", str(out),
                       *extra_flags]
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                logger.info(f"built native op '{name}' -> {out.name}")
            lib = ctypes.CDLL(str(out))
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning(f"native build of '{name}' failed, using Python "
                           f"fallback: {detail[:500]}")
            lib = None
    _CACHE[name] = lib
    return lib


def op_report() -> dict[str, bool]:
    """Which native ops are buildable/loaded (the ``ds_report`` compat
    matrix, reference ``env_report.py``)."""
    return {name: build_and_load(name) is not None
            for name in ("cpu_optimizer", "aio")}
