"""Op registry.

Analog of the reference ``op_builder/`` system (20 builders, JIT/AOT compile,
``DS_BUILD_*`` flags): on TPU, device kernels are Pallas (pure Python, no
build step) and only host-side native code (async I/O, CPU optimizer) needs
compilation. The registry maps an op name to its best available
implementation for the current platform, with graceful fallback to the XLA
reference implementation.
"""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, dict[str, Callable]] = {}


def register_op(name: str, platform: str = "default"):
    """Decorator: register ``fn`` as the implementation of ``name`` on ``platform``."""

    def deco(fn):
        _REGISTRY.setdefault(name, {})[platform] = fn
        return fn

    return deco


def get_op_builder(name: str, platform: str = "tpu") -> Callable:
    _ensure_builtin_ops()
    impls = _REGISTRY.get(name)
    if not impls:
        raise KeyError(f"unknown op '{name}'; registered: {sorted(_REGISTRY)}")
    if platform in impls:
        return impls[platform]
    if "default" in impls:
        return impls["default"]
    raise KeyError(f"op '{name}' has no implementation for platform '{platform}'")


def available_ops() -> list[str]:
    _ensure_builtin_ops()
    return sorted(_REGISTRY)


_BUILTIN_REGISTERED = False


def _ensure_builtin_ops() -> None:
    """Register the framework's real ops (lazily — the heavy modules only
    import when an op is actually requested).

    Builders mirror the reference's ``create_op_builder(name)`` contract:
    each returns the op's callable entry point for the platform."""
    global _BUILTIN_REGISTERED
    if _BUILTIN_REGISTERED:
        return
    _BUILTIN_REGISTERED = True

    @register_op("flash_attention")
    def _flash():
        from .flash_attention import flash_attention
        return flash_attention

    @register_op("decode_attention")
    def _decode():
        from .decode_attention import decode_attention
        return decode_attention

    @register_op("sparse_attention")
    def _sparse():
        from .sparse_attention import sparse_attention
        return sparse_attention

    @register_op("quantizer")
    def _quant():
        from . import quant
        return quant

    @register_op("cpu_optimizer")
    def _cpu_opt():
        from . import cpu_optimizer
        return cpu_optimizer

    @register_op("async_io")
    def _aio():
        from .aio import AsyncIOHandle
        return AsyncIOHandle

    @register_op("spatial_inference")
    def _spatial():
        from . import spatial
        return spatial

    @register_op("evoformer_attn")
    def _evo():
        from .evoformer import evoformer_attention
        return evoformer_attention

    @register_op("tiled_linear")
    def _tiled():
        from .tiled import tiled_matmul
        return tiled_matmul

    @register_op("fused_xent")
    def _xent():
        from .xent import fused_token_nll
        return fused_token_nll
