"""Op registry.

Analog of the reference ``op_builder/`` system (20 builders, JIT/AOT compile,
``DS_BUILD_*`` flags): on TPU, device kernels are Pallas (pure Python, no
build step) and only host-side native code (async I/O, CPU optimizer) needs
compilation. The registry maps an op name to its best available
implementation for the current platform, with graceful fallback to the XLA
reference implementation.
"""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, dict[str, Callable]] = {}


def register_op(name: str, platform: str = "default"):
    """Decorator: register ``fn`` as the implementation of ``name`` on ``platform``."""

    def deco(fn):
        _REGISTRY.setdefault(name, {})[platform] = fn
        return fn

    return deco


def get_op_builder(name: str, platform: str = "tpu") -> Callable:
    impls = _REGISTRY.get(name)
    if not impls:
        raise KeyError(f"unknown op '{name}'; registered: {sorted(_REGISTRY)}")
    if platform in impls:
        return impls[platform]
    if "default" in impls:
        return impls["default"]
    raise KeyError(f"op '{name}' has no implementation for platform '{platform}'")


def available_ops() -> list[str]:
    return sorted(_REGISTRY)
