"""aio tier microbenchmark: sweep n_threads × block size × O_DIRECT.

Analog of the reference's ``csrc/aio/py_test/`` suite (``ds_aio_basic.py`` /
``aio_bench_perf_sweep.py``), which exists to tune the NVMe swap tier's
queue-depth/block-size before committing a ZeRO-Infinity config. Reports
MB/s per (threads, block, direct) cell for sequential write and read of a
test file, plus the winning cell — feed those numbers into
``zero_optimization.offload_optimizer.buffer_count`` / aio settings.

CLI: ``dstpu_aio_bench [--path DIR] [--size-mb N] [--threads 1,2,4,8]
[--blocks 256k,1m,4m] [--no-direct] [--json OUT]``
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

from .aio import AsyncIOHandle


def _parse_size(s: str) -> int:
    s = s.strip().lower()
    mult = 1
    if s.endswith("k"):
        mult, s = 1 << 10, s[:-1]
    elif s.endswith("m"):
        mult, s = 1 << 20, s[:-1]
    return int(s) * mult


def bench_cell(path: str, size: int, n_threads: int, block: int,
               direct: bool, chunks: int = 8) -> dict:
    """One (threads, block, direct) cell: write then read ``size`` bytes
    split into ``chunks`` parallel tickets; MB/s from wall time."""
    h = AsyncIOHandle(n_threads=n_threads, block_size=block, use_direct=direct)
    per = size // chunks
    bufs = [np.random.default_rng(i).integers(
        0, 255, per, dtype=np.uint8).view(np.uint8) for i in range(chunks)]
    files = [os.path.join(path, f"aio_bench_{i}.bin") for i in range(chunks)]
    try:
        t0 = time.perf_counter()
        tickets = [h.submit_write(f, b) for f, b in zip(files, bufs)]
        for t in tickets:
            h.wait(t)
        w_dt = time.perf_counter() - t0

        outs = [np.zeros(per, np.uint8) for _ in range(chunks)]
        t0 = time.perf_counter()
        tickets = [h.submit_read(f, o) for f, o in zip(files, outs)]
        for t in tickets:
            h.wait(t)
        r_dt = time.perf_counter() - t0
        ok = all(np.array_equal(o, b) for o, b in zip(outs, bufs))
    finally:
        h.close()
        for f in files:
            try:
                os.unlink(f)
            except OSError:
                pass
    mb = size / (1 << 20)
    return {"threads": n_threads, "block": block, "direct": direct,
            "write_mb_s": round(mb / w_dt, 1), "read_mb_s": round(mb / r_dt, 1),
            "verified": ok}


def run_sweep(path: str, size: int, threads, blocks, direct_opts) -> list[dict]:
    os.makedirs(path, exist_ok=True)
    cells = []
    for direct in direct_opts:
        for n in threads:
            for b in blocks:
                cell = bench_cell(path, size, n, b, direct)
                cells.append(cell)
                print(f"threads={n:<3} block={b >> 10:>5}K "
                      f"direct={int(direct)}  "
                      f"write={cell['write_mb_s']:>8.1f} MB/s  "
                      f"read={cell['read_mb_s']:>8.1f} MB/s"
                      f"{'' if cell['verified'] else '  VERIFY-FAILED'}",
                      flush=True)
    return cells


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="dstpu_aio_bench",
        description="aio tier sweep (reference csrc/aio/py_test analog)")
    p.add_argument("--path", default="/tmp/dstpu_aio_bench")
    p.add_argument("--size-mb", type=int, default=64)
    p.add_argument("--threads", default="1,2,4,8")
    p.add_argument("--blocks", default="256k,1m,4m")
    p.add_argument("--no-direct", action="store_true",
                   help="skip the O_DIRECT cells (fs may not support it)")
    p.add_argument("--json", default=None, help="write results JSON here")
    args = p.parse_args(argv)

    threads = [int(t) for t in args.threads.split(",")]
    blocks = [_parse_size(b) for b in args.blocks.split(",")]
    direct_opts = [False] if args.no_direct else [False, True]
    cells = run_sweep(args.path, args.size_mb << 20, threads, blocks,
                      direct_opts)
    best_r = max(cells, key=lambda c: c["read_mb_s"])
    best_w = max(cells, key=lambda c: c["write_mb_s"])
    print(f"best read : threads={best_r['threads']} "
          f"block={best_r['block'] >> 10}K direct={int(best_r['direct'])} "
          f"({best_r['read_mb_s']} MB/s)")
    print(f"best write: threads={best_w['threads']} "
          f"block={best_w['block'] >> 10}K direct={int(best_w['direct'])} "
          f"({best_w['write_mb_s']} MB/s)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"cells": cells, "best_read": best_r,
                       "best_write": best_w}, f, indent=2)


if __name__ == "__main__":
    main()
