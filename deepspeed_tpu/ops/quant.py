"""Symmetric int8 block quantization used by the quantized collectives.

TPU-native analog of the reference's fused quantizer kernels
(``csrc/quantization/pt_binding.cpp``, ``deepspeed/ops/quantizer``) as they
are used by ZeRO++ (``runtime/zero/config.py:256``: ``zero_quantized_weights``
/ ``zero_quantized_gradients``) and the compressed-collective path
(``runtime/comm/coalesced_collectives.py:31``). Pure XLA: the quant/dequant
elementwise chains fuse into the surrounding program; the payoff is that the
*collective* (all-gather / all-to-all) moves int8 bytes instead of bf16/fp32.

Scales are per-row (last dim) for weight gathers and per-chunk-block for
gradient reduction — matching the reference's groupwise symmetric scheme.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rowwise_quant_int8(x: jax.Array):
    """Symmetric per-row int8: scale over the last dim. Returns (q, scale)
    with ``scale`` shaped ``x.shape[:-1] + (1,)`` in fp32."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def rowwise_dequant(q: jax.Array, scale: jax.Array, dtype=jnp.bfloat16):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def quant_blocks(xb: jax.Array):
    """(..., block) fp32 → symmetric int8 + per-block fp32 scale (last dim
    is the scale group). The shared core of the weight-gather (qwZ) and
    gradient (qgZ/1-bit) quantizers."""
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def blockwise_quant_int8(x: jax.Array, block: int = 2048):
    """Symmetric int8 over a flat vector with one fp32 scale per ``block``
    elements (pads internally; callers pass already-padded sizes)."""
    n = x.shape[-1]
    pad = (-n) % block
    xf = jnp.pad(x.astype(jnp.float32), [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xb = xf.reshape(x.shape[:-1] + (-1, block))
    return quant_blocks(xb)


def blockwise_dequant(q: jax.Array, scale: jax.Array, n: int,
                      dtype=jnp.float32):
    xb = q.astype(jnp.float32) * scale
    flat = xb.reshape(xb.shape[:-2] + (-1,))
    return flat[..., :n].astype(dtype)
