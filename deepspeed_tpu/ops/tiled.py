"""Tiled matmul: bound the gathered-weight working set of giant linears.

Analog of the reference's ``TiledLinear`` (``runtime/zero/tiling.py:32``),
which splits a huge linear into sub-linears so ZeRO-3 only materializes one
tile's worth of gathered parameters at a time. The JAX shape of the same
idea: scan over column tiles of the weight; inside the scan each tile is the
unit XLA gathers/keeps live, so peak memory holds ~one tile of W instead of
all of it (plus remat-friendliness for the giant vocab head).

Wired into the model head via ``TransformerConfig.tiled_head`` (> 1 tiles
the unembedding matmul on the XLA logits path; the fused-xent loss path
never materializes logits and ignores it)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def tiled_matmul(x: jnp.ndarray, w: jnp.ndarray, n_tiles: int) -> jnp.ndarray:
    """x @ w computed as a scan over ``n_tiles`` column tiles of ``w``.

    x: (..., K); w: (K, N) with N divisible by n_tiles → (..., N)."""
    K, N = w.shape
    if N % n_tiles != 0:
        raise ValueError(f"output dim {N} not divisible by n_tiles={n_tiles}")
    if n_tiles == 1:
        return x @ w
    tiles = w.reshape(K, n_tiles, N // n_tiles).swapaxes(0, 1)  # (T, K, N/T)

    def body(_, wt):
        return None, x @ wt

    _, out = lax.scan(body, None, tiles)                # (T, ..., N/T)
    return jnp.moveaxis(out, 0, -2).reshape(x.shape[:-1] + (N,))
