"""Spatial (diffusers UNet/VAE) inference ops.

Analog of the reference's ``csrc/spatial/csrc/opt_bias_add.cu`` (298 LoC of
fused bias-add variants for Stable-Diffusion-class models). On TPU these are
pure XLA fusion fodder — the functions exist so the op inventory is explicit
and callers get the fused forms in one call; XLA emits a single fused kernel
for each.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def bias_add(x, bias):
    """NHWC bias add (reference ``opt_bias_add``)."""
    return x + bias.astype(x.dtype)


def bias_add_add(x, bias, other):
    """bias-add fused with a residual add (``opt_bias_add_add``)."""
    return x + bias.astype(x.dtype) + other.astype(x.dtype)


def bias_geglu(x, bias):
    """GEGLU with fused bias (diffusers feed-forward): split the last dim,
    gate with GELU (``transformer_geglu`` spirit)."""
    y = x + bias.astype(x.dtype)
    u, g = jnp.split(y, 2, axis=-1)
    # exact erf GELU: the reference kernel / diffusers use the non-approx form
    return u * jax.nn.gelu(g, approximate=False)


def group_norm(x, scale, bias, num_groups: int = 32, eps: float = 1e-5):
    """NHWC GroupNorm (UNet's normalization; fp32 statistics)."""
    N, H, W, C = x.shape
    xg = x.astype(jnp.float32).reshape(N, H, W, num_groups, C // num_groups)
    mu = jnp.mean(xg, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xg, axis=(1, 2, 4), keepdims=True)
    y = ((xg - mu) * jax.lax.rsqrt(var + eps)).reshape(N, H, W, C)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)
