"""Host optimizer over offloaded fp32 master state (ZeRO-Offload core).

Reference: ``DeepSpeedCPUAdam`` (``ops/adam/cpu_adam.py:13``) over the AVX
C++ kernels. The wrapper owns contiguous fp32 numpy state and applies the
native step in place; a pure-numpy fallback keeps the path alive when the
toolchain is unavailable. The optional bf16 copy-back writes the compute
copy in the same pass (the reference's "simultaneous fp16 param copy").
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from .builder import build_and_load

_F32P = ctypes.POINTER(ctypes.c_float)
_U16P = ctypes.POINTER(ctypes.c_uint16)


def _lib():
    lib = build_and_load("cpu_optimizer")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        lib.ds_adam_step.argtypes = [_F32P, _F32P, _F32P, _F32P,
                                     ctypes.c_int64, ctypes.c_int64,
                                     ctypes.c_float, ctypes.c_float,
                                     ctypes.c_float, ctypes.c_float,
                                     ctypes.c_float, ctypes.c_int,
                                     ctypes.c_int, _U16P]
        lib.ds_lion_step.argtypes = [_F32P, _F32P, _F32P, ctypes.c_int64,
                                     ctypes.c_float, ctypes.c_float,
                                     ctypes.c_float, ctypes.c_float, _U16P]
        lib.ds_adagrad_step.argtypes = [_F32P, _F32P, _F32P, ctypes.c_int64,
                                        ctypes.c_float, ctypes.c_float,
                                        ctypes.c_float, _U16P]
        lib._sigs_set = True
    return lib


def _ptr(a: Optional[np.ndarray], typ):
    return a.ctypes.data_as(typ) if a is not None else typ()


def _check(name, *arrays):
    n = arrays[0].size
    for a in arrays:
        if a is None:
            continue
        assert a.flags["C_CONTIGUOUS"], f"{name}: arrays must be contiguous"
        assert a.size == n, f"{name}: size mismatch"
    return n


def adam_step(p: np.ndarray, m: np.ndarray, v: np.ndarray, g: np.ndarray,
              step: int, lr: float, betas=(0.9, 0.999), eps: float = 1e-8,
              weight_decay: float = 0.0, adamw: bool = True,
              bias_correction: bool = True,
              p_bf16: Optional[np.ndarray] = None) -> None:
    """In-place Adam(W) on flat fp32 arrays (semantics of
    ``runtime/optimizers.py adam()``)."""
    n = _check("adam", p, m, v, g, p_bf16)
    lib = _lib()
    if lib is not None:
        lib.ds_adam_step(_ptr(p, _F32P), _ptr(m, _F32P), _ptr(v, _F32P),
                         _ptr(g, _F32P), n, step, lr, betas[0], betas[1],
                         eps, weight_decay, int(adamw), int(bias_correction),
                         _ptr(p_bf16, _U16P))
        return
    # numpy fallback
    b1, b2 = betas
    bc1 = 1.0 - b1 ** step if bias_correction else 1.0
    bc2 = 1.0 - b2 ** step if bias_correction else 1.0
    grad = g if (adamw or not weight_decay) else g + weight_decay * p
    m *= b1
    m += (1 - b1) * grad
    v *= b2
    v += (1 - b2) * np.square(grad)
    upd = (m / bc1) / (np.sqrt(v / bc2) + eps)
    if adamw and weight_decay:
        upd += weight_decay * p
    p -= lr * upd
    if p_bf16 is not None:
        _f32_to_bf16_np(p, p_bf16)


def lion_step(p, m, g, lr, betas=(0.9, 0.99), weight_decay: float = 0.0,
              p_bf16=None) -> None:
    n = _check("lion", p, m, g, p_bf16)
    lib = _lib()
    if lib is not None:
        lib.ds_lion_step(_ptr(p, _F32P), _ptr(m, _F32P), _ptr(g, _F32P), n,
                         lr, betas[0], betas[1], weight_decay,
                         _ptr(p_bf16, _U16P))
        return
    b1, b2 = betas
    upd = np.sign(b1 * m + (1 - b1) * g)
    if weight_decay:
        upd = upd + weight_decay * p
    m *= b2
    m += (1 - b2) * g
    p -= lr * upd
    if p_bf16 is not None:
        _f32_to_bf16_np(p, p_bf16)


def adagrad_step(p, acc, g, lr, eps: float = 1e-10,
                 weight_decay: float = 0.0, p_bf16=None) -> None:
    n = _check("adagrad", p, acc, g, p_bf16)
    lib = _lib()
    if lib is not None:
        lib.ds_adagrad_step(_ptr(p, _F32P), _ptr(acc, _F32P), _ptr(g, _F32P),
                            n, lr, eps, weight_decay, _ptr(p_bf16, _U16P))
        return
    grad = g + weight_decay * p if weight_decay else g
    acc += np.square(grad)
    p -= lr * grad / (np.sqrt(acc) + eps)
    if p_bf16 is not None:
        _f32_to_bf16_np(p, p_bf16)


def _f32_to_bf16_np(src: np.ndarray, dst: np.ndarray) -> None:
    x = src.view(np.uint32)
    rounding = np.uint32(0x7FFF) + ((x >> np.uint32(16)) & np.uint32(1))
    np.copyto(dst, ((x + rounding) >> np.uint32(16)).astype(np.uint16))


def native_available() -> bool:
    return _lib() is not None
