"""Pallas flash attention (fused causal attention, fwd + bwd kernels).

TPU-native answer to the reference's fused transformer kernels
(``csrc/transformer/*.cu`` and the inference softmax/attention kernels,
~13 kLoC of CUDA — SURVEY §2.3 #8/#9): on TPU the elementwise zoo evaporates
into XLA fusion and the one kernel worth hand-writing is blockwise attention.

Design (standard flash attention 2, MXU-shaped):
- forward: grid (B, H, S/blk); per q-block online-softmax stream over k/v
  blocks (``fori_loop`` with a traced causal upper bound), accumulators in
  fp32 carries, saves per-row logsumexp for the backward.
- backward: two kernels — dq (grid over q blocks, streams k/v) and dk/dv
  (grid over k blocks, streams q/dO), both recomputing probabilities from
  the saved logsumexp; ``delta = rowsum(dO * O)`` precomputed outside.
- GQA: kv heads are repeated to H with ``jnp.repeat`` *outside* the
  custom_vjp, so the head-group sum in dk/dv falls out of autodiff.
- dtype: matmuls run on the MXU with fp32 accumulation
  (``preferred_element_type``); softmax math in fp32.

On non-TPU backends the kernels run in Pallas interpret mode (tests), and
inputs that the kernel doesn't cover (padding masks, non-divisible shapes)
fall back to the plain XLA attention.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BIG_NEG = -2.0 ** 30
SUBLANES = 8  # fp32 sublane tile: lse/delta rows replicated to (8, S)


# ---------------------------------------------------------------- forward
def _fwd_kernel(*refs, block: int, scale: float, causal: bool, masked: bool,
                biased: bool, alibi: bool = False):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    mask_ref = bias_ref = slopes_ref = None
    if masked:
        mask_ref = refs[i]; i += 1
    if biased:
        bias_ref = refs[i]; i += 1
    if alibi:
        slopes_ref = refs[i]; i += 1
    o_ref, lse_ref = refs[i:]
    iq = pl.program_id(2)
    h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
    q = q_ref[...].astype(jnp.float32) * scale          # (blk, hd)
    nkb = k_ref.shape[0] // block
    q_pos = iq * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)

    def body(jk, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(jk * block, block), :].astype(jnp.float32)
        v = v_ref[pl.ds(jk * block, block), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if bias_ref is not None:
            # additive score bias tile (blk, blk), streamed from the
            # (blk, S) row slice this q-block owns — never a full (S, S)
            # materialization (the whole point vs the dense path)
            s = s + bias_ref[:, pl.ds(jk * block, block)].astype(jnp.float32)
        if slopes_ref is not None:
            s = s + h_slope * _alibi_rel(iq, jk, block)
        keep = None
        if causal:
            kpos = jk * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            keep = q_pos >= kpos
        if mask_ref is not None:
            # key-padding mask row for this k block: (blk,) of {0., 1.}
            mk = mask_ref[0, pl.ds(jk * block, block)] > 0.5
            keep = mk[None, :] if keep is None else (keep & mk[None, :])
        if keep is not None:
            s = jnp.where(keep, s, BIG_NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p.astype(v.dtype), v,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((block, 1), BIG_NEG, jnp.float32)
    l0 = jnp.zeros((block, 1), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    ub = iq + 1 if causal else nkb
    m, l, acc = jax.lax.fori_loop(0, ub, body, (m0, l0, acc0))
    # l == 0 only for rows whose keys are ALL masked (e.g. left-padded
    # queries); clamp so o is 0, not NaN (their loss contribution is masked)
    l_safe = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    # (8, blk): replicated across sublanes to satisfy TPU (8, 128) tiling
    lse_ref[...] = jnp.broadcast_to((m[:, 0] + jnp.log(l_safe[:, 0]))[None, :],
                                    (SUBLANES, block))


def _mask_operand(mask, S):
    """(B, S) {0,1} key mask → (B, SUBLANES, S) fp32 kernel operand."""
    m = mask.astype(jnp.float32).reshape(mask.shape[0], 1, S)
    return jnp.broadcast_to(m, (mask.shape[0], SUBLANES, S))


def _alibi_rel(iq, jk, block):
    """(blk, blk) signed key−query distance for q block iq vs k block jk —
    the ALiBi ramp built IN-kernel, so long sequences never materialize an
    (H, S, S) bias operand (at 64k seq that operand alone would be 100+
    GB; the decode kernel does the same from the live length)."""
    q_pos = iq * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    k_pos = jk * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    return (k_pos - q_pos).astype(jnp.float32)


def _slopes_operand(slopes):
    """(H,) → (1, H) fp32 operand; each grid program receives ITS head's
    slope as a (1, 1) block via a static index map — no dynamic lane
    extract for Mosaic to lower."""
    return jnp.asarray(slopes, jnp.float32).reshape(1, -1)


def _slopes_spec(H):
    return pl.BlockSpec((1, 1), lambda b, h, i: (0, h))


def _bias_row_spec(bias_shape, B, H, block):
    """(blk, S) row-slice BlockSpec for a (BB, HH, S, S) bias with BB in
    {1, B} and HH in {1, H} (broadcast handled by the index map, NOT by
    materializing the broadcast in HBM)."""
    bb, hh = bias_shape[0], bias_shape[1]
    return pl.BlockSpec(
        (None, None, block, bias_shape[3]),
        lambda b, h, i: (b if bb > 1 else 0, h if hh > 1 else 0, i, 0))


def _bias_col_spec(bias_shape, B, H, block):
    """(S, blk) column-slice BlockSpec (dk/dv kernel: grid over k blocks)."""
    bb, hh = bias_shape[0], bias_shape[1]
    return pl.BlockSpec(
        (None, None, bias_shape[2], block),
        lambda b, h, j: (b if bb > 1 else 0, h if hh > 1 else 0, 0, j))


def _fwd_call(q, k, v, mask, bias, *, block: int, causal: bool,
              interpret: bool, alibi=None):
    B, H, S, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    grid = (B, H, S // block)
    masked, biased = mask is not None, bias is not None
    kernel = partial(_fwd_kernel, block=block, scale=scale, causal=causal,
                     masked=masked, biased=biased, alibi=alibi is not None)
    in_specs = [
        pl.BlockSpec((None, None, block, hd), lambda b, h, i: (b, h, i, 0)),
        pl.BlockSpec((None, None, S, hd), lambda b, h, i: (b, h, 0, 0)),
        pl.BlockSpec((None, None, S, hd), lambda b, h, i: (b, h, 0, 0)),
    ]
    args = [q, k, v]
    if masked:
        in_specs.append(pl.BlockSpec((None, SUBLANES, S),
                                     lambda b, h, i: (b, 0, 0)))
        args.append(mask)
    if biased:
        in_specs.append(_bias_row_spec(bias.shape, B, H, block))
        args.append(bias)
    if alibi is not None:
        in_specs.append(_slopes_spec(H))
        args.append(alibi)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, None, block, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, SUBLANES, block),
                         lambda b, h, i: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, SUBLANES, S), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------- backward
def _make_bwd_dq_kernel(block: int, scale: float, causal: bool, masked: bool,
                        biased: bool = False, grad_bias: bool = False,
                        alibi: bool = False):

    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        i = 6
        mask_ref = bias_ref = dbias_ref = slopes_ref = None
        if masked:
            mask_ref = refs[i]; i += 1
        if biased:
            bias_ref = refs[i]; i += 1
        if alibi:
            slopes_ref = refs[i]; i += 1
        h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
        dq_ref = refs[i]; i += 1
        if grad_bias:
            dbias_ref = refs[i]
            # causal bias rows never visit jk > iq: zero-fill so the
            # untouched upper triangle doesn't carry garbage
            dbias_ref[...] = jnp.zeros(dbias_ref.shape, dbias_ref.dtype)
        iq = pl.program_id(2)
        q = q_ref[...].astype(jnp.float32) * scale
        do = do_ref[...].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        nkb = k_ref.shape[0] // block
        q_pos = iq * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 0)

        def body(jk, dq):
            k = k_ref[pl.ds(jk * block, block), :].astype(jnp.float32)
            v = v_ref[pl.ds(jk * block, block), :].astype(jnp.float32)
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
            if bias_ref is not None:
                s = s + bias_ref[:, pl.ds(jk * block, block)].astype(
                    jnp.float32)
            if slopes_ref is not None:
                s = s + h_slope * _alibi_rel(iq, jk, block)
            keep = None
            if causal:
                kpos = jk * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 1)
                keep = q_pos >= kpos
            if mask_ref is not None:
                mk = mask_ref[0, pl.ds(jk * block, block)] > 0.5
                keep = mk[None, :] if keep is None else (keep & mk[None, :])
            # mask BEFORE exp: for all-masked rows lse ~ BIG_NEG and a raw
            # exp(s - lse) would overflow to inf
            if keep is not None:
                s = jnp.where(keep, s, BIG_NEG)
            p = jnp.exp(s - lse[:, None])
            if keep is not None:
                p = jnp.where(keep, p, 0.0)
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None])
            if dbias_ref is not None:
                # d(bias) == d(scores): each (iq, jk) tile is owned by
                # exactly one grid step, so this is a plain write
                dbias_ref[:, pl.ds(jk * block, block)] = ds.astype(
                    dbias_ref.dtype)
            return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

        ub = iq + 1 if causal else nkb
        dq = jax.lax.fori_loop(0, ub, body, jnp.zeros(q.shape, jnp.float32))
        dq_ref[...] = (dq * scale).astype(dq_ref.dtype)

    return kernel


def _make_bwd_dkv_kernel(block: int, scale: float, causal: bool, masked: bool,
                         biased: bool = False, alibi: bool = False):
    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        i = 6
        mask_ref = bias_ref = slopes_ref = None
        if masked:
            mask_ref = refs[i]; i += 1
        if biased:
            bias_ref = refs[i]; i += 1
        if alibi:
            slopes_ref = refs[i]; i += 1
        dk_ref, dv_ref = refs[i:]
        h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
        jk = pl.program_id(2)
        k = k_ref[...].astype(jnp.float32)               # (blk, hd)
        v = v_ref[...].astype(jnp.float32)
        nqb = q_ref.shape[0] // block
        k_pos = jk * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1)
        mk = None
        if mask_ref is not None:
            mk = mask_ref[0, pl.ds(jk * block, block)] > 0.5  # this k block

        def body(iq, carry):
            dk, dv = carry
            q = q_ref[pl.ds(iq * block, block), :].astype(jnp.float32) * scale
            do = do_ref[pl.ds(iq * block, block), :].astype(jnp.float32)
            lse = lse_ref[0, pl.ds(iq * block, block)]
            delta = delta_ref[0, pl.ds(iq * block, block)]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
            if bias_ref is not None:
                # (S, blk) column slice of the bias: rows iq-block
                s = s + bias_ref[pl.ds(iq * block, block), :].astype(
                    jnp.float32)
            if slopes_ref is not None:
                s = s + h_slope * _alibi_rel(iq, jk, block)
            keep = None
            if causal:
                q_pos = iq * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 0)
                keep = q_pos >= k_pos
            if mk is not None:
                keep = mk[None, :] if keep is None else (keep & mk[None, :])
            if keep is not None:
                s = jnp.where(keep, s, BIG_NEG)
            p = jnp.exp(s - lse[:, None])
            if keep is not None:
                p = jnp.where(keep, p, 0.0)
            dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None])
            dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
            return dk, dv

        lb = jk if causal else 0
        z = jnp.zeros(k.shape, jnp.float32)
        dk, dv = jax.lax.fori_loop(lb, nqb, body, (z, z))
        dk_ref[...] = dk.astype(dk_ref.dtype)
        dv_ref[...] = dv.astype(dv_ref.dtype)

    return kernel


def _bwd_call(q, k, v, o, lse, do, mask, bias, *, block: int, causal: bool,
              interpret: bool, grad_bias: bool = False, alibi=None):
    B, H, S, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None, :], (B, H, SUBLANES, S))
    grid = (B, H, S // block)
    masked, biased = mask is not None, bias is not None
    # dbias tiles are plain writes (one owner per grid step): only valid
    # when the bias carries its own full (B, H) leading dims — broadcast
    # biases would need cross-iteration accumulation
    assert not grad_bias or (biased and bias.shape[:2] == (B, H))
    blk_spec = pl.BlockSpec((None, None, block, hd), lambda b, h, i: (b, h, i, 0))
    full_spec = pl.BlockSpec((None, None, S, hd), lambda b, h, i: (b, h, 0, 0))
    row_blk = pl.BlockSpec((None, None, SUBLANES, block),
                           lambda b, h, i: (b, h, 0, i))
    row_full = pl.BlockSpec((None, None, SUBLANES, S),
                            lambda b, h, i: (b, h, 0, 0))
    mask_spec = pl.BlockSpec((None, SUBLANES, S), lambda b, h, i: (b, 0, 0))
    extra_args = ([mask] if masked else []) + ([bias] if biased else []) \
        + ([alibi] if alibi is not None else [])
    extra_row = ([mask_spec] if masked else []) \
        + ([_bias_row_spec(bias.shape, B, H, block)] if biased else []) \
        + ([_slopes_spec(H)] if alibi is not None else [])
    extra_col = ([mask_spec] if masked else []) \
        + ([_bias_col_spec(bias.shape, B, H, block)] if biased else []) \
        + ([_slopes_spec(H)] if alibi is not None else [])
    has_alibi = alibi is not None

    dq_outs = pl.pallas_call(
        _make_bwd_dq_kernel(block, scale, causal, masked, biased, grad_bias,
                            has_alibi),
        grid=grid,
        in_specs=[blk_spec, full_spec, full_spec, blk_spec, row_blk, row_blk]
                 + extra_row,
        out_specs=[blk_spec] + ([_bias_row_spec(bias.shape, B, H, block)]
                                if grad_bias else []),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)]
                  + ([jax.ShapeDtypeStruct(bias.shape, bias.dtype)]
                     if grad_bias else []),
        interpret=interpret,
    )(q, k, v, do, lse, delta, *extra_args)
    dq = dq_outs[0]
    dbias = dq_outs[1] if grad_bias else None

    dk, dv = pl.pallas_call(
        _make_bwd_dkv_kernel(block, scale, causal, masked, biased, has_alibi),
        grid=grid,
        in_specs=[full_spec, blk_spec, blk_spec, full_spec, row_full, row_full]
                 + extra_col,
        out_specs=[blk_spec, blk_spec],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *extra_args)
    return dq, dk, dv, dbias


# ------------------------------------------------------------- custom VJP
@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash(block, causal, interpret, q, k, v):
    o, _ = _fwd_call(q, k, v, None, None, block=block, causal=causal,
                     interpret=interpret)
    return o


def _flash_fwd(block, causal, interpret, q, k, v):
    o, lse = _fwd_call(q, k, v, None, None, block=block, causal=causal,
                       interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(block, causal, interpret, res, g):
    q, k, v, o, lse = res
    dq, dk, dv, _ = _bwd_call(q, k, v, o, lse, g, None, None, block=block,
                              causal=causal, interpret=interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_masked(block, causal, interpret, q, k, v, mask):
    o, _ = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                     interpret=interpret)
    return o


def _flash_masked_fwd(block, causal, interpret, q, k, v, mask):
    o, lse = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                       interpret=interpret)
    return o, (q, k, v, o, lse, mask)


def _flash_masked_bwd(block, causal, interpret, res, g):
    q, k, v, o, lse, mask = res
    dq, dk, dv, _ = _bwd_call(q, k, v, o, lse, g, mask, None, block=block,
                              causal=causal, interpret=interpret)
    return dq, dk, dv, jnp.zeros_like(mask)   # mask is {0,1} data, no grad


_flash_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _flash_biased(block, causal, interpret, grad_bias, q, k, v, bias, mask):
    o, _ = _fwd_call(q, k, v, mask, bias, block=block, causal=causal,
                     interpret=interpret)
    return o


def _flash_biased_fwd(block, causal, interpret, grad_bias, q, k, v, bias,
                      mask):
    o, lse = _fwd_call(q, k, v, mask, bias, block=block, causal=causal,
                       interpret=interpret)
    return o, (q, k, v, o, lse, bias, mask)


def _flash_biased_bwd(block, causal, interpret, grad_bias, res, g):
    q, k, v, o, lse, bias, mask = res
    dq, dk, dv, dbias = _bwd_call(q, k, v, o, lse, g, mask, bias,
                                  block=block, causal=causal,
                                  interpret=interpret, grad_bias=grad_bias)
    if dbias is None:
        # Broadcast-shaped biases (ALiBi slopes x positions, padding
        # biases) are positional constants: a zero cotangent is correct
        # and DCE'd under jit. Learned biases must come in full-shape
        # (B, H, S, S) to get a real dbias (enforced in flash_attention).
        dbias = jnp.zeros_like(bias)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dbias, dmask


_flash_biased.defvjp(_flash_biased_fwd, _flash_biased_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_alibi(block, causal, interpret, q, k, v, slopes, mask):
    o, _ = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                     interpret=interpret, alibi=slopes)
    return o


def _flash_alibi_fwd(block, causal, interpret, q, k, v, slopes, mask):
    o, lse = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                       interpret=interpret, alibi=slopes)
    return o, (q, k, v, o, lse, slopes, mask)


def _flash_alibi_bwd(block, causal, interpret, res, g):
    q, k, v, o, lse, slopes, mask = res
    dq, dk, dv, _ = _bwd_call(q, k, v, o, lse, g, mask, None, block=block,
                              causal=causal, interpret=interpret,
                              alibi=slopes)
    dmask = None if mask is None else jnp.zeros_like(mask)
    # slopes are deterministic positional constants: zero cotangent
    return dq, dk, dv, jnp.zeros_like(slopes), dmask


_flash_alibi.defvjp(_flash_alibi_fwd, _flash_alibi_bwd)


# ------------------------------------------------------------- public API
def flash_attention(q, k, v, *, mask: Optional[jnp.ndarray] = None,
                    bias: Optional[jnp.ndarray] = None,
                    bias_is_constant: bool = False,
                    alibi_slopes: Optional[jnp.ndarray] = None,
                    causal: bool = True, block: int = 128,
                    interpret: Optional[bool] = None):
    """Fused causal attention. q: (B, S, H, hd); k/v: (B, S, KV, hd).

    ``mask`` is a (B, S) key-padding mask ({0,1}); it is applied INSIDE the
    kernel (fwd and both bwd kernels), so padded/packed batches stay on the
    fused path — the reference-parity requirement the round-1 fallback
    violated.

    ``bias`` is an additive score bias, shape (S, S), (H, S, S),
    (B|1, H|1, S, S) — streamed into the fwd and both bwd kernels in
    (block, S) slices, never materializing (B, H, S, S) *scores* in HBM.
    Gradient handling by shape:

    - full (B, H, S, S): differentiable in-kernel (dbias = ds tiles — the
      evoformer pair-bias case, reference
      csrc/deepspeed4science/evoformer_attn/);
    - broadcast shapes with ``bias_is_constant=True``: index-map broadcast,
      explicit ``stop_gradient`` — zero HBM cost, for positional constants
      (ALiBi, additive masks);
    - broadcast shapes otherwise: broadcast OUTSIDE the kernel so the
      ``broadcast_to`` transpose sums a CORRECT cotangent for learned
      shared biases (costs a (B, H, S, S) bias materialization — still
      cheaper than the dense path, which adds scores+probs on top; pass
      ``bias_is_constant=True`` to opt out when the bias isn't trained).

    ``alibi_slopes``: (H,) per-head slopes — the ALiBi distance ramp is
    built IN-kernel from block indices (an (H, S, S) bias operand at 64k
    seq would be 100+ GB; slopes cost H floats). Mutually exclusive with
    ``bias``.

    The only remaining fallback is S not divisible by the block tile.
    """
    B, S, H, hd = q.shape
    assert bias is None or alibi_slopes is None, \
        "pass either bias or alibi_slopes, not both"
    blk = min(block, S)
    if S % blk != 0:
        from ..models.transformer import alibi_bias, causal_attention

        if alibi_slopes is not None:
            bias = alibi_bias(alibi_slopes, S)
        return causal_attention(q, k, v, mask=mask, causal=causal, bias=bias)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    KV = k.shape[2]
    if KV != H:  # GQA: differentiable repeat — dk/dv group-sum via autodiff
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    # (B, S, H, hd) -> (B, H, S, hd)
    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
    if alibi_slopes is not None:
        o = _flash_alibi(blk, causal, interpret, qt, kt, vt,
                         _slopes_operand(alibi_slopes),
                         _mask_operand(mask, S) if mask is not None else None)
    elif bias is not None:
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
        if bias.shape[:2] != (B, H):
            if bias_is_constant:
                bias = jax.lax.stop_gradient(bias)
            else:
                # learned shared bias: materialize the broadcast so its
                # transpose sums the true dbias (silent zero grads were
                # the round-4 review's finding #1)
                bias = jnp.broadcast_to(bias, (B, H) + bias.shape[2:])
        grad_bias = bias.shape[:2] == (B, H)
        o = _flash_biased(blk, causal, interpret, grad_bias, qt, kt, vt,
                          bias, _mask_operand(mask, S) if mask is not None
                          else None)
    elif mask is not None:
        o = _flash_masked(blk, causal, interpret, qt, kt, vt,
                          _mask_operand(mask, S))
    else:
        o = _flash(blk, causal, interpret, qt, kt, vt)
    return o.swapaxes(1, 2)


def make_flash_attention(block: int = 128, interpret: Optional[bool] = None,
                         bias_is_constant: bool = True):
    """attention_fn factory for :class:`TransformerLM`.

    ``bias_is_constant=True`` (the model-path default) stop-gradients a
    broadcast-shaped bias — correct for ALiBi ramps, WRONG for a learned
    bias. Callers training through the bias (e.g. evoformer pair bias)
    must pass ``bias_is_constant=False`` to get true dbias tiles."""

    def attn(q, k, v, *, mask=None, bias=None, alibi_slopes=None):
        # model-path biases are ALiBi distance ramps: positional
        # constants, streamed via index-map broadcast at zero HBM cost
        # (slopes preferred: the ramp is built in-kernel)
        return flash_attention(q, k, v, mask=mask, bias=bias,
                               alibi_slopes=alibi_slopes,
                               bias_is_constant=bias_is_constant, block=block,
                               interpret=interpret)

    # capability flags: constant-bias only under the default factory args —
    # learned-bias callers must rebuild with bias_is_constant=False
    attn.accepts_bias = True
    attn.bias_is_constant = bias_is_constant
    attn.accepts_alibi_slopes = True  # in-kernel ramp: no (H,S,S) operand
    return attn
