"""Pallas flash attention (fused causal attention, fwd + bwd kernels).

TPU-native answer to the reference's fused transformer kernels
(``csrc/transformer/*.cu`` and the inference softmax/attention kernels,
~13 kLoC of CUDA — SURVEY §2.3 #8/#9): on TPU the elementwise zoo evaporates
into XLA fusion and the one kernel worth hand-writing is blockwise attention.

Design (standard flash attention 2, MXU-shaped):
- forward: grid (B, H, S/blk); per q-block online-softmax stream over k/v
  blocks (``fori_loop`` with a traced causal upper bound), accumulators in
  fp32 carries, saves per-row logsumexp for the backward.
- backward: two kernels — dq (grid over q blocks, streams k/v) and dk/dv
  (grid over k blocks, streams q/dO), both recomputing probabilities from
  the saved logsumexp; ``delta = rowsum(dO * O)`` precomputed outside.
- GQA: kv heads are repeated to H with ``jnp.repeat`` *outside* the
  custom_vjp, so the head-group sum in dk/dv falls out of autodiff.
- dtype: matmul OPERANDS stay in their storage dtype (bf16 runs the MXU
  at full rate; pre-casting to f32 forces multi-pass emulation — round-5
  profile finding) with fp32 accumulation (``preferred_element_type``);
  softmax math in fp32; the 1/√hd scale applies to the f32 product.

On non-TPU backends the kernels run in Pallas interpret mode (tests), and
inputs that the kernel doesn't cover (padding masks, non-divisible shapes)
fall back to the plain XLA attention.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BIG_NEG = -2.0 ** 30
SUBLANES = 8  # fp32 sublane tile: lse/delta rows replicated to (8, S)
# fallback notices warn once per process via utils.logging.warning_once


# ---------------------------------------------------------------- forward
def _fwd_kernel(*refs, block: int, scale: float, causal: bool, masked: bool,
                biased: bool, alibi: bool = False):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    mask_ref = bias_ref = slopes_ref = None
    if masked:
        mask_ref = refs[i]; i += 1
    if biased:
        bias_ref = refs[i]; i += 1
    if alibi:
        slopes_ref = refs[i]; i += 1
    o_ref, lse_ref = refs[i:]
    iq = pl.program_id(2)
    h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
    q = q_ref[...]                                      # (blk, hd) bf16
    nkb = k_ref.shape[0] // block

    def body(jk, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(jk * block, block), :]
        v = v_ref[pl.ds(jk * block, block), :]
        # additive score bias tile (blk, blk), streamed from the (blk, S)
        # row slice this q-block owns — never a full (S, S)
        # materialization; key-padding mask row for this k block
        bias_tile = (bias_ref[:, pl.ds(jk * block, block)]
                     if bias_ref is not None else None)
        mk = (mask_ref[0, pl.ds(jk * block, block)] > 0.5
              if mask_ref is not None else None)
        s, keep = _masked_scores(q, k, iq, jk, block, causal, mk, h_slope,
                                 scale=scale, bias_tile=bias_tile)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p.astype(v.dtype), v,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((block, 1), BIG_NEG, jnp.float32)
    l0 = jnp.zeros((block, 1), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    ub = iq + 1 if causal else nkb
    m, l, acc = jax.lax.fori_loop(0, ub, body, (m0, l0, acc0))
    # l == 0 only for rows whose keys are ALL masked (e.g. left-padded
    # queries); clamp so o is 0, not NaN (their loss contribution is masked)
    l_safe = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    # (8, blk): replicated across sublanes to satisfy TPU (8, 128) tiling
    lse_ref[...] = jnp.broadcast_to((m[:, 0] + jnp.log(l_safe[:, 0]))[None, :],
                                    (SUBLANES, block))


def _mask_operand(mask, S):
    """(B, S) {0,1} key mask → (B, SUBLANES, S) fp32 kernel operand."""
    m = mask.astype(jnp.float32).reshape(mask.shape[0], 1, S)
    return jnp.broadcast_to(m, (mask.shape[0], SUBLANES, S))


def _alibi_rel(iq, jk, block):
    """(blk, blk) signed key−query distance for q block iq vs k block jk —
    the ALiBi ramp built IN-kernel, so long sequences never materialize an
    (H, S, S) bias operand (at 64k seq that operand alone would be 100+
    GB; the decode kernel does the same from the live length)."""
    q_pos = iq * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    k_pos = jk * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    return (k_pos - q_pos).astype(jnp.float32)


def _masked_scores(q, k, iq, jk, block, causal, mk, h_slope, *, scale,
                   bias_tile=None):
    """Shared (blk, blk) score tile for ALL six kernels (baseline and
    streamed, fwd and bwd): s = scale·q·kᵀ (+bias tile) (+ALiBi ramp),
    with causal / key-padding positions forced to BIG_NEG BEFORE any exp
    (for all-masked rows lse ~ BIG_NEG and a raw exp(s − lse) would
    overflow to inf — the round-4 fix, now in exactly one place).

    q/k arrive in their STORAGE dtype (bf16 in practice): the MXU runs
    bf16×bf16→f32 at full rate but emulates f32×f32 matmuls in multiple
    passes — pre-casting operands to f32 (the round-5 profile's finding)
    halves attention-matmul throughput. The 1/√hd scale therefore applies
    to the f32 product, not the operands (also exact for any hd). Returns
    (s, keep) where keep is None when nothing is masked."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if bias_tile is not None:
        s = s + bias_tile.astype(jnp.float32)
    if h_slope is not None:
        s = s + h_slope * _alibi_rel(iq, jk, block)
    keep = None
    if causal:
        q_pos = iq * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 0)
        k_pos = jk * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1)
        keep = q_pos >= k_pos
    if mk is not None:
        keep = mk[None, :] if keep is None else (keep & mk[None, :])
    if keep is not None:
        s = jnp.where(keep, s, BIG_NEG)
    return s, keep


def _probs_from_lse(s, keep, lse):
    """Backward-pass probabilities recomputed from the saved logsumexp,
    masked positions zeroed — shared by all four backward kernels."""
    p = jnp.exp(s - lse[:, None])
    return jnp.where(keep, p, 0.0) if keep is not None else p


def _slopes_operand(slopes):
    """(H,) → (1, H) fp32 operand; each grid program receives ITS head's
    slope as a (1, 1) block via a static index map — no dynamic lane
    extract for Mosaic to lower."""
    return jnp.asarray(slopes, jnp.float32).reshape(1, -1)


def _slopes_spec(H):
    return pl.BlockSpec((1, 1), lambda b, h, i: (0, h))


def _bias_row_spec(bias_shape, B, H, block):
    """(blk, S) row-slice BlockSpec for a (BB, HH, S, S) bias with BB in
    {1, B} and HH in {1, H} (broadcast handled by the index map, NOT by
    materializing the broadcast in HBM)."""
    bb, hh = bias_shape[0], bias_shape[1]
    return pl.BlockSpec(
        (None, None, block, bias_shape[3]),
        lambda b, h, i: (b if bb > 1 else 0, h if hh > 1 else 0, i, 0))


def _bias_col_spec(bias_shape, B, H, block):
    """(S, blk) column-slice BlockSpec (dk/dv kernel: grid over k blocks)."""
    bb, hh = bias_shape[0], bias_shape[1]
    return pl.BlockSpec(
        (None, None, bias_shape[2], block),
        lambda b, h, j: (b if bb > 1 else 0, h if hh > 1 else 0, 0, j))


def _fwd_call(q, k, v, mask, bias, *, block: int, causal: bool,
              interpret: bool, alibi=None):
    B, H, S, hd = q.shape
    if bias is None and _use_streamed(S, hd, q.dtype.itemsize):
        return _fwd_call_streamed(q, k, v, mask, block=block, causal=causal,
                                  interpret=interpret, alibi=alibi)
    scale = 1.0 / math.sqrt(hd)
    grid = (B, H, S // block)
    masked, biased = mask is not None, bias is not None
    kernel = partial(_fwd_kernel, block=block, scale=scale, causal=causal,
                     masked=masked, biased=biased, alibi=alibi is not None)
    in_specs = [
        pl.BlockSpec((None, None, block, hd), lambda b, h, i: (b, h, i, 0)),
        pl.BlockSpec((None, None, S, hd), lambda b, h, i: (b, h, 0, 0)),
        pl.BlockSpec((None, None, S, hd), lambda b, h, i: (b, h, 0, 0)),
    ]
    args = [q, k, v]
    if masked:
        in_specs.append(pl.BlockSpec((None, SUBLANES, S),
                                     lambda b, h, i: (b, 0, 0)))
        args.append(mask)
    if biased:
        in_specs.append(_bias_row_spec(bias.shape, B, H, block))
        args.append(bias)
    if alibi is not None:
        in_specs.append(_slopes_spec(H))
        args.append(alibi)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, None, block, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, SUBLANES, block),
                         lambda b, h, i: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, SUBLANES, S), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------- backward
def _make_bwd_dq_kernel(block: int, scale: float, causal: bool, masked: bool,
                        biased: bool = False, grad_bias: bool = False,
                        alibi: bool = False):

    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        i = 6
        mask_ref = bias_ref = dbias_ref = slopes_ref = None
        if masked:
            mask_ref = refs[i]; i += 1
        if biased:
            bias_ref = refs[i]; i += 1
        if alibi:
            slopes_ref = refs[i]; i += 1
        h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
        dq_ref = refs[i]; i += 1
        if grad_bias:
            dbias_ref = refs[i]
            # causal bias rows never visit jk > iq: zero-fill so the
            # untouched upper triangle doesn't carry garbage
            dbias_ref[...] = jnp.zeros(dbias_ref.shape, dbias_ref.dtype)
        iq = pl.program_id(2)
        q = q_ref[...]                                   # storage dtype
        do = do_ref[...]
        lse = lse_ref[0]
        delta = delta_ref[0]
        nkb = k_ref.shape[0] // block

        def body(jk, dq):
            k = k_ref[pl.ds(jk * block, block), :]
            v = v_ref[pl.ds(jk * block, block), :]
            bias_tile = (bias_ref[:, pl.ds(jk * block, block)]
                         if bias_ref is not None else None)
            mk = (mask_ref[0, pl.ds(jk * block, block)] > 0.5
                  if mask_ref is not None else None)
            s, keep = _masked_scores(q, k, iq, jk, block, causal, mk,
                                     h_slope, scale=scale,
                                     bias_tile=bias_tile)
            p = _probs_from_lse(s, keep, lse)
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None])
            if dbias_ref is not None:
                # d(bias) == d(scores): each (iq, jk) tile is owned by
                # exactly one grid step, so this is a plain write
                dbias_ref[:, pl.ds(jk * block, block)] = ds.astype(
                    dbias_ref.dtype)
            return dq + jnp.dot(ds.astype(k.dtype), k,
                                preferred_element_type=jnp.float32)

        ub = iq + 1 if causal else nkb
        dq = jax.lax.fori_loop(0, ub, body, jnp.zeros(q.shape, jnp.float32))
        dq_ref[...] = (dq * scale).astype(dq_ref.dtype)

    return kernel


def _make_bwd_dkv_kernel(block: int, scale: float, causal: bool, masked: bool,
                         biased: bool = False, alibi: bool = False):
    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        i = 6
        mask_ref = bias_ref = slopes_ref = None
        if masked:
            mask_ref = refs[i]; i += 1
        if biased:
            bias_ref = refs[i]; i += 1
        if alibi:
            slopes_ref = refs[i]; i += 1
        dk_ref, dv_ref = refs[i:]
        h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
        jk = pl.program_id(2)
        k = k_ref[...]                                   # (blk, hd) storage
        v = v_ref[...]
        nqb = q_ref.shape[0] // block
        mk = None
        if mask_ref is not None:
            mk = mask_ref[0, pl.ds(jk * block, block)] > 0.5  # this k block

        def body(iq, carry):
            dk, dv = carry
            q = q_ref[pl.ds(iq * block, block), :]
            do = do_ref[pl.ds(iq * block, block), :]
            lse = lse_ref[0, pl.ds(iq * block, block)]
            delta = delta_ref[0, pl.ds(iq * block, block)]
            # (S, blk) column slice of the bias: rows iq-block
            bias_tile = (bias_ref[pl.ds(iq * block, block), :]
                         if bias_ref is not None else None)
            s, keep = _masked_scores(q, k, iq, jk, block, causal, mk,
                                     h_slope, scale=scale,
                                     bias_tile=bias_tile)
            p = _probs_from_lse(s, keep, lse)
            dv = dv + jnp.dot(p.astype(do.dtype).T, do,
                              preferred_element_type=jnp.float32)
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None])
            dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                              preferred_element_type=jnp.float32)
            return dk, dv

        lb = jk if causal else 0
        z = jnp.zeros(k.shape, jnp.float32)
        dk, dv = jax.lax.fori_loop(lb, nqb, body, (z, z))
        # dk accumulated against UNSCALED q: apply the 1/√hd chain-rule
        # factor once at the end (q used to arrive pre-scaled)
        dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
        dv_ref[...] = dv.astype(dv_ref.dtype)

    return kernel


def _bwd_call(q, k, v, o, lse, do, mask, bias, *, block: int, causal: bool,
              interpret: bool, grad_bias: bool = False, alibi=None):
    B, H, S, hd = q.shape
    if bias is None and _use_streamed(S, hd, q.dtype.itemsize):
        return _bwd_call_streamed(q, k, v, o, lse, do, mask, block=block,
                                  causal=causal, interpret=interpret,
                                  alibi=alibi)
    scale = 1.0 / math.sqrt(hd)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None, :], (B, H, SUBLANES, S))
    grid = (B, H, S // block)
    masked, biased = mask is not None, bias is not None
    # dbias tiles are plain writes (one owner per grid step): only valid
    # when the bias carries its own full (B, H) leading dims — broadcast
    # biases would need cross-iteration accumulation
    assert not grad_bias or (biased and bias.shape[:2] == (B, H))
    blk_spec = pl.BlockSpec((None, None, block, hd), lambda b, h, i: (b, h, i, 0))
    full_spec = pl.BlockSpec((None, None, S, hd), lambda b, h, i: (b, h, 0, 0))
    row_blk = pl.BlockSpec((None, None, SUBLANES, block),
                           lambda b, h, i: (b, h, 0, i))
    row_full = pl.BlockSpec((None, None, SUBLANES, S),
                            lambda b, h, i: (b, h, 0, 0))
    mask_spec = pl.BlockSpec((None, SUBLANES, S), lambda b, h, i: (b, 0, 0))
    extra_args = ([mask] if masked else []) + ([bias] if biased else []) \
        + ([alibi] if alibi is not None else [])
    extra_row = ([mask_spec] if masked else []) \
        + ([_bias_row_spec(bias.shape, B, H, block)] if biased else []) \
        + ([_slopes_spec(H)] if alibi is not None else [])
    extra_col = ([mask_spec] if masked else []) \
        + ([_bias_col_spec(bias.shape, B, H, block)] if biased else []) \
        + ([_slopes_spec(H)] if alibi is not None else [])
    has_alibi = alibi is not None

    dq_outs = pl.pallas_call(
        _make_bwd_dq_kernel(block, scale, causal, masked, biased, grad_bias,
                            has_alibi),
        grid=grid,
        in_specs=[blk_spec, full_spec, full_spec, blk_spec, row_blk, row_blk]
                 + extra_row,
        out_specs=[blk_spec] + ([_bias_row_spec(bias.shape, B, H, block)]
                                if grad_bias else []),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)]
                  + ([jax.ShapeDtypeStruct(bias.shape, bias.dtype)]
                     if grad_bias else []),
        interpret=interpret,
    )(q, k, v, do, lse, delta, *extra_args)
    dq = dq_outs[0]
    dbias = dq_outs[1] if grad_bias else None

    dk, dv = pl.pallas_call(
        _make_bwd_dkv_kernel(block, scale, causal, masked, biased, has_alibi),
        grid=grid,
        in_specs=[full_spec, blk_spec, blk_spec, full_spec, row_full, row_full]
                 + extra_col,
        out_specs=[blk_spec, blk_spec],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *extra_args)
    return dq, dk, dv, dbias


# ----------------------------------------------- streamed (long-seq) kernels
# The baseline kernels above stage the ENTIRE (S, hd) K/V (fwd, dq) or
# Q/dO (dkv) operand in VMEM and fori_loop over it — simple and fast up
# to ~8k tokens, but the staged operand grows linearly with S and blows
# the ~16 MiB scoped-VMEM budget near 16-32k (round-5 measurement: the
# 32k fwd wants a 32.5 MiB stack allocation). Past _STREAM_VMEM_BYTES
# the calls switch to a 4D grid (B, H, nq, nk) that streams the inner
# operand block-by-block through the grid's innermost dimension, carrying
# the online-softmax state (fwd: m/l/acc; bwd: grad accumulators) in VMEM
# scratch across inner steps — constant VMEM in S, the canonical TPU
# flash-attention shape. Causal skipping is a pl.when guard (idle DMA for
# the never-visible triangle, no compute). Bias operands stay on the
# baseline path: learned-bias callers (evoformer pair stacks) are
# short-sequence by construction.
_STREAM_VMEM_BYTES = 6 * 1024 * 1024


def _use_streamed(S, hd, itemsize) -> bool:
    # 2 operands (k+v or q+do) x double buffering; callers pre-exclude
    # biased inputs (bias stays on the baseline path). 6 MiB: S=16384 at
    # hd=64 bf16 computes to exactly 8 MiB and the baseline form measured
    # a 16.8 MiB scoped-vmem OOM there (round-5 16k row) — the boundary
    # must stream; S<=8192 (4.2 MiB) measured fine on the baseline form.
    return 2 * S * hd * itemsize * 2 > _STREAM_VMEM_BYTES


def _vmem_scratch(block, hd):
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.VMEM((block, 128), jnp.float32),     # m (lane-replicated)
            pltpu.VMEM((block, 128), jnp.float32),     # l
            pltpu.VMEM((block, hd), jnp.float32)]      # acc


def _fwd_kernel_streamed(*refs, block: int, scale: float, causal: bool,
                         masked: bool, alibi: bool, nk: int):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    mask_ref = slopes_ref = None
    if masked:
        mask_ref = refs[i]; i += 1
    if alibi:
        slopes_ref = refs[i]; i += 1
    o_ref, lse_ref = refs[i:i + 2]
    m_scr, l_scr, acc_scr = refs[i + 2:]
    iq, jk = pl.program_id(2), pl.program_id(3)

    @pl.when(jk == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, BIG_NEG, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        mk = mask_ref[0, :] > 0.5 if mask_ref is not None else None
        h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
        s, keep = _masked_scores(q, k, iq, jk, block, causal, mk, h_slope,
                                 scale=scale)
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l, l_scr.shape)

    if causal:
        pl.when(jk <= iq)(_step)
    else:
        _step()

    @pl.when(jk == (iq if causal else nk - 1))
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.maximum(l, jnp.float32(1e-30))
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        m_col = m_scr[:, 0]
        lse_ref[...] = jnp.broadcast_to(
            (m_col + jnp.log(l_safe[:, 0]))[None, :], (SUBLANES, block))


def _fwd_call_streamed(q, k, v, mask, *, block: int, causal: bool,
                       interpret: bool, alibi=None):
    B, H, S, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    nq = nk = S // block
    masked = mask is not None
    kernel = partial(_fwd_kernel_streamed, block=block, scale=scale,
                     causal=causal, masked=masked, alibi=alibi is not None,
                     nk=nk)
    in_specs = [
        pl.BlockSpec((None, None, block, hd), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((None, None, block, hd), lambda b, h, i, j: (b, h, j, 0)),
        pl.BlockSpec((None, None, block, hd), lambda b, h, i, j: (b, h, j, 0)),
    ]
    args = [q, k, v]
    if masked:
        in_specs.append(pl.BlockSpec((None, SUBLANES, block),
                                     lambda b, h, i, j: (b, 0, j)))
        args.append(mask)
    if alibi is not None:
        in_specs.append(pl.BlockSpec((1, 1), lambda b, h, i, j: (0, h)))
        args.append(alibi)
    return pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, None, block, hd),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((None, None, SUBLANES, block),
                         lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, SUBLANES, S), jnp.float32),
        ],
        scratch_shapes=_vmem_scratch(block, hd),
        interpret=interpret,
    )(*args)


def _make_bwd_dq_kernel_streamed(block: int, scale: float, causal: bool,
                                 masked: bool, alibi: bool, nk: int):
    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        i = 6
        mask_ref = slopes_ref = None
        if masked:
            mask_ref = refs[i]; i += 1
        if alibi:
            slopes_ref = refs[i]; i += 1
        dq_ref = refs[i]
        dq_scr = refs[i + 1]
        iq, jk = pl.program_id(2), pl.program_id(3)

        @pl.when(jk == 0)
        def _init():
            dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

        def _step():
            q = q_ref[...]
            do = do_ref[...]
            lse = lse_ref[0]
            delta = delta_ref[0]
            k = k_ref[...]
            v = v_ref[...]
            mk = mask_ref[0, :] > 0.5 if mask_ref is not None else None
            h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
            s, keep = _masked_scores(q, k, iq, jk, block, causal, mk,
                                     h_slope, scale=scale)
            p = _probs_from_lse(s, keep, lse)
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None])
            dq_scr[...] = dq_scr[...] + jnp.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

        if causal:
            pl.when(jk <= iq)(_step)
        else:
            _step()

        @pl.when(jk == (iq if causal else nk - 1))
        def _finalize():
            dq_ref[...] = (dq_scr[...] * scale).astype(dq_ref.dtype)

    return kernel


def _make_bwd_dkv_kernel_streamed(block: int, scale: float, causal: bool,
                                  masked: bool, alibi: bool, nq: int):
    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
        i = 6
        mask_ref = slopes_ref = None
        if masked:
            mask_ref = refs[i]; i += 1
        if alibi:
            slopes_ref = refs[i]; i += 1
        dk_ref, dv_ref = refs[i:i + 2]
        dk_scr, dv_scr = refs[i + 2:]
        jk, iq = pl.program_id(2), pl.program_id(3)   # iq innermost

        @pl.when(iq == 0)
        def _init():
            dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
            dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

        def _step():
            k = k_ref[...]
            v = v_ref[...]
            q = q_ref[...]
            do = do_ref[...]
            lse = lse_ref[0]
            delta = delta_ref[0]
            mk = mask_ref[0, :] > 0.5 if mask_ref is not None else None
            h_slope = slopes_ref[0, 0] if slopes_ref is not None else None
            s, keep = _masked_scores(q, k, iq, jk, block, causal, mk,
                                     h_slope, scale=scale)
            p = _probs_from_lse(s, keep, lse)
            dv_scr[...] = dv_scr[...] + jnp.dot(
                p.astype(do.dtype).T, do, preferred_element_type=jnp.float32)
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None])
            dk_scr[...] = dk_scr[...] + jnp.dot(
                ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32)

        if causal:
            pl.when(iq >= jk)(_step)
        else:
            _step()

        @pl.when(iq == nq - 1)
        def _finalize():
            # dk accumulated against UNSCALED q (see baseline dkv kernel)
            dk_ref[...] = (dk_scr[...] * scale).astype(dk_ref.dtype)
            dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)

    return kernel


def _bwd_call_streamed(q, k, v, o, lse, do, mask, *, block: int, causal: bool,
                       interpret: bool, alibi=None):
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None, :], (B, H, SUBLANES, S))
    nq = nk = S // block
    masked = mask is not None
    q_blk = pl.BlockSpec((None, None, block, hd),
                         lambda b, h, i, j: (b, h, i, 0))
    kv_blk = pl.BlockSpec((None, None, block, hd),
                          lambda b, h, i, j: (b, h, j, 0))
    row_q = pl.BlockSpec((None, None, SUBLANES, block),
                         lambda b, h, i, j: (b, h, 0, i))
    mask_kv = pl.BlockSpec((None, SUBLANES, block),
                           lambda b, h, i, j: (b, 0, j))
    slope_spec = pl.BlockSpec((1, 1), lambda b, h, i, j: (0, h))
    extra_args = ([mask] if masked else []) \
        + ([alibi] if alibi is not None else [])
    extra_dq = ([mask_kv] if masked else []) \
        + ([slope_spec] if alibi is not None else [])

    dq = pl.pallas_call(
        _make_bwd_dq_kernel_streamed(block, scale, causal, masked,
                                     alibi is not None, nk),
        grid=(B, H, nq, nk),
        in_specs=[q_blk, kv_blk, kv_blk, q_blk, row_q, row_q] + extra_dq,
        out_specs=[q_blk],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block, hd), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *extra_args)[0]

    # dkv grid: iq runs innermost so dk/dv accumulate across q blocks
    q_blk2 = pl.BlockSpec((None, None, block, hd),
                          lambda b, h, j, i: (b, h, i, 0))
    kv_blk2 = pl.BlockSpec((None, None, block, hd),
                           lambda b, h, j, i: (b, h, j, 0))
    row_q2 = pl.BlockSpec((None, None, SUBLANES, block),
                          lambda b, h, j, i: (b, h, 0, i))
    mask_kv2 = pl.BlockSpec((None, SUBLANES, block),
                            lambda b, h, j, i: (b, 0, j))
    slope2 = pl.BlockSpec((1, 1), lambda b, h, j, i: (0, h))
    extra_dkv = ([mask_kv2] if masked else []) \
        + ([slope2] if alibi is not None else [])
    dk, dv = pl.pallas_call(
        _make_bwd_dkv_kernel_streamed(block, scale, causal, masked,
                                      alibi is not None, nq),
        grid=(B, H, nk, nq),
        in_specs=[q_blk2, kv_blk2, kv_blk2, q_blk2, row_q2, row_q2]
                 + extra_dkv,
        out_specs=[kv_blk2, kv_blk2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block, hd), jnp.float32)] * 2,
        interpret=interpret,
    )(q, k, v, do, lse, delta, *extra_args)
    return dq, dk, dv, None


# ------------------------------------------------------------- custom VJP
@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash(block, causal, interpret, q, k, v):
    o, _ = _fwd_call(q, k, v, None, None, block=block, causal=causal,
                     interpret=interpret)
    return o


def _flash_fwd(block, causal, interpret, q, k, v):
    o, lse = _fwd_call(q, k, v, None, None, block=block, causal=causal,
                       interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(block, causal, interpret, res, g):
    q, k, v, o, lse = res
    dq, dk, dv, _ = _bwd_call(q, k, v, o, lse, g, None, None, block=block,
                              causal=causal, interpret=interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_masked(block, causal, interpret, q, k, v, mask):
    o, _ = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                     interpret=interpret)
    return o


def _flash_masked_fwd(block, causal, interpret, q, k, v, mask):
    o, lse = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                       interpret=interpret)
    return o, (q, k, v, o, lse, mask)


def _flash_masked_bwd(block, causal, interpret, res, g):
    q, k, v, o, lse, mask = res
    dq, dk, dv, _ = _bwd_call(q, k, v, o, lse, g, mask, None, block=block,
                              causal=causal, interpret=interpret)
    return dq, dk, dv, jnp.zeros_like(mask)   # mask is {0,1} data, no grad


_flash_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _flash_biased(block, causal, interpret, grad_bias, q, k, v, bias, mask):
    o, _ = _fwd_call(q, k, v, mask, bias, block=block, causal=causal,
                     interpret=interpret)
    return o


def _flash_biased_fwd(block, causal, interpret, grad_bias, q, k, v, bias,
                      mask):
    o, lse = _fwd_call(q, k, v, mask, bias, block=block, causal=causal,
                       interpret=interpret)
    return o, (q, k, v, o, lse, bias, mask)


def _flash_biased_bwd(block, causal, interpret, grad_bias, res, g):
    q, k, v, o, lse, bias, mask = res
    dq, dk, dv, dbias = _bwd_call(q, k, v, o, lse, g, mask, bias,
                                  block=block, causal=causal,
                                  interpret=interpret, grad_bias=grad_bias)
    if dbias is None:
        # Broadcast-shaped biases (ALiBi slopes x positions, padding
        # biases) are positional constants: a zero cotangent is correct
        # and DCE'd under jit. Learned biases must come in full-shape
        # (B, H, S, S) to get a real dbias (enforced in flash_attention).
        dbias = jnp.zeros_like(bias)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dbias, dmask


_flash_biased.defvjp(_flash_biased_fwd, _flash_biased_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_alibi(block, causal, interpret, q, k, v, slopes, mask):
    o, _ = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                     interpret=interpret, alibi=slopes)
    return o


def _flash_alibi_fwd(block, causal, interpret, q, k, v, slopes, mask):
    o, lse = _fwd_call(q, k, v, mask, None, block=block, causal=causal,
                       interpret=interpret, alibi=slopes)
    return o, (q, k, v, o, lse, slopes, mask)


def _flash_alibi_bwd(block, causal, interpret, res, g):
    q, k, v, o, lse, slopes, mask = res
    dq, dk, dv, _ = _bwd_call(q, k, v, o, lse, g, mask, None, block=block,
                              causal=causal, interpret=interpret,
                              alibi=slopes)
    dmask = None if mask is None else jnp.zeros_like(mask)
    # slopes are deterministic positional constants: zero cotangent
    return dq, dk, dv, jnp.zeros_like(slopes), dmask


_flash_alibi.defvjp(_flash_alibi_fwd, _flash_alibi_bwd)


# ------------------------------------------------------------- public API
def flash_attention(q, k, v, *, mask: Optional[jnp.ndarray] = None,
                    bias: Optional[jnp.ndarray] = None,
                    bias_is_constant: bool = False,
                    alibi_slopes: Optional[jnp.ndarray] = None,
                    causal: bool = True, block: int = 512,
                    interpret: Optional[bool] = None):
    """Fused causal attention. q: (B, S, H, hd); k/v: (B, S, KV, hd).

    ``mask`` is a (B, S) key-padding mask ({0,1}); it is applied INSIDE the
    kernel (fwd and both bwd kernels), so padded/packed batches stay on the
    fused path — the reference-parity requirement the round-1 fallback
    violated.

    ``bias`` is an additive score bias, shape (S, S), (H, S, S),
    (B|1, H|1, S, S) — streamed into the fwd and both bwd kernels in
    (block, S) slices, never materializing (B, H, S, S) *scores* in HBM.
    Gradient handling by shape:

    - full (B, H, S, S): differentiable in-kernel (dbias = ds tiles — the
      evoformer pair-bias case, reference
      csrc/deepspeed4science/evoformer_attn/);
    - broadcast shapes with ``bias_is_constant=True``: index-map broadcast,
      explicit ``stop_gradient`` — zero HBM cost, for positional constants
      (ALiBi, additive masks);
    - broadcast shapes otherwise: broadcast OUTSIDE the kernel so the
      ``broadcast_to`` transpose sums a CORRECT cotangent for learned
      shared biases (costs a (B, H, S, S) bias materialization — still
      cheaper than the dense path, which adds scores+probs on top; pass
      ``bias_is_constant=True`` to opt out when the bias isn't trained).

    ``alibi_slopes``: (H,) per-head slopes — the ALiBi distance ramp is
    built IN-kernel from block indices (an (H, S, S) bias operand at 64k
    seq would be 100+ GB; slopes cost H floats). Mutually exclusive with
    ``bias``.

    ``block`` default 512 (round-5 A/B on a v5e, 1B decoder seq 1024:
    block 128 → 421.5 ms/step, 256 → 334.9, 512 → 305.5 — wider tiles
    feed the MXU 512-wide dots and cut the kv-loop trips 4×; a (512,
    512) f32 score tile is ~1 MiB of VMEM, comfortably under budget).
    Shapes not divisible by the block clamp it to S (single tile), then
    shrink toward the largest power-of-two divisor of S ≥ 128 (512 → 256
    → 128, one-shot warning) so S = 768/1152/1920 stay fused.

    The only remaining fallback is S with no fused-eligible divisor
    (warned once — the dense path is an HBM cliff at long sequence).
    """
    B, S, H, hd = q.shape
    assert bias is None or alibi_slopes is None, \
        "pass either bias or alibi_slopes, not both"
    blk = min(block, S)
    if S % blk != 0:
        # Shrink to the largest halving of the block ≥ 128 that divides S
        # before giving up: S = 768/1152/1920 are divisible by 256 or 128
        # and must stay fused — the dense fallback materializes
        # (B, H, S, S) scores. Candidates derive from blk (a 1024 caller
        # block still tries 512 first), wider-first because wider tiles
        # feed the MXU better (the 512-vs-256 A/B in the docstring).
        cand = blk // 2
        while cand >= 128:
            if S % cand == 0:
                from ..utils.logging import warning_once

                warning_once(
                    f"flash_attention: seq {S} not divisible by block "
                    f"{blk}; shrinking to {cand} to stay on the fused "
                    "path (wider tiles feed the MXU better — pad S to "
                    f"a multiple of {blk} to avoid the shrink)")
                blk = cand
                break
            cand //= 2
    # Mosaic has no f16: fp16-compute inputs (any of q/k/v — an fp16 KV
    # cache under a bf16 trunk counts) take the same XLA fallback as
    # non-divisible shapes; bf16/f32 stay fused. Warn ONCE for the f16
    # case: the dense path materializes (B, H, S, S) scores, an HBM cliff
    # at long sequence that would otherwise surface as an opaque OOM.
    f16_in = any(jnp.dtype(x.dtype) == jnp.float16 for x in (q, k, v)) \
        and jax.default_backend() == "tpu"
    if f16_in:
        from ..utils.logging import warning_once

        warning_once(
            "flash_attention: float16 inputs fall back to the dense "
            "XLA path on TPU (Mosaic has no f16). The dense path "
            "materializes (B, H, S, S) scores — prefer bf16 compute "
            "for long sequences.")
    if f16_in or S % blk != 0:
        if S % blk != 0:
            from ..utils.logging import warning_once

            warning_once(
                f"flash_attention: seq {S} has no fused-eligible block "
                f"divisor (tried {blk}, 256, 128); demoting to the "
                "dense XLA path, which materializes (B, H, S, S) "
                "scores in HBM")
        from ..models.transformer import alibi_bias, causal_attention

        if alibi_slopes is not None:
            bias = alibi_bias(alibi_slopes, S)
        return causal_attention(q, k, v, mask=mask, causal=causal, bias=bias)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    KV = k.shape[2]
    if KV != H:  # GQA: differentiable repeat — dk/dv group-sum via autodiff
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    # (B, S, H, hd) -> (B, H, S, hd)
    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
    if alibi_slopes is not None:
        o = _flash_alibi(blk, causal, interpret, qt, kt, vt,
                         _slopes_operand(alibi_slopes),
                         _mask_operand(mask, S) if mask is not None else None)
    elif bias is not None:
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
        if bias.shape[:2] != (B, H):
            if bias_is_constant:
                bias = jax.lax.stop_gradient(bias)
            else:
                # learned shared bias: materialize the broadcast so its
                # transpose sums the true dbias (silent zero grads were
                # the round-4 review's finding #1)
                bias = jnp.broadcast_to(bias, (B, H) + bias.shape[2:])
        grad_bias = bias.shape[:2] == (B, H)
        o = _flash_biased(blk, causal, interpret, grad_bias, qt, kt, vt,
                          bias, _mask_operand(mask, S) if mask is not None
                          else None)
    elif mask is not None:
        o = _flash_masked(blk, causal, interpret, qt, kt, vt,
                          _mask_operand(mask, S))
    else:
        o = _flash(blk, causal, interpret, qt, kt, vt)
    return o.swapaxes(1, 2)


def make_flash_attention(block: int = 512, interpret: Optional[bool] = None,
                         bias_is_constant: bool = True):
    """attention_fn factory for :class:`TransformerLM`.

    ``bias_is_constant=True`` (the model-path default) stop-gradients a
    broadcast-shaped bias — correct for ALiBi ramps, WRONG for a learned
    bias. Callers training through the bias (e.g. evoformer pair bias)
    must pass ``bias_is_constant=False`` to get true dbias tiles."""

    def attn(q, k, v, *, mask=None, bias=None, alibi_slopes=None):
        # model-path biases are ALiBi distance ramps: positional
        # constants, streamed via index-map broadcast at zero HBM cost
        # (slopes preferred: the ramp is built in-kernel)
        return flash_attention(q, k, v, mask=mask, bias=bias,
                               alibi_slopes=alibi_slopes,
                               bias_is_constant=bias_is_constant, block=block,
                               interpret=interpret)

    # capability flags: constant-bias only under the default factory args —
    # learned-bias callers must rebuild with bias_is_constant=False
    attn.accepts_bias = True
    attn.bias_is_constant = bias_is_constant
    attn.accepts_alibi_slopes = True  # in-kernel ramp: no (H,S,S) operand
    return attn
