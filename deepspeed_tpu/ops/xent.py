"""Fused softmax-cross-entropy over the unembedding: Pallas TPU kernel.

The naive head computes ``logits = h @ W`` ((T, V), ~800 MiB bf16 for GPT-2
shapes), then reduces them — three-plus HBM round-trips over the largest
tensor in the step, and the backward materializes a (T, V) d_logits as
well. This kernel streams W in (block_v, d) tiles and keeps each logits
tile in VMEM only: forward emits just the per-token NLL and logsumexp
(flash-attention's online-softmax trick applied to the vocab dim, the same
role the reference's fused CUDA softmax/logits kernels play,
``csrc/transformer/inference/csrc/softmax.cu``); backward recomputes
logits per tile and feeds ``p - onehot`` straight into the dx / dW
matmuls. HBM traffic drops from O(T*V) tensors to O(T + V*d) operands.

Layout: W is taken in (V, d) — the natural layout of a tied embedding
table, so no transpose is ever materialized. An optional output bias
(BERT's decoder bias) rides along: (V,) added per tile, gradient
accumulated in the dW kernel. The backward runs two kernels with
transposed grids (dx accumulates over vocab tiles per token block; dW and
dbias over token blocks per vocab tile) because a Pallas TPU output block
may only be revisited on consecutive grid steps.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

SUBLANES = 8
BIG_NEG = -1e30


def _tile_logits(x, w, b, vj, V):
    """One (bt, bv) logits tile in f32, vocab padding masked."""
    bt, bv = x.shape[0], w.shape[0]
    logits = lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    logits = logits + b[None, :]
    col = vj * bv + lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    return jnp.where(col < V, logits, BIG_NEG), col


# ------------------------------------------------------------------ forward
def _fwd_kernel(x_ref, w_ref, b_ref, t_ref, nll_ref, lse_ref,
                m_sc, s_sc, tgt_sc, *, V: int, n_vj: int,
                partials: bool = False):
    """``partials=False``: emit per-token (nll, lse). ``partials=True``
    (TP vocab shards): emit per-token (target-logit partial, shard-local
    logsumexp m + log s); the cross-shard combine (pmax/psum) happens
    upstream in ``_fwd_tp``. Both modes share every tile op; only _emit
    differs."""
    vj = pl.program_id(1)

    @pl.when(vj == 0)
    def _init():
        m_sc[...] = jnp.full(m_sc.shape, BIG_NEG, jnp.float32)
        s_sc[...] = jnp.zeros(s_sc.shape, jnp.float32)
        tgt_sc[...] = jnp.zeros(tgt_sc.shape, jnp.float32)

    logits, col = _tile_logits(x_ref[...], w_ref[...],
                               b_ref[0, :].astype(jnp.float32), vj, V)
    t = t_ref[0, :]                                    # (bt,) int32
    # col < V guard: under TP a FOREIGN shard's shifted target id can land
    # in this shard's padded vocab region [V, Vp), where logits are
    # BIG_NEG — matching it would poison the psum'd target partial with
    # -1e30 (real hit: NeoX vocab 50304 / tp 4 pads 12576→12800)
    tgt_sc[...] += jnp.sum(
        jnp.where((col == t[:, None]) & (col < V), logits, 0.0),
        axis=1, keepdims=True)
    m = m_sc[...]
    m_new = jnp.maximum(m, jnp.max(logits, axis=1, keepdims=True))
    s_sc[...] = (s_sc[...] * jnp.exp(m - m_new)
                 + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    m_sc[...] = m_new

    @pl.when(vj == n_vj - 1)
    def _emit():
        if partials:
            # shard-local (m, tgt) ride out for the cross-shard combine;
            # s is carried as log for a numerically uniform psum upstream
            a = m_sc[:, 0] + jnp.log(jnp.maximum(s_sc[:, 0], 1e-30))
            nll_ref[...] = jnp.broadcast_to(tgt_sc[:, 0][None, :],
                                            nll_ref.shape)
            lse_ref[...] = jnp.broadcast_to(a[None, :], lse_ref.shape)
        else:
            lse = m_sc[:, 0] + jnp.log(s_sc[:, 0])
            # (SUBLANES, bt): replicated across sublanes for (8,128) tiling
            nll_ref[...] = jnp.broadcast_to((lse - tgt_sc[:, 0])[None, :],
                                            nll_ref.shape)
            lse_ref[...] = jnp.broadcast_to(lse[None, :], lse_ref.shape)


# ----------------------------------------------------------------- backward
def _dlogits(x, w, b, t, lse, g, vj, V):
    """Recompute one logits tile; return (softmax - onehot) * dnll (f32)."""
    logits, col = _tile_logits(x, w, b, vj, V)
    p = jnp.exp(logits - lse[:, None])                 # exact: saved lse
    # col < V: a foreign target in the padded region must not set a onehot
    # (its dw/db rows are sliced off and padded w rows are zeros, so the
    # damage would be bounded — but keep fwd/bwd masking identical)
    onehot = ((col == t[:, None]) & (col < V)).astype(jnp.float32)
    return (p - onehot) * g[:, None]                   # (bt, bv)


def _dx_kernel(x_ref, w_ref, b_ref, t_ref, lse_ref, g_ref, dx_ref, acc_sc,
               *, V: int, n_vj: int):
    vj = pl.program_id(1)

    @pl.when(vj == 0)
    def _init():
        acc_sc[...] = jnp.zeros(acc_sc.shape, jnp.float32)

    dl = _dlogits(x_ref[...], w_ref[...], b_ref[0, :].astype(jnp.float32),
                  t_ref[0, :], lse_ref[0, :], g_ref[0, :], vj, V)
    acc_sc[...] += jnp.dot(dl.astype(w_ref.dtype), w_ref[...],
                           preferred_element_type=jnp.float32)

    @pl.when(vj == n_vj - 1)
    def _emit():
        dx_ref[...] = acc_sc[...].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, b_ref, t_ref, lse_ref, g_ref, dw_ref, db_ref,
               acc_sc, bacc_sc, *, V: int, n_ti: int):
    vj = pl.program_id(0)
    ti = pl.program_id(1)

    @pl.when(ti == 0)
    def _init():
        acc_sc[...] = jnp.zeros(acc_sc.shape, jnp.float32)
        bacc_sc[...] = jnp.zeros(bacc_sc.shape, jnp.float32)

    x = x_ref[...]
    dl = _dlogits(x, w_ref[...], b_ref[0, :].astype(jnp.float32),
                  t_ref[0, :], lse_ref[0, :], g_ref[0, :], vj, V)
    acc_sc[...] += lax.dot_general(dl.astype(x.dtype), x,
                                   (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
    bacc_sc[...] += jnp.sum(dl, axis=0, keepdims=True)

    @pl.when(ti == n_ti - 1)
    def _emit():
        dw_ref[...] = acc_sc[...].astype(dw_ref.dtype)
        db_ref[...] = jnp.broadcast_to(bacc_sc[...], db_ref.shape)


# ----------------------------------------------------------------- wrapper
def _pad_to(x, mult, axis):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _rep(v):
    """(T,) → (SUBLANES, T) replicated operand for TPU tiling."""
    return jnp.broadcast_to(v[None, :], (SUBLANES, v.shape[0]))


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _pow2_ceil(n):
    return 1 << max(0, math.ceil(math.log2(max(1, n))))


def _resolve_interpret(interpret):
    return jax.default_backend() != "tpu" if interpret is None else interpret


# Element budget for the kernels' VMEM stack ((bt + bv) x d tiles,
# double-buffered): the default (256, 512) tiles measure ~13 MiB of scoped
# VMEM at d=2048 (and 16.8 MiB at d=2560 — the round-5 remote-compile OOM),
# so (256+512)*2048 elements is the proven-safe ceiling.
_TILE_ELEM_BUDGET = (256 + 512) * 2048
_MIN_TILE = 128


def fused_xent_eligible_d(d: int) -> bool:
    """Can the kernels' tiles be shrunk to fit scoped VMEM at this feature
    width? Past d=6144 even the minimum (128, 128) tiles blow the budget —
    gates must route the XLA loss path instead."""
    return (2 * _MIN_TILE) * d <= _TILE_ELEM_BUDGET


def fused_xent_eligible(cfg_dtype, compute_dtype, d_model: int) -> bool:
    """Shared hardware-eligibility gate for the decoder and T5 loss paths
    (model-structure checks stay with each model). False when:

    - float16 could reach the kernel on TPU, via EITHER the trunk's
      activation dtype (cfg) or the engine's compute params (fp16 engines
      cast params to f16 even when cfg.dtype stays bf16) — Mosaic has no
      f16 ("Unsupported type in mosaic dialect", round-5 smoke); interpret
      mode on other backends handles f16 fine;
    - the feature width is past what tile-shrinking can fit in scoped VMEM
      (fused_xent_eligible_d)."""
    if jax.default_backend() == "tpu" and (
            jnp.dtype(cfg_dtype) == jnp.float16
            or (compute_dtype is not None
                and jnp.dtype(compute_dtype) == jnp.float16)):
        return False
    return fused_xent_eligible_d(d_model)


def _pow2_floor_tile(b):
    """Normalize a user block to a lane-aligned power of two: a 192 block
    would otherwise reach Mosaic as a misaligned 192-lane tile whenever
    the VMEM budget doesn't force shrinking (the shrink-loop clamp alone
    only covers the shrinking case)."""
    p = 1 << (int(b).bit_length() - 1)       # power-of-two floor
    return max(_MIN_TILE, p)


def _blocks(T, V, block_t, block_v, d=0):
    bt = min(_pow2_floor_tile(block_t), _pow2_ceil(T))
    bv = min(_pow2_floor_tile(block_v), _pow2_ceil(V))
    # shrink tiles (largest first) until the ELEMENT budget holds at this
    # d — a ratio-with-floor underestimates past d~4096 (round-5 review).
    # Each halving clamps at _MIN_TILE: a non-power-of-two user block
    # (e.g. 192) must land on the 128 lane floor, not sail past it to 96.
    while d and (bt + bv) * d > _TILE_ELEM_BUDGET \
            and (bt > _MIN_TILE or bv > _MIN_TILE):
        if bv >= bt and bv > _MIN_TILE:
            bv = max(_MIN_TILE, bv // 2)
        else:
            bt = max(_MIN_TILE, bt // 2)
    return bt, bv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_token_nll(x, w, bias, targets, block_t=256, block_v=512,
                    interpret=None):
    """Per-token NLL of ``softmax(x @ w.T + bias)`` with no (T, V) tensor.

    x: (T, d) compute dtype; w: (V, d) — unembedding in embedding-table
    layout; bias: (V,) or None; targets: (T,) int32 in [0, V).
    Returns (T,) fp32 NLL. Differentiable in x, w, bias.
    """
    nll, _ = _fwd(x, w, bias, targets, block_t, block_v, interpret)
    return nll


def _operands(x, w, bias, targets, bt, bv, extra=()):
    xp = _pad_to(x, bt, 0)
    wp = _pad_to(w, bv, 0)
    bp = _pad_to(jnp.zeros((w.shape[0],), x.dtype) if bias is None
                 else bias.astype(x.dtype), bv, 0)
    tp = _pad_to(targets, bt, 0)
    return xp, wp, _rep(bp), _rep(tp), *(
        _rep(_pad_to(e, bt, 0)) for e in extra)


def _fwd(x, w, bias, targets, block_t, block_v, interpret, partials=False):
    T, d = x.shape
    V = w.shape[0]
    interpret = _resolve_interpret(interpret)
    bt, bv = _blocks(T, V, block_t, block_v, d)
    xp, wp, bp, tp = _operands(x, w, bias, targets, bt, bv)
    Tp, Vp = xp.shape[0], wp.shape[0]
    n_ti, n_vj = Tp // bt, Vp // bv
    nll, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, V=V, n_vj=n_vj, partials=partials),
        grid=(n_ti, n_vj),
        in_specs=[
            pl.BlockSpec((bt, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            pl.BlockSpec((SUBLANES, bv), lambda i, j: (0, j)),
            pl.BlockSpec((SUBLANES, bt), lambda i, j: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((SUBLANES, bt), lambda i, j: (0, i)),
            pl.BlockSpec((SUBLANES, bt), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((SUBLANES, Tp), jnp.float32),
            jax.ShapeDtypeStruct((SUBLANES, Tp), jnp.float32),
        ],
        scratch_shapes=[_vmem((bt, 1)), _vmem((bt, 1)), _vmem((bt, 1))],
        interpret=interpret,
    )(xp, wp, bp, tp)
    return nll[0, :T], lse[0, :T]


def _fwd_rule(x, w, bias, targets, block_t, block_v, interpret):
    nll, lse_p = _fwd(x, w, bias, targets, block_t, block_v, interpret)
    return nll, (x, w, bias, targets, lse_p)


def _bwd_kernels(x, w, bias, targets, lse, g, block_t, block_v, interpret):
    """Shared dx/dW/dbias pass: recompute-logits kernels against a given
    per-token lse (the GLOBAL one under TP). Returns (dx, dw, db[:V])."""
    T, d = x.shape
    V = w.shape[0]
    interpret = _resolve_interpret(interpret)
    bt, bv = _blocks(T, V, block_t, block_v, d)
    # padded tokens enter with g = 0: no contribution to dx / dW / dbias
    # (their padded lse of 0 is therefore harmless)
    xp, wp, bp, tp, gp, lp = _operands(
        x, w, bias, targets, bt, bv,
        extra=(g.astype(jnp.float32), lse.astype(jnp.float32)))
    Tp, Vp = xp.shape[0], wp.shape[0]
    n_ti, n_vj = Tp // bt, Vp // bv

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, V=V, n_vj=n_vj),
        grid=(n_ti, n_vj),
        in_specs=[
            pl.BlockSpec((bt, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            pl.BlockSpec((SUBLANES, bv), lambda i, j: (0, j)),
            pl.BlockSpec((SUBLANES, bt), lambda i, j: (0, i)),
            pl.BlockSpec((SUBLANES, bt), lambda i, j: (0, i)),
            pl.BlockSpec((SUBLANES, bt), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((bt, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Tp, d), x.dtype),
        scratch_shapes=[_vmem((bt, d))],
        interpret=interpret,
    )(xp, wp, bp, tp, lp, gp)

    dw, db = pl.pallas_call(
        functools.partial(_dw_kernel, V=V, n_ti=n_ti),
        grid=(n_vj, n_ti),
        in_specs=[
            pl.BlockSpec((bt, d), lambda j, i: (i, 0)),
            pl.BlockSpec((bv, d), lambda j, i: (j, 0)),
            pl.BlockSpec((SUBLANES, bv), lambda j, i: (0, j)),
            pl.BlockSpec((SUBLANES, bt), lambda j, i: (0, i)),
            pl.BlockSpec((SUBLANES, bt), lambda j, i: (0, i)),
            pl.BlockSpec((SUBLANES, bt), lambda j, i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((bv, d), lambda j, i: (j, 0)),
            pl.BlockSpec((SUBLANES, bv), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Vp, d), w.dtype),
            jax.ShapeDtypeStruct((SUBLANES, Vp), jnp.float32),
        ],
        scratch_shapes=[_vmem((bv, d)), _vmem((1, bv))],
        interpret=interpret,
    )(xp, wp, bp, tp, lp, gp)

    return dx[:T], dw[:V], db[0, :V]


def _bwd_rule(block_t, block_v, interpret, res, g):
    x, w, bias, targets, lse = res
    dx, dw, db = _bwd_kernels(x, w, bias, targets, lse, g,
                              block_t, block_v, interpret)
    # bias=None is an empty pytree argument: its cotangent is None too
    dbias = None if bias is None else db.astype(bias.dtype)
    zeros_t = np.zeros(targets.shape, jax.dtypes.float0)
    return dx, dw, dbias, zeros_t


fused_token_nll.defvjp(_fwd_rule, _bwd_rule)


# ------------------------------------------------ tensor-parallel (vocab)
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_token_nll_tp(x, w_shard, bias_shard, targets, axis="model",
                       block_t=256, block_v=512, interpret=None):
    """Vocab-sharded fused NLL — call INSIDE shard_map with ``axis`` bound.

    Each shard streams its own (V/P, d) slice of the unembedding through
    the kernel in partials mode (shard-local logsumexp + target-logit
    partial), then two collectives assemble the global loss: the same
    max/sum-exp exchange the pipeline's vocab-sharded head does in XLA,
    but with no shard ever materializing its (T, V/P) logits. Targets are
    GLOBAL ids; shards own contiguous equal slices.
    """
    nll, _ = _fwd_tp(x, w_shard, bias_shard, targets, axis,
                     block_t, block_v, interpret)
    return nll


def _fwd_tp(x, w_shard, bias_shard, targets, axis, block_t, block_v,
            interpret):
    v_local = w_shard.shape[0]
    off = lax.axis_index(axis) * v_local
    t_loc = (targets - off).astype(jnp.int32)   # foreign ids never match
    tgt_p, lse_l = _fwd(x, w_shard, bias_shard, t_loc,
                        block_t, block_v, interpret, partials=True)
    m_g = lax.pmax(lse_l, axis)
    lse_g = m_g + jnp.log(lax.psum(jnp.exp(lse_l - m_g), axis))
    tgt_g = lax.psum(tgt_p, axis)
    return lse_g - tgt_g, lse_g


def _fwd_tp_rule(x, w_shard, bias_shard, targets, axis, block_t, block_v,
                 interpret):
    nll, lse_g = _fwd_tp(x, w_shard, bias_shard, targets, axis,
                         block_t, block_v, interpret)
    return nll, (x, w_shard, bias_shard, targets, lse_g)


def _bwd_tp_rule(axis, block_t, block_v, interpret, res, g):
    x, w_shard, bias_shard, targets, lse_g = res
    v_local = w_shard.shape[0]
    off = lax.axis_index(axis) * v_local
    t_loc = (targets - off).astype(jnp.int32)
    # Under check_vma=False shard_map distributes a replicated output's
    # cotangent as g/axis_size per shard; undo that so each shard's
    # slice-local dw/dbias (and its dx partial, which shard_map's
    # replicated-x backward then psums) carry the full signal.
    # CAUTION (JAX-upgrade checklist, pinned jax==0.9.0): this
    # unmentioned-out-axis transpose convention is a JAX internal, not
    # documented API — a release that changes it would silently double- or
    # under-scale TP gradients. test_xent.py's TP-equivalence test pins it;
    # re-run that test first on any JAX bump (docs/OPERATIONS.md).
    g = g * lax.psum(jnp.float32(1.0), axis)
    dx_l, dw, db = _bwd_kernels(x, w_shard, bias_shard, t_loc, lse_g, g,
                                block_t, block_v, interpret)
    # each shard returns only its vocab slice's dx contribution;
    # shard_map's backward for the replicated x operand performs the
    # cross-shard psum (an explicit psum here double-counts)
    dx = dx_l
    dbias = None if bias_shard is None else db.astype(bias_shard.dtype)
    zeros_t = np.zeros(targets.shape, jax.dtypes.float0)
    return dx, dw, dbias, zeros_t


fused_token_nll_tp.defvjp(_fwd_tp_rule, _bwd_tp_rule)
