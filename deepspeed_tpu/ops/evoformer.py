"""Evoformer (DS4Science) attention: pair-biased, gated attention.

Analog of the reference's evoformer attention kernels
(``csrc/deepspeed4science/evoformer_attn/``, ~15 kLoC of CUTLASS): the
AlphaFold-style attention variant — scores take an additive pair-represent-
ation bias, the output is gated by a sigmoid projection of the input.

The reference's kernels exist precisely for the BIASED case: streaming
attention that never materializes the (B, H, S, S) score tensor even when
a pair bias is added. Here that is the Pallas flash kernel's ``bias``
operand (ops/flash_attention.py): the bias is streamed in (block, S)
slices through the forward and both backward kernels, and a full-shape
(B, H, S, S) bias is differentiable (dbias tiles written by the dq
kernel) — the pair-representation gradient AlphaFold training needs.
``dense_biased_attention`` remains only as the fallback for sequence
lengths the block tiling cannot cover.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dense_biased_attention(q, k, v, bias, *, mask=None, causal: bool = False):
    """XLA fallback: materializes (B, H, S, S) scores. Only used when S
    doesn't divide the flash block tile. One implementation with the plain
    trunk attention (its bias arg takes every broadcast rank) — two dense
    paths would drift numerically."""
    from ..models.transformer import causal_attention

    return causal_attention(q, k, v, mask=mask, causal=causal, bias=bias)


def evoformer_attention(q, k, v, *, bias: Optional[jnp.ndarray] = None,
                        gate: Optional[jnp.ndarray] = None,
                        causal: bool = False,
                        interpret: Optional[bool] = None):
    """q/k/v: (B, S, H, hd); bias: broadcastable to (B, H, S, S);
    gate: (B, S, H, hd) pre-sigmoid gating values. Returns (B, S, H, hd).

    Mirrors the reference kernel contract (``EvoformerAttnBuilder``):
    ``softmax(q·kᵀ/√d + bias) · v``, then ``sigmoid(gate) ⊙ out``.

    Biased and bias-free paths BOTH stream through the flash kernel; a
    full-shape (B, H, S, S) bias additionally flows gradients back into
    the pair representation (dbias)."""
    from .flash_attention import flash_attention

    out = flash_attention(q, k, v, bias=bias, causal=causal,
                          interpret=interpret)
    if gate is not None:
        out = out * jax.nn.sigmoid(gate.astype(out.dtype))
    return out
