"""Evoformer (DS4Science) attention: pair-biased, gated attention.

Analog of the reference's evoformer attention kernels
(``csrc/deepspeed4science/evoformer_attn/``, ~15 kLoC of CUTLASS): the
AlphaFold-style attention variant — scores take an additive pair-represent-
ation bias, the output is gated by a sigmoid projection of the input, and
the memory-efficient streaming the CUTLASS kernels hand-build is what the
flash kernel already does on TPU.

Two paths:
- ``evoformer_attention``: XLA implementation with bias + gating (fp32
  softmax) — the general case, including the (B, H, S, S) bias tensors
  AlphaFold's triangle attention produces;
- when the bias is None the call routes through the Pallas flash kernel
  (ops/flash_attention.py), which is the memory-efficient case that
  matters for long sequences.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def evoformer_attention(q, k, v, *, bias: Optional[jnp.ndarray] = None,
                        gate: Optional[jnp.ndarray] = None,
                        causal: bool = False,
                        interpret: Optional[bool] = None):
    """q/k/v: (B, S, H, hd); bias: broadcastable to (B, H, S, S);
    gate: (B, S, H, hd) pre-sigmoid gating values. Returns (B, S, H, hd).

    Mirrors the reference kernel contract (``EvoformerAttnBuilder``):
    ``softmax(q·kᵀ/√d + bias) · v``, then ``sigmoid(gate) ⊙ out``."""
    B, S, H, hd = q.shape
    if bias is None:
        from .flash_attention import flash_attention

        out = flash_attention(q, k, v, causal=causal, interpret=interpret)
    else:
        scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
        scores = scores / math.sqrt(hd)
        scores = scores + jnp.broadcast_to(bias, (B, H, S, S)).astype(jnp.float32)
        if causal:
            tri = jnp.tril(jnp.ones((S, S), bool))
            scores = jnp.where(tri[None, None], scores,
                               jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhst,bthd->bshd", probs, v)
    if gate is not None:
        out = out * jax.nn.sigmoid(gate.astype(out.dtype))
    return out
